"""OSD daemon — the data-plane node (src/osd/OSD.cc + PrimaryLogPG).

One ``OSDDaemon`` is one storage node: a local object store, a
messenger endpoint, and the current OSDMap. It plays both reference
roles:

- **replica**: serves ECSubWrite/ECSubRead from peer primaries against
  its local store (handle_sub_write/read, osd/ECBackend.cc:912,998).
- **primary**: serves client ``OSDOp``s for objects it leads. Per-PG
  state mirrors the reference's PG objects: each (pool, pg) gets an
  ``RMWPipeline`` + ``ReadPipeline`` bound to a ``_PGBackend`` that
  routes shard i of the acting set to the right peer (itself included)
  — the ECSwitch-ctor wiring (osd/ECSwitch.h:36-48) resolved through
  the osdmap instead of static config.

Map flow: daemons subscribe to the monitor in-process (the MOSDMap
push channel collapsed to a callback — the wire format exists in
``cluster.osdmap`` serialization; transporting it is deployment
plumbing, not protocol). On a map change, PGs whose acting set changed
are dropped and lazily rebuilt; a NEW primary recovers per-object
state (size, cumulative crcs) from the OI_KEY/HINFO_KEY attrs its
local shard stores carry (the object_info_t takeover path).

Wrong-primary requests answer ``eagain`` + the daemon's epoch, and the
client re-targets (Objecter resend contract, osdc/Objecter.cc:2127).

Peering — the authoritative-log election, the self-rewind, interval
fencing and returning-member admission — is driven by the per-PG
state machine in ``cluster/peering.py`` (the PeeringState.cc analog;
the pre-FSM thread-and-flags path was folded out in round 16 after
four rounds of green soaks — ROADMAP closeout 1b). This module keeps
the peering PRIMITIVES the FSM composes: ``_own_pg_info``,
``_bump_fence``, ``_pgmeta_write_les``, ``_sub_write_interval_ok``,
the PGInfo/PGActivate services, and ``_catch_up_shard``.

Client ops are serialized by a daemon op lock (the reference serializes
per-PG via op queues; the mClock scheduler seam slots in here).
Peer-failure evidence flows to the monitor via ``report_failure``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from ceph_tpu.msg.messages import (
    BackfillReserve,
    BackfillReserveReply,
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteBatch,
    ECSubWriteBatchReply,
    ECSubWriteReply,
    GetAttrs,
    NotifyAck,
    OSDOp,
    OSDOpReply,
    PGActivate,
    PGActivateAck,
    PGInfo,
    PGInfoReply,
    PGList,
    PGListReply,
    Ping,
    Pong,
    WatchNotify,
)
from ceph_tpu.msg.messages import serve_get_attrs
from ceph_tpu.msg.messenger import Connection, Messenger
from ceph_tpu.msg.shard_server import NetShardBackend
from ceph_tpu.codecs import registry
from ceph_tpu.pipeline.extents import ExtentSet
from ceph_tpu.pipeline.hashinfo import HashInfo
from ceph_tpu.pipeline.pglog import PGLog
from ceph_tpu.pipeline.read import ReadPipeline, ShardReadError
from ceph_tpu.pipeline.recovery import RecoveryBackend
from ceph_tpu.pipeline.rmw import (
    HINFO_KEY,
    OI_KEY,
    SI_KEY,
    RMWPipeline,
    ShardBackend,
    pack_oi,
    parse_oi,
)
from ceph_tpu.pipeline.stripe import StripeInfo
from ceph_tpu.store import MemStore, Transaction
from ceph_tpu.utils import tracer
from ceph_tpu.utils.lockdep import DebugLock
from ceph_tpu.utils.mclock import MClockScheduler

from . import qos as _qos
from .osdmap import OSDMap, SHARD_NONE
from .peering import PgPeeringFsm, crash_points, make_peering_perf

#: ops whose re-application a lost-reply resend must not repeat
_MUTATING_OPS = frozenset(
    {"write", "remove", "setxattr", "rmxattr", "omapset", "rollback",
     "append", "truncate", "writefull"}
)

#: client ops the per-tick coalescer may batch: plain EC writes.
#: Appends stay solo (their offset resolves against the PREVIOUS
#: op's committed size, which a batch-mate could move); reads and
#: metadata ops gain nothing from encode batching.
_COALESCE_OPS = frozenset({"write", "writefull"})


class _ClientOpItem:
    """One queued client op as the mClock scheduler carries it:
    callable (the classic serial path) but introspectable, so the
    worker can recognize a RUN of coalescable writes and execute
    them as one tick batch."""

    __slots__ = ("daemon", "conn", "msg", "shard")

    def __init__(self, daemon: "OSDDaemon", conn, msg) -> None:
        self.daemon = daemon
        self.conn = conn
        self.msg = msg
        #: op-shard this item was routed to at dispatch; execution
        #: serializes under that shard's lock (shard 0 == the classic
        #: single _op_lock path)
        self.shard = 0

    def __call__(self) -> None:
        self.daemon._run_client_op(self.conn, self.msg, self.shard)

    def coalescable(self) -> bool:
        return self.msg.op in _COALESCE_OPS


class _CoalCtx:
    """Per-op state threaded through the coalesced batch's three
    phases (serial prelude under the op lock -> concurrent per-PG
    execution -> serial epilogue)."""

    __slots__ = (
        "conn", "msg", "spec", "pgid", "epoch", "pg", "w_offset",
        "result_size", "attrs", "trunc_attrs", "done", "outcome",
        "size", "trace_ctx",
    )

    def __init__(self, conn, msg, spec, pgid, epoch) -> None:
        self.conn = conn
        self.msg = msg
        self.spec = spec
        self.pgid = pgid
        self.epoch = epoch
        self.pg = None
        self.w_offset = 0
        self.result_size = 0
        self.attrs = None
        self.trunc_attrs = None
        #: (trace_id, osd_op span id) captured at submit: later batch
        #: phases (the writefull truncate half) re-enter this context
        #: so their sub-op spans stay under the op's primary subtree
        self.trace_ctx = (None, None)
        self.done: list = []
        #: ("ok", None) | ("eio", detail: recorded under the reqid)
        #: | ("exc", detail: NOT recorded — mirrors the serial path,
        #: where an exception bypasses _record_completed)
        self.outcome = None
        self.size = 0


#: coalesced tick-batch sizes, log2 (1, 2, 4, ... 1024 ops)
_COAL_BUCKETS = [float(1 << i) for i in range(11)]


def _coalesce_perf(name: str):
    """The daemon's coalescing observability (`perf dump` section
    ``osd.<id>.coalesce``): how many ops rode a multi-op tick batch,
    the batch-size histogram, and the sub-write frames the per-peer
    fan-out packing saved."""
    from ceph_tpu.utils import PerfCountersBuilder, perf_collection

    return (
        PerfCountersBuilder(perf_collection, name)
        .add_u64_counter(
            "op_coalesced", "client ops executed in a multi-op batch"
        )
        .add_histogram(
            "batch_size", _COAL_BUCKETS,
            "coalesced tick-batch size in ops (log2 buckets)",
        )
        .add_u64_counter(
            "subwrite_batches", "multi-sub-write frames sent to peers"
        )
        .add_u64_counter(
            "subwrite_batched_ops",
            "sub-writes that shared a frame with at least one other",
        )
        .create_perf_counters()
    )


def make_net_perf(name: str):
    """The per-daemon ``net`` counter set (``perf dump`` section
    ``osd.<id>.net``, Prometheus via the exporter): what the seeded
    fault plane did to this daemon's links, and what the dedup tiers
    absorbed — the observability half of the chaos contract (injected
    faults MUST show up here, absorbed duplicates MUST show up there,
    and the ledger still balances exactly-once)."""
    from ceph_tpu.utils import PerfCountersBuilder, perf_collection

    return (
        PerfCountersBuilder(perf_collection, name)
        .add_u64_counter(
            "frames_dropped", "frames dropped by fault injection"
        )
        .add_u64_counter(
            "frames_delayed", "frames delayed by fault injection"
        )
        .add_u64_counter(
            "frames_duped", "frames duplicated by fault injection"
        )
        .add_u64_counter(
            "frames_reordered", "frames reordered by fault injection"
        )
        .add_u64_counter(
            "resends_absorbed",
            "duplicate/straggler sub-write acks with no pending op",
        )
        .add_u64_counter(
            "dedup_hits",
            "resent client mutations replayed from the reqid cache",
        )
        .create_perf_counters()
    )


def make_rmw_crash_perf(name: str):
    """The per-daemon ``rmw_crash`` counter set (``perf dump`` section
    ``osd.<id>.rmw_crash``): how replay converged state after a
    mid-commit crash — log entries rolled FORWARD onto returning
    members, divergent objects rolled BACK to the elected authority,
    and divergent creates removed."""
    from ceph_tpu.utils import PerfCountersBuilder, perf_collection

    return (
        PerfCountersBuilder(perf_collection, name)
        .add_u64_counter(
            "rollforwards",
            "objects replayed forward from the pg log onto a "
            "returning member",
        )
        .add_u64_counter(
            "rollbacks",
            "divergent objects rebuilt from survivors on replay",
        )
        .add_u64_counter(
            "divergent_removes",
            "divergent creates removed on replay",
        )
        .create_perf_counters()
    )


def make_loc(pool_id: int, oid: str) -> str:
    """Pool-scoped store key: two pools writing the same client oid
    must not collide in an OSD's flat object namespace (the hobject's
    pool field, src/include/object.h)."""
    return f"{pool_id}:{oid}"


def split_loc(loc: str) -> tuple[int, str]:
    pool_id, _, oid = loc.partition(":")
    return int(pool_id), oid


#: separator between a head loc and its snapshot-clone suffix. Clones
#: are full objects living in the HEAD's PG (the hobject snap field
#: role, src/common/hobject.h — placement hashes the head name only).
SNAP_SEP = "\x1fsnap\x1f"


def clone_loc(loc: str, snapid: int) -> str:
    return f"{loc}{SNAP_SEP}{snapid}"


def head_of_loc(loc: str) -> str:
    """The head object's loc (identity for non-clones)."""
    return loc.split(SNAP_SEP, 1)[0]


def snap_of_loc(loc: str) -> int:
    """Clone's snapid, 0 for a head object."""
    parts = loc.split(SNAP_SEP, 1)
    return int(parts[1]) if len(parts) == 2 else 0




#: replicated reqid-dedup window attr (the pg-log reqid role,
#: osd_types.h osd_reqid_t + PGLog dedup): the last few mutating
#: reqids and their result sizes travel on every shard txn, so a NEW
#: primary after failover can replay a resent op's result instead of
#: re-applying it (appends would otherwise duplicate)
REQ_KEY = "rq"
REQ_WINDOW = 8


def pack_reqs(window: "list[tuple[str, int]]") -> bytes:
    return ";".join(f"{r},{s}" for r, s in window[-REQ_WINDOW:]).encode()


def parse_reqs(raw: bytes) -> "list[tuple[str, int]]":
    out = []
    for part in raw.decode().split(";"):
        if not part:
            continue
        r, _, s = part.rpartition(",")
        out.append((r, int(s)))
    return out


def shard_key(loc: str, shard: int) -> str:
    """On-disk object name for ONE logical shard (the ghobject_t
    shard_id field, src/common/hobject.h): an OSD can hold shard j of
    an object under the old layout AND shard i under the new one while
    backfill runs — distinct keys, so data movement never clobbers the
    still-serving copy."""
    return f"{loc}#s{shard}"


def split_shard_key(key: str) -> tuple[str, int]:
    loc, _, s = key.rpartition("#s")
    return loc, int(s)


def first_live(acting: "list[int]") -> int:
    """First non-hole member — THE primary-selection rule (matches
    OSDMap.pg_primary; one definition, used everywhere the daemon
    derives primacy from an acting list it already holds)."""
    return next((o for o in acting if o != SHARD_NONE), SHARD_NONE)


class _AnyShardStores(dict):
    """shard-id → store mapping that answers EVERY key with the
    daemon's one store: an OSD holds whichever logical shard the
    acting set assigns it, keyed on disk by oid alone."""

    def __init__(self, store) -> None:
        super().__init__()
        self._store = store

    def __missing__(self, key):
        return self._store


class _PGBackend:
    """ShardBackend surface bound to one PG's acting set: shard i
    routes to acting[i] — local store or peer sub-op (the per-PG
    ECBackend dispatch seam)."""

    def __init__(self, daemon: "OSDDaemon", acting: list[int]) -> None:
        self.daemon = daemon
        self.acting = list(acting)
        #: positions being caught up from the log: routable for
        #: recovery PUSHES but excluded from avail (reads/writes must
        #: not trust them until the replay completes)
        self.recovering: set[int] = set()

    def avail_shards(self) -> set[int]:
        net_up = self.daemon.peers.avail_shards() | {self.daemon.osd_id}
        out = set()
        for i, osd in enumerate(self.acting):
            if osd == SHARD_NONE or i in self.recovering:
                continue
            if osd in net_up:
                out.add(i)
            elif self.daemon.osdmap.is_up(osd):
                # LOCALLY down-marked but the map says up: a lossy-link
                # transient, not a death. Quarantine the position —
                # writes hole-journal around it NOW, and once the
                # recheck probe clears the mark the tick's catch-up
                # replays what it missed and re-admits it. Without
                # this, the mark clearing silently returned a member
                # whose store missed every write of the mark window to
                # the READ set: one stale chunk, torn decodes (the
                # kill x net_flaky composition found it).
                self.recovering.add(i)
        return out

    def read_shard_async(self, shard, oid, extents, cb) -> None:
        osd = self.acting[shard]
        key = shard_key(oid, shard)
        if osd == SHARD_NONE or (
            osd == self.daemon.osd_id
            and not self.daemon.store.exists(key)
        ):
            # a live shard-holder ALWAYS has the object (every write
            # touches it): absent means this store never got it —
            # error, never zero-fill (that would decode garbage)
            self.daemon.peers._inbox.put(
                lambda: cb(shard, ShardReadError(shard, oid, kind="missing"))
            )
        elif osd == self.daemon.osd_id:
            with tracer.span(
                "sub_read", osd=self.daemon.osd_id, shard=shard,
                local=True,
            ):
                self.daemon.local.read_shard_async(
                    self.daemon.osd_id, key, extents,
                    lambda _s, res: cb(shard, res),
                )
        else:
            self.daemon.peers.read_shard_async(
                osd, key, extents, lambda _s, res: cb(shard, res),
                logical=shard,
            )

    def read_shard(self, shard, oid, extents):
        osd = self.acting[shard]
        key = shard_key(oid, shard)
        if osd == self.daemon.osd_id:
            if not self.daemon.store.exists(key):
                raise ShardReadError(shard, oid, kind="missing")
            return self.daemon.local.read_shard(
                self.daemon.osd_id, key, extents
            )
        return self.daemon.peers.read_shard(
            osd, key, extents, logical=shard
        )

    def submit_shard_txn(self, shard, txn, ack) -> None:
        from dataclasses import replace as _dc_replace

        osd = self.acting[shard]
        if osd == SHARD_NONE:
            return  # parked: recovery's problem once the shard returns
        loc = txn.oids()[0] if txn.oids() else ""
        txn = Transaction(
            ops=[
                _dc_replace(op, oid=shard_key(op.oid, shard))
                for op in txn.ops
            ]
        )
        if osd == self.daemon.osd_id:
            # the primary's own shard goes through handle_sub_write
            # too: ECInject write type 3 aborts it like any receiver
            # (ECBackend.cc:922-926 fires on every OSD, primary
            # included), and the sub-op is traced like any receiver's
            # (a trace missing exactly the primary's shard would
            # misread as a skipped member). Remote shards consult and
            # trace in _dispatch instead.
            from ceph_tpu.pipeline.inject import ec_inject

            if ec_inject.test_write_error3(loc):
                threading.Thread(
                    target=self.daemon.stop, daemon=True
                ).start()
                return
            with tracer.span(
                "sub_write", osd=self.daemon.osd_id, shard=shard,
                local=True,
            ):
                self.daemon.local.submit_shard_txn(
                    self.daemon.osd_id, txn, ack
                )
        else:
            self.daemon.peers.submit_shard_txn(osd, txn, ack)

    def drain_until(self, pred, timeout: float = 30.0) -> None:
        self.daemon.peers.drain_until(pred, timeout)


class _ScrubStore:
    """One shard's store as ``be_deep_scrub`` expects it, backed by
    the PG's (possibly remote) shard reads."""

    def __init__(self, pg: "_PG", shard: int) -> None:
        self.pg = pg
        self.shard = shard

    def read(self, oid: str, offset: int, length: int) -> bytes:
        try:
            bufs = self.pg.backend.read_shard(
                self.shard, oid, ExtentSet([(offset, offset + length)])
            )
        except Exception:
            raise FileNotFoundError(oid) from None
        return b"".join(bufs[o] for o in sorted(bufs))


class _ScrubBackendView:
    """Adapter giving ``be_deep_scrub`` its backend surface
    (avail_shards + stores[shard].read) over a cluster PG."""

    def __init__(self, pg: "_PG") -> None:
        self.pg = pg
        self.stores = {
            s: _ScrubStore(pg, s) for s in range(len(pg.acting))
        }

    def avail_shards(self) -> set[int]:
        return self.pg.backend.avail_shards()


class _PG:
    """Primary-side state for one placement group. Holds the full
    per-PG pipeline stack the reference's PG object holds: RMW, reads,
    the op log (PGLog — the recovery journal), and a RecoveryBackend
    for log-driven catch-up of returning members."""

    def __init__(self, daemon: "OSDDaemon", pool: str, pg: int,
                 raw: list[int], acting: list[int]) -> None:
        spec = daemon.osdmap.pools[pool]
        profile = dict(daemon.osdmap.profiles[spec.profile_name])
        self.pool = pool
        self.pgid = pg
        self.raw = list(raw)        # CRUSH membership (rebalance id)
        self.acting = list(acting)  # raw with down members as holes
        #: positions that were ALREADY holes when this instance was
        #: created: the op log cannot vouch for their gap — a member
        #: returning to one needs a full-shard refresh, not log replay
        self.born_holes: set[int] = {
            i for i, o in enumerate(acting) if o == SHARD_NONE
        }
        self.backfilling = False    # pg_temp installed, data moving
        self.backfill_dirty: set[str] = set()  # written mid-backfill
        self.backfill_done = False  # moved; drop on next map change
        #: positions with a _catch_up_shard thread in flight (guarded
        #: by daemon._pg_lock) — spawn sites dedup through this so a
        #: shard is never caught up by two racing threads
        self._catchup_inflight: set[int] = set()
        #: peering gate (the PG active state): client ops eagain until
        #: the serving primary has run the authoritative-log election
        #: for this interval. Non-primaries are trivially peered —
        #: they only serve sub-ops, which the (peered) primary drives.
        self.peered = threading.Event()
        if first_live(acting) != daemon.osd_id:
            self.peered.set()
        # explicit peering FSM (cluster/peering.py) — the only driver
        # of the peered gate since the legacy thread-and-flags path
        # folded out (round 16)
        self.fsm = PgPeeringFsm(daemon, self)
        self.codec = registry.factory(spec.plugin, profile)
        chunk = daemon.chunk_size
        self.sinfo = StripeInfo(spec.k, spec.m, spec.k * chunk)
        self.backend = _PGBackend(daemon, acting)
        self.pglog = PGLog(spec.k + spec.m)
        self.rmw = RMWPipeline(
            self.sinfo, self.codec, self.backend,
            perf_name=f"osd.{daemon.osd_id}.{pool}.{pg}.rmw",
            pglog=self.pglog,
        )
        # writes stamp (epoch, tid) eversions into OI attrs
        self.rmw.epoch = daemon.osdmap.epoch
        # RMW crash points (rmw.prepare_done / primary_before_commit)
        # fire with the owning daemon so osd= filters and kill resolve
        self.rmw.owner = daemon
        # ECInject write type 2: the primary marks ITSELF down via the
        # mon command when the final sub-write commit arrives
        # (ECBackend.cc:1158-1167). Async: osd_down propagates the map
        # to every daemon synchronously, which must not run under the
        # ack path's locks.
        self.rmw.on_osd_down_inject = lambda: threading.Thread(
            target=lambda: daemon.monitor.osd_down(daemon.osd_id),
            daemon=True,
        ).start()
        self.reads = ReadPipeline(
            self.sinfo, self.codec, self.backend,
            lambda oid: daemon._object_size(self, oid),
            perf_name=f"osd.{daemon.osd_id}.{pool}.{pg}.read",
        )
        self.recovery = RecoveryBackend(
            self.sinfo, self.codec, self.backend,
            lambda oid: daemon._object_size(self, oid),
            self.rmw.hinfo,
            perf_name=f"osd.{daemon.osd_id}.{pool}.{pg}.recovery",
            user_attrs_fn=lambda oid: daemon._recovery_attrs(self, oid),
            eversion_fn=lambda oid: daemon._authoritative_eversion(self, oid),
        )


class OSDDaemon:
    """One storage daemon: store + messenger + per-PG pipelines."""

    def __init__(
        self,
        osd_id: int,
        monitor,
        store=None,
        chunk_size: int = 4096,
        op_timeout: float = 15.0,
        tick_period: float = 2.0,
        scheduler_profiles=None,
        secret: bytes | None = None,
    ) -> None:
        from ceph_tpu.utils.log import get_logger

        self.osd_id = osd_id
        self.log = get_logger(f"osd.{osd_id}")
        self.monitor = monitor
        self.store = store if store is not None else MemStore(f"osd.{osd_id}")
        self.chunk_size = chunk_size
        self.op_timeout = op_timeout
        from ceph_tpu.utils import config as _netcfg

        self.local = ShardBackend(_AnyShardStores(self.store))
        self.peers = NetShardBackend(
            {}, secret=secret, name=f"osd.{osd_id}",
            timeout=_netcfg.get("osd_peer_rpc_timeout"),
        )
        #: coalescing observability + the sub-write frame-packing hook
        self.coalesce_pc = _coalesce_perf(f"osd.{osd_id}.coalesce")
        #: peering observability (elections, rewinds, fence rejects,
        #: state dwell times) — shared by the FSM and legacy paths
        self.peering_pc = make_peering_perf(f"osd.{osd_id}.peering")
        #: net-fault observability: both of this daemon's messengers
        #: (serving + peer-client) report into the ONE osd.<id>.net
        #: set, so a link's faults land on the daemon that owns the
        #: faulted endpoint
        self.net_pc = make_net_perf(f"osd.{osd_id}.net")
        self.peers.messenger.net_pc = self.net_pc
        #: crash-replay observability (rollbacks/rollforwards)
        self.rmw_crash_pc = make_rmw_crash_perf(f"osd.{osd_id}.rmw_crash")
        self.peers.on_subwrite_batch = self._on_subwrite_batch
        # stamp my map interval into every sub-write (replica fence)
        self.peers.interval_fn = lambda: (
            self.osdmap.epoch, self.osd_id
        )
        #: (pool_id, pgid) -> newest interval epoch whose ELECTION has
        #: queried me (or that I activated): answering a peering query
        #: fences this member against sub-writes from older intervals
        #: of that PG — the same_interval_since discard rule
        #: (osd/PeeringState.h; OSD::require_same_or_newer_map)
        self._fence_epochs: dict[tuple[int, int], int] = {}
        self.osdmap: OSDMap = monitor.osdmap
        self.messenger = Messenger(f"osd.{osd_id}", secret=secret)
        self.messenger.net_pc = self.net_pc
        self.messenger.set_dispatcher(self._dispatch)
        self.addr: tuple[str, int] | None = None
        self._pgs: dict[tuple[str, int], _PG] = {}
        self._backfills: dict[tuple[str, int], threading.Thread] = {}
        self.tick_period = tick_period
        self._doomed_pool_ids: set[int] = set()
        self._gc_clean_streak = 2  # nothing doomed yet
        self._tick_stop: threading.Event | None = None
        self._tick_thread: threading.Thread | None = None
        #: mClock QoS arbitration between client IO and background
        #: work (the osd/scheduler/mClockScheduler seam): client ops
        #: run ON the worker in tag order; recovery/backfill admit
        #: through it (their IO still runs on their own threads)
        self.scheduler = MClockScheduler(scheduler_profiles)
        self._sched_cv = threading.Condition()
        #: QoS observability: the osd.N.qos aggregate set plus lazily
        #: created per-class osd.N.qos.pool.<label> sets. The scheduler
        #: keeps the lifetime counts; the tick syncs them into perf by
        #: delta so the exporter and perf dump see them.
        self.qos_pc = _qos.make_qos_perf(f"osd.{osd_id}.qos")
        self._qos_class_pcs: dict = {}
        self._qos_prev: dict[str, tuple] = {}
        self._qos_timeout_warned: set[str] = set()
        self._tick_warn_at = float("-inf")
        #: (stamp, cumulative client served_cost, cumulative total
        #: served_cost, total queue depth) at the last slosh
        #: re-derivation — the demand/capacity measurement window
        self._qos_demand_mark: "tuple[float, float, float, int] | None" = None
        #: measured service capacity (cost units/s): the max sustained
        #: rate observed over BACKLOGGED tick windows, decayed so
        #: transients fade — the osd bench auto-capacity analog.
        #: osd_mclock_capacity is clamped to it before profiles are
        #: derived, so notional capacities far above what the host can
        #: actually serve cannot oversubscribe the reservation phase.
        self._qos_cap_est: float | None = None
        #: explicit ctor profiles pin the table: the slosh knob only
        #: re-derives when the daemon runs on config-driven defaults
        self._qos_static_profiles = scheduler_profiles is not None
        #: class -> spec row last applied from pool metadata
        self._qos_specs_applied: dict[str, tuple] = {}
        _qos.register_scheduler(f"osd.{osd_id}", self.scheduler)
        self._worker: threading.Thread | None = None
        # op-serializing + structural locks, lockdep-tracked when the
        # `lockdep` config arms the detector (utils/lockdep.py; the
        # rank map documents the intended order: op -> pg -> stores)
        # -- sharded op execution (osd_op_num_shards analog): ops
        # route to a shard by (pool, pg) hash; each shard owns an
        # op-serializing lock and — at nshards > 1 — its own worker
        # thread and FIFO, so one EC write parked in a replicated
        # drain cannot wedge other PGs' queue heads (the round-19
        # flood-kill p99 head-of-line cliff). Shard 0's lock IS
        # self._op_lock: at the default nshards=1 the daemon runs
        # the classic single-worker path byte-for-byte (and tests
        # that grab d._op_lock directly keep meaning what they did).
        from ceph_tpu.utils import config as _shcfg

        self._op_nshards = max(1, int(_shcfg.get("osd_op_num_shards")))
        self._op_shards = [
            DebugLock("osd.op", rank=20, op_serializing=True)
            for _ in range(self._op_nshards)
        ]
        self._op_lock = self._op_shards[0]
        #: per-shard FIFO + its wakeup (nshards > 1 only): the
        #: dispatcher (the classic worker thread) drains the mClock
        #: queue in tag order and appends here; shard workers run
        #: their own queue in dispatch order
        self._op_shard_queues = [deque() for _ in range(self._op_nshards)]
        self._op_shard_cvs = [
            threading.Condition() for _ in range(self._op_nshards)
        ]
        self._op_shard_workers: list[threading.Thread] = []
        self._op_rr = 0  # round-robin cursor for unroutable thunks
        #: leaf lock for the reqid-cache dicts' STRUCTURAL mutations
        #: (new-key inserts, trims, clears, key-union iteration).
        #: Under one worker these were _op_lock-serialized; shards
        #: mutate them concurrently. Per-loc read-modify-write stays
        #: safe without it (same loc -> same PG -> same shard lock);
        #: existing-key setitems are GIL-atomic and stay bare. Rank
        #: sits above op(20)/pg(30) and below the store tier (60+):
        #: _req_window seeds from store.getattr while holding it.
        self._reqcache_lock = DebugLock("osd.reqcache", rank=35)
        self._pg_lock = DebugLock("osd.pg", rank=30)
        self._pgmeta_lock = DebugLock("osd.pgmeta")  # serializes les updates
        #: mon config db entries this daemon has applied to the
        #: process config's "mon" layer (name -> value)
        self._mon_cfg_applied: dict[str, str] = {}
        # -- backfill reservations (backfill_reservation.rst): the
        # OSD's two AsyncReservers (common/AsyncReserver.h) bound
        # concurrent backfills to osd_max_backfills, as the driving
        # primary (local) and as a data-receiving target (remote)
        from ceph_tpu.utils import config as _cfg
        from ceph_tpu.utils.reserver import AsyncReserver

        self.local_reserver = AsyncReserver(
            lambda: _cfg.get("osd_max_backfills")
        )
        self.remote_reserver = AsyncReserver(
            lambda: _cfg.get("osd_max_backfills")
        )
        # Completed-mutation results by client reqid (pg-log reqid
        # dedup analog): a resend whose first attempt applied but whose
        # reply was lost replays the recorded outcome instead of
        # re-applying (remove would otherwise surface enoent for a
        # successful op). Bounded FIFO; guarded by _op_lock.
        self._completed_ops: "OrderedDict[str, OSDOpReply]" = OrderedDict()
        #: loc -> [(reqid, size)] rolling window mirroring the
        #: replicated REQ_KEY attr (seeded from storage on takeover)
        self._req_windows: dict[str, list] = {}
        #: loc -> reqids seeded from a stored attr and not yet proven
        #: durable. A dead primary may have stamped the attr on fewer
        #: than k shards — such an op was never acked and is not
        #: reconstructible, so replaying it as a success would lie to
        #: the client (round-4 advisor finding). Entries leave the set
        #: once a quorum poll proves >= k shards recorded them.
        self._req_unverified: dict[str, set] = {}
        #: loc -> monotonic time of its last durability fan-out
        self._req_poll_at: dict[str, float] = {}
        #: async durability fan-outs (_take_or_spawn_poll): results
        #: awaiting consumption, locs with a poller running, and the
        #: daemon-wide budget bounding concurrent poller threads
        self._req_poll_results: dict[str, tuple] = {}
        self._req_polls_inflight: set[str] = set()
        self._req_poll_lock = DebugLock("osd.req_poll")
        self._req_poll_sem = threading.Semaphore(self.REQ_POLL_BUDGET)
        #: queued reqid-cache invalidations from _kick_peering /
        #: pool deletion, applied under _op_lock by the next client
        #: op (_drain_req_flushes). _kick_peering cannot take
        #: _op_lock itself: it runs under _pg_lock, and the op path
        #: nests _op_lock -> _pg_lock (via _get_pg), so the reverse
        #: order would deadlock — the round-5 unlocked clear() raced
        #: in-flight ops instead, letting a mid-op window re-insert
        #: survive the rewind. Entries: ("pg", pool_id, pg_num, pgid)
        #: | ("pool", pool_id) | None (= flush everything). Guarded
        #: by _req_flush_lock, a leaf lock never held across another
        #: acquire.
        self._req_flush: set = set()
        self._req_flush_lock = DebugLock("osd.req_flush", rank=90)
        self._completed_cap = 1024
        self._stopped = False
        # -- background scrub scheduling (osd/scrubber/osd_scrub.cc):
        # per-PG stamps drive randomized shallow/deep due times; the
        # tick kicks due scrubs onto their own thread, capped at
        # osd_max_scrubs concurrent, each object admitting through the
        # mClock "scrub" class (client > recovery > scrub).
        self._scrub_stamps: dict[tuple[str, int], list[float]] = {}
        self._scrub_jitter: dict[tuple[str, int], float] = {}
        self._scrubs_running = 0
        #: PGs with a scrub in flight (stamps only move on completion,
        #: so without this a slow scrub would be re-scheduled — the
        #: per-PG reservation role)
        self._scrubs_inflight: set[tuple[str, int]] = set()
        self._scrub_lock = DebugLock("osd.scrub")
        #: (pool, pgid) -> (monotonic stamp, kind, n_errors, repaired)
        self.scrub_history: dict[tuple[str, int], tuple] = {}
        # -- PG-stats reporting (the MPGStats sender): the tick ships
        # one pg_stats record per led PG + an osd_stat to the monitor
        # every osd_stats_report_interval seconds (0 = off)
        self._last_stats_report = 0.0
        self._stats_seq = 0
        #: (map epoch, {(pool, pgid) I lead per CRUSH}) — the primary
        #: sweep is O(pools x pg_num x CRUSH), so it recomputes only
        #: when the epoch moves, never per report
        self._led_cache: tuple[int, set] = (-1, set())
        # -- watch/notify soft state (osd/Watch.cc role)
        self._watch_lock = DebugLock("osd.watch")
        #: (pool, loc) -> {cookie: Connection}
        self._watchers: dict[tuple[str, str], dict] = {}
        self._pending_notifies: dict[int, tuple] = {}
        self._next_notify_id = 1

    # -- lifecycle ------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self.addr = self.messenger.bind(host, port)
        self.monitor.osd_boot(self.osd_id, self.addr)
        self.monitor.subscribe(self._on_map)
        # QoS specs already in the boot map apply now; later changes
        # ride the map push (_on_map)
        self._apply_qos_specs(self.osdmap)
        if self.tick_period > 0:
            self._tick_stop = threading.Event()
            self._tick_thread = threading.Thread(
                target=self._tick_loop, daemon=True
            )
            self._tick_thread.start()
        self._worker = threading.Thread(target=self._worker_loop, daemon=True)
        self._worker.start()
        if self._op_nshards > 1:
            for i in range(self._op_nshards):
                t = threading.Thread(
                    target=self._shard_loop, args=(i,),
                    name=f"osd.{self.osd_id}-shard{i}", daemon=True,
                )
                t.start()
                self._op_shard_workers.append(t)
        return self.addr

    def _worker_loop(self) -> None:
        """The op-queue worker (the OSD shard thread role): pulls
        work in mClock tag order and runs it. With osd_op_num_shards
        > 1 this thread becomes the DISPATCHER: mClock tag order is
        still honored here (dequeue() withholds work until its tag
        time), but execution hands off to per-shard workers so one
        op parked in a replicated drain no longer blocks the queue
        head for every other PG."""
        import time as _time

        while not self._stopped:
            with self._sched_cv:
                got = self.scheduler.dequeue()
                if got is None:
                    nr = self.scheduler.next_ready()
                    wait = 0.2
                    if nr is not None:
                        wait = max(0.001, min(nr - _time.monotonic(), 0.2))
                    self._sched_cv.wait(wait)
                    continue
            _cls, fn = got
            if self._op_nshards > 1:
                self._dispatch_to_shard(fn)
                continue
            batch, leftover = self._collect_coalesce(fn)
            if batch is not None:
                self._run_thunk(lambda: self._run_coalesced_batch(batch))
            else:
                self._run_thunk(fn)
            if leftover is not None:
                self._run_thunk(leftover)

    # -- shard routing (nshards > 1) -----------------------------------
    def _op_shard_index(self, pool: str, pgid: int) -> int:
        """(pool, pg) -> shard. Stable across map epochs (the pg hash
        moves only on pg-split), so every path that serializes against
        a PG's client ops — scrub, catch-up push, backfill final pass,
        peering rewind — lands on the same lock the dispatcher routes
        that PG's ops to."""
        import zlib as _zlib

        return _zlib.crc32(f"{pool}.{pgid}".encode()) % self._op_nshards

    def _op_lock_for(self, pool: str, pgid: int):
        return self._op_shards[self._op_shard_index(pool, pgid)]

    def _dispatch_to_shard(self, fn) -> None:
        """Route one dequeued work item. Client ops hash by their
        object's PG (same object -> same shard -> dispatch order
        preserved); admit() grant thunks (ev.set) and other bare
        callables run INLINE — they are instant, and running them on
        the dispatcher keeps QoS grant timing exactly where the
        scheduler decided it."""
        if not isinstance(fn, _ClientOpItem):
            self._run_thunk(fn)
            return
        msg = fn.msg
        try:
            pgid = (
                int(msg.offset) if msg.op == "pgls"
                else self.osdmap.object_to_pg(msg.pool, msg.oid)
            )
            idx = self._op_shard_index(msg.pool, pgid)
        except Exception:
            idx = 0  # unroutable (pool gone mid-flight): any shard
        fn.shard = idx
        cv = self._op_shard_cvs[idx]
        with cv:
            self._op_shard_queues[idx].append(fn)
            cv.notify()

    def _shard_loop(self, idx: int) -> None:
        """One op shard's worker: drains its own FIFO in dispatch
        order. Coalescable write runs collect from THIS shard's queue
        only — batch-mates already share the shard lock the batch
        executes under."""
        q = self._op_shard_queues[idx]
        cv = self._op_shard_cvs[idx]
        while True:
            with cv:
                if not q:
                    if self._stopped:
                        return
                    cv.wait(0.2)
                    continue
                fn = q.popleft()
            batch = self._collect_shard_coalesce(idx, fn)
            if batch is not None:
                self._run_thunk(
                    lambda: self._run_coalesced_batch(batch, idx)
                )
            else:
                self._run_thunk(fn)

    def _collect_shard_coalesce(self, idx: int, fn):
        """Shard-local analog of _collect_coalesce: pull the RUN of
        coalescable writes at the head of this shard's queue. No
        leftover handling — a non-coalescable head item simply stays
        queued in position."""
        from ceph_tpu.utils import config as _cfg

        if not (
            isinstance(fn, _ClientOpItem)
            and fn.coalescable()
            and _cfg.get("osd_op_coalescing")
        ):
            return None
        items = [fn]
        cap = _cfg.get("osd_coalesce_max")
        q, cv = self._op_shard_queues[idx], self._op_shard_cvs[idx]
        with cv:
            while (
                len(items) < cap
                and q
                and isinstance(q[0], _ClientOpItem)
                and q[0].coalescable()
            ):
                items.append(q.popleft())
        if len(items) == 1:
            return None
        return items

    def _run_thunk(self, fn) -> None:
        try:
            fn()
        except Exception as e:
            # Op errors reply themselves deeper down; anything
            # surfacing here is an unexpected pipeline fault —
            # keep the worker alive but dump the gather ring so
            # the verbose context survives (Log::dump_recent).
            self.log.error(
                "unexpected worker exception:", type(e).__name__, e
            )
            from ceph_tpu.utils.log import root_log

            root_log.dump_recent("osd worker exception")

    def _collect_coalesce(self, fn):
        """When the dequeued work is a coalescable client write and
        op coalescing is on, drain the RUN of coalescable writes
        queued behind it (the per-OSD-tick window: whatever an async
        client put on the wire together executes together). Returns
        (batch, leftover): batch None means run ``fn`` the classic
        way; leftover is the first non-coalescable item pulled while
        collecting, run after the batch in its dequeue position."""
        from ceph_tpu.utils import config as _cfg

        if not (
            isinstance(fn, _ClientOpItem)
            and fn.coalescable()
            and _cfg.get("osd_op_coalescing")
        ):
            return None, None
        items = [fn]
        cap = _cfg.get("osd_coalesce_max")
        leftover = None
        while len(items) < cap:
            with self._sched_cv:
                got = self.scheduler.dequeue()
            if got is None:
                break
            _c, nfn = got
            if isinstance(nfn, _ClientOpItem) and nfn.coalescable():
                items.append(nfn)
            else:
                leftover = nfn  # queue order: runs after the batch
                break
        if len(items) == 1:
            return None, leftover
        return items, leftover

    def _on_subwrite_batch(self, n: int) -> None:
        self.coalesce_pc.inc("subwrite_batches")
        self.coalesce_pc.inc("subwrite_batched_ops", n)

    def _schedule(self, class_name: str, fn, cost: float = 1.0) -> None:
        with self._sched_cv:
            self.scheduler.enqueue(class_name, fn, cost)
            self._sched_cv.notify()

    def admit(self, class_name: str, cost: float = 1.0) -> None:
        """QoS admission gate for background work: blocks until the
        scheduler grants a slot. Times out permissively (work proceeds
        unthrottled rather than deadlocking when the worker is stuck
        behind a lock the caller holds). A STOPPED daemon grants
        immediately — its worker is gone, and a lingering background
        sweep (scheduled scrub over a corpse) must not crawl at one
        object per timeout."""
        if self._stopped:
            return
        ev = threading.Event()
        self._schedule(class_name, ev.set, cost)
        deadline = time.monotonic() + self.op_timeout
        while not ev.wait(timeout=0.5):
            if self._stopped:
                return
            if time.monotonic() >= deadline:
                self._note_admit_timeout(class_name)
                return

    def _note_admit_timeout(self, class_name: str) -> None:
        """An admit() wait expired and the caller proceeds
        unthrottled. That fallback is deliberate (it beats a deadlock
        when the worker is parked behind a lock the caller holds) but
        it must not be silent: QoS guarantees quietly stop holding.
        Count it per class and WRN the cluster log once per class per
        daemon, with the locks this thread holds — the usual culprit."""
        self.qos_pc.inc("admit_timeout")
        self._qos_class_pc(class_name).inc("admit_timeout")
        if class_name in self._qos_timeout_warned:
            return
        self._qos_timeout_warned.add(class_name)
        from ceph_tpu.utils import lockdep
        from ceph_tpu.utils.cluster_log import cluster_log

        held = [h.lock.name for h in lockdep._held()]
        cluster_log.log(
            f"osd.{self.osd_id}", "qos_admit_timeout",
            f"mclock admit for class {class_name!r} timed out after "
            f"{self.op_timeout:.1f}s; work proceeds unthrottled "
            f"(held locks: {held or 'none'})",
            severity="WRN", epoch=self.osdmap.epoch,
            qos_class=class_name,
        )

    def _tick_loop(self) -> None:
        while not self._tick_stop.wait(self.tick_period):
            try:
                self.tick()
            except Exception as e:
                # a failed tick must not kill the retry loop — but a
                # PERSISTENTLY failing tick silently stalls scrub
                # scheduling, pool GC, re-heal and stats reporting, so
                # it surfaces as a rate-limited cluster-log WRN
                self._note_tick_error(e)

    def _note_tick_error(self, e: BaseException) -> None:
        import traceback

        now = time.monotonic()
        if now - self._tick_warn_at < 30.0:
            return
        self._tick_warn_at = now
        tb = traceback.extract_tb(e.__traceback__)
        where = "?"
        if tb:
            f = tb[-1]
            where = f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno} in {f.name}"
        from ceph_tpu.utils.cluster_log import cluster_log

        cluster_log.log(
            f"osd.{self.osd_id}", "tick_error",
            f"tick failed: {type(e).__name__}: {e} (at {where})",
            severity="WRN", epoch=self.osdmap.epoch,
        )

    # -- QoS plane upkeep ----------------------------------------------
    def _qos_class_pc(self, class_name: str):
        """Lazily build one class's osd.N.qos.pool.<label> perf set
        (the exporter renders the label as a Prometheus dimension)."""
        pc = self._qos_class_pcs.get(class_name)
        if pc is None:
            pc = _qos.make_qos_class_perf(
                f"osd.{self.osd_id}.qos", class_name
            )
            self._qos_class_pcs[class_name] = pc
        return pc

    def _apply_qos_specs(self, osdmap: OSDMap) -> None:
        """Install per-pool / per-tenant QoS specs carried in pool
        metadata into the live scheduler (the map push applying an
        ``osd pool qos set`` without a daemon restart). A tenant row
        lands on ``client.<tenant>``; a pool-wide row (tenant "") on
        ``client.<pool>``. Rows that left the map drop back to prefix
        inheritance from the base ``client`` profile."""
        want: dict[str, tuple] = {}
        for pool, spec in osdmap.pools.items():
            for row in getattr(spec, "qos", ()):
                want[_qos.client_class(row[0], pool)] = tuple(row[1:])
        if want == self._qos_specs_applied:
            return
        with self._sched_cv:
            table = dict(self.scheduler.profiles)
            for cls in set(self._qos_specs_applied) - set(want):
                table.pop(cls, None)
            for cls, row in want.items():
                table[cls] = _qos.QoSSpec(*row).to_profile()
            self.scheduler.set_profiles(table)
        self._qos_specs_applied = want

    def _qos_tick(self) -> None:
        """Per-tick QoS upkeep: sync the scheduler's per-class service
        counts into the osd.N.qos perf sets (delta-based — the
        scheduler counts, perf exposes) and turn the slosh knob:
        re-derive the base profile table from osd_mclock_profile /
        osd_mclock_capacity with client demand measured over the tick
        window, so reservation capacity idle clients aren't using
        flows to recovery and backfill."""
        from ceph_tpu.utils import config as _cfg

        with self._sched_cv:
            snap = self.scheduler.dump()
        total_depth, worst_lag = 0, 0.0
        client_cost = total_cost = 0.0
        for cls, st in snap.items():
            total_depth += st["depth"]
            worst_lag = max(worst_lag, st["tag_lag_s"])
            total_cost += st["served_cost"]
            if cls == "client" or cls.startswith("client."):
                client_cost += st["served_cost"]
            prev = self._qos_prev.get(cls, (0, 0, 0))
            d_r = st["dequeued_r"] - prev[0]
            d_p = st["dequeued_p"] - prev[1]
            d_t = st["throttled"] - prev[2]
            self._qos_prev[cls] = (
                st["dequeued_r"], st["dequeued_p"], st["throttled"]
            )
            if d_r:
                self.qos_pc.inc("dequeue_r", d_r)
            if d_p:
                self.qos_pc.inc("dequeue_p", d_p)
            if d_t:
                self.qos_pc.inc("throttle", d_t)
            cpc = self._qos_class_pc(cls)
            if d_r or d_p:
                cpc.inc("dequeue", d_r + d_p)
            if d_t:
                cpc.inc("throttle", d_t)
            cpc.set("queue_depth", st["depth"])
        self.qos_pc.set("queue_depth", total_depth)
        self.qos_pc.set("tag_lag_ms", int(worst_lag * 1000))
        self.qos_pc.set("qos_classes", len(snap))
        if self._qos_static_profiles:
            return  # explicit ctor profiles: the caller owns the table
        now = time.monotonic()
        mark = self._qos_demand_mark
        self._qos_demand_mark = (now, client_cost, total_cost,
                                 total_depth)
        demand = 0.0
        if mark is not None and now > mark[0]:
            dt = now - mark[0]
            demand = max(client_cost - mark[1], 0.0) / dt
            # capacity estimate: only windows that STARTED backlogged
            # measure the server (an idle window's low rate is demand,
            # not capacity); decay so a one-off fast window fades
            if mark[3] > 0:
                rate = max(total_cost - mark[2], 0.0) / dt
                est = self._qos_cap_est
                self._qos_cap_est = (
                    rate if est is None else max(rate, 0.9 * est)
                )
        capacity = _cfg.get("osd_mclock_capacity")
        # The measured estimate bounds ONLY the reservation clock (the
        # admission guard below): oversubscribed floors starve the
        # weight phase. Limits keep the configured capacity — a
        # cratered estimate throttling the limit-fraction classes
        # would depress the measured rate and lock itself low, since
        # a weak floor slows nothing but a tight ceiling does.
        admit_cap = capacity
        if self._qos_cap_est is not None:
            admit_cap = min(capacity, max(self._qos_cap_est, 1.0))
        self.qos_pc.set("capacity", int(admit_cap))
        try:
            table = _qos.derive_profiles(
                _cfg.get("osd_mclock_profile"),
                capacity,
                client_demand=demand,
            )
        except ValueError:
            return  # a bad profile name must not kill the tick
        # spec rows pushed from pool metadata ride on top of the
        # derived base table (from the pristine rows, NOT the live
        # profiles — those may already be normalization-scaled), then
        # the sum(reservations) <= frac * admit_cap admission guard
        # rescales the reservation clocks against what the host is
        # measured to actually serve
        for cls, row in self._qos_specs_applied.items():
            table[cls] = _qos.QoSSpec(*row).to_profile()
        table = _qos.normalize_reservations(table, admit_cap)
        with self._sched_cv:
            self.scheduler.set_profiles(table)

    def stop(self) -> None:
        self._stopped = True
        with self._sched_cv:
            self._sched_cv.notify_all()
        for cv in self._op_shard_cvs:
            with cv:
                cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=2.0)
        for t in self._op_shard_workers:
            t.join(timeout=2.0)
        # backfill threads write to the store: they must land before a
        # caller closes it
        for t in list(self._backfills.values()):
            if t.is_alive():
                t.join(timeout=5.0)
        if self._tick_stop is not None:
            self._tick_stop.set()
            self._tick_thread.join(timeout=2.0)
        self.peers.shutdown()
        self.messenger.shutdown()
        # live ops this daemon owned died with it: finish them so the
        # tracker (and the slow-op watchdog) never carries corpses
        from ceph_tpu.utils.optracker import op_tracker

        op_tracker.finish_all(
            f"osd.{self.osd_id}", event="daemon_stopped"
        )

    # -- map handling ---------------------------------------------------
    def _apply_mon_config(self, osdmap: OSDMap) -> None:
        """Overlay my slice of the mon-replicated config db into the
        process config's "mon" layer (the MConfig push a daemon gets
        on subscription; mon/ConfigMonitor.h:15). Scopes apply in
        ascending specificity: global < "osd" < "osd.<id>". Observers
        registered on the process config fire on any change. NOTE:
        the process config is global, so in a many-daemons-per-
        process test the last daemon to apply an id-scoped value
        wins — class/global scopes are the meaningful ones there."""
        from ceph_tpu.utils import config

        eff: dict[str, str] = {}
        for scope in ("", "osd", f"osd.{self.osd_id}"):
            for (who, name), val in osdmap.config.items():
                if who == scope:
                    eff[name] = val
        applied: dict[str, str] = {}
        for name, val in eff.items():
            if self._mon_cfg_applied.get(name) == val:
                applied[name] = val
                continue
            try:
                config.set(name, val, layer="mon")
                applied[name] = val
            except Exception as e:
                # NOT recorded at the new value: the next map carrying
                # it retries instead of silently diverging. A
                # previously applied value stays recorded, so a later
                # monitor-side rm still clears the stale layer entry.
                if name in self._mon_cfg_applied:
                    applied[name] = self._mon_cfg_applied[name]
                self.log.error(
                    "mon config", name, "rejected:",
                    type(e).__name__, str(e),
                )
        for name in set(self._mon_cfg_applied) - set(eff):
            try:
                config.rm(name, layer="mon")
            except Exception:
                pass
        self._mon_cfg_applied = applied

    def _on_map(self, osdmap: OSDMap) -> None:
        if self._stopped:
            return
        to_recover: list[tuple[_PG, list[int]]] = []
        to_release: list[tuple[_PG, list[int]]] = []
        with self._pg_lock:
            if osdmap.epoch < self.osdmap.epoch:
                return  # late delivery from a racing notifier thread
            # config applies AFTER the stale-epoch guard (a late old
            # map must not revert newer values) and under _pg_lock so
            # concurrent deliveries can't interleave apply/rm
            self._apply_mon_config(osdmap)
            self._apply_qos_specs(osdmap)
            # pool identity is the ID (names are reusable, ids never
            # are) — and deletions accumulate so a skipped epoch or a
            # straggler write can't leak keys forever
            live_ids = {s.pool_id for s in osdmap.pools.values()}
            dead_ids = set()
            for spec in self.osdmap.pools.values():
                if spec.pool_id not in live_ids:
                    self._doomed_pool_ids.add(spec.pool_id)
                    self._gc_clean_streak = 0
                    dead_ids.add(spec.pool_id)
            if dead_ids:
                # a deleted pool's soft state is garbage its id will
                # never reclaim: prune the interval fences and queue a
                # reqid-cache flush for its objects, or a long-lived
                # daemon grows per-(pool, pg) / per-object entries
                # without bound across create/delete churn. Prune by
                # the DOOMED set, not by absence from live_ids: a
                # fence can legitimately precede this member's
                # knowledge of its pool (peering messages from a
                # newer map), and must survive until that pool is
                # provably deleted.
                doomed_now = dead_ids | self._doomed_pool_ids
                for key in [
                    k for k in self._fence_epochs if k[0] in doomed_now
                ]:
                    del self._fence_epochs[key]
                with self._req_flush_lock:
                    for pid in dead_ids:
                        self._req_flush.add(("pool", pid))
            self.osdmap = osdmap
            for osd, info in osdmap.osds.items():
                if osd == self.osd_id:
                    continue
                if info.up and info.addr:
                    if self.peers.addrs.get(osd) != info.addr:
                        self.peers.set_addr(osd, info.addr)
                    else:
                        # the map says it's up: a locally observed
                        # transient failure must not exclude it forever
                        self.peers.down_shards.discard(osd)
                else:
                    self.peers.down_shards.add(osd)
            maybe_backfill: list[tuple[str, int, "_PG"]] = []
            for key, pg in list(self._pgs.items()):
                pool, pgid = key
                spec = osdmap.pools.get(pool)
                if spec is None:
                    del self._pgs[key]
                    continue
                # new epoch reaches surviving PGs' eversion stamps
                pg.rmw.epoch = osdmap.epoch
                if osdmap.pg_to_raw(pool, pgid) != pg.raw:
                    if pg.backfill_done:
                        # this PG's data already moved to the CRUSH
                        # layout; retire the old-layout instance
                        del self._pgs[key]
                        continue
                    # membership changed: data must MOVE. If I'm the
                    # serving primary, install pg_temp (keep serving
                    # from the old layout) and backfill to the CRUSH
                    # target; otherwise drop — reads fail cleanly via
                    # the misplaced-shard guard until someone
                    # backfills. The pg_temp request commits a map
                    # change (recursive _on_map), so it runs after
                    # this lock is released.
                    primary = first_live(pg.acting)
                    if (
                        primary == self.osd_id
                        and (pool, pgid) not in osdmap.pg_temp
                    ):
                        maybe_backfill.append((pool, pgid, pg))
                        continue
                    if (pool, pgid) in osdmap.pg_temp:
                        continue  # serving via pg_temp; backfilling
                    del self._pgs[key]
                    continue
                new_acting = osdmap.pg_to_up_acting(pool, pgid)
                if new_acting == pg.acting:
                    continue
                # same members, liveness flipped: heal in place. A
                # member that RETURNED is behind — it joins in
                # ``recovering`` state (pushes route to it, but reads
                # and writes don't trust it) until the log replay
                # completes; only then does it become available.
                healed = [
                    i for i, osd in enumerate(new_acting)
                    if osd != SHARD_NONE and pg.acting[i] == SHARD_NONE
                ]
                downed = [
                    i for i, osd in enumerate(new_acting)
                    if osd == SHARD_NONE and pg.acting[i] != SHARD_NONE
                ]
                pg.acting[:] = new_acting
                pg.backend.acting[:] = new_acting
                pg.backend.recovering.update(healed)
                pg.backend.recovering.difference_update(downed)
                # interval change: whoever serves as primary now must
                # re-run the authoritative-log election before serving
                # this interval (and re-activate les). Non-primaries
                # open their gate — the primary's peering judges them.
                if first_live(new_acting) == self.osd_id:
                    self._kick_peering(pg)
                else:
                    pg.fsm.post_interval()  # -> replica, gate open
                if downed:
                    to_release.append((pg, downed))
                if healed:
                    to_recover.append((pg, healed))
        # drive recovery OUTSIDE the pg lock on worker threads: a
        # born-hole refresh is O(objects in PG) of network IO, and this
        # callback runs on the monitor's notify path
        # a member that died with sub-write acks outstanding must not
        # wedge in-flight ops behind the op timeout: release its acks
        # (extents stay dirty in the pg log). OUTSIDE _pg_lock — the
        # release may dispatch the next queued op, whose RMW backend
        # read blocks on the messenger.
        for pg, downed in to_release:
            for i in downed:
                pg.rmw.on_shard_down(i)
        for pg, healed in to_recover:
            if first_live(pg.acting) != self.osd_id:
                # only the SERVING PRIMARY drives catch-up (the
                # reference's recovery model). A demoted instance
                # replaying ITS pglog onto a member of a PG someone
                # else now leads raced the new primary's live writes
                # — rebuild-at-T, push-at-T+δ lost updates clobbered
                # freshly committed extents on one shard (the
                # torn-RMW leg of ROADMAP #1, found by the
                # primary-victim smoke). The new primary's election
                # judges every member by its gathered infos and
                # drains EVERY stale recovering mark itself (see
                # _peer_pass), so marks left here are not leaked.
                continue
            for shard in healed:
                if pg.acting[shard] == self.osd_id:
                    # my OWN position healed: the FSM's election pass
                    # (already kicked above) judges and repairs my
                    # store and re-admits the position at Active —
                    # a replica catch-up against oneself would be an
                    # RPC to nobody that fails and holes the primary
                    # position (THE round-8 peering flake / ROADMAP
                    # #1 ENOENT)
                    continue
                self._spawn_catch_up(pg, shard)
        for pool, pgid, pg in maybe_backfill:
            if self._request_pg_temp(pool, pgid, pg):
                self._start_backfill(pool, pgid, pg)
            else:
                with self._pg_lock:
                    self._pgs.pop((pool, pgid), None)
        self._maybe_gc_pools()
        # temp-head adoption: whoever serves as primary under a
        # pg_temp mapping drives its backfill (covers temps installed
        # by OTHER daemons and primaries without a PG instance)
        self._adopt_pg_temps()
        # eager interval peering for PGs with no live instance
        self._peer_new_intervals()

    def _maybe_gc_pools(self) -> None:
        if self._doomed_pool_ids and self._gc_clean_streak < 2:
            threading.Thread(target=self._gc_pools, daemon=True).start()

    def _gc_pools(self) -> None:
        """A deleted pool's shard data is garbage (its id is never
        reused): drop every key it owned (the reference's async pool
        deletion sweep). Re-runs on later map changes/ticks until TWO
        consecutive sweeps find nothing — stragglers from ops in
        flight at deletion time get caught by the second pass."""
        doomed = set(self._doomed_pool_ids)
        batch: list[str] = []
        removed = 0

        def flush() -> None:
            nonlocal removed
            if not batch:
                return
            self.admit("gc")
            txn = Transaction()
            for key in batch:
                txn.touch(key).remove(key)
            try:
                self.store.queue_transactions(txn)
                removed += len(batch)
            except Exception:
                pass  # retried by the next sweep
            batch.clear()

        for key in self.store.list_objects():
            if key.startswith("pgmeta\x02"):
                try:
                    meta_pool = int(key.split("\x02")[1])
                except (IndexError, ValueError):
                    continue
                if meta_pool in doomed:
                    batch.append(key)
                    if len(batch) >= 64:
                        flush()
                continue
            try:
                loc, _si = split_shard_key(key)
                pool_id, _oid = split_loc(loc)
            except ValueError:
                continue
            if pool_id in doomed:
                batch.append(key)
                if len(batch) >= 64:
                    flush()
        flush()
        self._gc_clean_streak = 0 if removed else (
            self._gc_clean_streak + 1
        )

    def _adopt_pg_temps(self) -> None:
        osdmap = self.osdmap
        for (pool, pgid) in list(osdmap.pg_temp):
            if pool not in osdmap.pools:
                continue
            acting = osdmap.pg_to_up_acting(pool, pgid)
            if first_live(acting) != self.osd_id:
                continue
            pg = self._get_pg(pool, pgid)
            self._start_backfill(pool, pgid, pg)

    def _spawn_catch_up(self, pg: _PG, shard: int) -> None:
        """Start a catch-up thread for one position, at most one in
        flight per (pg, shard) — every spawn site (map healed
        transition, tick re-heal, the FSM's behind-member and
        stale-recovering drains) routes through here."""
        with self._pg_lock:
            if shard in pg._catchup_inflight:
                return
            pg._catchup_inflight.add(shard)

        def run() -> None:
            try:
                self._catch_up_shard(pg, shard)
            finally:
                with self._pg_lock:
                    pg._catchup_inflight.discard(shard)

        threading.Thread(target=run, daemon=True).start()

    def _catch_up_shard(self, pg: _PG, shard: int) -> None:
        """Replay the op log onto a returned member until it is clean
        (writes racing the replay append new dirty entries — loop),
        then admit it to the acting set. A member whose absence
        PREDATES this PG instance gets a full-shard refresh first —
        the log holds no record of what it missed, so every object's
        shard is rebuilt from the survivors (the authoritative-log
        peering decision collapsed to 'refresh when the log cannot
        vouch'). On failure the position reverts to a hole; the next
        map change retries."""
        try:
            # the interval election first: catch-up judges the
            # returning member against authoritative state, which is
            # only established once the primary has peered
            if not pg.peered.wait(timeout=60):
                raise RuntimeError("peering never completed")
            if pg.acting[shard] == self.osd_id:
                # my own position is the election's to admit, never a
                # peer transfer (see _admit_self_positions); a stray
                # spawn must not RPC to itself and hole the position
                pg.fsm.post("retry")
                return
            crash_points.fire(
                "catchup.pre_listing", daemon=self, pg=pg, shard=shard
            )
            # every rebuild-and-push below holds _op_lock,
            # serializing with the live write path — a push computed
            # from survivors read at T must not land at T+δ over an
            # extent a client write committed in between (the
            # lost-update shard tear the primary-victim soak caught)
            push_lock = self._op_lock_for(pg.pool, pg.pgid)
            # Pristine member stamps, captured before any replay or
            # refresh can overwrite them (see _member_listing).
            member_listing = self._member_listing(pg, shard)
            refreshed: set[str] = set()
            if shard in pg.born_holes:
                spec = self.osdmap.pools[pg.pool]
                target_osd = pg.acting[shard]
                # the returning member's own (stale) reports must not
                # vouch for objects: only OTHER survivors count
                hints = self._backfill_scan(
                    pg.pool, pg.pgid, spec, pg, exclude=target_osd
                )
                for loc in sorted(hints):
                    # byte-proportional: a 4 MB refresh consumes ~65x
                    # the recovery budget of a 4 KB one
                    self.admit(
                        "recovery", cost=_qos.op_cost(max(hints[loc], 0))
                    )
                    size = self._object_size(pg, loc)
                    known = bool(size) or self._have_object(pg, loc)
                    size_hint = None
                    if not known and hints[loc] > 0:
                        # a PEER holds it even though my store doesn't
                        # (my own copy is incomplete): recover, never
                        # delete a surviving good shard. The hint goes
                        # to recovery directly — priming the live
                        # pipeline with it could resurrect a size for
                        # an object a racing remove just dropped.
                        size_hint = hints[loc]
                        known = True
                    if not known:
                        # gone while the member was away: propagate
                        # the delete (its stale copy fed the scan)
                        with push_lock:
                            self._push_delete(target_osd, loc, shard)
                        continue
                    with push_lock:
                        pg.recovery.recover_object(
                            loc, {shard}, size=size_hint
                        )
                    refreshed.add(loc)
                pg.born_holes.discard(shard)
            def _dirty() -> bool:
                return bool(
                    pg.pglog.dirty_extents(shard)
                    or pg.pglog.dirty_deletes(shard)
                    or pg.pglog.dirty_xattrs(shard)
                )

            for _ in range(8):
                self.admit("recovery")
                with push_lock:
                    replayed = pg.recovery.recover_from_log(
                        pg.pglog, shard
                    )
                if replayed:
                    self.rmw_crash_pc.inc(
                        "rollforwards", len(replayed)
                    )
                if not _dirty():
                    break
            # Eversion divergence pass: log replay brings the member
            # up to the authoritative history it MISSED; this catches
            # what it should never have had — writes it applied that
            # the cluster did not commit (divergent ex-primary). Any
            # object whose stored stamp disagrees with authoritative
            # history is rebuilt from survivors; objects unknown to
            # authoritative state are removed.
            target_osd = pg.acting[shard]
            rollback, divergent_deletes = self._divergent_objects(
                pg, shard, member_listing
            )
            # the born-hole refresh already rebuilt these (their
            # pre-refresh stamps are stale by construction)
            rollback -= refreshed
            for loc in sorted(rollback):
                self.admit(
                    "recovery",
                    cost=_qos.op_cost(self._object_size(pg, loc)),
                )
                self.log.info(
                    "pg", f"{pg.pool}/{pg.pgid}:", "divergent object",
                    loc, "on shard", shard, "- rolling back"
                )
                with push_lock:
                    pg.recovery.recover_object(loc, {shard})
                self.rmw_crash_pc.inc("rollbacks")
            for loc in sorted(divergent_deletes):
                self.log.info(
                    "pg", f"{pg.pool}/{pg.pgid}:", "divergent create",
                    loc, "on shard", shard, "- removing"
                )
                with push_lock:
                    self._push_delete(target_osd, loc, shard)
                self.rmw_crash_pc.inc("divergent_removes")
            # Admission is an EVENT on the PG's peering queue — it
            # cannot interleave an election, so a mid-judgment member
            # can never vote. The final clean check runs under the op
            # lock on the drainer: client writes (which also take
            # _op_lock) cannot append dirty entries between the check
            # and the admit, so a still-behind shard can never enter
            # the read set and serve stale bytes into EC decode.
            crash_points.fire(
                "catchup.pre_admit", daemon=self, pg=pg, shard=shard
            )
            if not pg.fsm.admit_caught_up(shard):
                raise RuntimeError(
                    f"shard {shard} admission rejected "
                    "(interval moved or still dirty)"
                )
            self.log.info(
                "pg", f"{pg.pool}/{pg.pgid}:", "shard", shard,
                "caught up, admitted"
            )
        except Exception as e:
            self.log.error(
                "pg", f"{pg.pool}/{pg.pgid}:", "shard", shard,
                "catch-up failed", f"({type(e).__name__}: {e});",
                "reverting to hole"
            )
            with self._pg_lock:
                pg.acting[shard] = SHARD_NONE
                pg.backend.acting[shard] = SHARD_NONE
                pg.backend.recovering.discard(shard)

    def _get_pg(self, pool: str, pgid: int) -> _PG:
        with self._pg_lock:
            pg = self._pgs.get((pool, pgid))
            if pg is None:
                raw = self.osdmap.pg_to_raw(pool, pgid)
                acting = self.osdmap.pg_to_up_acting(pool, pgid)
                pg = _PG(self, pool, pgid, raw, acting)
                self._pgs[(pool, pgid)] = pg
                if not pg.peered.is_set():
                    # fresh instance with me as serving primary: the
                    # interval must be peered before ops are served —
                    # a restarted ex-primary's own store is not
                    # authority (PeeringState.cc:1565 find_best_info)
                    self._kick_peering(pg)
            return pg

    # -- object-info recovery (new-primary takeover) --------------------
    def _scan_pg_keys(
        self, pool_id: int, pg_num: int, pgid: int
    ) -> list[tuple[str, int]]:
        """Own-store scan: (loc, shard_index) pairs of this PG's keys
        (shared by the PGList service, backfill scan, and GC)."""
        from ceph_tpu.placement import stable_hash

        out = []
        for key in self.store.list_objects():
            try:
                loc, si = split_shard_key(key)
                pool_id2, oid = split_loc(loc)
            except ValueError:
                continue
            if (
                pool_id2 == pool_id
                and stable_hash(str(pool_id), head_of_loc(oid))
                % pg_num == pgid
            ):
                # clones hash by their HEAD name: they live (and
                # backfill, recover, scrub) in the head's PG
                out.append((loc, si))
        return out

    def _sub_write_interval_ok(self, msg, loc: str) -> bool:
        """Replica-side interval fence for sub-writes: once a NEWER
        interval's election has queried (or activated) this member for
        the object's PG, sub-writes stamped with an older map epoch
        are rejected — they come from a superseded primary whose
        commit would be invisible to the authority the election chose
        (same_interval_since discard; OSD::require_same_or_newer_map).
        Unfenced messages (standalone pipeline tiers) pass."""
        if msg.from_osd < 0 or not msg.epoch:
            return True
        try:
            from ceph_tpu.placement import stable_hash

            pool_id, oid = split_loc(loc)
            for spec in self.osdmap.pools.values():
                if spec.pool_id == pool_id:
                    pgid = stable_hash(
                        str(pool_id), head_of_loc(oid)
                    ) % spec.pg_num
                    fence = self._fence_epochs.get((pool_id, pgid), 0)
                    if msg.epoch < fence:
                        self.peering_pc.inc("interval_fences_rejected")
                        self.log.info(
                            "fence: sub-write from osd.", msg.from_osd,
                            f"e{msg.epoch} rejected:", loc,
                            f"interval e{fence} already peered here",
                        )
                        return False
                    return True
        except Exception:
            pass  # unparseable loc etc.: do not wedge the data path
        return True

    def _my_key(self, pg: _PG, oid: str) -> str | None:
        """My shard key for this object, from my acting position."""
        try:
            pos = pg.acting.index(self.osd_id)
        except ValueError:
            return None
        return shard_key(oid, pos)

    def _have_object(self, pg: _PG, oid: str) -> bool:
        key = self._my_key(pg, oid)
        return key is not None and self.store.exists(key)

    def _replicated_attrs(
        self, pg: _PG, oid: str, prefixes: tuple = ("u:", "m:")
    ) -> dict[str, bytes]:
        """The primary's replicated-attr map for an object (user
        xattrs ``u:``, omap entries ``m:``), restored onto recovered
        shards alongside the identity attrs."""
        key = self._my_key(pg, oid)
        if key is None:
            return {}
        try:
            return {
                k: v for k, v in self.store.getattrs(key).items()
                if k.startswith(prefixes)
            }
        except FileNotFoundError:
            return {}

    def _user_attrs(self, pg: _PG, oid: str) -> dict[str, bytes]:
        return self._replicated_attrs(pg, oid, ("u:",))

    def _recovery_attrs(self, pg: _PG, oid: str) -> dict[str, bytes]:
        """Attrs restored onto recovered shards: the replicated user/
        omap attrs PLUS the reqid-dedup window. Without the window, a
        member rebuilt after an absence keeps its ANCIENT ``rq`` attr
        — and when it later becomes the primary it seeds suspect
        reqids so old they have left every other member's window,
        which classify ambiguous forever and wedge the object in
        eagain (chaos-tier find; the legacy self-catch-up bug masked
        this by accidentally seeding an empty window)."""
        attrs = self._replicated_attrs(pg, oid)
        key = self._my_key(pg, oid)
        if key is not None:
            try:
                attrs[REQ_KEY] = self.store.getattr(key, REQ_KEY)
            except (FileNotFoundError, KeyError):
                pass
        return attrs

    def _object_exists(self, pg: _PG, oid: str) -> bool:
        """The client-visible existence test the op handlers share."""
        return bool(self._object_size(pg, oid)) or self._have_object(
            pg, oid
        )

    def _authoritative_record(
        self, pg: _PG, oid: str
    ) -> "tuple[str, tuple[int, int] | None]":
        """Three-way authority lookup: ``("ev", (epoch, tid))`` when
        the latest committed write's stamp is known, ``("absent",
        None)`` when the primary AFFIRMATIVELY has no record of the
        object (its shard store is readable and the object is not
        there), ``("unknown", None)`` when the authority could not be
        judged — primary holds no shard of the object, the OI attr is
        missing/corrupt, or only a pre-eversion stamp exists.  The
        distinction matters for divergence handling: "absent" licenses
        deleting a returning member's copy; "unknown" must not (the
        primary's own incomplete local state would otherwise destroy a
        committed shard)."""
        ev = pg.rmw.object_eversion(oid)
        if ev is not None:
            return ("ev", ev)
        ev = pg.pglog.last_eversion(oid)
        if ev is not None and ev != (0, 0):
            return ("ev", ev)
        key = self._my_key(pg, oid)
        if key is None:
            return ("unknown", None)
        try:
            _size, ev = parse_oi(self.store.getattr(key, OI_KEY))
        except FileNotFoundError:
            return ("absent", None)
        except (KeyError, ValueError):
            return ("unknown", None)
        return ("unknown", None) if ev == (0, 0) else ("ev", ev)

    def _authoritative_eversion(
        self, pg: _PG, oid: str
    ) -> "tuple[int, int] | None":
        """The (epoch, tid) the object's latest committed write
        stamped, from the live pipeline or my own shard's OI attr —
        the eversion_t comparison source (osd_types.h)."""
        return self._authoritative_record(pg, oid)[1]

    def _member_listing(self, pg: _PG, shard: int) -> list:
        """The returning member's PG listing WITH its pristine
        eversion stamps. Must be fetched BEFORE any log replay:
        recovery pushes overwrite the member's OI stamps with the
        authoritative eversion, which would mask divergence on any
        object also written during the absence. Failures propagate —
        the catch-up's except path reverts the position to a hole
        rather than admitting an unjudged shard."""
        target_osd = pg.acting[shard]
        spec = self.osdmap.pools[pg.pool]
        return self.peers.list_pg(
            target_osd, spec.pool_id, spec.pg_num, pg.pgid
        )

    def _divergent_objects(
        self, pg: _PG, shard: int, listing: list
    ) -> tuple[set[str], set[str]]:
        """(rollback, delete) for a returning member's shard: objects
        whose stored (pre-replay) eversion does not match
        authoritative history.

        The PGLog::rewind_divergent_log role: a partitioned ex-primary
        may hold locally-applied writes the cluster never committed —
        its stamp differs from the authoritative one, so the shard's
        bytes must be rebuilt from survivors (rollback), and objects
        the authoritative state never heard of must be removed, or EC
        decode would mix divergent bytes into every read."""
        rollback: set[str] = set()
        delete: set[str] = set()
        for loc, si, _size, *ev in listing:
            if si != shard:
                continue  # old-layout leftovers: backfill/GC territory
            member_ev = tuple(ev) if len(ev) == 2 else (0, 0)
            if member_ev == (0, 0):
                continue  # pre-eversion stamp: nothing to judge
            kind, auth = self._authoritative_record(pg, loc)
            if kind == "absent":
                # Primary affirmatively never heard of it: a divergent
                # create — remove before it can pollute EC decodes.
                delete.add(loc)
            elif kind == "unknown" or member_ev != auth:
                # Unjudgeable authority (primary's own attr unreadable
                # or pre-eversion) degrades to rollback — rebuilding
                # from survivors is safe either way; deletion is not.
                rollback.add(loc)
        return rollback, delete

    # -- peering: authoritative-log election ---------------------------
    # The find_best_info / choose_acting analog
    # (osd/PeeringState.cc:1565, :2413): on taking the primary role
    # for a changed interval, gather (last_epoch_started, last_update)
    # from every up member, elect the authoritative log, rewind SELF
    # against the winner when self is not it, and only then activate
    # the interval (les := epoch, pushed durably to members). A
    # returning ex-primary is thereby corrected at ADMISSION time —
    # its divergent writes carry the old interval's les/epoch, so it
    # loses the election to any member that served the newer interval.

    def _pgmeta_key(self, pool_id: int, pgid: int) -> str:
        # deliberately not shard_key-parseable: object scans skip it
        return f"pgmeta\x02{pool_id}\x02{pgid}"

    def _pgmeta_read(self, pool_id: int, pgid: int) -> int:
        """Stored last_epoch_started, 0 when never activated."""
        try:
            return int(
                self.store.getattr(self._pgmeta_key(pool_id, pgid), "les")
            )
        except (FileNotFoundError, KeyError, ValueError):
            return 0

    def _pgmeta_acting(self, pool_id: int, pgid: int) -> "list | None":
        """The acting set I last activated this PG with (primaries
        only), or None — the interval-change detector for PGs with no
        live instance."""
        try:
            raw = self.store.getattr(
                self._pgmeta_key(pool_id, pgid), "acting"
            )
            return [int(x) for x in raw.decode().split(",") if x != ""]
        except (FileNotFoundError, KeyError, ValueError):
            return None

    def _pgmeta_write_les(
        self, pool_id: int, pgid: int, epoch: int,
        acting: "list | None" = None,
    ) -> None:
        # one lock for the read-check-write: a local activation
        # (peering thread) and a remote PGActivate (messenger thread)
        # interleaving here could write epochs out of order and
        # REGRESS the ledger — which a later election would read as a
        # stale interval and rank the member down
        with self._pgmeta_lock:
            key = self._pgmeta_key(pool_id, pgid)
            les = self._pgmeta_read(pool_id, pgid)
            if epoch <= les:
                return  # activation epochs are monotone
            txn = Transaction().touch(key).setattr(
                key, "les", str(epoch).encode()
            )
            if acting is not None:
                txn.setattr(
                    key, "acting",
                    ",".join(str(o) for o in acting).encode(),
                )
            self.store.queue_transactions(txn)

    def _peer_new_intervals(self) -> None:
        """Eager interval peering (the reference instantiates PGs on
        every member and peers each interval change; PGs here are
        otherwise lazy): after a map change, every PG I now serve as
        primary whose acting set differs from the one I last
        ACTIVATED gets instantiated and peered. Without this, an
        interval with no client IO would leave no durable les trace —
        and a returning ex-primary could then win the election with
        its divergent (higher-tid) stamps."""
        osdmap = self.osdmap
        for pool, spec in osdmap.pools.items():
            for pgid in range(spec.pg_num):
                if (pool, pgid) in osdmap.pg_temp:
                    continue  # backfill owns pg_temp intervals
                acting = osdmap.pg_to_up_acting(pool, pgid)
                if first_live(acting) != self.osd_id:
                    continue
                if self._pgmeta_acting(spec.pool_id, pgid) == acting:
                    continue  # interval unchanged since my activation
                existed = (pool, pgid) in self._pgs
                pg = self._get_pg(pool, pgid)
                if existed:
                    # a freshly instantiated PG was already kicked by
                    # _get_pg — kicking again would run the whole
                    # PGInfo/activation round twice
                    self._kick_peering(pg)

    def _own_pg_info(
        self, pool_id: int, pg_num: int, pgid: int
    ) -> tuple[int, tuple[int, int]]:
        """My pg_info_t analog, from durable state only: les from the
        pgmeta ledger, last_update = max committed OI eversion over
        the shard copies AT MY CURRENT ACTING POSITION (divergent
        local applies can inflate the tid but never the les — only
        post-peering activation writes that).

        The si scoping matters (round-5 chaos seed 7702): stale keys
        at OTHER positions — old-layout leftovers the divergence scan
        deliberately leaves to backfill/GC — must not inflate the
        vote, or a rewound member's lingering tampered leftovers
        out-rank clean logs at les ties."""
        my_pos = None
        for pool, spec in self.osdmap.pools.items():
            if spec.pool_id == pool_id:
                acting = self.osdmap.pg_to_up_acting(pool, pgid)
                if self.osd_id in acting:
                    my_pos = acting.index(self.osd_id)
                break
        lu = (0, 0)
        for loc, si in self._scan_pg_keys(pool_id, pg_num, pgid):
            if my_pos is not None and si != my_pos:
                continue
            try:
                _size, ev = parse_oi(
                    self.store.getattr(shard_key(loc, si), OI_KEY)
                )
            except (FileNotFoundError, KeyError, ValueError):
                continue
            if tuple(ev) > lu:
                lu = tuple(ev)
        return self._pgmeta_read(pool_id, pgid), lu

    def _bump_fence(self, pool_id: int, pgid: int, epoch: int) -> None:
        key = (pool_id, pgid)
        if epoch > self._fence_epochs.get(key, 0):
            self._fence_epochs[key] = epoch

    def _handle_pg_info(self, conn: Connection, msg: PGInfo) -> None:
        # FENCE FIRST: once this member answers an interval-E
        # election, a superseded primary's older-interval sub-writes
        # must not commit through it — otherwise a write can land
        # AFTER the election read this member's log and be invisible
        # to the new authority (the round-5 kill/revive thrash lost a
        # committed append to exactly that interleaving).
        if msg.epoch:
            self._bump_fence(msg.pool_id, msg.pgid, msg.epoch)
        les, lu = self._own_pg_info(msg.pool_id, msg.pg_num, msg.pgid)
        conn.send(PGInfoReply(msg.tid, msg.shard, les, lu[0], lu[1]))

    def _handle_pg_activate(self, conn: Connection, msg: PGActivate) -> None:
        self._bump_fence(msg.pool_id, msg.pgid, msg.epoch)
        self._pgmeta_write_les(msg.pool_id, msg.pgid, msg.epoch)
        conn.send(PGActivateAck(msg.tid, msg.shard))

    def _kick_peering(self, pg: _PG) -> None:
        """Clear the peered gate and run the election on its own
        thread (peering does network RPC + possibly O(PG) recovery;
        callers hold locks). A kick landing while a run is already in
        flight closes the gate and flags a RE-RUN: the in-flight
        election saw the OLD interval, and letting it open the gate
        for the new one would serve exactly the unpeered window this
        machinery exists to prevent (round-5 review finding)."""
        # The election may rewind/recover objects underneath the
        # in-memory reqid-window cache: a revived ex-primary that
        # seeded windows from its STALE store before losing the
        # election kept judging (and replaying!) from them after
        # recovery rewrote the attrs — the round-5 kill/revive thrash
        # lost a committed append to exactly that. Ops are gated until
        # peering completes, so invalidating here makes the first
        # post-peering op re-seed from the post-rewind store. The
        # invalidation is QUEUED (drained under _op_lock — see
        # _req_flush) and scoped to THIS PG: re-peering one PG must
        # not make every object in every pool re-pay the quorum
        # durability poll, and _req_poll_at goes with the windows so
        # a re-seeded object never eats a stale-cooldown eagain.
        spec = self.osdmap.pools.get(pg.pool)
        with self._req_flush_lock:
            if spec is None:
                # pool spec gone mid-kick: can't map locs to this PG
                # any more — flush everything rather than leak stale
                # windows past the rewind
                self._req_flush.add(None)
            else:
                self._req_flush.add(
                    ("pg", spec.pool_id, spec.pg_num, pg.pgid)
                )
        # the interval event serializes with every other peering
        # event of this PG; the gate flips synchronously inside
        # post_interval (ops eagain the moment the interval moves)
        pg.fsm.post_interval()

    def _object_size(self, pg: _PG, oid: str) -> int:
        size = pg.rmw.object_size(oid)
        if size:
            return size
        key = self._my_key(pg, oid)
        if key is None:
            return 0
        try:
            size, ev = parse_oi(self.store.getattr(key, OI_KEY))
        except (FileNotFoundError, KeyError, ValueError):
            return 0
        hinfo = None
        try:
            hinfo = HashInfo.from_bytes(self.store.getattr(key, HINFO_KEY))
        except (FileNotFoundError, KeyError, ValueError):
            pass
        pg.rmw.prime_object(oid, size, hinfo, eversion=ev)
        return size

    # -- dispatch -------------------------------------------------------
    def _dispatch(self, conn: Connection, msg) -> None:
        if isinstance(msg, Ping):
            conn.send(Pong(msg.tid, self.osd_id))
        elif isinstance(msg, ECSubWrite):
            oids = msg.txn.oids()
            # Fence EVERY distinct object in the transaction, not just
            # oids[0]: a txn touching objects in more than one PG must
            # clear every PG's fence epoch, or a superseded primary
            # could slip a stale sub-write past the fence through a
            # multi-object batch (ADVICE round-5 item).
            locs = list(dict.fromkeys(
                split_shard_key(o)[0] for o in oids
            )) or [""]
            loc = locs[0]
            if not all(
                self._sub_write_interval_ok(msg, l) for l in locs
            ):
                # interval fence (OSD::require_same_or_newer_map /
                # the MOSDECSubOpWrite map_epoch check): a superseded
                # primary whose map lags behind mine must not commit
                # through me — without this, a revived ex-primary
                # served an append from its stale state and tore the
                # log the REAL primary was appending to (round-5
                # kill/revive thrash find). Rejected: the stale op
                # never acks, its client resends against a fresh map.
                conn.send(
                    ECSubWriteReply(msg.tid, msg.shard, committed=False)
                )
                return
            from ceph_tpu.pipeline.inject import ec_inject

            if ec_inject.test_write_error3(loc):
                # ECInject write type 3: handle_sub_write aborts the
                # OSD (ceph_abort, ECBackend.cc:922-926). The write is
                # never applied, the ack never sent; heartbeats and the
                # mon take it from here. Stop on a side thread — stop()
                # joins the worker/messenger threads this may run on.
                threading.Thread(target=self.stop, daemon=True).start()
                return
            def _applied_ack() -> None:
                # crash point: the txn is durable in this member's
                # store, the ack not yet on the wire — a kill here is
                # the half-committed sub-write (the sender parks; on
                # restart the pg log rolls this member forward or the
                # election rolls its divergence back)
                crash_points.fire(
                    "rmw.subwrite_applied_before_ack", daemon=self,
                    tid=msg.tid, shard=msg.shard,
                )
                conn.send(ECSubWriteReply(msg.tid, msg.shard))

            with tracer.continue_trace(msg.trace_id, msg.parent_span):
                with tracer.span(
                    "sub_write", osd=self.osd_id, shard=msg.shard,
                    tid=msg.tid,
                ):
                    self.local.submit_shard_txn(
                        self.osd_id, msg.txn, _applied_ack
                    )
        elif isinstance(msg, ECSubWriteBatch):
            self._handle_sub_write_batch(conn, msg)
        elif isinstance(msg, ECSubRead):
            with tracer.continue_trace(msg.trace_id, msg.parent_span):
                with tracer.span(
                    "sub_read", osd=self.osd_id, shard=msg.shard,
                    tid=msg.tid,
                ):
                    self._handle_sub_read(conn, msg)
        elif isinstance(msg, GetAttrs):
            serve_get_attrs(self.store, self.osd_id, conn, msg)
        elif isinstance(msg, PGList):
            self._handle_pg_list(conn, msg)
        elif isinstance(msg, PGInfo):
            self._handle_pg_info(conn, msg)
        elif isinstance(msg, PGActivate):
            self._handle_pg_activate(conn, msg)
        elif isinstance(msg, BackfillReserve):
            self._handle_backfill_reserve(conn, msg)
        elif isinstance(msg, OSDOp):
            self._handle_client_op(conn, msg)
        elif isinstance(msg, NotifyAck):
            self._handle_notify_ack(msg)

    def _handle_sub_write_batch(
        self, conn: Connection, msg: ECSubWriteBatch
    ) -> None:
        """One frame, many sub-writes (the round-10 fan-out batching).
        Every item passes the SAME gates the solo ECSubWrite path
        runs — per-loc interval fence, ECInject consultation — and
        applies independently: a fenced/stale item answers
        committed=False in the batch reply without poisoning its
        batch-mates; an injected drop simply stays un-acked (parked
        at the sender, like a lost solo ack)."""
        import types

        from ceph_tpu.pipeline.inject import ec_inject

        results: list[tuple[int, bool]] = []
        for tid, shard, epoch, from_osd, txn in msg.items:
            oids = txn.oids()
            locs = list(dict.fromkeys(
                split_shard_key(o)[0] for o in oids
            )) or [""]
            stamp = types.SimpleNamespace(epoch=epoch, from_osd=from_osd)
            if not all(
                self._sub_write_interval_ok(stamp, l) for l in locs
            ):
                results.append((tid, False))
                continue
            if ec_inject.test_write_error3(locs[0]):
                # abort the daemon mid-batch (ECBackend.cc:922-926):
                # nothing later applies, no reply — every un-acked
                # item parks at the sender
                threading.Thread(target=self.stop, daemon=True).start()
                return
            acked: list[bool] = []
            with tracer.span(
                "sub_write", osd=self.osd_id, shard=shard, tid=tid,
            ):
                self.local.submit_shard_txn(
                    self.osd_id, txn, lambda a=acked: a.append(True)
                )
            if acked:
                # same applied-but-unacked crash class as the solo
                # path: everything up to here is durable, this item's
                # ack (and its batch-mates') may never leave
                crash_points.fire(
                    "rmw.subwrite_applied_before_ack", daemon=self,
                    tid=tid, shard=shard,
                )
                results.append((tid, True))
        conn.send(ECSubWriteBatchReply(msg.tid, self.osd_id, results))

    def _handle_sub_read(self, conn: Connection, msg: ECSubRead) -> None:
        def reply(_shard, result) -> None:
            if isinstance(result, Exception):
                kind = getattr(result, "kind", "eio")
                conn.send(ECSubReadReply(msg.tid, msg.shard, error=kind))
            else:
                offsets = sorted(result)
                conn.send(
                    ECSubReadReply(
                        msg.tid, msg.shard, offsets,
                        [bytes(result[o]) for o in offsets],
                    )
                )

        if msg.logical is not None and not self.store.exists(msg.oid):
            conn.send(ECSubReadReply(msg.tid, msg.shard, error="missing"))
            return
        self.local.read_shard_async(
            self.osd_id, msg.oid,
            ExtentSet((s, e) for s, e in msg.extents), reply,
        )

    def _handle_pg_list(self, conn: Connection, msg: PGList) -> None:
        """Backfill scan service: which of this PG's objects do I
        hold, which logical shard are they, how big is the object.
        Placement math from the message, not my (possibly old) map."""
        from ceph_tpu.placement import stable_hash

        oids = []
        for loc, si in self._scan_pg_keys(msg.pool_id, msg.pg_num, msg.pgid):
            size, ev = -1, (0, 0)
            try:
                size, ev = parse_oi(
                    self.store.getattr(shard_key(loc, si), OI_KEY)
                )
            except (FileNotFoundError, KeyError, ValueError):
                pass
            oids.append((loc, si, size, ev[0], ev[1]))
        conn.send(PGListReply(msg.tid, msg.shard, oids))

    # -- client ops (the PrimaryLogPG::do_op role) ----------------------
    def _handle_client_op(self, conn: Connection, msg: OSDOp) -> None:
        """Reader thread: enqueue in mClock order; the worker runs it
        (OSD::enqueue_op -> mClock queue -> dequeue_op, osd/OSD.cc:
        9874,9933). Cost scales with payload so a large write consumes
        proportionally more of the class's rate."""
        if msg.op in ("watch", "unwatch"):
            # quick registry flips: reader thread, no queueing
            self._run_client_op(conn, msg)
            return
        if msg.op == "notify":
            # A notify WAITS for acks. Not on the worker (it would
            # freeze all queued IO) and not on this reader either —
            # when the notifier also watches the object over this
            # same connection, its own ack arrives HERE and a parked
            # reader would deadlock against itself. Own short-lived
            # thread.
            threading.Thread(
                target=self._run_client_op, args=(conn, msg),
                name="notify", daemon=True,
            ).start()
            return
        from ceph_tpu.utils import config as _cfg

        cost = _qos.op_cost(max(len(msg.data), msg.length))
        # multi-tenant classing: a tagged op queues under its tenant's
        # own mClock clocks (client.<tenant>), an untagged one under
        # its pool's (client.<pool>) — the flooding neighbor throttles
        # against its own tags. osd_op_qos=false is the escape hatch:
        # everything shares the flat "client" class again.
        cls = (
            _qos.client_class(msg.tenant, msg.pool)
            if _cfg.get("osd_op_qos") else "client"
        )
        self._schedule(cls, _ClientOpItem(self, conn, msg), cost)

    def _run_client_op(
        self, conn: Connection, msg: OSDOp, shard: int = 0
    ) -> None:
        try:
            # adopt the client's trace context (the wire hop of the
            # ZTracer-through-the-pipeline pattern): this daemon's
            # spans — and the sub-op spans it fans out — share the
            # client op's trace id
            with tracer.continue_trace(msg.trace_id, msg.parent_span):
                with tracer.span(
                    "osd_op", op=msg.op, oid=msg.oid,
                    osd=self.osd_id, tid=msg.tid,
                ):
                    reply = self._execute_client_op(msg, conn, shard)
        except Exception as e:  # never kill the worker
            self.log.error(
                "client op", msg.op, f"{msg.pool}/{msg.oid}",
                "tid", msg.tid, "failed:", type(e).__name__, e
            )
            reply = OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio", data=str(e).encode()
            )
        if msg.op in _MUTATING_OPS and not reply.error:
            # crash point: the mutation is committed cluster-wide, the
            # client reply not yet sent — a kill here forces the
            # client's ambiguous resend, which MUST dedup through the
            # replicated reqid window on the takeover primary (outside
            # the try above: an armed abort must lose the reply like
            # the crash it models, never morph into an eio answer)
            crash_points.fire(
                "rmw.primary_committed_before_reply", daemon=self,
                tid=msg.tid, op=msg.op,
            )
        conn.send(reply)

    def _execute_client_op(
        self, msg: OSDOp, conn: "Connection | None" = None,
        shard: int = 0,
    ) -> OSDOpReply:
        epoch = self.osdmap.epoch
        spec = self.osdmap.pools.get(msg.pool)
        if spec is None:
            return OSDOpReply(msg.tid, epoch, error="enoent")
        if msg.op == "pgls":
            # PG-addressed, not object-addressed: offset carries pgid
            pgid = msg.offset
            if self.osdmap.pg_primary(msg.pool, pgid) != self.osd_id:
                return OSDOpReply(msg.tid, epoch, error="eagain")
            return self._op_pgls(msg, spec, pgid)
        if self.osdmap.primary(msg.pool, msg.oid) != self.osd_id:
            return OSDOpReply(msg.tid, epoch, error="eagain")
        pgid = self.osdmap.object_to_pg(msg.pool, msg.oid)
        # peering gate: a primary that has not finished this
        # interval's authoritative-log election must not serve — its
        # own store may hold divergent state (the returning
        # ex-primary). Ops WAIT briefly (the reference queues ops on
        # a peering PG until it activates, waiting_for_peered), then
        # eagain for the client's resend backoff. Peering never
        # depends on this worker thread (no QoS admission on the
        # rewind path), so the wait cannot deadlock.
        if not self._get_pg(msg.pool, pgid).peered.wait(timeout=5.0):
            return OSDOpReply(msg.tid, epoch, error="eagain")
        client_oid = msg.oid
        msg.oid = make_loc(spec.pool_id, msg.oid)  # pool-scoped store key
        # watch/notify live OUTSIDE the op lock: a notify waits for
        # acks (reader threads deliver them) and must not starve IO
        if msg.op == "watch":
            return self._op_watch(msg, conn)
        if msg.op == "unwatch":
            return self._op_unwatch(msg)
        if msg.op == "notify":
            return self._op_notify(msg, client_oid)
        with self._op_shards[shard]:
            self._drain_req_flushes()
            reply, pg = self._mutating_gate(msg, spec, pgid, epoch)
            if reply is not None:
                return reply
            if msg.op == "write":
                return self._record_completed(msg, self._op_write(pg, msg))
            if msg.op == "append":
                # atomic under _op_lock: offset resolves to the
                # CURRENT size, so concurrent appends serialize
                # without overlap (rados_append)
                msg.offset = self._object_size(pg, msg.oid)
                return self._record_completed(msg, self._op_write(pg, msg))
            if msg.op == "truncate":
                return self._record_completed(
                    msg, self._op_truncate(pg, msg)
                )
            if msg.op == "writefull":
                # write-then-shrink under one lock scope: the object
                # is exactly the payload afterwards (rados_write_full).
                # The reqid window stamps ONLY the final sub-op: a
                # crash between the two would otherwise make every
                # resend replay the half-applied state (stale tail
                # never cut); with the write unstamped, the resend
                # re-runs both halves — idempotent.
                saved_reqid = msg.reqid
                msg.reqid = ""
                try:
                    reply = self._op_write(pg, msg)
                finally:
                    msg.reqid = saved_reqid
                if reply.error:
                    return self._record_completed(msg, reply)
                msg.offset = len(msg.data)
                return self._record_completed(
                    msg, self._op_truncate(pg, msg)
                )
            if msg.op == "rollback":
                return self._record_completed(
                    msg, self._op_rollback(pg, spec, msg)
                )
            if msg.op == "read":
                if msg.snap:
                    return self._op_snap_read(pg, spec, msg)
                return self._op_read(pg, msg)
            if msg.op == "stat":
                if not self._object_exists(pg, msg.oid):
                    return OSDOpReply(msg.tid, epoch, error="enoent")
                size = self._object_size(pg, msg.oid)
                return OSDOpReply(msg.tid, epoch, size=size)
            if msg.op == "remove":
                return self._record_completed(msg, self._op_remove(pg, msg))
            if msg.op in ("setxattr", "rmxattr"):
                return self._record_completed(msg, self._op_setxattr(pg, msg))
            if msg.op == "getxattr":
                return self._op_getxattr(pg, msg)
            if msg.op == "getxattrs":
                return self._op_getxattrs(pg, msg)
            if msg.op == "omapset":
                return self._record_completed(msg, self._op_omapset(pg, msg))
            if msg.op == "omapget":
                return self._op_omapget(pg, msg)
            if msg.op == "omaplist":
                return self._op_omaplist(pg, msg)
            return OSDOpReply(msg.tid, epoch, error="eio",
                              data=f"bad op {msg.op!r}".encode())

    # -- coalesced tick execution (the round-10 serving tier) ----------
    # Concurrent client EC writes queued at this daemon execute as ONE
    # tick batch: the bookkeeping prelude (dedup gate, durability
    # settlement, COW, reqid-window stamping) runs SERIALLY under
    # _op_lock exactly as the classic path would, then per-PG groups
    # execute concurrently — encodes from different PGs share batched
    # device dispatches through the streaming ring
    # (pipeline/dispatcher.py), and every group's sub-writes stage per
    # peer OSD and flush as one framed message (ECSubWriteBatch).
    # Per-op error isolation: one op's failure (inject, codec fault,
    # degraded read) replies eio for THAT op; batch-mates commit.

    def _run_coalesced_batch(
        self, items: "list[_ClientOpItem]", shard: int = 0
    ) -> None:
        to_send: list[tuple] = []
        pre: list[_CoalCtx] = []
        for it in items:
            msg = it.msg
            epoch = self.osdmap.epoch
            try:
                spec = self.osdmap.pools.get(msg.pool)
                if spec is None:
                    to_send.append((it.conn, OSDOpReply(
                        msg.tid, epoch, error="enoent")))
                    continue
                if self.osdmap.primary(msg.pool, msg.oid) != self.osd_id:
                    to_send.append((it.conn, OSDOpReply(
                        msg.tid, epoch, error="eagain")))
                    continue
                pgid = self.osdmap.object_to_pg(msg.pool, msg.oid)
                # peering gate BEFORE the lock (the serial path's
                # ordering): peering never needs the op worker
                if not self._get_pg(msg.pool, pgid).peered.wait(
                    timeout=5.0
                ):
                    to_send.append((it.conn, OSDOpReply(
                        msg.tid, epoch, error="eagain")))
                    continue
                msg.oid = make_loc(spec.pool_id, msg.oid)
                pre.append(_CoalCtx(it.conn, msg, spec, pgid, epoch))
            except Exception as e:
                to_send.append((it.conn, OSDOpReply(
                    msg.tid, epoch, error="eio",
                    data=str(e).encode())))
        executed = 0
        if pre:
            with self._op_shards[shard]:
                self._drain_req_flushes()
                pending = pre
                while pending:
                    # one WAVE per distinct object: a second op on the
                    # same object waits for its predecessor's commit
                    # AND reqid-window stamp (the serial path's
                    # ordering), so it defers to the next wave
                    wave: list[_CoalCtx] = []
                    deferred: list[_CoalCtx] = []
                    seen: set[str] = set()
                    for ctx in pending:
                        if ctx.msg.oid in seen:
                            deferred.append(ctx)
                            continue
                        seen.add(ctx.msg.oid)
                        if not self._coalesce_prelude(ctx, to_send):
                            continue
                        wave.append(ctx)
                    if wave:
                        self._coalesce_execute(wave)
                        for ctx in wave:
                            to_send.append(
                                (ctx.conn, self._coalesce_epilogue(ctx))
                            )
                        executed += len(wave)
                    pending = deferred
        if len(items) > 1:
            self.coalesce_pc.inc("op_coalesced", executed)
            self.coalesce_pc.hinc("batch_size", len(items))
        for conn, reply in to_send:
            try:
                conn.send(reply)
            except (ConnectionError, OSError):
                pass  # client gone; its resend finds the answer cached

    def _coalesce_prelude(
        self, ctx: _CoalCtx, to_send: list
    ) -> bool:
        """Serial per-op prelude under _op_lock: the shared mutating
        gate, then the write-shape bookkeeping the classic handlers
        do before dispatch. False = the op answered here (gate reply
        or prelude fault) and must not execute."""
        msg = ctx.msg
        try:
            reply, pg = self._mutating_gate(
                msg, ctx.spec, ctx.pgid, ctx.epoch
            )
        except Exception as e:
            to_send.append((ctx.conn, OSDOpReply(
                msg.tid, ctx.epoch, error="eio",
                data=str(e).encode())))
            return False
        if reply is not None:
            to_send.append((ctx.conn, reply))
            return False
        ctx.pg = pg
        try:
            cur = self._object_size(pg, msg.oid)  # prime on takeover
            if msg.op == "write":
                ctx.w_offset = msg.offset
                ctx.result_size = max(cur, msg.offset + len(msg.data))
                ctx.attrs = self._req_attr_for(
                    pg, msg.oid, msg.reqid, ctx.result_size
                )
            else:  # writefull: write half stays reqid-unstamped (a
                # crash between write and shrink must re-run both —
                # see the serial handler), the truncate half carries
                # the window. Window state is frozen for the whole
                # batch (_op_lock held; all window mutations are in
                # serial phases), so precomputing here is exact.
                ctx.w_offset = 0
                ctx.result_size = len(msg.data)
                ctx.attrs = None
                ctx.trunc_attrs = self._req_attr_for(
                    pg, msg.oid, msg.reqid, len(msg.data)
                )
        except Exception as e:
            to_send.append((ctx.conn, OSDOpReply(
                msg.tid, ctx.epoch, error="eio",
                data=str(e).encode())))
            return False
        return True

    def _coalesce_execute(self, wave: "list[_CoalCtx]") -> None:
        """Run one wave: per-PG groups execute concurrently, each
        group pipelining its ops through the PG's RMW machinery.
        Sub-writes stage per peer for the whole wave (one frame per
        peer), encodes ride the streaming ring across groups."""
        groups: dict[tuple, list[_CoalCtx]] = {}
        for ctx in wave:
            groups.setdefault(
                (ctx.msg.pool, ctx.pgid), []
            ).append(ctx)
        with self.peers.subwrite_batching():
            if len(groups) == 1:
                self._coalesce_run_group(next(iter(groups.values())))
            else:
                threads = [
                    threading.Thread(
                        target=self._coalesce_run_group, args=(ctxs,),
                        daemon=True,
                        name=f"osd.{self.osd_id}-coal",
                    )
                    for ctxs in groups.values()
                ]
                for t in threads:
                    t.start()
                # drains inside each group are op_timeout-bounded, so
                # the join only guards against a pathological stall
                cap = self.op_timeout * (2 * len(wave)) + 10.0
                for t in threads:
                    t.join(timeout=cap)
        for ctx in wave:
            if ctx.outcome is None:
                ctx.outcome = ("exc", "coalesced execution stalled")

    def _coalesce_run_group(self, ctxs: "list[_CoalCtx]") -> None:
        """One PG's slice of a wave, on its own thread. Writes
        PIPELINE: every op submits before the first drain (the RMW
        in-order commit machinery keeps tid order), so the group's
        sub-writes share per-peer frames and its encodes overlap
        other groups' in the ring."""
        from ceph_tpu.pipeline import dispatcher as _disp

        with _disp.coalescing_scope():
            live: list[_CoalCtx] = []
            for ctx in ctxs:
                try:
                    with tracer.continue_trace(
                        ctx.msg.trace_id, ctx.msg.parent_span
                    ), tracer.span(
                        "osd_op", op=ctx.msg.op, oid=ctx.msg.oid,
                        osd=self.osd_id, tid=ctx.msg.tid,
                    ):
                        ctx.trace_ctx = tracer.current()
                        ctx.pg.rmw.submit(
                            ctx.msg.oid, ctx.w_offset, ctx.msg.data,
                            on_commit=lambda op, c=ctx: c.done.append(op),
                            extra_attrs=ctx.attrs,
                        )
                    live.append(ctx)
                except Exception as e:
                    ctx.outcome = ("exc", f"{type(e).__name__}: {e}")
            self._coalesce_drain(live)
            for ctx in list(live):
                if ctx.done and ctx.done[0].error is not None:
                    ctx.outcome = ("eio", str(ctx.done[0].error))
                    live.remove(ctx)
                elif not ctx.done:
                    # drain timed out with the write still in flight:
                    # stalled (a truncate queued behind it would only
                    # deepen the wedge — the serial path raises here)
                    live.remove(ctx)
            # writefull second half: the shrink that makes the object
            # exactly the payload (pipelined + drained the same way)
            trunc = [c for c in live if c.msg.op == "writefull"]
            for ctx in trunc:
                ctx.done = []
                try:
                    # re-enter the op's own osd_op context: the shrink's
                    # sub-op spans must land under the SAME primary
                    # subtree the write half opened (the serial path
                    # runs both halves inside one osd_op span) — the
                    # coalesced-path trace gap of CAPABILITIES §4b
                    with tracer.continue_trace(*ctx.trace_ctx):
                        ctx.pg.rmw.submit_truncate(
                            ctx.msg.oid, len(ctx.msg.data),
                            on_commit=lambda op, c=ctx: c.done.append(op),
                            extra_attrs=ctx.trunc_attrs,
                        )
                except Exception as e:
                    ctx.outcome = ("exc", f"{type(e).__name__}: {e}")
                    live.remove(ctx)
            self._coalesce_drain([c for c in trunc if c in live])
            for ctx in list(live):
                if ctx.done and ctx.done[0].error is not None:
                    ctx.outcome = ("eio", str(ctx.done[0].error))
                    live.remove(ctx)
            for ctx in live:
                if not ctx.done:
                    continue  # drain timeout: outcome set by caller
                ctx.size = (
                    len(ctx.msg.data) if ctx.msg.op == "writefull"
                    else ctx.pg.rmw.object_size(ctx.msg.oid)
                )
                ctx.outcome = ("ok", None)

    def _coalesce_drain(self, ctxs: "list[_CoalCtx]") -> None:
        if not ctxs:
            return
        try:
            ctxs[0].pg.backend.drain_until(
                lambda: all(bool(c.done) for c in ctxs),
                timeout=self.op_timeout * (1 + len(ctxs)),
            )
        except TimeoutError:
            pass  # un-done ops surface as stalled in the epilogue

    def _coalesce_epilogue(self, ctx: _CoalCtx) -> OSDOpReply:
        """Serial per-op completion under _op_lock: window commit,
        backfill-dirty marking, reply + resend-replay recording —
        the same tail the classic handlers run."""
        msg, pg = ctx.msg, ctx.pg
        kind, detail = ctx.outcome
        if kind == "ok":
            self._req_commit(pg, msg.oid, msg.reqid, ctx.result_size)
            if pg.backfilling:
                with self._pg_lock:
                    pg.backfill_dirty.add(msg.oid)
            return self._record_completed(
                msg, OSDOpReply(msg.tid, ctx.epoch, size=ctx.size)
            )
        if kind == "eio":
            if self._transient_degraded(pg, detail or ""):
                return OSDOpReply(msg.tid, ctx.epoch, error="eagain")
            return self._record_completed(
                msg, OSDOpReply(msg.tid, ctx.epoch, error="eio",
                                data=(detail or "").encode())
            )
        # "exc": mirrors the serial path's exception catch — replied
        # eio but NOT recorded for resend replay
        self.log.error(
            "coalesced op", msg.op, msg.oid, "tid", msg.tid,
            "failed:", detail,
        )
        return OSDOpReply(
            msg.tid, ctx.epoch, error="eio",
            data=(detail or "").encode(),
        )

    def _mutating_gate(
        self, msg: OSDOp, spec, pgid: int, epoch: int
    ) -> "tuple[OSDOpReply | None, _PG | None]":
        """The dedup/durability gate every client op passes before its
        handler (caller holds ``_op_lock``; shared by the serial and
        the coalesced execution paths so they cannot diverge). Returns
        ``(reply, pg)`` — a non-None reply short-circuits the op."""
        polled = None  # durability fan-out, shared consult->resolve
        if msg.op in _MUTATING_OPS and msg.reqid:
            cached = self._completed_ops.get(msg.reqid)
            if cached is not None:
                self.net_pc.inc("dedup_hits")
                return OSDOpReply(
                    msg.tid, epoch, error=cached.error,
                    size=cached.size, data=cached.data,
                ), None
            # failover path: the replicated per-object window (the
            # pg-log reqid role) survives the old primary — a
            # resent append/write/truncate replays its recorded
            # result instead of re-applying. A STORAGE-seeded
            # entry must first prove durable: the dead primary may
            # have stamped it on < k shards (never acked, not
            # reconstructible) — replaying that as success loses
            # the write (round-4 advisor finding).
            pg0 = self._get_pg(msg.pool, pgid)
            hit = next(
                (t for t in self._req_window(pg0, msg.oid)
                 if t[0] == msg.reqid), None
            )
            if hit is not None:
                unv = self._req_unverified.get(msg.oid)
                if unv and msg.reqid in unv:
                    # async fan-out: a cached verdict resolves
                    # NOW; otherwise a poller thread is working
                    # (or cooldown/budget defers one) and the op
                    # parks in the client's retry loop — eagain,
                    # never a multi-second wait on the op worker
                    polled = self._take_or_spawn_poll(
                        pg0, msg.oid
                    )
                    if polled is None:
                        return OSDOpReply(
                            msg.tid, epoch, error="eagain"
                        ), None
                    members = sum(
                        1 for o in pg0.acting if o != SHARD_NONE
                    )
                    verdict = self._classify_req(
                        polled[0], msg.reqid, pg0.rmw.sinfo.k,
                        max(members - len(polled[0]), 0),
                    )
                else:
                    verdict = "durable"
                if verdict == "durable":
                    if unv:
                        unv.discard(msg.reqid)
                    self.net_pc.inc("dedup_hits")
                    return OSDOpReply(msg.tid, epoch, size=hit[1]), None
                if verdict == "unknown":
                    # unreachable members could still prove the
                    # op durable — back off instead of guessing
                    return OSDOpReply(
                        msg.tid, epoch, error="eagain"
                    ), None
                if verdict == "ambiguous":
                    return OSDOpReply(
                        msg.tid, epoch, error="eio",
                        data=b"resent op is not durable and later "
                             b"writes exist (unfound analog)",
                    ), None
                # "reapply": first attempt reached < k shards and
                # nothing newer exists anywhere — drop the seeded
                # entry and re-execute, healing the torn stripe.
                # An append re-applies at its ORIGINAL offset (the
                # recorded result size minus the payload), not the
                # current size a partial apply may have inflated.
                self.log.info(
                    "op", msg.oid, "resend", msg.reqid,
                    "not durable - re-applying"
                )
                self._req_windows[msg.oid] = [
                    t for t in self._req_window(pg0, msg.oid)
                    if t[0] != msg.reqid
                ]
                if unv:
                    unv.discard(msg.reqid)
                if msg.op == "append":
                    msg.op = "write"
                    msg.offset = max(hit[1] - len(msg.data), 0)
        pg = self._get_pg(msg.pool, pgid)
        if msg.op in _MUTATING_OPS:
            # settle storage-seeded reqid entries BEFORE anything
            # reads this object's size or stamps its window: a
            # torn never-acked write must be erased and rolled
            # back, or an append would build on the inflated OI
            # and a committed op's attr stamp would launder the
            # entry to every shard (round-5 review finding)
            if not self._resolve_unverified_reqs(
                pg, msg.oid, polled=polled
            ):
                return OSDOpReply(msg.tid, epoch, error="eagain"), None
            # copy-on-first-write after a pool snapshot: the head
            # must be preserved as the newest snap's clone BEFORE
            # any mutation lands (make_writeable role,
            # osd/PrimaryLogPG.cc)
            self._maybe_cow(pg, spec, msg.oid)
        return None, pg

    def _transient_degraded(self, pg: _PG, err) -> bool:
        """True when a below-min-size abort is a TRANSIENT local view
        (lossy-link down-marks on members the map still calls up —
        the recheck probe clears them within a tick): the op should
        answer eagain for the client's resend ladder, not a terminal
        eio. A genuinely under-replicated PG (map-level holes below
        k) keeps the fast eio."""
        text = str(err)
        if (
            "shards available" not in text
            and "cannot decode" not in text
            and "interval changed" not in text
        ):
            return False
        acting = self.osdmap.pg_to_up_acting(pg.pool, pg.pgid)
        live = sum(1 for o in acting if o != SHARD_NONE)
        return live >= pg.sinfo.k

    def _record_completed(self, msg: OSDOp, reply: OSDOpReply) -> OSDOpReply:
        """Remember a mutation's outcome under its client reqid so a
        resend (lost reply) replays the result instead of re-applying.
        Caller holds _op_lock. eagain is never recorded — it is an
        invitation to retry, and a cached one would replay forever."""
        if reply.error == "eagain":
            return reply
        if msg.reqid:
            # insert + trim under the reqcache leaf: shards record
            # concurrently, and an interleaved popitem while another
            # shard trims must not double-evict past the cap
            with self._reqcache_lock:
                self._completed_ops[msg.reqid] = reply
                while len(self._completed_ops) > self._completed_cap:
                    self._completed_ops.popitem(last=False)
        return reply

    def _drain_req_flushes(self) -> None:
        """Apply queued reqid-cache invalidations. Caller holds
        _op_lock; runs before any window is consulted, so an entry a
        mid-kick op re-inserted (it held _op_lock across the kick)
        is dropped before the next op can judge from it."""
        with self._req_flush_lock:
            if not self._req_flush:
                return
            pending, self._req_flush = self._req_flush, set()
        # the apply phase iterates a key-union of the reqid dicts:
        # another shard's _req_window seeding a NEW loc mid-union
        # would blow up the iteration — structural phase takes the
        # reqcache leaf (rank 35; _req_poll_lock nests under it)
        with self._reqcache_lock:
            self._apply_req_flushes(pending)

    def _apply_req_flushes(self, pending: set) -> None:
        if None in pending:
            self._req_windows.clear()
            self._req_unverified.clear()
            self._req_poll_at.clear()
            with self._req_poll_lock:
                # a verdict polled in the flushed interval must not
                # judge a window re-seeded in the new one
                self._req_poll_results.clear()
            return
        from ceph_tpu.placement import stable_hash

        pools = {e[1] for e in pending if e[0] == "pool"}
        pgs = {(e[1], e[3]): e[2] for e in pending if e[0] == "pg"}
        doomed = []
        with self._req_poll_lock:
            poll_locs = set(self._req_poll_results)
        for loc in (
            self._req_windows.keys()
            | self._req_unverified.keys()
            | self._req_poll_at.keys()
            | poll_locs
        ):
            try:
                pool_id, oid = split_loc(loc)
            except ValueError:
                doomed.append(loc)  # unparseable: never judge from it
                continue
            if pool_id in pools:
                doomed.append(loc)
                continue
            for (pid, pgid), pg_num in pgs.items():
                if pool_id == pid and stable_hash(
                    str(pid), head_of_loc(oid)
                ) % pg_num == pgid:
                    doomed.append(loc)
                    break
        for loc in doomed:
            self._req_windows.pop(loc, None)
            self._req_unverified.pop(loc, None)
            self._req_poll_at.pop(loc, None)
            with self._req_poll_lock:
                self._req_poll_results.pop(loc, None)

    def _req_window(self, pg: _PG, loc: str) -> list:
        """This object's reqid window, seeding from the stored attr
        the first time (the takeover path: a new primary reads what
        the old one replicated)."""
        win = self._req_windows.get(loc)
        if win is None:
            win = []
            key = self._my_key(pg, loc)
            if key is not None:
                try:
                    win = parse_reqs(self.store.getattr(key, REQ_KEY))
                except (FileNotFoundError, KeyError, ValueError):
                    pass
            # structural inserts + trim under the reqcache leaf: the
            # trim's next(iter(...)) and a sibling shard's new-key
            # insert must not interleave. No double-seed race to
            # resolve — same loc always lands on the same shard.
            with self._reqcache_lock:
                if win:
                    # storage-seeded entries are suspect until a
                    # quorum poll proves them durable (see
                    # _verify_req_durable)
                    self._req_unverified[loc] = {t[0] for t in win}
                if len(self._req_windows) > 4096:
                    old = next(iter(self._req_windows))
                    self._req_windows.pop(old)
                    self._req_unverified.pop(old, None)
                    self._req_poll_at.pop(old, None)
                self._req_windows[loc] = win
        return win

    #: deadline for the one-shot durability fan-out (rare failover
    #: path; it runs on its OWN thread — never under _op_lock, never
    #: on the op worker — so it cannot stall unrelated client ops)
    REQ_POLL_TIMEOUT = 2.5
    #: minimum spacing between fan-out STARTS for the SAME unsettled
    #: object (client retries answer eagain; a finished poll's cached
    #: verdict is consumed regardless of the cooldown)
    REQ_POLL_COOLDOWN = 1.0
    #: daemon-wide cap on concurrent fan-out threads: an adversarial
    #: burst of torn objects must not spawn unbounded pollers — ops
    #: past the budget answer eagain and retry into a free slot
    REQ_POLL_BUDGET = 2

    def _take_or_spawn_poll(self, pg: _PG, loc: str):
        """PARK-AND-RE-ENTER for the durability fan-out (ADVICE r5
        osd_daemon:1912: the 2.5 s fan-out used to run under _op_lock
        ON the single op worker, so a handful of torn objects
        serialized multi-second stalls onto every client op).

        Returns a finished poll's ``(windows, infos)`` if one is
        cached for this object, else starts one on a dedicated
        thread (cooldown- and budget-gated) and returns None — the
        caller answers eagain, the client's retry loop re-enters,
        and a later attempt consumes the verdict synchronously. The
        op worker never blocks. Caller holds _op_lock."""
        with self._req_poll_lock:
            res = self._req_poll_results.pop(loc, None)
            if res is not None:
                return res
            if loc in self._req_polls_inflight:
                return None  # fan-out already running: retry later
        import time as _time

        now = _time.monotonic()
        if now - self._req_poll_at.get(loc, 0.0) < self.REQ_POLL_COOLDOWN:
            return None
        if not self._req_poll_sem.acquire(blocking=False):
            return None  # budget exhausted: eagain, retry into a slot
        with self._reqcache_lock:  # possibly a new key: structural
            self._req_poll_at[loc] = now
        with self._req_poll_lock:
            self._req_polls_inflight.add(loc)

        def run() -> None:
            try:
                polled = self._poll_req_state(pg, loc)
            except Exception:
                polled = ([], [])  # classify from nothing -> back off
            finally:
                self._req_poll_sem.release()
            with self._req_poll_lock:
                self._req_polls_inflight.discard(loc)
                self._req_poll_results[loc] = polled
                while len(self._req_poll_results) > 256:
                    # an abandoned verdict (client gave up) must not
                    # accumulate forever
                    self._req_poll_results.pop(
                        next(iter(self._req_poll_results))
                    )

        threading.Thread(
            target=run, daemon=True,
            name=f"osd.{self.osd_id}-req-poll",
        ).start()
        return None

    def _poll_req_state(self, pg: _PG, loc: str):
        """ONE async fan-out to the acting members for the object's
        replicated REQ window + OI (the scrub-tally get_attrs_async
        pattern — sequential sync RPCs under _op_lock stalled the
        daemon for members that are slow exactly during failover).

        Returns ``(windows, infos)``: parsed reqid windows from every
        member that answered (self included, read locally), and the
        OTHER members' (size, eversion) OIs — the rollback target
        source."""
        results: list = []
        pending = 0
        for si, osd in enumerate(pg.acting):
            if osd == SHARD_NONE or osd == self.osd_id:
                continue
            if si in pg.backend.recovering:
                # a RETURNED member mid-log-replay is behind: its
                # window/OI reflect the state from before it died, so
                # its "I have no record of that op" is not evidence —
                # counting it erased a committed append in the
                # kill/revive thrash (round-5 chaos find). It stays
                # un-answered (-> "unknown"/eagain) until the replay
                # admits it; then its vote counts.
                continue
            key = shard_key(loc, si)
            if self.peers.get_attrs_async(
                osd, key, [REQ_KEY, OI_KEY],
                lambda r, _o=osd: results.append(r),
            ):
                pending += 1
        windows: list = []
        infos: list = []
        try:
            key = self._my_key(pg, loc)
            raw = self.store.getattr(key, REQ_KEY) if key else None
            windows.append(parse_reqs(raw) if raw else [])
        except (FileNotFoundError, KeyError, ValueError):
            windows.append([])
        try:
            self.peers.drain_until(
                lambda: len(results) >= pending,
                timeout=self.REQ_POLL_TIMEOUT,
            )
        except TimeoutError:
            pass  # best-effort deadline: classify from who answered
        for r in results:
            if isinstance(r, Exception):
                continue  # unreachable: cannot vouch either way
            if getattr(r, "error", None):
                if r.error == "enoent":
                    # a DEFINITIVE "no record at my position" is an
                    # answer, not an absence of one: it votes an empty
                    # window, or a torn create (stamped only on the
                    # successor) would classify "unknown" forever and
                    # wedge the object in eagain (round-5 review).
                    # Safe even for an op committed at pre-remap
                    # positions: re-apply is a fixed-offset write.
                    windows.append([])
                continue
            attrs = r.attrs
            try:
                raw = attrs.get(REQ_KEY)
                windows.append(parse_reqs(raw) if raw else [])
            except ValueError:
                windows.append([])
            try:
                raw = attrs.get(OI_KEY)
                if raw:
                    size, ev = parse_oi(raw)
                    infos.append((size, tuple(ev)))
            except ValueError:
                pass
        return windows, infos

    @staticmethod
    def _classify_req(
        windows: list, reqid: str, k: int, unanswered: int = 0
    ) -> str:
        """Durability verdict for one suspect reqid over the polled
        windows (round-4 advisor finding: a storage-seeded entry may
        record an op the dead primary applied on fewer than k shards
        — never acked to the client, not reconstructible).

        ``"durable"``: >= k members recorded the reqid (sub-writes
        apply in tid order per shard, so those k copies are at a
        consistent version and any shard can be rebuilt).
        ``"unknown"``: the members that did NOT answer could still
        bring support to k — absence of an answer is not evidence of
        non-durability (a partitioned quorum must not erase a
        committed op; round-5 review finding). Callers back off.
        ``"reapply"``: provably under-supported and nowhere followed
        by a later mutation — re-executing the resend is safe and
        heals the torn stripe.
        ``"ambiguous"``: provably under-supported but later writes
        exist in some window; re-applying would clobber them — fail
        the resend instead of lying. The reference blocks such
        objects as "unfound" (osd_types.h pg_missing_t;
        PeeringState::proc_master_log rolls back what no quorum can
        support)."""
        support = 0
        later = False
        for win in windows:
            ids = [t[0] for t in win]
            if reqid in ids:
                support += 1
                if ids[-1] != reqid:
                    later = True
        if support >= k:
            return "durable"
        if support + unanswered >= k:
            return "unknown"
        return "ambiguous" if later else "reapply"

    def _resolve_unverified_reqs(
        self, pg: _PG, loc: str, polled=None
    ) -> bool:
        """Settle every storage-seeded window entry BEFORE a new op
        stamps the window onward (round-5 review finding: stamping an
        unverified entry into a committed op's attr replicates it to
        all shards, laundering a torn never-acked write into a
        'durable' one). Durable entries stay; provably-under-
        supported ones are erased from the window and the object is
        rolled back to its committed state so the new op builds on
        clean bytes.

        Returns False when the object's state CANNOT be settled now
        (too few members answered to classify, an entry is ambiguous,
        or the rollback could not establish the committed state) —
        the caller must not mutate the object (eagain; the client's
        backoff retries once the members answer). ``polled`` reuses a
        fan-out the caller already paid for."""
        win0 = self._req_window(pg, loc)  # force the storage seed
        unv = self._req_unverified.get(loc)
        if not unv:
            return True
        if polled is not None:
            windows, infos = polled
        else:
            # async fan-out (cooldown + budget inside): no verdict
            # ready yet -> eagain; the client's retry re-enters and
            # consumes it once the poller thread finishes. The old
            # synchronous poll held _op_lock for the full 2.5 s
            # deadline and several torn objects serialized that stall
            # onto every client op (ADVICE r5).
            res = self._take_or_spawn_poll(pg, loc)
            if res is None:
                return False
            windows, infos = res
        k = pg.rmw.sinfo.k
        members = sum(1 for o in pg.acting if o != SHARD_NONE)
        unanswered = max(members - len(windows), 0)
        keep, dropped = [], []
        for t in win0:
            if t[0] not in unv:
                keep.append(t)
                continue
            verdict = self._classify_req(windows, t[0], k, unanswered)
            if verdict == "durable":
                keep.append(t)
            elif verdict == "reapply":
                dropped.append(t[0])
            else:
                # unknown/ambiguous: not settleable — keep everything
                # marked and make the caller back off rather than
                # build on (or erase) state we cannot judge
                return False
        if dropped and not self._rollback_torn_object(pg, loc, infos):
            return False  # window untouched: retry when members answer
        self._req_windows[loc] = keep
        self._req_unverified.pop(loc, None)
        if dropped:
            self.log.info(
                "op", loc, "erased non-durable seeded reqids",
                dropped, "- object rolled back to committed state"
            )
        return True

    def _rollback_torn_object(
        self, pg: _PG, loc: str, infos: list
    ) -> bool:
        """Roll my shard back to the committed state and report
        success. The committed state is the max OI eversion WITNESSED
        by >= k members — witnessing is monotone (a shard whose OI is
        at ev' >= ev necessarily applied the commit at ev, sub-writes
        being in tid order), so members carrying a torn later stamp
        still vote for the committed prefix. My own (possibly torn)
        OI witnesses too. Plain agreement-counting needed k matching
        REMOTE OIs, unattainable for m=1 pools (round-5 review)."""
        k = pg.rmw.sinfo.k
        evs = [ev for _size, ev in infos]
        my_size = 0
        try:
            key = self._my_key(pg, loc)
            if key is not None:
                my_size, my_ev = parse_oi(self.store.getattr(key, OI_KEY))
                evs.append(tuple(my_ev))
        except (FileNotFoundError, KeyError, ValueError):
            pass
        good = [
            ev for ev in set(evs)
            if sum(1 for e in evs if e >= ev) >= k
        ]
        if not good:
            self.log.error(
                "op", loc, "cannot roll back torn object:",
                "no k-witnessed committed OI among reachable members"
            )
            return False
        target = max(good)
        sizes = [s for s, ev in infos if ev == target]
        size = max(sizes) if sizes else my_size
        pg.rmw.prime_object(loc, max(size, 0), eversion=target)
        try:
            my_pos = pg.acting.index(self.osd_id)
        except ValueError:
            return False
        try:
            pg.recovery.recover_object(loc, {my_pos})
        except Exception as e:
            self.log.error(
                "op", loc, "torn-object rollback recovery failed:",
                type(e).__name__, str(e),
            )
            return False
        return True

    def _req_attr_for(self, pg: _PG, loc: str, reqid: str,
                      size: int) -> "dict[str, bytes] | None":
        """extra_attrs carrying the window INCLUDING this op — stamped
        into the op's own shard txns, atomically replicated with it.
        PURE: the in-memory window only updates via _req_commit once
        the op actually commits — a failed op's reqid must never be
        replayable as a success."""
        if not reqid:
            return None
        # settle seeded entries FIRST: stamping an unverified reqid
        # into this op's replicated attr would spread it to every
        # shard and launder a torn write into a "durable" one. The
        # client-op path already settled (or eagained) before calling
        # here — failing loudly covers any future caller that didn't.
        if not self._resolve_unverified_reqs(pg, loc):
            raise RuntimeError(
                f"unsettled seeded reqid window for {loc!r}"
            )
        win = [t for t in self._req_window(pg, loc) if t[0] != reqid]
        win.append((reqid, size))
        del win[:-REQ_WINDOW]
        return {REQ_KEY: pack_reqs(win)}

    def _req_commit(self, pg: _PG, loc: str, reqid: str,
                    size: int) -> None:
        if not reqid:
            return
        win = [t for t in self._req_window(pg, loc) if t[0] != reqid]
        win.append((reqid, size))
        del win[:-REQ_WINDOW]
        self._req_windows[loc] = win

    def _op_write(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        cur = self._object_size(pg, msg.oid)  # prime attrs on takeover
        result_size = max(cur, msg.offset + len(msg.data))
        done: list = []
        pg.rmw.submit(
            msg.oid, msg.offset, msg.data,
            on_commit=lambda op: done.append(op),
            extra_attrs=self._req_attr_for(
                pg, msg.oid, msg.reqid, result_size
            ),
        )
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        op = done[0]
        if op.error is not None:
            if self._transient_degraded(pg, op.error):
                # lossy-link transient (map still healthy): the
                # client's resend ladder retries past it
                return OSDOpReply(
                    msg.tid, self.osdmap.epoch, error="eagain"
                )
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=str(op.error).encode(),
            )
        self._req_commit(pg, msg.oid, msg.reqid, result_size)
        if pg.backfilling:
            with self._pg_lock:
                pg.backfill_dirty.add(msg.oid)  # re-pushed pre-cutover
        return OSDOpReply(
            msg.tid, self.osdmap.epoch, size=pg.rmw.object_size(msg.oid)
        )

    def _op_truncate(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        """rados_trunc: msg.offset carries the new size. Rides the
        RMW pipeline's per-object FIFO so it serializes with in-flight
        writes."""
        self._object_size(pg, msg.oid)  # prime from attrs on takeover
        done: list = []
        pg.rmw.submit_truncate(
            msg.oid, msg.offset, on_commit=lambda op: done.append(op),
            extra_attrs=self._req_attr_for(
                pg, msg.oid, msg.reqid, msg.offset
            ),
        )
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        op = done[0]
        if op.error is not None:
            if self._transient_degraded(pg, op.error):
                # lossy-link transient (map still healthy): the
                # client's resend ladder retries past it
                return OSDOpReply(
                    msg.tid, self.osdmap.epoch, error="eagain"
                )
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=str(op.error).encode(),
            )
        self._req_commit(pg, msg.oid, msg.reqid, msg.offset)
        if pg.backfilling:
            with self._pg_lock:
                pg.backfill_dirty.add(msg.oid)
        return OSDOpReply(msg.tid, self.osdmap.epoch, size=msg.offset)

    def _op_read(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        if not self._object_exists(pg, msg.oid):
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        size = self._object_size(pg, msg.oid)
        length = msg.length if msg.length else max(size - msg.offset, 0)
        done: list = []
        pg.reads.submit(
            msg.oid, msg.offset, length, on_complete=lambda op: done.append(op)
        )
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        op = done[0]
        if op.error is not None:
            if self._transient_degraded(pg, op.error):
                # lossy-link transient (map still healthy): the
                # client's resend ladder retries past it
                return OSDOpReply(
                    msg.tid, self.osdmap.epoch, error="eagain"
                )
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=str(op.error).encode(),
            )
        return OSDOpReply(
            msg.tid, self.osdmap.epoch, size=size, data=op.data
        )

    def _op_remove(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        if not self._object_exists(pg, msg.oid):
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        done: list = []
        pg.rmw.submit_remove(msg.oid, on_commit=lambda op: done.append(op))
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        op = done[0]
        if op.error is not None:
            if self._transient_degraded(pg, op.error):
                # lossy-link transient (map still healthy): the
                # client's resend ladder retries past it
                return OSDOpReply(
                    msg.tid, self.osdmap.epoch, error="eagain"
                )
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=str(op.error).encode(),
            )
        if pg.backfilling:
            with self._pg_lock:
                pg.backfill_dirty.add(msg.oid)
        return OSDOpReply(msg.tid, self.osdmap.epoch)

    # -- snapshots (pool snaps + clone-on-first-write) ------------------
    def _read_full(self, pg: _PG, loc: str) -> bytes:
        """Whole-object read through the read pipeline (reconstructs
        under erasures like any client read). Caller holds _op_lock."""
        size = self._object_size(pg, loc)
        if size == 0:
            return b""
        done: list = []
        pg.reads.submit(
            loc, 0, size, on_complete=lambda op: done.append(op)
        )
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        op = done[0]
        if op.error is not None:
            raise IOError(f"read {loc}: {op.error}")
        return op.data

    def _write_internal(self, pg: _PG, loc: str, data: bytes) -> None:
        done: list = []
        pg.rmw.submit(loc, 0, data, on_commit=lambda op: done.append(op))
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        if done[0].error is not None:
            raise IOError(f"write {loc}: {done[0].error}")

    def _remove_internal(self, pg: _PG, loc: str) -> None:
        done: list = []
        pg.rmw.submit_remove(loc, on_commit=lambda op: done.append(op))
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)

    def _maybe_cow(self, pg: _PG, spec, loc: str) -> None:
        """Preserve the head as the newest snap's clone before the
        first mutation after that snap. The head predates the snap iff
        its last-write epoch <= the snap's creation epoch — objects
        created after the snap never clone (and snap reads of them
        answer enoent). Caller holds _op_lock."""
        if not spec.snaps or snap_of_loc(loc):
            return  # no snaps / already a clone (rollback internals)
        snapid, _name, snap_epoch = spec.snaps[-1]
        cl = clone_loc(loc, snapid)
        if self._object_exists(pg, cl):
            return
        if not self._object_exists(pg, loc):
            return
        # A write stamped at the snap's own commit epoch happened
        # AFTER it (the snap commit is itself the map change) — only
        # strictly-older eversions predate the snap.
        ev = self._authoritative_eversion(pg, loc)
        if ev is not None and ev[0] >= snap_epoch:
            return  # head born/written after the snap: nothing to keep
        data = self._read_full(pg, loc)
        self._write_internal(pg, cl, data)
        attrs = dict(self._replicated_attrs(pg, loc))
        # The clone remembers the epoch its CONTENT was last written
        # at — older snaps consult it to tell "existed then" from
        # "born between snaps" (see _resolve_snap). Replicated (u:)
        # so shard rebuilds keep it; the \x1f makes client-namespace
        # collisions impossible.
        attrs["u:\x1forigin"] = str(ev[0] if ev else 0).encode()
        done: list = []
        pg.rmw.submit_attr_updates(
            cl, attrs, on_commit=lambda op: done.append(op)
        )
        pg.backend.drain_until(
            lambda: bool(done), timeout=self.op_timeout
        )

    def _resolve_snap(
        self, pg: _PG, spec, loc: str, snapid: int
    ) -> "str | None":
        """The loc serving a read at snapshot ``snapid``: the oldest
        clone at-or-after it, else the head when the head predates the
        snap, else None (object did not exist then)."""
        entry = next(
            (s for s in spec.snaps if s[0] == snapid), None
        )
        if entry is None:
            return None  # snap deleted (or never existed)
        for sid, _n, _e in spec.snaps:
            if sid < snapid:
                continue
            cl = clone_loc(loc, sid)
            if self._object_exists(pg, cl):
                # A later clone only serves an EARLIER snap if its
                # content predates that snap — otherwise the object
                # was born between the snaps and reading the clone
                # would resurrect it at a time it did not exist.
                origin = self._replicated_attrs(
                    pg, cl, ("u:\x1forigin",)
                ).get("u:\x1forigin")
                if origin is not None and int(origin) >= entry[2]:
                    return None  # monotonic: later clones only newer
                return cl
        if self._object_exists(pg, loc):
            ev = self._authoritative_eversion(pg, loc)
            # strictly-older epoch = head predates the snap (same
            # strictness as _maybe_cow; an unknown eversion reads as
            # old — serving stale head beats refusing a valid read)
            if ev is None or ev[0] < entry[2]:
                return loc
        return None

    def _op_snap_read(self, pg: _PG, spec, msg: OSDOp) -> OSDOpReply:
        src = self._resolve_snap(pg, spec, msg.oid, msg.snap)
        if src is None:
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        redirected = OSDOp(
            msg.tid, msg.epoch, msg.pool, src, "read",
            msg.offset, msg.length,
        )
        return self._op_read(pg, redirected)

    def _op_rollback(self, pg: _PG, spec, msg: OSDOp) -> OSDOpReply:
        """rados_ioctx_snap_rollback: head becomes the snap's content
        (the pre-rollback head was preserved by the _maybe_cow that
        ran before this op)."""
        src = self._resolve_snap(pg, spec, msg.oid, msg.snap)
        if src is None:
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        data = self._read_full(pg, src) if src != msg.oid else None
        if data is None:
            return OSDOpReply(msg.tid, self.osdmap.epoch)  # already it
        # the snapshot's ATTR state comes back too (minus the clone's
        # internal origin marker) — _maybe_cow preserved it for this
        attrs = {
            k: v
            for k, v in self._replicated_attrs(pg, src).items()
            if k != "u:\x1forigin"
        }
        self._remove_internal(pg, msg.oid)
        self._write_internal(pg, msg.oid, data)
        if attrs:
            done: list = []
            pg.rmw.submit_attr_updates(
                msg.oid, attrs, on_commit=lambda op: done.append(op)
            )
            pg.backend.drain_until(
                lambda: bool(done), timeout=self.op_timeout
            )
        if pg.backfilling:
            with self._pg_lock:
                pg.backfill_dirty.add(msg.oid)
        return OSDOpReply(
            msg.tid, self.osdmap.epoch, size=len(data)
        )

    def _gc_dropped_snaps(self) -> None:
        """Tick sweep: delete my shard keys of clones whose snapid the
        pool no longer lists (snap trimming, each member trims its own
        shards independently). The store scan only runs when the
        cluster's snap state CHANGED since the last sweep (plus once
        at startup), so steady-state ticks pay nothing."""
        state = tuple(
            sorted(
                (spec.pool_id, tuple(s[0] for s in spec.snaps))
                for spec in self.osdmap.pools.values()
            )
        )
        if state == getattr(self, "_snap_state_swept", None):
            return
        swept_clean = True
        live: dict[int, set[int]] = {}
        for spec in self.osdmap.pools.values():
            live[spec.pool_id] = {s[0] for s in spec.snaps}
        for key in list(self.store.list_objects()):
            try:
                loc, _si = split_shard_key(key)
                pool_id, _oid = split_loc(loc)
            except ValueError:
                continue
            sid = snap_of_loc(loc)
            if not sid:
                continue
            if sid not in live.get(pool_id, set()):
                try:
                    self.store.queue_transactions(
                        Transaction().remove(key)
                    )
                except Exception:
                    swept_clean = False  # keep the sweep armed
        if swept_clean:
            # only a FULLY clean sweep disarms: a failed removal (or
            # an exception above) leaves the state mismatch in place
            # so the next tick rescans
            self._snap_state_swept = state

    # -- watch / notify (librados watch/notify role) --------------------
    def _op_watch(self, msg: OSDOp, conn) -> OSDOpReply:
        """Register the sending connection as a watcher of the object
        (cookie in msg.name). Soft state on the primary — a primary
        change or daemon restart drops it, like the reference's watch
        timeout forces re-watch."""
        if conn is None:
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=b"watch needs a connection",
            )
        with self._watch_lock:
            self._watchers.setdefault(
                (msg.pool, msg.oid), {}
            )[msg.name] = conn
        return OSDOpReply(msg.tid, self.osdmap.epoch)

    def _op_unwatch(self, msg: OSDOp) -> OSDOpReply:
        with self._watch_lock:
            entry = self._watchers.get((msg.pool, msg.oid), {})
            entry.pop(msg.name, None)
        return OSDOpReply(msg.tid, self.osdmap.epoch)

    def _op_notify(self, msg: OSDOp, client_oid: str) -> OSDOpReply:
        """Fan the payload to every watcher, wait for acks (bounded),
        reply with who acked / who timed out (notify_ack collection,
        osd/Watch.cc role)."""
        import json as _json

        # client-supplied, but capped: one misbehaving notifier must
        # not park this reader thread forever
        timeout = min((msg.length / 1000.0) if msg.length else 1.0, 30.0)
        with self._watch_lock:
            watchers = dict(self._watchers.get((msg.pool, msg.oid), {}))
            notify_id = self._next_notify_id
            self._next_notify_id += 1
        if not watchers:
            return OSDOpReply(
                msg.tid, self.osdmap.epoch,
                data=_json.dumps({"acked": [], "missed": []}).encode(),
            )
        ev = threading.Event()
        state = {"pending": set(watchers), "acked": []}
        with self._watch_lock:
            self._pending_notifies[notify_id] = (state, ev)
        dead = []
        for cookie, wconn in watchers.items():
            try:
                wconn.send(WatchNotify(
                    notify_id, cookie, msg.pool, client_oid, msg.data
                ))
            except Exception:
                dead.append(cookie)
        if dead:
            with self._watch_lock:
                for cookie in dead:
                    state["pending"].discard(cookie)
                    self._watchers.get(
                        (msg.pool, msg.oid), {}
                    ).pop(cookie, None)
                if not state["pending"]:
                    ev.set()
        ev.wait(timeout)
        with self._watch_lock:
            self._pending_notifies.pop(notify_id, None)
            acked = list(state["acked"])
            missed = sorted(state["pending"])
        return OSDOpReply(
            msg.tid, self.osdmap.epoch,
            data=_json.dumps(
                {"acked": sorted(acked), "missed": missed}
            ).encode(),
        )

    def _handle_notify_ack(self, msg) -> None:
        with self._watch_lock:
            entry = self._pending_notifies.get(msg.notify_id)
            if entry is None:
                return
            state, ev = entry
            if msg.cookie in state["pending"]:
                state["pending"].discard(msg.cookie)
                state["acked"].append(msg.cookie)
            if not state["pending"]:
                ev.set()

    def _op_pgls(self, msg, spec, pgid: int):
        """List one PG's objects (the PGLS op behind rados ls). The
        primary's own scan suffices when its acting set is whole
        (every write touched it); peers are consulted only when the
        set has holes/recovering members — an object written while MY
        position was a hole must still list."""
        import json as _json

        pg = self._get_pg(msg.pool, pgid)
        degraded = pg.backend.recovering or any(
            o == SHARD_NONE for o in pg.acting
        )
        if degraded:
            locs = set(self._backfill_scan(msg.pool, pgid, spec, pg))
        else:
            locs = {
                loc for loc, _si in self._scan_pg_keys(
                    spec.pool_id, spec.pg_num, pgid
                )
            }
        # snapshot clones are internal objects: they backfill and
        # scrub, but never list (rados ls shows heads only)
        oids = sorted(
            split_loc(loc)[1]
            for loc in locs
            if not snap_of_loc(loc)
        )
        return OSDOpReply(
            msg.tid, self.osdmap.epoch,
            data=_json.dumps(oids).encode(),
        )

    def _meta_read_guard(
        self, pg: _PG, msg: OSDOp
    ) -> "OSDOpReply | None":
        """Common gate for metadata reads served from the primary's
        own shard copy: enoent when the object doesn't exist, a
        degraded-metadata EIO when the object exists but MY copy is
        missing (hole-written, not yet refreshed)."""
        if not self._object_exists(pg, msg.oid):
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        key = self._my_key(pg, msg.oid)
        if key is None or not self.store.exists(key):
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=b"primary shard copy missing (recovering)",
            )
        return None

    def _run_attr_update(
        self, pg: _PG, msg: OSDOp, updates: "dict[str, bytes | None]"
    ) -> OSDOpReply:
        """Submit one logged attr batch and wait for commit (shared by
        the xattr and omap mutation handlers)."""
        done: list = []
        pg.rmw.submit_attr_updates(
            msg.oid, updates, on_commit=lambda op: done.append(op)
        )
        pg.backend.drain_until(lambda: bool(done), timeout=self.op_timeout)
        op = done[0]
        if op.error is not None:
            if self._transient_degraded(pg, op.error):
                # lossy-link transient (map still healthy): the
                # client's resend ladder retries past it
                return OSDOpReply(
                    msg.tid, self.osdmap.epoch, error="eagain"
                )
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=str(op.error).encode(),
            )
        if pg.backfilling:
            with self._pg_lock:
                pg.backfill_dirty.add(msg.oid)
        return OSDOpReply(msg.tid, self.osdmap.epoch)

    def _op_setxattr(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        if not self._object_exists(pg, msg.oid):
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        value = msg.data if msg.op == "setxattr" else None
        return self._run_attr_update(pg, msg, {"u:" + msg.name: value})

    def _op_getxattr(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        if not self._object_exists(pg, msg.oid):
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        key = self._my_key(pg, msg.oid)
        try:
            val = self.store.getattr(key, "u:" + msg.name)
        except FileNotFoundError:
            # the OBJECT is missing from my own shard (written while
            # my position was a hole, not yet refreshed): a degraded-
            # metadata condition, NOT proof the attr doesn't exist
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=b"primary shard copy missing (recovering)",
            )
        except KeyError:
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enodata")
        return OSDOpReply(msg.tid, self.osdmap.epoch, data=val)

    def _op_getxattrs(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        import json as _json

        bad = self._meta_read_guard(pg, msg)
        if bad is not None:
            return bad
        attrs = self._user_attrs(pg, msg.oid)
        return OSDOpReply(
            msg.tid, self.osdmap.epoch,
            data=_json.dumps(
                {k[2:]: v.hex() for k, v in attrs.items()}
            ).encode(),
        )

    def _op_omapset(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        """Batched omap mutations: data = json {key: hex value | null
        (remove)} — one ordered, logged commit for the whole batch
        (rados omap_set/omap_rm_keys)."""
        import json as _json

        if not self._object_exists(pg, msg.oid):
            return OSDOpReply(msg.tid, self.osdmap.epoch, error="enoent")
        try:
            kv = _json.loads(msg.data.decode())
            updates = {
                "m:" + k: (bytes.fromhex(v) if v is not None else None)
                for k, v in kv.items()
            }
        except (ValueError, AttributeError) as e:
            return OSDOpReply(
                msg.tid, self.osdmap.epoch, error="eio",
                data=f"bad omap batch: {e}".encode(),
            )
        return self._run_attr_update(pg, msg, updates)

    def _op_omapget(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        import json as _json

        bad = self._meta_read_guard(pg, msg)
        if bad is not None:
            return bad
        want = _json.loads(msg.data.decode()) if msg.data else None
        attrs = self._replicated_attrs(pg, msg.oid, ("m:",))
        out = {}
        for k, v in attrs.items():
            bare = k[2:]
            if want is None or bare in want:
                out[bare] = v.hex()
        return OSDOpReply(
            msg.tid, self.osdmap.epoch, data=_json.dumps(out).encode()
        )

    def _op_omaplist(self, pg: _PG, msg: OSDOp) -> OSDOpReply:
        """Sorted key range: name = start-after cursor, length = max
        entries (rados omap_get_keys2 pagination shape)."""
        import json as _json

        bad = self._meta_read_guard(pg, msg)
        if bad is not None:
            return bad
        attrs = self._replicated_attrs(pg, msg.oid, ("m:",))
        keys = sorted(k[2:] for k in attrs)
        if msg.name:
            import bisect

            keys = keys[bisect.bisect_right(keys, msg.name):]
        limit = msg.length or len(keys)
        page = keys[:limit]  # encode only the returned page's values
        return OSDOpReply(
            msg.tid, self.osdmap.epoch,
            data=_json.dumps(
                [[k, attrs["m:" + k].hex()] for k in page]
            ).encode(),
        )

    # -- backfill (rebalance data movement, pg_temp-protected) ----------
    def _request_pg_temp(self, pool: str, pgid: int, pg: _PG) -> bool:
        try:
            self.monitor.pg_temp_set(pool, pgid, list(pg.raw))
            return True
        except Exception:
            return False

    def _handle_backfill_reserve(
        self, conn: Connection, msg: BackfillReserve
    ) -> None:
        """Remote-reservation service (the MBackfillReserve target
        side): a request's GRANT reply may be delayed until a slot
        frees — the requesting primary blocks in reserve_backfill,
        which is exactly the throttle."""
        key = (msg.pool_id, msg.pgid)
        if msg.action == "release":
            self.remote_reserver.release(key)
            conn.send(BackfillReserveReply(msg.tid, msg.shard, True))
            return

        def grant(conn=conn, tid=msg.tid, shard=msg.shard) -> None:
            try:
                conn.send(BackfillReserveReply(tid, shard, True))
            except Exception:
                # requester gone: free the slot for the next in line
                self.remote_reserver.release(key)

        self.remote_reserver.request(key, msg.prio, grant)

    def _start_backfill(self, pool: str, pgid: int, pg: _PG) -> None:
        key = (pool, pgid)
        with self._pg_lock:
            if key in self._backfills and self._backfills[key].is_alive():
                return
            t = threading.Thread(
                target=self._backfill_pg, args=(pool, pgid, pg), daemon=True
            )
            self._backfills[key] = t
        pg.backfilling = True
        t.start()

    def tick(self) -> None:
        """Periodic maintenance: restart stalled backfills for PGs I
        serve under pg_temp (a failed pass leaves the temp mapping in
        place; the tick is the retry seam), finish pool-deletion
        sweeps, and kick due background scrubs."""
        self._adopt_pg_temps()
        self._maybe_gc_pools()
        self._maybe_schedule_scrubs()
        self._gc_dropped_snaps()
        # lossy-link hygiene: a peer down-marked by a single lost ack
        # (RPC expiry under the injected fault plane, or any transient
        # stall) is re-probed while the map still says it's up; a Pong
        # that postdates the mark clears it. Real failures never pong,
        # so their marks stand until the map changes.
        self.peers.recheck_down(
            {o for o in self.peers.down_shards if self.osdmap.is_up(o)}
        )
        # a failed peering pass leaves the gate closed; retry here
        with self._pg_lock:
            stuck = [
                pg for pg in self._pgs.values()
                if not pg.peered.is_set()
                and first_live(pg.acting) == self.osd_id
                and not pg.fsm._draining
            ]
        for pg in stuck:
            self._kick_peering(pg)
        # a failed shard catch-up reverts the member to a hole
        # (_catch_up_shard's except path) — with no further map
        # epoch, nothing would ever retry and the PG stays degraded
        # forever on a settled cluster. The tick re-heals: any shard
        # the CURRENT map says is up but my acting view holds as a
        # hole goes back through the recovering -> catch-up pipeline.
        to_heal: list[tuple[_PG, int]] = []
        with self._pg_lock:
            for (pool, pgid), pg in self._pgs.items():
                if first_live(pg.acting) != self.osd_id:
                    continue
                if pool not in self.osdmap.pools or pg.backfilling:
                    continue
                if self.osdmap.pg_to_raw(pool, pgid) != pg.raw:
                    continue  # layout moved: backfill's problem
                map_acting = self.osdmap.pg_to_up_acting(pool, pgid)
                for i, osd in enumerate(map_acting):
                    if (
                        osd != SHARD_NONE
                        and pg.acting[i] == SHARD_NONE
                        and i not in pg.backend.recovering
                    ):
                        pg.acting[i] = osd
                        pg.backend.acting[i] = osd
                        pg.backend.recovering.add(i)
                        to_heal.append((pg, i))
                # lossy-link quarantine drain: a position parked in
                # ``recovering`` by avail_shards (locally down-marked
                # while the map said up) re-enters through catch-up
                # once the peer answers pings again — the replay
                # brings it the writes hole-journaled past it, and
                # only the admission returns it to the read set
                for i, osd in enumerate(pg.acting):
                    if (
                        osd != SHARD_NONE
                        and osd != self.osd_id
                        and i in pg.backend.recovering
                        and i not in pg._catchup_inflight
                        and self.osdmap.is_up(osd)
                        and osd not in self.peers.down_shards
                    ):
                        to_heal.append((pg, i))
        for pg, shard in to_heal:
            self.log.info(
                "pg", f"{pg.pool}/{pg.pgid}:", "re-healing shard",
                shard, "(previous catch-up failed)"
            )
            if pg.acting[shard] == self.osd_id:
                # my own position: the election re-admits it (see
                # _admit_self_positions) — never a transfer to self
                pg.fsm.post_interval()
                continue
            self._spawn_catch_up(pg, shard)
        self._qos_tick()
        self.report_pg_stats()

    # -- PG-stats reporting (the MPGStats sender) -----------------------
    def report_pg_stats(self, force: bool = False) -> int:
        """Ship one pg_stats record per PG this daemon serves as
        primary, plus its osd_stat, to the monitor's PGMap. Driven by
        the tick at ``osd_stats_report_interval``; ``force`` flushes
        now regardless (the CLI surfaces call it so `status`/`pg
        dump`/`df` read fresh numbers without waiting a tick).
        Returns accepted records."""
        from ceph_tpu.utils import config as _cfg

        iv = _cfg.get("osd_stats_report_interval")
        if iv <= 0 and not force:
            return 0
        now = time.monotonic()
        if not force and now - self._last_stats_report < iv:
            return 0
        self._last_stats_report = now
        if self._stopped:
            return 0
        osdmap = self.osdmap
        self._stats_seq += 1
        led_keys = self._map_led_pgs(osdmap)
        # ONE store pass serves every PG's census AND the osd_stat —
        # per-PG scans would be O(keys x pgs) per report
        census, used, n_keys = self._stats_census(osdmap, led_keys)
        stats = []
        with self._pg_lock:
            led = [
                (key, pg) for key, pg in self._pgs.items()
                if first_live(pg.acting) == self.osd_id
            ]
        covered: set[tuple[str, int]] = set()
        for (pool, pgid), pg in led:
            spec = osdmap.pools.get(pool)
            if spec is None:
                continue
            if (pool, pgid) not in led_keys:
                continue  # demoted: the new primary reports
            try:
                stats.append(self._collect_pg_stats(
                    pool, pgid, pg, spec, osdmap,
                    census.get((pool, pgid), {}),
                ))
                covered.add((pool, pgid))
            except Exception:
                pass  # a half-built PG must not sink the report
        # instance-less PGs the map says I lead (idle since boot, or
        # re-adopted after a revive without an interval change) still
        # report — from the store census + map acting alone — so the
        # PGMap never serves a stale record for a PG whose primary is
        # alive (the stats-derived recovery wait keys on fresh epochs)
        with self._pg_lock:
            have_instance = set(self._pgs)
        for pool, pgid in led_keys:
            if (pool, pgid) in covered or (pool, pgid) in have_instance:
                continue
            spec = osdmap.pools.get(pool)
            if spec is None:
                continue
            try:
                stats.append(self._collect_idle_pg_stats(
                    pool, pgid, spec, osdmap,
                    census.get((pool, pgid), {}),
                ))
            except Exception:
                pass
        from .pgmap import OSDStat

        cap = getattr(self.store, "device_size", 0) or _cfg.get(
            "osd_device_capacity_bytes"
        )
        osd_stat = OSDStat(
            osd=self.osd_id, used_bytes=used,
            capacity_bytes=int(cap), num_objects=n_keys,
        )
        try:
            return self.monitor.pg_stats_report(
                self.osd_id, osdmap.epoch, stats, osd_stat
            )
        except Exception:
            return 0  # a mon hiccup must not kill the tick loop

    def _map_led_pgs(self, osdmap: OSDMap) -> set:
        """{(pool, pgid) whose CRUSH primary I am}, cached per map
        epoch — the primary sweep must not run per report."""
        epoch, cached = self._led_cache
        if epoch == osdmap.epoch:
            return cached
        led = {
            (pool, pgid)
            for pool, spec in osdmap.pools.items()
            for pgid in range(spec.pg_num)
            if osdmap.pg_primary(pool, pgid) == self.osd_id
        }
        self._led_cache = (osdmap.epoch, led)
        return led

    def _stats_census(
        self, osdmap: OSDMap, led_keys: set
    ) -> tuple[dict, int, int]:
        """One pass over my store: ({(pool, pgid) -> {loc: logical
        size}} for the PGs in ``led_keys``, used bytes, key count).
        Logical sizes come from the OI attr (the object_info_t size),
        shard bytes from stat; keys of PGs led elsewhere only feed
        the used-bytes total."""
        from ceph_tpu.placement import stable_hash

        by_id = {
            spec.pool_id: (pool, spec)
            for pool, spec in osdmap.pools.items()
        }
        census: dict[tuple[str, int], dict[str, int]] = {}
        used = 0
        keys = self.store.list_objects()
        for key in keys:
            try:
                used += self.store.stat(key)
            except (FileNotFoundError, OSError):
                pass
            try:
                loc, _si = split_shard_key(key)
                pool_id, oid = split_loc(loc)
            except ValueError:
                continue
            entry = by_id.get(pool_id)
            if entry is None:
                continue
            pool, spec = entry
            pgid = stable_hash(
                str(pool_id), head_of_loc(oid)
            ) % spec.pg_num
            if (pool, pgid) not in led_keys:
                continue
            sized = census.setdefault((pool, pgid), {})
            if loc in sized:
                continue
            try:
                size, _ev = parse_oi(self.store.getattr(key, OI_KEY))
            except (FileNotFoundError, KeyError, ValueError):
                size = 0
            sized[loc] = size
        return census, used, len(keys)

    def _collect_pg_stats(
        self, pool: str, pgid: int, pg: _PG, spec, osdmap: OSDMap,
        sized: "dict[str, int]",
    ):
        """One pg_stats_t record from live primary state + the shared
        store census (``sized``: loc -> logical size for this PG):
        state bits, object/byte counts, degraded/misplaced tallies,
        and the cumulative client/recovery counters the PGMap cuts
        rates from."""
        from .pgmap import PGStats

        acting = tuple(pg.acting)
        holes = {i for i, o in enumerate(acting) if o == SHARD_NONE}
        recovering = set(pg.backend.recovering) - holes
        degraded_pos = holes | recovering
        live = len(acting) - len(holes)
        peered = pg.peered.is_set()
        backfilling = bool(pg.backfilling) or (
            (pool, pgid) in osdmap.pg_temp
        )
        states = []
        if not peered:
            states.append("peering")
        elif live < spec.k:
            states.append("down")
        else:
            states.append("active")
        if holes:
            states.append("undersized")
        if degraded_pos:
            states.append("degraded")
        if recovering:
            states.append("recovering")
        if backfilling:
            states.append("backfilling")
        if (
            peered and live >= spec.k and not degraded_pos
            and not backfilling
        ):
            states.append("clean")
        # object/byte census from my own shard keys (the primary
        # holds one shard of every object it leads; OI attrs carry
        # the logical size — no peer IO, no pipeline locks)
        n_obj = len(sized)
        n_bytes = sum(sized.values())
        misplaced = 0
        if (pool, pgid) in osdmap.pg_temp:
            target = osdmap.pg_to_raw(pool, pgid, ignore_temp=True)
            moved = sum(
                1 for a, t in zip(acting, target) if a != t
            )
            misplaced = n_obj * moved
        rmw = pg.rmw.perf
        reads = pg.reads.perf
        rec = pg.recovery.perf
        return PGStats(
            pool=pool,
            pool_id=spec.pool_id,
            pgid=pgid,
            state=tuple(sorted(states)),
            up=tuple(osdmap.pg_to_raw(pool, pgid)),
            acting=acting,
            num_objects=n_obj,
            num_bytes=n_bytes,
            degraded=n_obj * len(degraded_pos),
            misplaced=misplaced,
            log_size=len(pg.pglog.entries),
            client_write_ops=rmw.get("write_ops"),
            client_write_bytes=rmw.get("write_bytes"),
            client_read_ops=reads.get("read_ops"),
            client_read_bytes=reads.get("read_bytes"),
            recovery_ops=rec.get("recovery_ops"),
            recovery_bytes=rec.get("recovered_bytes"),
            reported_epoch=osdmap.epoch,
            reported_seq=self._stats_seq,
            primary=self.osd_id,
        )

    def _collect_idle_pg_stats(
        self, pool: str, pgid: int, spec, osdmap: OSDMap,
        sized: "dict[str, int]",
    ):
        """A pg_stats record for a PG I lead per the map but hold no
        live instance for (no client IO this interval): state from
        the map acting set, census from the shared store pass, zero
        IO counters."""
        from .pgmap import PGStats

        acting = tuple(osdmap.pg_to_up_acting(pool, pgid))
        holes = sum(1 for o in acting if o == SHARD_NONE)
        live = len(acting) - holes
        states = ["active"] if live >= spec.k else ["down"]
        if holes:
            states += ["undersized", "degraded"]
        elif live >= spec.k:
            states.append("clean")
        return PGStats(
            pool=pool,
            pool_id=spec.pool_id,
            pgid=pgid,
            state=tuple(sorted(states)),
            up=tuple(osdmap.pg_to_raw(pool, pgid)),
            acting=acting,
            num_objects=len(sized),
            num_bytes=sum(sized.values()),
            degraded=len(sized) * holes,
            reported_epoch=osdmap.epoch,
            reported_seq=self._stats_seq,
            primary=self.osd_id,
        )

    # -- background scrub scheduler (osd/scrubber/osd_scrub.cc role) ----
    def _scrub_due(
        self, key: tuple[str, int], now: float
    ) -> "str | None":
        """"deep"/"shallow" when the PG's randomized due time passed,
        else None. Each PG gets a stable jitter fraction so scrubs
        spread inside the interval instead of storming together
        (osd_scrub_interval_randomize_ratio)."""
        import random

        from ceph_tpu.utils import config

        stamps = self._scrub_stamps.setdefault(key, [0.0, 0.0])
        jitter = self._scrub_jitter.setdefault(key, random.random())
        ratio = config.get("osd_scrub_interval_randomize_ratio")
        shallow_iv = config.get("osd_scrub_min_interval") * (
            1.0 + jitter * ratio
        )
        deep_iv = config.get("osd_deep_scrub_interval") * (
            1.0 + jitter * ratio
        )
        if stamps[1] == 0.0 or now - stamps[1] >= deep_iv:
            return "deep"
        if stamps[0] == 0.0 or now - stamps[0] >= shallow_iv:
            # chance-based early deepening (PrimaryLogScrub's
            # deep_scrub_on_error/randomize behavior)
            if random.random() < config.get(
                "osd_deep_scrub_randomize_ratio"
            ):
                return "deep"
            return "shallow"
        return None

    def _maybe_schedule_scrubs(self) -> None:
        import time as _time

        from ceph_tpu.utils import config

        now = _time.monotonic()
        with self._scrub_lock:
            if self._scrubs_running >= config.get("osd_max_scrubs"):
                return
        with self._pg_lock:
            keys = list(self._pgs)
        for key in keys:
            pool, pgid = key
            if pool not in self.osdmap.pools:
                continue
            if self.osdmap.pg_primary(pool, pgid) != self.osd_id:
                continue  # only the primary scrubs (reservation holder)
            kind = self._scrub_due(key, now)
            if kind is None:
                continue
            with self._scrub_lock:
                if self._scrubs_running >= config.get("osd_max_scrubs"):
                    return
                if key in self._scrubs_inflight:
                    continue  # still running: not due again yet
                self._scrubs_inflight.add(key)
                self._scrubs_running += 1
            threading.Thread(
                target=self._run_scheduled_scrub,
                args=(pool, pgid, kind),
                name=f"scrub-{pool}-{pgid}",
                daemon=True,
            ).start()

    def _run_scheduled_scrub(
        self, pool: str, pgid: int, kind: str
    ) -> None:
        import time as _time

        from ceph_tpu.utils import config

        key = (pool, pgid)
        try:
            if kind == "deep":
                results = self.scrub_pg(
                    pool, pgid,
                    repair=config.get("osd_scrub_auto_repair"),
                )
            else:
                results = self.scrub_pg_shallow(pool, pgid)
            n_err = sum(len(r.errors) for r in results)
            repaired = any(getattr(r, "repaired", False) for r in results)
            now = _time.monotonic()
            stamps = self._scrub_stamps.setdefault(key, [0.0, 0.0])
            stamps[0] = now
            if kind == "deep":
                stamps[1] = now
            self.scrub_history[key] = (now, kind, n_err, repaired)
            if n_err:
                self.log.info(
                    "scheduled", kind, "scrub", f"{pool}/{pgid}:",
                    n_err, "errors",
                    "(repaired)" if repaired else "",
                )
                from ceph_tpu.utils.cluster_log import cluster_log

                cluster_log.log(
                    f"osd.{self.osd_id}", "scrub_error",
                    f"{kind} scrub of pg {pool}/{pgid}: {n_err} "
                    f"errors{' (repaired)' if repaired else ''}",
                    severity="WRN", epoch=self.osdmap.epoch,
                    repaired=repaired,
                )
        except Exception as e:
            # scrubbing must never take the daemon down; the PG stays
            # due and the next tick retries
            self.log.error(
                "scheduled scrub failed", f"{pool}/{pgid}:",
                type(e).__name__, e,
            )
        finally:
            with self._scrub_lock:
                self._scrubs_running -= 1
                self._scrubs_inflight.discard(key)

    def scrub_pg_shallow(self, pool: str, pgid: int) -> "list":
        """Metadata-only scrub: every object's shards must agree on
        the HashInfo attr (consensus without dissent) — no payload
        reads (the reference's shallow scrub compares metadata only).
        """
        from ceph_tpu.pipeline.recovery import ScrubError, ScrubResult

        spec = self.osdmap.pools[pool]
        pg = self._get_pg(pool, pgid)
        locs = sorted(self._backfill_scan(pool, pgid, spec, pg))
        results = []
        op_lock = self._op_lock_for(pool, pgid)
        for loc in locs:
            self.admit("scrub")
            with op_lock:
                if not self._object_size(pg, loc) and not (
                    self._have_object(pg, loc)
                ):
                    continue
                result = ScrubResult(loc)
                hinfo, dissent = self._consensus_hinfo(pg, loc)
                if hinfo is None:
                    result.errors.append(ScrubError(
                        -1, "hinfo_conflict" if dissent else "missing_attr"
                    ))
                elif dissent:
                    result.errors.append(
                        ScrubError(-1, "hinfo_dissent", str(dissent))
                    )
                results.append(result)
        return results

    def _backfill_pg(self, pool: str, pgid: int, pg: _PG) -> None:
        """Move every object of the PG to its CRUSH target layout,
        then drop pg_temp (the reference's backfill machinery:
        interval scan + push, last_backfill semantics collapsed to a
        dirty-set re-pass + final quiesce under the op lock).

        Reservation protocol (backfill_reservation.rst): a LOCAL slot
        from my reserver first, then a REMOTE slot from every
        reachable backfill target; only then does data move. A target
        whose remote reserver is full delays its grant — this thread
        waits, which IS the cluster-wide throttle. All slots release
        on exit (success or failure)."""
        key = (pool, pgid)
        local_granted = threading.Event()
        self.local_reserver.request(key, 0, local_granted.set)
        remote_reserved: list[int] = []
        try:
            if not local_granted.wait(timeout=60):
                raise RuntimeError("local backfill slot never granted")
            spec0 = self.osdmap.pools[pool]
            targets = sorted(
                set(self.osdmap.pg_to_raw(pool, pgid, ignore_temp=True))
                - {SHARD_NONE, self.osd_id}
            )
            for osd in targets:
                if osd not in self.peers.avail_shards():
                    continue  # pushes to it will fail+retry anyway
                # track BEFORE the RPC: a timed-out request may still
                # be queued (or later granted) at the target — the
                # finally must release/cancel it either way, or the
                # slot leaks when this backfill never retries
                remote_reserved.append(osd)
                if not self.peers.reserve_backfill(
                    osd, spec0.pool_id, pgid, 0, timeout=60.0
                ):
                    raise RuntimeError(
                        f"osd.{osd} backfill reservation not granted"
                    )
            self._backfill_pg_reserved(pool, pgid, pg)
        except Exception:
            # survivors short / peer died / reservation timed out:
            # keep pg_temp (the PG stays served from the old layout);
            # tick() retries
            pg.backfilling = False
        finally:
            for osd in remote_reserved:
                try:
                    self.peers.release_backfill(
                        osd, spec0.pool_id, pgid
                    )
                except Exception:
                    pass
            self.local_reserver.release(key)

    def _backfill_pg_reserved(
        self, pool: str, pgid: int, pg: _PG
    ) -> None:
        from ceph_tpu.utils.optracker import op_tracker

        # one tracked op per backfill pass, each object move a marked
        # item: a wedged backfill shows WHERE it parked (scan, a
        # specific object's push, the final locked pass)
        top = op_tracker.register(
            "backfill", daemon=f"osd.{self.osd_id}",
            pool=pool, pgid=pgid,
        )
        try:
            spec = self.osdmap.pools[pool]
            # pass 1: scan + move everything currently known
            hints = self._backfill_scan(pool, pgid, spec, pg)
            top.mark_event("scanned", objects=len(hints))
            self.log.debug(
                "backfill pg", f"{pool}/{pgid}:", len(hints),
                "objects to place"
            )
            for oid in sorted(hints):
                # QoS: each object move admits through the backfill
                # class, at byte-proportional cost, so client IO keeps
                # its reservation
                self.admit(
                    "backfill", cost=_qos.op_cost(max(hints[oid], 0))
                )
                # clear the dirty flag BEFORE pushing: a client write
                # landing mid-push re-marks it and the final pass
                # re-pushes; discarding after would erase that evidence
                with self._pg_lock:
                    pg.backfill_dirty.discard(oid)
                top.mark_event("item", oid=oid)
                self._backfill_object(pool, pgid, pg, oid, hints[oid])
            # final pass: writes that landed mid-backfill, under the
            # op lock so nothing new sneaks in; then drop pg_temp
            top.mark_event("final_pass")
            with self._op_lock_for(pool, pgid):
                while True:
                    with self._pg_lock:
                        dirty = set(pg.backfill_dirty)
                        pg.backfill_dirty.clear()
                    if not dirty:
                        break
                    for oid in sorted(dirty):
                        self._backfill_object(pool, pgid, pg, oid)
                pg.backfilling = False
                pg.backfill_done = True  # _on_map drops, not re-temps
                self.monitor.pg_temp_clear(pool, pgid)
            self._backfill_gc(pool, pgid, pg, spec)
            top.finish("done")
        except Exception as e:
            # survivors short / peer died mid-pass: keep pg_temp (the
            # PG stays served from the old layout); tick() retries
            top.finish(f"error:{type(e).__name__}")
            pg.backfilling = False

    def _backfill_scan(
        self, pool: str, pgid: int, spec, pg: _PG,
        exclude: int | None = None,
    ) -> dict[str, int]:
        """Union of the PG's oids across my store and every reachable
        member of both layouts (old holders + targets with partial
        prior pushes), with the best known ro size per oid — the size
        hint covers objects the primary's own store is missing."""
        oids: dict[str, int] = {}
        for loc, _si in self._scan_pg_keys(spec.pool_id, spec.pg_num, pgid):
            oids[loc] = -1
        peers = (set(pg.acting) | set(
            self.osdmap.pg_to_raw(pool, pgid, ignore_temp=True)
        )) - {SHARD_NONE, self.osd_id, exclude}
        for osd in sorted(peers):
            if osd not in self.peers.avail_shards():
                continue
            try:
                for oid, _si, size, *_ev in self.peers.list_pg(
                    osd, spec.pool_id, spec.pg_num, pgid
                ):
                    oids[oid] = max(oids.get(oid, -1), size)
            except Exception:
                continue  # scan is best-effort; pushes verify reality
        return oids

    def _backfill_object(
        self, pool: str, pgid: int, pg: _PG, oid: str,
        size_hint: int = -1,
    ) -> None:
        """Push one object's shards to the CRUSH target layout."""
        from ceph_tpu.pipeline.read import (
            get_min_avail_to_read_shards,
            reconstruct_shards,
        )
        from ceph_tpu.pipeline.shard_map import ShardExtentMap

        target = self.osdmap.pg_to_raw(pool, pgid, ignore_temp=True)
        size = self._object_size(pg, oid)
        exists = bool(size) or self._have_object(pg, oid)
        if not exists and size_hint > 0:
            # a peer holds it even though my store doesn't (written
            # while my position was a hole): not a delete
            size, exists = size_hint, True
            pg.rmw.prime_object(oid, size)
        reachable = self.peers.avail_shards() | {self.osd_id}
        moves = [
            i for i, tgt in enumerate(target)
            if tgt != SHARD_NONE and tgt != pg.acting[i]
            and tgt in reachable  # a down target would wedge the push;
            # it catches up via log recovery when it returns
        ]
        if not moves:
            return
        if not exists:
            # removed mid-backfill: propagate the delete to targets
            for i in moves:
                self._push_delete(target[i], oid, i)
            return
        shard_len = pg.sinfo.object_size_to_shard_size(size, 0)
        want = {i: ExtentSet([(0, shard_len)]) for i in moves}
        avail = pg.backend.avail_shards()
        reads, need_decode = get_min_avail_to_read_shards(
            pg.sinfo, pg.codec, want, avail
        )
        smap = ShardExtentMap(pg.sinfo)
        for sr in reads.values():
            for start, buf in pg.backend.read_shard(
                sr.shard, oid, sr.extents
            ).items():
                smap.insert(sr.shard, start, buf)
        if need_decode:
            # reconstruct_shards, not a bare smap.decode: when the
            # plan carried CLAY sub-chunk selectors the survivors hold
            # only repair planes, which fractional repair consumes and
            # a windowed decode would mis-read as missing data
            reconstruct_shards(
                pg.sinfo, pg.codec, smap, want, reads, size
            )
        hinfo = pg.rmw.hinfo(oid)
        my_key = self._my_key(pg, oid)
        try:
            hinfo_bytes = (
                hinfo.to_bytes() if hinfo is not None
                else self.store.getattr(my_key, HINFO_KEY)
                if my_key is not None else None
            )
        except (FileNotFoundError, KeyError):
            hinfo_bytes = None
        user_attrs = self._replicated_attrs(pg, oid)
        for i in moves:
            key = shard_key(oid, i)
            buf = bytes(smap.get(i, 0, shard_len))
            txn = Transaction().touch(key).write(key, 0, buf)
            txn.truncate(key, shard_len)
            if hinfo_bytes is not None:
                txn.setattr(key, HINFO_KEY, hinfo_bytes)
            txn.setattr(
                key, OI_KEY,
                pack_oi(size, self._authoritative_eversion(pg, oid) or (0, 0)),
            )
            txn.setattr(key, SI_KEY, str(i).encode())
            for aname, aval in user_attrs.items():
                txn.setattr(key, aname, aval)
            self._push_shard_txn(target[i], txn)

    def _push_delete(self, osd: int, loc: str, shard: int) -> None:
        """Propagate a whole-object delete to one shard holder
        (touch+remove: no-op if the key never existed)."""
        key = shard_key(loc, shard)
        self._push_shard_txn(osd, Transaction().touch(key).remove(key))

    def _push_shard_txn(self, osd: int, txn) -> None:
        """Synchronous push to one osd (local or peer)."""
        if osd == self.osd_id:
            self.store.queue_transactions(txn)
            return
        done: list = []
        self.peers.submit_shard_txn(osd, txn, lambda: done.append(1))
        self.peers.drain_until(lambda: bool(done), timeout=self.op_timeout)

    def _backfill_gc(
        self, pool: str, pgid: int, pg: _PG, spec
    ) -> None:
        """Drop copies that don't belong to the new layout: ex-members
        lose all their pg keys; members that changed position lose the
        old position's key (shard-scoped keys make this precise)."""
        target = self.osdmap.pg_to_raw(pool, pgid, ignore_temp=True)
        members = (set(pg.acting) | set(target)) - {SHARD_NONE}
        for osd in sorted(members):
            if osd == self.osd_id:
                held = self._scan_pg_keys(spec.pool_id, spec.pg_num, pgid)
            else:
                if osd not in self.peers.avail_shards():
                    continue  # unreachable: stale copies are inert
                             # (shard keys can't be misread as current)
                try:
                    held = [
                        (loc, si) for loc, si, _sz, *_ev in self.peers.list_pg(
                            osd, spec.pool_id, spec.pg_num, pgid
                        )
                    ]
                except Exception:
                    continue
            for loc, si in held:
                keep = 0 <= si < len(target) and target[si] == osd
                if keep:
                    continue
                key = shard_key(loc, si)
                try:
                    self._push_shard_txn(
                        osd, Transaction().touch(key).remove(key)
                    )
                except Exception:
                    pass

    # -- deep scrub (be_deep_scrub over the wire + repair) --------------
    def scrub_pg(
        self, pool: str, pgid: int, repair: bool = False
    ) -> "list":
        """Deep-scrub every object of a PG I lead: read each live
        shard's hashed window, verify against the persisted HashInfo
        cumulative CRCs (ECBackend.cc:1829-1869 — the verify loop IS
        ``pipeline.recovery.be_deep_scrub``, run over the wire through
        an adapter), and with ``repair`` rebuild mismatched shards from
        the good ones. Objects are enumerated across MY store and every
        reachable member (the same union scan backfill uses) so a
        primary missing its own shard key still scrubs the object."""
        spec = self.osdmap.pools[pool]
        pg = self._get_pg(pool, pgid)
        locs = sorted(self._backfill_scan(pool, pgid, spec, pg))
        results = []
        for loc in locs:
            # deep scrub reads every live shard's payload: price the
            # sweep by object size, not per-object flat
            self.admit(
                "scrub", cost=_qos.op_cost(self._object_size(pg, loc))
            )
            # serialize with client ops: a scrub racing a mid-commit
            # write would see mixed-epoch shards and (with repair)
            # write the mixture back
            with self._op_lock_for(pool, pgid):
                results.append(self._scrub_object(pg, loc, repair))
        return results

    def _scrub_object(self, pg: _PG, oid: str, repair: bool):
        from ceph_tpu.pipeline.recovery import (
            ScrubError,
            ScrubResult,
            be_deep_scrub,
        )

        if not self._object_size(pg, oid) and not self._have_object(
            pg, oid
        ):
            # removed between enumeration and this lock: clean skip,
            # not an inconsistency
            return ScrubResult(oid)
        hinfo, dissent = self._consensus_hinfo(pg, oid)
        if hinfo is None:
            result = ScrubResult(oid)
            result.errors.append(ScrubError(
                -1, "hinfo_conflict" if dissent else "missing_attr"
            ))
            return result
        if dissent:
            self.log.info(
                "scrub", oid + ":", "hinfo dissent from shards", dissent,
                "- majority copy wins"
            )
        result = be_deep_scrub(
            pg.sinfo, _ScrubBackendView(pg), oid, hinfo=hinfo
        )
        bad = sorted({e.shard for e in result.errors if e.shard >= 0})
        if repair and bad:
            try:
                # the rebuilt shards must carry the ELECTED hinfo, not
                # whatever (possibly divergent) copy the rmw cache was
                # primed with — else the dissenting attr survives the
                # repair and every later scrub re-flags the shard
                pg.rmw.prime_object(
                    oid, self._object_size(pg, oid), hinfo
                )
                pg.recovery.recover_object(oid, set(bad))
                result.repaired = True
            except Exception as e:
                result.errors.append(ScrubError(-1, "read_error", str(e)))
        return result

    def _gather_hinfo_votes(
        self, pg: _PG, oid: str
    ) -> "dict[bytes, tuple[list[int], tuple[int, int]]]":
        """attr-bytes -> (holder positions, newest accompanying OI
        eversion). One concurrent fan-out: all remote fetches go out
        before any reply is awaited (no per-member round trips, no
        long _op_lock stalls on a slow peer). Members still under
        catch-up (backend.recovering) do not vote — their attrs are
        mid-replay by definition."""
        votes: dict[bytes, tuple[list[int], tuple[int, int]]] = {}

        def tally(pos: int, attrs: dict) -> None:
            raw = attrs.get(HINFO_KEY)
            if not raw:
                return
            ev = (0, 0)
            oi = attrs.get(OI_KEY)
            if oi:
                try:
                    _sz, ev = parse_oi(oi)
                except ValueError:
                    pass
            holders, best = votes.setdefault((bytes(raw)), ([], (0, 0)))
            holders.append(pos)
            votes[bytes(raw)] = (holders, max(best, ev))

        reachable = self.peers.avail_shards() | {self.osd_id}
        pending: set[int] = set()

        def on_reply(pos: int, reply) -> None:
            pending.discard(pos)
            if not isinstance(reply, Exception) and not reply.error:
                tally(pos, reply.attrs)

        for pos, osd in enumerate(pg.acting):
            if (
                osd == SHARD_NONE
                or osd not in reachable
                or pos in pg.backend.recovering
            ):
                continue
            key = shard_key(oid, pos)
            if osd == self.osd_id:
                try:
                    attrs = self.store.getattrs(key)
                    tally(pos, {
                        HINFO_KEY: attrs.get(HINFO_KEY),
                        OI_KEY: attrs.get(OI_KEY),
                    })
                except Exception:
                    pass  # corrupt/missing attrs: this shard abstains
                continue
            if self.peers.get_attrs_async(
                osd, key, [HINFO_KEY, OI_KEY],
                lambda r, p=pos: on_reply(p, r),
            ):
                pending.add(pos)
        if pending:
            try:
                self.peers.drain_until(
                    lambda: not pending, timeout=self.op_timeout
                )
            except TimeoutError:
                pass  # non-repliers abstain
        return votes

    def _consensus_hinfo(
        self, pg: _PG, oid: str
    ) -> "tuple[HashInfo | None, list[int]]":
        """(elected HashInfo, dissenting shard positions).

        Every shard's store carries its own copy of the object's
        HashInfo attr; trusting only the PRIMARY's copy lets a
        divergent ex-primary 'repair' the good majority into garbage.
        Election, in order (the auth_log_shard role scoped to the
        integrity attr scrub consumes):

        1. If this primary has LIVE history for the object (in-memory
           rmw state or an in-window pg log entry — trustworthy, unlike
           a cold-boot attr), the copy whose accompanying OI eversion
           matches it wins regardless of count: two stale copies must
           not outvote the one member holding the committed write.
        2. Otherwise plurality of the cast votes; a TIE elects nobody
           (hinfo_conflict, no repair) — a coin flip must never
           overwrite a good shard."""
        votes = self._gather_hinfo_votes(pg, oid)
        if not votes:
            return None, []
        # ONLY write-origin evidence anchors the election: rmw stamps
        # recorded by this pipeline's own writes, or in-window pg log
        # entries. object_eversion may be primed from the primary's
        # own cold attr — which is exactly what a divergent ex-primary
        # would use to elect itself.
        live_ev = pg.rmw.live_eversion(oid) or pg.pglog.last_eversion(oid)
        winner = None
        if live_ev is not None and live_ev != (0, 0):
            matching = [
                raw for raw, (_h, ev) in votes.items() if ev == live_ev
            ]
            if len(matching) == 1:
                winner = matching[0]
        if winner is None:
            counts = sorted(
                (len(h) for h, _ev in votes.values()), reverse=True
            )
            if len(counts) > 1 and counts[0] == counts[1]:
                return None, sorted(
                    pos for h, _ev in votes.values() for pos in h
                )
            winner = max(votes.items(), key=lambda kv: len(kv[1][0]))[0]
        dissent = sorted(
            pos for raw, (holders, _ev) in votes.items()
            if raw != winner for pos in holders
        )
        try:
            return HashInfo.from_bytes(winner), dissent
        except (TypeError, ValueError):
            return None, dissent

    def scrub_all(self, repair: bool = False) -> "dict":
        """Scrub every PG this daemon currently leads."""
        out = {}
        for pool, spec in self.osdmap.pools.items():
            for pgid in range(spec.pg_num):
                acting = self.osdmap.pg_to_up_acting(pool, pgid)
                primary = next(
                    (o for o in acting if o != SHARD_NONE), SHARD_NONE
                )
                if primary == self.osd_id:
                    out[(pool, pgid)] = self.scrub_pg(pool, pgid, repair)
        return out

    # -- failure detection ----------------------------------------------
    def report_down_peers(self) -> None:
        """Forward locally observed peer deaths to the monitor (the
        OSD→mon failure-report channel; OSDMonitor quorum-counts them)."""
        for osd in sorted(self.peers.down_shards):
            if self.osdmap.is_up(osd):
                self.monitor.report_failure(self.osd_id, osd)

    def __repr__(self) -> str:
        return f"OSDDaemon(osd.{self.osd_id}, e{self.osdmap.epoch})"
