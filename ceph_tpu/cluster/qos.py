"""Multi-tenant QoS plane — the osd_mclock / dmClock analog.

The scheduler core (utils/mclock.py) arbitrates named classes; this
module is everything that makes those classes MEAN something in a
multi-tenant cluster:

- **Tenant identity.**  A client opens ``open_ioctx(pool,
  tenant="gold")``; the tenant rides every op through the objecter and
  the OSD op wire format (``MOSDOp`` carries the entity the same way)
  and lands in a dynamic mClock class ``client.<tenant>`` —
  ``client.<pool>`` when untagged — so one flooding tenant queues
  behind its own tags, not everyone's (``client_class``).

- **QoS specs.**  ``QoSSpec`` declares reservation/weight/limit in
  ops/s AND bytes/s per pool or per tenant.  Both axes convert through
  the byte-cost quantum into the scheduler's single cost-unit clock:
  an op costs ``1 + nbytes/65536`` units (``op_cost``), so a spec's
  effective reservation is ``res_ops + res_bytes/65536`` units/s —
  guaranteed op quanta plus guaranteed byte quanta (the dmclock
  cost-per-io + cost-per-byte folding).  Specs live in pool metadata
  on the monitor (``PoolSpec.qos``, ``osd pool qos set``) and reach
  every OSD with the map push, so a spec change applies live.

- **The byte-cost model.**  ``op_cost`` prices client ops, recovery
  pushes, backfill items and scrub sweeps by payload size — a 4 MB
  push can no longer starve a 4 KB stat stream by costing the same.

- **The recovery-vs-client slosh knob.**  ``derive_profiles`` builds
  the base-class profile table from ``osd_mclock_profile``
  (high_client / balanced / high_recovery: fractions of
  ``osd_mclock_capacity``) and re-derives background reservations from
  MEASURED client demand: reservation capacity the clients aren't
  using sloshes to recovery/backfill instead of sitting idle (the
  reference's mclock profile auto-tuning role).

- **Observability.**  ``make_qos_perf`` builds the ``osd.N.qos``
  aggregate set; ``make_qos_class_perf`` builds per-class
  ``osd.N.qos.pool.<label>`` sets so the Prometheus exporter renders
  the tenant as a ``pool`` label (the round-15 suffix mechanism).
  The admin-socket ``dump_mclock`` (registered here, EC101: the utils
  tier never imports up) shows live per-class tags and queue depths
  for every registered daemon.
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass

from ceph_tpu.utils.mclock import ClientProfile

#: one cost unit per this many payload bytes (the 64 KiB the client
#: op path has always normalized against)
COST_QUANTUM_BYTES = 65536

#: slosh-knob presets: fraction of osd_mclock_capacity each base class
#: is guaranteed (res), its spare-capacity weight, and its cap (lim,
#: 0 = uncapped) — the osd_mclock_profile built-in profile shapes
MCLOCK_PROFILES: dict[str, dict[str, tuple[float, float, float]]] = {
    "high_client": {
        "client":   (0.80, 4.0, 0.0),
        "recovery": (0.10, 0.5, 0.20),
        "backfill": (0.05, 0.25, 0.10),
        "scrub":    (0.0, 0.1, 0.05),
        "gc":       (0.0, 0.1, 0.05),
    },
    "balanced": {
        "client":   (0.50, 2.0, 0.0),
        "recovery": (0.25, 1.0, 0.50),
        "backfill": (0.10, 0.5, 0.25),
        "scrub":    (0.0, 0.2, 0.10),
        "gc":       (0.0, 0.2, 0.10),
    },
    "high_recovery": {
        "client":   (0.30, 1.0, 0.0),
        "recovery": (0.60, 2.0, 0.0),
        "backfill": (0.20, 1.0, 0.50),
        "scrub":    (0.0, 0.2, 0.10),
        "gc":       (0.0, 0.2, 0.10),
    },
}


def op_cost(nbytes: int) -> float:
    """Byte-proportional mClock cost of one op: a base quantum for the
    fixed per-op work plus one unit per 64 KiB of payload."""
    return 1.0 + max(int(nbytes), 0) / COST_QUANTUM_BYTES


def client_class(tenant: str, pool: str) -> str:
    """The dynamic mClock class a client op schedules under:
    ``client.<tenant>`` when tagged, ``client.<pool>`` otherwise.
    Both inherit the base ``client`` profile until a QoS spec of their
    own lands (mclock ``_profile_for`` prefix resolution)."""
    return f"client.{tenant}" if tenant else f"client.{pool}"


def class_label(class_name: str) -> str:
    """The dot-free exporter label for a class: the tenant/pool part
    of a ``client.<x>`` class, the class name itself otherwise (the
    ``.pool.<label>`` suffix only splits when the label is dot-free)."""
    if "." in class_name:
        return class_name.split(".", 1)[1].replace(".", "_")
    return class_name


@dataclass(frozen=True)
class QoSSpec:
    """One pool's or tenant's QoS declaration: reservation / weight /
    limit with BOTH an ops/s and a bytes/s axis.  ``to_profile`` folds
    the axes into the scheduler's cost-unit clock (see module doc)."""

    res_ops: float = 0.0
    res_bytes: float = 0.0
    weight: float = 1.0
    lim_ops: float = 0.0
    lim_bytes: float = 0.0

    def to_profile(self) -> ClientProfile:
        res = self.res_ops + self.res_bytes / COST_QUANTUM_BYTES
        lim = self.lim_ops + self.lim_bytes / COST_QUANTUM_BYTES
        return ClientProfile(
            reservation=res, weight=max(self.weight, 1e-9), limit=lim,
        )

    def to_obj(self) -> dict:
        return {
            "res_ops": self.res_ops, "res_bytes": self.res_bytes,
            "weight": self.weight,
            "lim_ops": self.lim_ops, "lim_bytes": self.lim_bytes,
        }

    @classmethod
    def from_obj(cls, o: dict) -> "QoSSpec":
        return cls(
            res_ops=float(o.get("res_ops", 0.0)),
            res_bytes=float(o.get("res_bytes", 0.0)),
            weight=float(o.get("weight", 1.0)),
            lim_ops=float(o.get("lim_ops", 0.0)),
            lim_bytes=float(o.get("lim_bytes", 0.0)),
        )


def derive_profiles(
    profile_name: str,
    capacity: float,
    client_demand: float = 0.0,
) -> dict[str, ClientProfile]:
    """Build the base-class profile table for one slosh-knob setting.

    ``capacity`` is the daemon's notional service rate in cost units/s
    (``osd_mclock_capacity``); each preset guarantees fractions of it.
    ``client_demand`` is the MEASURED client service rate (cost
    units/s over the recent tick window): reservation capacity the
    clients demonstrably aren't using — ``client_res - demand``, never
    negative — is re-granted to recovery and backfill pro rata to
    their own reservations, so an idle cluster recovers at full tilt
    while a saturated one keeps the configured floor.  Monotone in the
    knob: high_client <= balanced <= high_recovery recovery rates for
    any fixed demand."""
    shape = MCLOCK_PROFILES.get(profile_name)
    if shape is None:
        raise ValueError(
            f"unknown mclock profile {profile_name!r} "
            f"(one of {sorted(MCLOCK_PROFILES)})"
        )
    capacity = max(capacity, 1.0)
    table: dict[str, ClientProfile] = {}
    for cls, (res_frac, wgt, lim_frac) in shape.items():
        table[cls] = ClientProfile(
            reservation=res_frac * capacity,
            weight=wgt,
            limit=lim_frac * capacity,
        )
    client_res = table["client"].reservation
    spare = max(client_res - max(client_demand, 0.0), 0.0)
    bg_res = (
        table["recovery"].reservation + table["backfill"].reservation
    )
    if spare > 0.0 and bg_res > 0.0:
        for cls in ("recovery", "backfill"):
            p = table[cls]
            grant = spare * (p.reservation / bg_res)
            lim = p.limit
            if lim > 0.0:
                lim = max(lim, p.reservation + grant)
            table[cls] = ClientProfile(
                reservation=p.reservation + grant,
                weight=p.weight, limit=lim,
            )
    return table


#: reservations may claim at most this fraction of the (measured)
#: capacity; the rest is the weight phase's guaranteed floor, so
#: weight-only classes can never be starved outright by oversubscribed
#: reservations (the dmClock paper's sum(rho_i) <= capacity admission
#: condition, enforced by scaling instead of rejecting)
RESERVATION_FRAC = 0.8


def normalize_reservations(
    table: dict[str, ClientProfile],
    capacity: float,
    frac: float = RESERVATION_FRAC,
) -> dict[str, ClientProfile]:
    """Scale every reservation down pro rata when their sum exceeds
    ``frac * capacity``.

    Reservations are promises against real service capacity; when the
    configured specs oversubscribe the *measured* rate (a 1000-unit/s
    notional capacity on a host that serves 80), the reservation phase
    never drains and weight-only classes starve until their clients
    time out and resend — the resend storm is the noisy-neighbor cliff
    this guard removes.  Weights and limits pass through untouched:
    only the constraint clocks are rescaled, so relative guarantees
    survive."""
    if capacity <= 0.0 or frac <= 0.0:
        return table
    total = sum(p.reservation for p in table.values())
    budget = frac * capacity
    if total <= budget:
        return table
    f = budget / total
    return {
        cls: ClientProfile(
            reservation=p.reservation * f,
            weight=p.weight, limit=p.limit,
        )
        for cls, p in table.items()
    }


# -- perf sets (EC103: counters declared through the builder) ----------
def make_qos_perf(name: str):
    """The ``osd.N.qos`` aggregate set: scheduler-wide dequeue /
    throttle / admit-timeout counters and queue-depth / tag-lag
    gauges (perf dump + exporter)."""
    from ceph_tpu.utils.perf_counters import (
        PerfCountersBuilder, perf_collection,
    )

    return (
        PerfCountersBuilder(perf_collection, name)
        .add_u64_counter(
            "dequeue_r", "ops dequeued in the reservation phase"
        )
        .add_u64_counter(
            "dequeue_p", "ops dequeued in the weight phase"
        )
        .add_u64_counter(
            "throttle", "dequeue stalls with every class limit-gated"
        )
        .add_u64_counter(
            "admit_timeout",
            "admit() waits that timed out and proceeded unthrottled",
        )
        .add_u64_gauge("queue_depth", "ops queued across all classes")
        .add_u64_gauge(
            "tag_lag_ms",
            "worst per-class head tag lag (ms behind its clocks)",
        )
        .add_u64_gauge(
            "qos_classes", "mClock classes with live queue state"
        )
        .add_u64_gauge(
            "capacity",
            "effective capacity (cost units/s) the profile table is "
            "derived against: osd_mclock_capacity clamped to the "
            "measured backlogged service rate (the osd bench "
            "auto-capacity analog)",
        )
        .create_perf_counters()
    )


def make_qos_class_perf(base: str, class_name: str):
    """One class's ``<base>.pool.<label>`` set — the exporter splits
    the suffix into a ``pool`` label, so per-tenant dequeue/throttle
    counters land as a proper Prometheus dimension."""
    from ceph_tpu.utils.perf_counters import (
        PerfCountersBuilder, perf_collection,
    )

    return (
        PerfCountersBuilder(
            perf_collection, f"{base}.pool.{class_label(class_name)}"
        )
        .add_u64_counter(
            "dequeue", "ops dequeued for this class (both phases)"
        )
        .add_u64_counter(
            "throttle", "dequeue stalls while this class was "
                        "limit-gated at the head"
        )
        .add_u64_counter(
            "admit_timeout", "admit() timeouts charged to this class"
        )
        .add_u64_gauge("queue_depth", "ops queued in this class")
        .create_perf_counters()
    )


# -- the dump_mclock admin surface -------------------------------------
#: daemon name -> its scheduler (weak: a stopped daemon drops out)
_schedulers: "weakref.WeakValueDictionary[str, object]" = (
    weakref.WeakValueDictionary()
)


def register_scheduler(daemon: str, scheduler) -> None:
    """Hang a daemon's scheduler on the ``dump_mclock`` surface."""
    _schedulers[daemon] = scheduler


def _register_admin() -> None:
    """``dump_mclock`` registers HERE (not in utils/admin_socket.py's
    builtins) so the utils tier never imports up into the cluster
    tier — ECLint EC101 pins that layering."""
    from ceph_tpu.utils.admin_socket import admin_socket

    def _dump(daemon=None):
        if daemon is not None:
            sched = _schedulers.get(str(daemon))
            return sched.dump() if sched is not None else {}
        return {
            name: sched.dump()
            for name, sched in sorted(_schedulers.items())
        }

    try:
        admin_socket.register(
            "dump_mclock", _dump,
            "live mClock state per daemon: per-class profiles, queue "
            "depths, head tags, tag lag and service counters",
        )
    except ValueError:
        pass  # already registered (module reloaded)


_register_admin()
