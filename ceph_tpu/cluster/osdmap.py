"""OSDMap — the epoch-versioned cluster map (src/osd/OSDMap.h).

Behavioral mirror of the reference's map contract:

- The map is an immutable value at an epoch; changes arrive as
  ``Incremental`` deltas (OSDMap::Incremental, src/osd/OSDMap.h:150)
  applied functionally: ``new_map = old_map.apply(incr)``.
- Devices carry the four orthogonal reference states: **up/down**
  (liveness — flips on failure, does NOT move data) and **in/out**
  (placement membership — flips rebalance data). A down-but-in OSD
  leaves a *hole* in an EC acting set (the CRUSH_ITEM_NONE shard,
  ``SHARD_NONE`` here), which is exactly what makes a PG degraded
  rather than remapped (OSDMap::pg_to_up_acting_osds,
  src/osd/OSDMap.h:1307).
- Pools bind a name/id to pg_num + an EC profile; profiles are
  key→value maps validated by the codec plugin at creation
  (ErasureCodeProfile, erasure-code/ErasureCodeInterface.h:167).
- Placement: object → PG by stable hash, PG → ordered device list by
  straw2 over in-devices (``placement.CrushMap``) — position i of the
  acting set is EC shard i (osd/ECSwitch.h:36-48 wiring).

Maps serialize to framed json (control-plane sizes are tiny) so the
monitor can publish them over the messenger tier.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace

from ceph_tpu.placement import CrushMap, Device, stable_hash

#: Acting-set hole: the shard's OSD is down (CRUSH_ITEM_NONE analog).
SHARD_NONE = -1


@dataclass(frozen=True)
class OSDInfo:
    """One device's map entry (osd_info_t + addrs + weights).
    ``new`` distinguishes a never-booted device (auto-marked in on
    first boot, mon_osd_auto_mark_new_in) from one an operator marked
    out — an OUT osd that reboots STAYS out until `osd in`."""

    id: int
    weight: float = 1.0
    zone: str = ""
    up: bool = False
    in_: bool = False
    addr: tuple[str, int] | None = None
    new: bool = True
    #: crush location, sorted (type, bucket) pairs — e.g.
    #: (("host", "h1"), ("rack", "r2")). Empty = flat placement.
    location: tuple[tuple[str, str], ...] = ()

    def to_obj(self) -> dict:
        return {
            "id": self.id,
            "weight": self.weight,
            "zone": self.zone,
            "up": self.up,
            "in": self.in_,
            "addr": list(self.addr) if self.addr else None,
            "new": self.new,
            "location": [list(kv) for kv in self.location],
        }

    @classmethod
    def from_obj(cls, o: dict) -> "OSDInfo":
        return cls(
            o["id"], o["weight"], o["zone"], o["up"], o["in"],
            tuple(o["addr"]) if o["addr"] else None,
            o.get("new", False),
            tuple(tuple(kv) for kv in o.get("location", ())),
        )


@dataclass(frozen=True)
class PoolSpec:
    """One pool (pg_pool_t): placement params + EC profile binding."""

    name: str
    pool_id: int
    pg_num: int
    profile_name: str
    k: int
    m: int
    plugin: str
    distinct_zones: bool = False
    #: named crush rule (OSDMap.crush_rules); empty = flat straw2
    crush_rule: str = ""
    #: pool snapshots: ((snapid, name, created_epoch), ...) ascending
    #: (pg_pool_t snaps); snap_seq is the next id to issue
    snaps: tuple[tuple[int, str, int], ...] = ()
    snap_seq: int = 0
    #: per-tenant QoS declarations riding the map to every OSD
    #: (cluster/qos.py QoSSpec rows): ((tenant, res_ops, res_bytes,
    #: weight, lim_ops, lim_bytes), ...) ascending by tenant; the
    #: ``""`` tenant is the pool-wide default (the class
    #: ``client.<pool>`` untagged ops fall back to)
    qos: tuple[tuple[str, float, float, float, float, float], ...] = ()

    @property
    def size(self) -> int:
        return self.k + self.m

    @property
    def min_size(self) -> int:
        """Fewest live shards that still allow serving IO (k, as the
        reference defaults EC min_size to k... + 1 is advisory)."""
        return self.k

    def to_obj(self) -> dict:
        return {
            "name": self.name,
            "pool_id": self.pool_id,
            "pg_num": self.pg_num,
            "profile_name": self.profile_name,
            "k": self.k,
            "m": self.m,
            "plugin": self.plugin,
            "distinct_zones": self.distinct_zones,
            "crush_rule": self.crush_rule,
            "snaps": [list(s) for s in self.snaps],
            "snap_seq": self.snap_seq,
            "qos": [list(q) for q in self.qos],
        }

    @classmethod
    def from_obj(cls, o: dict) -> "PoolSpec":
        return cls(
            o["name"], o["pool_id"], o["pg_num"], o["profile_name"],
            o["k"], o["m"], o["plugin"], o["distinct_zones"],
            o.get("crush_rule", ""),
            tuple(tuple(s) for s in o.get("snaps", ())),
            o.get("snap_seq", 0),
            tuple(tuple(q) for q in o.get("qos", ())),
        )


@dataclass(frozen=True)
class Incremental:
    """Epoch delta (OSDMap::Incremental). Field semantics:

    - ``new_osds``: add/replace device entries (boot, crush add,
      reweight — the full entry travels; maps are small).
    - ``down`` / ``up`` / ``out`` / ``in_``: state flips by id.
    - ``new_pools`` / ``removed_pools``, ``new_profiles``.
    """

    epoch: int  # the epoch this incremental PRODUCES
    new_osds: tuple[OSDInfo, ...] = ()
    up: tuple[int, ...] = ()
    down: tuple[int, ...] = ()
    in_: tuple[int, ...] = ()
    out: tuple[int, ...] = ()
    new_pools: tuple[PoolSpec, ...] = ()
    removed_pools: tuple[str, ...] = ()
    new_profiles: tuple[tuple[str, tuple[tuple[str, str], ...]], ...] = ()
    #: pg_temp installs: ((pool, pgid, (osd, ...)), ...) — the PG
    #: serves from this membership until backfill completes
    new_pg_temp: tuple[tuple[str, int, tuple[int, ...]], ...] = ()
    del_pg_temp: tuple[tuple[str, int], ...] = ()
    #: crush rule installs: ((name, ((step, ...), ...)), ...)
    new_rules: tuple[tuple[str, tuple[tuple, ...]], ...] = ()
    #: central config db edits: ((who, name, value-or-None), ...) —
    #: the ConfigMonitor analog (mon/ConfigMonitor.h:15). ``who`` is
    #: "" (global), "osd" (class), or "osd.N"; None value removes.
    #: Riding the map incremental gives the config db the same
    #: Paxos replication, epoch ordering, and subscription push the
    #: map itself has (the reference pairs MConfig with MOSDMap on
    #: the same monitor).
    new_config: tuple[tuple[str, str, "str | None"], ...] = ()

    def to_bytes(self) -> bytes:
        return json.dumps({
            "epoch": self.epoch,
            "new_osds": [o.to_obj() for o in self.new_osds],
            "up": list(self.up),
            "down": list(self.down),
            "in": list(self.in_),
            "out": list(self.out),
            "new_pools": [p.to_obj() for p in self.new_pools],
            "removed_pools": list(self.removed_pools),
            "new_profiles": [
                [n, [list(kv) for kv in prof]] for n, prof in self.new_profiles
            ],
            "new_pg_temp": [
                [pool, pgid, list(acting)]
                for pool, pgid, acting in self.new_pg_temp
            ],
            "del_pg_temp": [list(k) for k in self.del_pg_temp],
            "new_rules": [
                [n, [list(s) for s in steps]]
                for n, steps in self.new_rules
            ],
            "new_config": [list(c) for c in self.new_config],
        }).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Incremental":
        o = json.loads(raw.decode())
        return cls(
            o["epoch"],
            tuple(OSDInfo.from_obj(x) for x in o["new_osds"]),
            tuple(o["up"]),
            tuple(o["down"]),
            tuple(o["in"]),
            tuple(o["out"]),
            tuple(PoolSpec.from_obj(x) for x in o["new_pools"]),
            tuple(o["removed_pools"]),
            tuple(
                (n, tuple(tuple(kv) for kv in prof))
                for n, prof in o["new_profiles"]
            ),
            tuple(
                (pool, pgid, tuple(acting))
                for pool, pgid, acting in o.get("new_pg_temp", ())
            ),
            tuple(tuple(k) for k in o.get("del_pg_temp", ())),
            tuple(
                (n, tuple(tuple(s) for s in steps))
                for n, steps in o.get("new_rules", ())
            ),
            tuple(
                (who, name, val)
                for who, name, val in o.get("new_config", ())
            ),
        )


class OSDMap:
    """Immutable cluster map at one epoch."""

    def __init__(
        self,
        epoch: int = 0,
        osds: dict[int, OSDInfo] | None = None,
        pools: dict[str, PoolSpec] | None = None,
        profiles: dict[str, dict[str, str]] | None = None,
        pg_temp: dict[tuple[str, int], tuple[int, ...]] | None = None,
        crush_rules: dict[str, tuple] | None = None,
        config: dict[tuple[str, str], str] | None = None,
    ) -> None:
        self.epoch = epoch
        self.osds: dict[int, OSDInfo] = dict(osds or {})
        self.pools: dict[str, PoolSpec] = dict(pools or {})
        self.profiles: dict[str, dict[str, str]] = {
            k: dict(v) for k, v in (profiles or {}).items()
        }
        #: (pool, pgid) -> temporary membership serving the PG while
        #: backfill moves data to the CRUSH mapping (OSDMap pg_temp)
        self.pg_temp: dict[tuple[str, int], tuple[int, ...]] = dict(
            pg_temp or {}
        )
        #: named multi-step crush rules (crush_do_rule programs)
        self.crush_rules: dict[str, tuple] = {
            n: tuple(tuple(s) for s in steps)
            for n, steps in (crush_rules or {}).items()
        }
        #: central config db: (who, name) -> value — the mon-
        #: replicated option store (ConfigMonitor analog); daemons
        #: apply their slice into the process config's "mon" layer on
        #: every map they learn
        self.config: dict[tuple[str, str], str] = dict(config or {})
        # straw2 input: in-devices with positive weight. Down-but-in
        # devices STAY (holes, not movement).
        self._crush = CrushMap([
            Device(o.id, o.weight, o.zone)
            for o in self.osds.values()
            if o.in_ and o.weight > 0
        ])
        # Bucket hierarchy for rule-based pools: built from device
        # locations (out devices excluded — they contribute no
        # weight anywhere, so whole subtrees can empty out).
        # Non-strict: a historical map must always LOAD; the monitor
        # rejects conflicting locations at command time.
        from ceph_tpu.crush import CrushHierarchy

        self._hierarchy = CrushHierarchy(strict=False)
        for o in self.osds.values():
            if o.in_ and o.weight > 0:
                self._hierarchy.add_device(
                    Device(o.id, o.weight, o.zone), dict(o.location)
                )

    # -- placement arithmetic ------------------------------------------
    def object_to_pg(self, pool: str, oid: str) -> int:
        spec = self._pool(pool)
        return stable_hash(str(spec.pool_id), oid) % spec.pg_num

    def pg_to_raw(
        self, pool: str, pg: int, ignore_temp: bool = False
    ) -> list[int]:
        """Membership for a PG, ignoring up/down: position i is EC
        shard i. A pg_temp override wins (the PG serves from its OLD
        layout while backfill runs); ``ignore_temp`` asks for the pure
        CRUSH mapping — the backfill TARGET. This is the REBALANCE
        identity — it changes only when devices are added/removed/
        reweighted/outed (or pg_temp flips), never on a liveness flip,
        so callers can tell 'same members, one down' (heal + log
        recovery) from 'different members' (backfill). Short when the
        cluster has fewer in-devices than k+m."""
        spec = self._pool(pool)
        if not ignore_temp:
            temp = self.pg_temp.get((pool, pg))
            if temp is not None:
                return list(temp)
        if spec.crush_rule and spec.crush_rule in self.crush_rules:
            raw = self._hierarchy.run_rule(
                self.crush_rules[spec.crush_rule],
                (stable_hash(str(spec.pool_id), pg),),
                spec.size,
            )
        else:
            n = min(spec.size, len(self._crush.devices))
            raw = self._crush.select(
                stable_hash(str(spec.pool_id), pg),
                n,
                distinct_zones=spec.distinct_zones,
            ) if n else []
        return raw + [SHARD_NONE] * (spec.size - len(raw))

    def pg_to_up_acting(self, pool: str, pg: int) -> list[int]:
        """Ordered acting set for a PG; position i is EC shard i. Down
        OSDs appear as ``SHARD_NONE`` holes (degraded, not remapped).
        When fewer in-devices exist than k+m, the tail positions are
        holes too (the undersized-PG state — CRUSH simply runs out)."""
        return [
            o if o != SHARD_NONE and self.osds[o].up else SHARD_NONE
            for o in self.pg_to_raw(pool, pg)
        ]

    def object_to_acting(self, pool: str, oid: str) -> list[int]:
        return self.pg_to_up_acting(pool, self.object_to_pg(pool, oid))

    def pg_primary(self, pool: str, pg: int) -> int:
        """First live shard-holder of a PG (the EC primary rule);
        SHARD_NONE if every acting shard is down. THE primary
        selection — client targeting and OSD self-identification must
        agree for the eagain retry contract to converge."""
        for o in self.pg_to_up_acting(pool, pg):
            if o != SHARD_NONE:
                return o
        return SHARD_NONE

    def primary(self, pool: str, oid: str) -> int:
        return self.pg_primary(pool, self.object_to_pg(pool, oid))

    def _pool(self, pool: str) -> PoolSpec:
        spec = self.pools.get(pool)
        if spec is None:
            raise KeyError(f"no such pool: {pool!r}")
        return spec

    # -- state queries --------------------------------------------------
    def is_up(self, osd: int) -> bool:
        return osd in self.osds and self.osds[osd].up

    def get_addr(self, osd: int) -> tuple[str, int] | None:
        info = self.osds.get(osd)
        return info.addr if info else None

    def up_osds(self) -> set[int]:
        return {o.id for o in self.osds.values() if o.up}

    # -- evolution ------------------------------------------------------
    def apply(self, incr: Incremental) -> "OSDMap":
        if incr.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental {incr.epoch} does not follow epoch {self.epoch}"
            )
        osds = dict(self.osds)
        for o in incr.new_osds:
            osds[o.id] = o
        for i in incr.up:
            osds[i] = replace(osds[i], up=True)
        for i in incr.down:
            osds[i] = replace(osds[i], up=False)
        for i in incr.in_:
            osds[i] = replace(osds[i], in_=True)
        for i in incr.out:
            osds[i] = replace(osds[i], in_=False)
        pools = dict(self.pools)
        for p in incr.new_pools:
            pools[p.name] = p
        for name in incr.removed_pools:
            pools.pop(name, None)
        profiles = {k: dict(v) for k, v in self.profiles.items()}
        for name, prof in incr.new_profiles:
            profiles[name] = dict(prof)
        pg_temp = dict(self.pg_temp)
        for pool, pgid, acting in incr.new_pg_temp:
            pg_temp[(pool, pgid)] = tuple(acting)
        for key in incr.del_pg_temp:
            pg_temp.pop(tuple(key), None)
        for name in incr.removed_pools:
            pg_temp = {
                k: v for k, v in pg_temp.items() if k[0] != name
            }
        rules = dict(self.crush_rules)
        for name, steps in incr.new_rules:
            rules[name] = tuple(tuple(s) for s in steps)
        cfg = dict(self.config)
        for who, name, val in incr.new_config:
            if val is None:
                cfg.pop((who, name), None)
            else:
                cfg[(who, name)] = val
        return OSDMap(
            self.epoch + 1, osds, pools, profiles, pg_temp, rules, cfg
        )

    # -- serialization --------------------------------------------------
    def to_bytes(self) -> bytes:
        return json.dumps({
            "epoch": self.epoch,
            "osds": [o.to_obj() for o in self.osds.values()],
            "pools": [p.to_obj() for p in self.pools.values()],
            "profiles": self.profiles,
            "pg_temp": [
                [pool, pgid, list(acting)]
                for (pool, pgid), acting in self.pg_temp.items()
            ],
            "crush_rules": [
                [n, [list(s) for s in steps]]
                for n, steps in self.crush_rules.items()
            ],
            "config": [
                [who, name, val]
                for (who, name), val in self.config.items()
            ],
        }).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "OSDMap":
        o = json.loads(raw.decode())
        return cls(
            o["epoch"],
            {x["id"]: OSDInfo.from_obj(x) for x in o["osds"]},
            {x["name"]: PoolSpec.from_obj(x) for x in o["pools"]},
            o["profiles"],
            {
                (pool, pgid): tuple(acting)
                for pool, pgid, acting in o.get("pg_temp", ())
            },
            {
                n: tuple(tuple(s) for s in steps)
                for n, steps in o.get("crush_rules", ())
            },
            {
                (who, name): val
                for who, name, val in o.get("config", ())
            },
        )

    def __repr__(self) -> str:
        up = sum(1 for o in self.osds.values() if o.up)
        return (
            f"OSDMap(e{self.epoch}, {len(self.osds)} osds ({up} up), "
            f"{len(self.pools)} pools)"
        )
