r"""Per-PG peering state machine — the ``PeeringState.cc`` analog.

Round 8 found (and round 12 pins) the cost of *implicit* peering: the
election, the self-rewind, the returning-member catch-up and the
interval fences lived as cooperating threads inside ``osd_daemon.py``,
composed only by locks and flags (``_peering``/``_repeer``/the
``peered`` Event). Under churn the composition raced — most visibly,
a daemon whose OWN position healed after a down/up flap treated itself
as a returning *member* and ran the replica catch-up against itself
(``peers.list_pg(self)``, an RPC to nobody), failed, and reverted its
own primary position to a hole: every committed object then answered
ENOENT and writes tore stripes around the phantom hole (ROADMAP #1's
"zeros-head torn write_full / committed-read ENOENT").

This module makes the composition *explicit*: one small state machine
per PG, where every map-epoch advance, kick, retry and catch-up
completion is an **event** processed by at most one drainer thread at
a time. Interleavings that used to need careful locking are now
impossible to express — a catch-up admission cannot overlap an
election, a gate cannot open with an interval event still queued, and
a daemon's own healed position is re-admitted by the election that
judged its store, never by a peer RPC to itself.

State map (reference analogs, osd/PeeringState.{h,cc}):

====================  ==================================================
state                 PeeringState.cc analog
====================  ==================================================
``reset``             Reset — interval accepted, per-interval state torn
                      down (``on_new_interval``)
``getinfo``           Peering/GetInfo — query every up member for its
                      pg_info (les, last_update); answering fences the
                      member against older-interval sub-writes
                      (``require_same_or_newer_map``)
``getlog``            Peering/GetLog — ``find_best_info`` (:1565): elect
                      the authoritative log over (les, last_update)
``getmissing``        Peering/GetMissing — reconcile SELF against the
                      elected authority: divergent objects roll back,
                      divergent creates are removed
                      (``PGLog::rewind_divergent_log``), objects the
                      authority committed while this primary was away
                      are rebuilt into its store (the pg_missing_t
                      recovery set, collapsed to synchronous repair),
                      and each repaired object adopts the authority's
                      HashInfo + reqid-window attrs (rebuilds verify
                      against the elected truth; stale windows would
                      re-seed ancient suspect reqids that classify
                      ambiguous forever)
``activating``        Active/Activating — les := interval epoch, durable
                      on self and every reachable member (the MOSDPGLog
                      activation push)
``active``            Active — gate open, serving; the primary drains
                      every ``recovering`` mark it now owns by driving
                      the member catch-ups itself (the peering ->
                      recovery handoff; only the serving primary pushes,
                      and its pushes serialize with its own live writes
                      under the op lock)
``replica``           Started/ReplicaActive — not the serving primary
                      this interval; trivially peered (sub-ops are
                      driven by the peered primary)
``down``              Down — fewer live members than k: nothing can be
                      served or judged until the map changes
``incomplete``        Incomplete — the election could not complete
                      (no votes, interval moved mid-pass, transition
                      fault); the gate stays closed and the tick retries
====================  ==================================================

Transitions::

                       map_advance / kick
                             |
                             v
        +------------------ reset ------------------+
        |                    |                      |
        | (not primary)      | (primary, live>=k)   | (live<k)
        v                    v                      v
     replica              getinfo                 down
        ^                    |        \
        |                    v         \ (no votes / moved)
        |                 getlog -------> incomplete <--- (fault)
        |                    |                ^  (tick retry
        |     (lost election)|                |   re-enters reset)
        |                    v                |
        |               getmissing -----------+
        |                    |
        |                    v
        |               activating -----------+
        |                    |
        |                    v
        +<--------------- active  <--- catchup_done admits members

Election replies are gathered synchronously *inside* the GetInfo
transition — the transition is atomic with respect to every other
event, which is the serialization that matters; a map advance arriving
mid-gather queues behind the pass and re-runs it from ``reset``.

Crash points: every transition passes named yield points
(``peering.<state>.<point>``; ``catchup.*`` fire on the legacy path
too) through the process-global :data:`crash_points` registry, in the
spirit of ``loadgen/faults.py``'s op-offset hooks — tests arm a point
to pause (and later release), fail the transition, kill the daemon, or
run a callback, turning 1-in-20 loadgen interleavings into pinned,
repeatable regression tests.

The pre-refactor thread-and-flags peering (the ``osd_peering_fsm=
false`` bisection escape hatch) was folded out in round 16 after four
rounds of green soaks — the FSM is the only peering driver, which is
also what keeps the lockdep certification surface single
(ROADMAP closeout 1b).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ceph_tpu.utils.lockdep import DebugLock

from .osdmap import SHARD_NONE

# -- states --------------------------------------------------------------
RESET = "reset"
GETINFO = "getinfo"
GETLOG = "getlog"
GETMISSING = "getmissing"
ACTIVATING = "activating"
ACTIVE = "active"
REPLICA = "replica"
DOWN = "down"
INCOMPLETE = "incomplete"

STATES = (
    RESET, GETINFO, GETLOG, GETMISSING, ACTIVATING, ACTIVE,
    REPLICA, DOWN, INCOMPLETE,
)

#: state dwell-time histogram bounds, ms (log2)
_DWELL_BUCKETS_MS = [0.25 * (1 << i) for i in range(16)]


def make_peering_perf(name: str):
    """The per-daemon ``peering`` counter set (``perf dump`` section
    ``osd.<id>.peering``, Prometheus via the exporter): elections run,
    self-rewinds, sub-writes rejected by the interval fence, per-state
    dwell times and whole-pass peering latency."""
    from ceph_tpu.utils import PerfCountersBuilder, perf_collection

    return (
        PerfCountersBuilder(perf_collection, name)
        .add_u64_counter(
            "elections_run",
            "authoritative-log elections run (GetInfo rounds)",
        )
        .add_u64_counter(
            "rewinds",
            "elections this daemon lost and reconciled itself "
            "against the winner (GetMissing passes)",
        )
        .add_u64_counter(
            "interval_fences_rejected",
            "sub-writes rejected for carrying a superseded interval "
            "epoch (same_interval_since discards)",
        )
        .add_histogram(
            "state_dwell_ms", _DWELL_BUCKETS_MS,
            "time spent in each peering state, ms (log2 buckets)",
        )
        .add_avg(
            "peering_ms",
            "interval-accepted to gate-open, ms, per completed pass",
        )
        .create_perf_counters()
    )


# -- crash-point fault injection -----------------------------------------
# The registry moved to the neutral utils layer (round 13) so the RMW
# pipeline fires points too without a pipeline -> cluster import; the
# peering surface re-exports it unchanged (same singleton object).
from ceph_tpu.utils.crash_points import (  # noqa: F401  (re-export)
    ArmedPoint,
    CrashPointAbort,
    CrashPointRegistry,
    crash_points,
)


# -- the per-PG state machine --------------------------------------------
class PgPeeringFsm:
    """One PG's peering driver. Events (``map_advance``, ``kick``,
    ``retry``, ``catchup_admit``) enqueue via :meth:`post`; a single
    drainer thread at a time processes them in order, so transitions
    never overlap. The ``peered`` gate on the PG stays the op-path
    surface — this machine is the only writer of it."""

    def __init__(self, daemon, pg) -> None:
        from .osd_daemon import first_live

        self.daemon = daemon
        self.pg = pg
        # born in role: a non-primary instance is trivially peered
        # from construction (its gate is pre-set by the _PG ctor) and
        # may never receive an event until the next interval
        self.state = (
            RESET if first_live(pg.acting) == daemon.osd_id
            else REPLICA
        )
        self._mu = DebugLock("osd.peering_events")
        self._events: deque = deque()
        self._draining = False
        self._entered_at = time.monotonic()
        self._pass_started = None  # monotonic, reset -> active timing
        #: transition trail (bounded) — test/debug observability
        self.history: deque = deque(maxlen=64)
        #: live tracked op of the pass in flight (dump_ops_in_flight
        #: shows a wedged election with its state timeline)
        self._pass_top = None

    # -- event surface --------------------------------------------------
    def post_interval(self) -> None:
        """An interval change (map advance / kick). The gate flips
        synchronously — callers rely on ops eagain-ing the moment the
        interval moves, exactly like the legacy ``_kick_peering`` —
        and the election pass runs from the drainer."""
        d, pg = self.daemon, self.pg
        from .osd_daemon import first_live

        if first_live(pg.acting) == d.osd_id:
            pg.peered.clear()
        else:
            pg.peered.set()
        self.post("map_advance")

    def post(self, kind: str, **kw) -> None:
        with self._mu:
            self._events.append((kind, kw))
            if self._draining:
                return
            self._draining = True
        threading.Thread(
            target=self._drain, daemon=True,
            name=f"peering-osd.{self.daemon.osd_id}-"
                 f"{self.pg.pool}.{self.pg.pgid}",
        ).start()

    def admit_caught_up(self, shard: int, timeout: float = 30.0) -> bool:
        """Catch-up completion as an event: the final clean-check and
        admission run on the drainer, serialized with elections (a
        member can never be admitted mid-judgment). Returns False when
        the FSM is not serving (interval moved — the caller reverts
        the position to a hole and the tick re-heals it under the new
        interval)."""
        done = threading.Event()
        res: list[bool] = []
        self.post("catchup_admit", shard=shard, done=done, res=res)
        if not done.wait(timeout):
            return False
        return bool(res and res[0])

    # -- drainer ---------------------------------------------------------
    def _drain(self) -> None:
        while True:
            with self._mu:
                if not self._events or self.daemon._stopped:
                    self._events.clear()
                    self._draining = False
                    return
                kind, kw = self._events.popleft()
            try:
                if kind == "catchup_admit":
                    self._handle_admit(**kw)
                else:
                    self._run_tracked_pass()
            except Exception as e:
                self.daemon.log.error(
                    "pg", f"{self.pg.pool}/{self.pg.pgid}:",
                    "peering pass failed",
                    f"({type(e).__name__}: {e}); gate stays closed",
                )
                from ceph_tpu.utils.cluster_log import cluster_log

                cluster_log.log(
                    f"osd.{self.daemon.osd_id}", "peering_stalled",
                    f"pg {self.pg.pool}/{self.pg.pgid} peering pass "
                    f"failed ({type(e).__name__}: {e}); gate stays "
                    "closed",
                    severity="WRN", epoch=self.daemon.osdmap.epoch,
                )
                self._enter(INCOMPLETE)

    def _run_tracked_pass(self) -> None:
        """One peering pass as a live tracked op: every state entry is
        a mark_event, so a pass wedged mid-election shows up in
        dump_ops_in_flight with exactly where it is parked."""
        from ceph_tpu.utils.optracker import op_tracker

        with op_tracker.track(
            "peering", daemon=f"osd.{self.daemon.osd_id}",
            pool=self.pg.pool, pgid=self.pg.pgid,
        ) as top:
            self._pass_top = top
            try:
                self._peer_pass()
            finally:
                self._pass_top = None

    def _enter(self, state: str) -> None:
        now = time.monotonic()
        dwell_ms = (now - self._entered_at) * 1e3
        try:
            self.daemon.peering_pc.hinc("state_dwell_ms", dwell_ms)
        except Exception:
            pass  # counters must never fault a transition
        if self._pass_top is not None:
            self._pass_top.mark_event(state)
        self.history.append((self.state, state))
        self.state = state
        self._entered_at = now

    def _interval_moved(self, epoch0: int, acting0: list) -> bool:
        return (
            self.daemon.osdmap.epoch != epoch0
            or list(self.pg.acting) != acting0
        )

    # -- the peering pass (reset -> ... -> active) -----------------------
    def _peer_pass(self) -> None:
        d, pg = self.daemon, self.pg
        if d._stopped:
            return
        self._enter(RESET)
        self._pass_started = time.monotonic()
        crash_points.fire("peering.reset", daemon=d, pg=pg)
        with d._pg_lock:
            acting0 = list(pg.acting)
            epoch0 = d.osdmap.epoch
        spec = d.osdmap.pools.get(pg.pool)
        from .osd_daemon import first_live

        if spec is None:
            self._enter(DOWN)  # pool deleted under the PG
            return
        if first_live(acting0) != d.osd_id:
            # not the serving primary this interval: trivially peered
            # (the primary's election judges this member; sub-ops are
            # fenced by epoch, not by this gate)
            self._enter(REPLICA)
            self._admit_self_positions(acting0)
            pg.peered.set()
            return
        # electing: the gate is closed for the whole pass. Interval
        # events already closed it synchronously; tick retries and
        # self-heal re-kicks close it here so a rewind can never race
        # in-flight client ops.
        pg.peered.clear()
        live = sum(1 for o in acting0 if o != SHARD_NONE)
        if live < pg.rmw.sinfo.k:
            # Down: too few members to serve OR to judge — reads
            # could not decode and an election over < k members
            # cannot establish authority. Ops eagain until a map
            # brings members back.
            self._enter(DOWN)
            from ceph_tpu.utils.cluster_log import cluster_log

            cluster_log.log(
                f"osd.{d.osd_id}", "pg_down",
                f"pg {pg.pool}/{pg.pgid} down: {live} live members "
                f"< k={pg.rmw.sinfo.k}",
                severity="WRN", epoch=epoch0,
            )
            return

        # -- GetInfo: fence + query every votable member ----------------
        self._enter(GETINFO)
        crash_points.fire(
            "peering.getinfo.pre_fence", daemon=d, pg=pg, epoch=epoch0
        )
        try:
            my_pos = acting0.index(d.osd_id)
        except ValueError:
            self._enter(INCOMPLETE)
            return
        d.peering_pc.inc("elections_run")
        infos: dict[int, tuple[int, tuple[int, int]]] = {}
        for idx, osd in enumerate(acting0):
            if osd == SHARD_NONE:
                continue
            if (
                idx in pg.backend.recovering
                and osd != d.osd_id
            ):
                # mid-catch-up member: its stamps are mid-JUDGMENT;
                # it votes again once admitted (via catchup_admit,
                # which this queue serializes after us)
                continue
            if osd == d.osd_id:
                d._bump_fence(spec.pool_id, pg.pgid, epoch0)
                infos[osd] = d._own_pg_info(
                    spec.pool_id, spec.pg_num, pg.pgid
                )
                continue
            try:
                infos[osd] = d.peers.get_pg_info(
                    osd, spec.pool_id, spec.pg_num, pg.pgid,
                    epoch=epoch0,
                )
            except Exception:
                continue  # down members don't vote
        crash_points.fire(
            "peering.getinfo.queried", daemon=d, pg=pg, infos=infos
        )
        if d.osd_id not in infos:
            self._enter(INCOMPLETE)
            return

        # -- GetLog: elect the authoritative log ------------------------
        self._enter(GETLOG)
        best = max(
            infos, key=lambda o: (infos[o], o == d.osd_id, -o)
        )
        crash_points.fire(
            "peering.getlog.elected", daemon=d, pg=pg, best=best
        )
        if self._interval_moved(epoch0, acting0):
            self._enter(INCOMPLETE)  # the queued advance re-runs
            return

        # -- GetMissing: reconcile self against the winner --------------
        adopted: dict = {}
        if best != d.osd_id and infos[best] > infos[d.osd_id]:
            self._enter(GETMISSING)
            d.log.info(
                "pg", f"{pg.pool}/{pg.pgid}:", "peering: osd.", best,
                "has the authoritative log", infos[best],
                "over mine", infos[d.osd_id], "- reconciling self"
            )
            crash_points.fire(
                "peering.getmissing.pre_rewind", daemon=d, pg=pg,
                best=best,
            )
            adopted = self._recover_from_authority(
                spec, my_pos, best
            )
            crash_points.fire(
                "peering.getmissing.post_rewind", daemon=d, pg=pg
            )

        # -- Activating: les := epoch, durable everywhere ---------------
        self._enter(ACTIVATING)
        if self._interval_moved(epoch0, acting0):
            self._enter(INCOMPLETE)
            return
        crash_points.fire(
            "peering.activating.pre_les", daemon=d, pg=pg, epoch=epoch0
        )
        d._pgmeta_write_les(
            spec.pool_id, pg.pgid, epoch0, acting=acting0
        )
        for osd in acting0:
            if osd in (SHARD_NONE, d.osd_id):
                continue
            try:
                d.peers.activate_pg(osd, spec.pool_id, pg.pgid, epoch0)
            except Exception:
                pass  # a partitioned member keeps its old les — that
                #       is what future elections rank it down by
        crash_points.fire(
            "peering.activating.post_les", daemon=d, pg=pg
        )

        # -- Active: gate-open, atomic wrt queued interval events -------
        with self._mu:
            if any(k != "catchup_admit" for k, _ in self._events):
                # a newer interval is already queued: opening the
                # gate now would serve exactly the unpeered window
                # this machine exists to prevent
                self._enter(INCOMPLETE)
                return
            if self._interval_moved(epoch0, acting0):
                self._enter(INCOMPLETE)
                self._events.append(("retry", {}))
                return
            self._enter(ACTIVE)
            # serve the NEW interval from the store, not the last
            # primacy's in-memory projections...
            pg.rmw.on_interval_change()
            # ...then re-adopt the elected authority's knowledge: the
            # wipe above must not un-know objects committed while this
            # primary was away (their absence from MY store would
            # otherwise answer committed reads with ENOENT)
            for loc, (size, aev) in adopted.items():
                if aev != (0, 0):
                    pg.rmw.prime_object(
                        loc, max(size, 0), eversion=aev
                    )
            self._admit_self_positions(acting0)
            pg.peered.set()
            if self._pass_started is not None:
                d.peering_pc.ainc(
                    "peering_ms",
                    (time.monotonic() - self._pass_started) * 1e3,
                )
        d.log.info(
            "pg", f"{pg.pool}/{pg.pgid}:", "peered at epoch", epoch0,
            "(authority: osd.", best, ")"
        )
        from ceph_tpu.utils.cluster_log import cluster_log

        cluster_log.log(
            f"osd.{d.osd_id}", "pg_peered",
            f"pg {pg.pool}/{pg.pgid} peered at epoch {epoch0} "
            f"(authority: osd.{best})",
            epoch=epoch0,
        )
        # Drain every recovering mark the primary now owns: _on_map
        # marks healed (down -> up) members on EVERY instance, but
        # only the serving primary may drive the catch-up — a mark
        # left by a map transition this instance saw while NOT the
        # primary would otherwise persist forever, keeping the member
        # un-votable and un-pollable (the eagain-forever wedge the
        # chaos tier caught). Content-staleness judgment itself stays
        # with the catch-up's stamp-divergence pass — the gathered
        # (les, lu) infos are NOT a staleness oracle (a divergent
        # self-inflated lu would rank every healthy member 'behind'
        # and storm rollbacks toward a bogus authority).
        drain: list[int] = []
        with d._pg_lock:
            for idx, osd in enumerate(acting0):
                if osd in (SHARD_NONE, d.osd_id):
                    continue
                if (
                    pg.acting[idx] == osd
                    and idx in pg.backend.recovering
                ):
                    drain.append(idx)
        for idx in drain:
            d._spawn_catch_up(pg, idx)
        crash_points.fire("peering.active", daemon=d, pg=pg)

    def _admit_self_positions(self, acting: list) -> None:
        """Re-admit this daemon's OWN healed positions. The legacy
        path ran the replica catch-up against itself here — an RPC to
        nobody that failed and holed the position (THE round-8 flake).
        The election pass that just completed already judged and
        repaired this store (GetMissing), so admission is a
        bookkeeping flip, not a transfer."""
        d, pg = self.daemon, self.pg
        for pos, osd in enumerate(acting):
            if osd != d.osd_id:
                continue
            if pos in pg.backend.recovering:
                pg.backend.recovering.discard(pos)
                pg.rmw.on_shard_recovered(pos)
            if self.state == ACTIVE:
                pg.born_holes.discard(pos)

    def _recover_from_authority(
        self, spec, my_pos: int, best: int
    ) -> dict:
        """GetMissing: reconcile my shard against the elected
        authority (``PGLog::rewind_divergent_log`` applied to the
        ex-primary itself, plus the pg_missing_t recovery the legacy
        rewind skipped). Three legs:

        - divergent object (my stamp not in authoritative history):
          rebuild my shard from survivors — failure fails the pass
          (serving divergent bytes is the one forbidden outcome);
        - divergent create (only I ever heard of it): remove;
        - missing object (authority committed it while I was away):
          rebuild my shard best-effort — on failure the adopted prime
          still serves it degraded (reads decode from survivors).

        Returns the adopted authority map ``loc -> (size, eversion)``
        for re-priming after the gate-open cache wipe."""
        from ceph_tpu.pipeline.rmw import OI_KEY, parse_oi
        from ceph_tpu.store import Transaction

        from .osd_daemon import shard_key

        d, pg = self.daemon, self.pg
        d.peering_pc.inc("rewinds")
        listing = d.peers.list_pg(
            best, spec.pool_id, spec.pg_num, pg.pgid
        )
        auth: dict[str, tuple[int, tuple[int, int]]] = {}
        for loc, _si, size, *ev in listing:
            aev = tuple(ev) if len(ev) == 2 else (0, 0)
            if loc not in auth or aev > auth[loc][1]:
                auth[loc] = (size, aev)
        # my own pristine stamps, BEFORE any recovery can overwrite
        mine: dict[str, tuple[int, int]] = {}
        for loc, si in d._scan_pg_keys(
            spec.pool_id, spec.pg_num, pg.pgid
        ):
            if si != my_pos:
                continue
            try:
                _size, ev = parse_oi(
                    d.store.getattr(shard_key(loc, si), OI_KEY)
                )
            except (FileNotFoundError, KeyError, ValueError):
                continue
            mine[loc] = tuple(ev)
        # adopt the authority's knowledge: later judgments must answer
        # from the elected history, not from my divergent attrs
        for loc, (size, aev) in auth.items():
            if aev != (0, 0):
                pg.rmw.prime_object(loc, max(size, 0), eversion=aev)
        divergent = sorted(
            loc for loc, mev in mine.items()
            if mev != (0, 0) and loc in auth and auth[loc][1] != mev
        )
        creates = sorted(
            loc for loc, mev in mine.items()
            if mev != (0, 0) and loc not in auth
        )
        missing = sorted(
            loc for loc, (size, aev) in auth.items()
            if loc not in mine and aev != (0, 0)
            and not d.store.exists(shard_key(loc, my_pos))
        )
        # the AUTHORITY's HashInfo for every object about to be
        # rebuilt: the recovery verify must check the rebuild against
        # the elected truth — my own cached/stored hinfo may be the
        # divergent interval's, and verifying against it false-fails
        # the rollback and wedges the pass (observed on the legacy
        # path as a HashInfo-verify peering failure)
        with d._pg_lock:
            best_pos = (
                pg.acting.index(best) if best in pg.acting else None
            )
        auth_hinfos, auth_reqs = (
            self._fetch_auth_attrs(
                best, best_pos, divergent + missing
            )
            if best_pos is not None else ({}, {})
        )

        def _reprime(loc: str) -> None:
            size, aev = auth[loc]
            pg.rmw.forget_object(loc)  # drop my stale hinfo/stamps
            pg.rmw.prime_object(
                loc, max(size, 0), hinfo=auth_hinfos.get(loc),
                eversion=aev,
            )

        for loc in creates:
            d.log.info(
                "pg", f"{pg.pool}/{pg.pgid}:",
                "peering: divergent create", loc, "- removing"
            )
            key = shard_key(loc, my_pos)
            d.store.queue_transactions(
                Transaction().touch(key).remove(key)
            )
            pg.rmw.forget_object(loc)
            d.rmw_crash_pc.inc("divergent_removes")
        def _adopt_req_window(loc: str) -> None:
            # my shard's reqid-dedup attr must advance to the
            # AUTHORITY's window alongside the rebuilt bytes: my own
            # (stale) window would otherwise re-seed ancient suspect
            # reqids that classify ambiguous forever and wedge the
            # object in eagain (chaos-tier find)
            raw = auth_reqs.get(loc)
            if raw is None:
                return
            from .osd_daemon import REQ_KEY

            key = shard_key(loc, my_pos)
            if d.store.exists(key):
                d.store.queue_transactions(
                    Transaction().setattr(key, REQ_KEY, raw)
                )

        for loc in divergent:
            d.log.info(
                "pg", f"{pg.pool}/{pg.pgid}:",
                "peering: divergent object", loc,
                "- rolling back from survivors"
            )
            # NO QoS admission: peering is control plane and must
            # never wait on the data plane (the worker may be parked
            # in the peering gate)
            _reprime(loc)
            pg.recovery.recover_object(loc, {my_pos})
            _adopt_req_window(loc)
            d.rmw_crash_pc.inc("rollbacks")
        for loc in missing:
            try:
                _reprime(loc)
                size = auth[loc][0]
                pg.recovery.recover_object(
                    loc, {my_pos}, size=size if size > 0 else None
                )
                _adopt_req_window(loc)
                d.rmw_crash_pc.inc("rollforwards")
            except Exception as e:
                # best-effort: the adopted prime serves it degraded;
                # scrub / the next pass repairs the shard copy
                d.log.info(
                    "pg", f"{pg.pool}/{pg.pgid}:",
                    "peering: missing object", loc,
                    "not rebuilt yet", f"({type(e).__name__}: {e})"
                )
        return auth

    def _fetch_auth_attrs(
        self, best: int, best_pos: int, locs: list
    ) -> tuple[dict, dict]:
        """One concurrent fan-out for the elected authority's HINFO +
        reqid-window attrs (all shards carry the same cumulative-crc
        attr, so the winner's copy at its own position is the elected
        truth; the window attr is the freshest committed dedup
        state). Fetch failures simply omit the loc — the rebuild then
        skips the hash verify rather than wedging on an unverifiable
        one, and the window keeps its (settleable-or-not) old value."""
        from ceph_tpu.pipeline.hashinfo import HashInfo
        from ceph_tpu.pipeline.rmw import HINFO_KEY

        from .osd_daemon import REQ_KEY, shard_key

        d = self.daemon
        hinfos: dict = {}
        reqs: dict = {}
        pending: set = set()

        def on_reply(loc: str, r) -> None:
            pending.discard(loc)
            if isinstance(r, Exception) or getattr(r, "error", None):
                return
            raw = r.attrs.get(HINFO_KEY)
            if raw:
                try:
                    hinfos[loc] = HashInfo.from_bytes(raw)
                except (TypeError, ValueError):
                    pass
            rq = r.attrs.get(REQ_KEY)
            if rq:
                reqs[loc] = bytes(rq)
        for loc in locs:
            key = shard_key(loc, best_pos)
            if d.peers.get_attrs_async(
                best, key, [HINFO_KEY, REQ_KEY],
                lambda r, l=loc: on_reply(l, r),
            ):
                pending.add(loc)
        if pending:
            try:
                d.peers.drain_until(
                    lambda: not pending, timeout=d.op_timeout
                )
            except TimeoutError:
                pass  # non-repliers omit: verify skipped, not wedged
        return hinfos, reqs

    # -- catch-up admission ---------------------------------------------
    def _handle_admit(self, shard: int, done, res: list) -> None:
        """Admit a caught-up member — on the drainer, so it cannot
        interleave an election (the round-5 'mid-judgment member
        voted' class is unexpressible). The final clean-check runs
        under the op lock: client writes cannot append dirty entries
        between the check and the admit. Admission does NOT require
        the gate to be open — a member clean against the current
        pglog is admissible in any state (rejecting mid-pass forced
        full catch-up restarts under churn, stretching the degraded
        window until reads starved below k); the position must still
        be a live member, though."""
        d, pg = self.daemon, self.pg
        ok = False
        try:
            crash_points.fire(
                "peering.admit", daemon=d, pg=pg, shard=shard
            )
            if pg.acting[shard] != SHARD_NONE:
                def _dirty() -> bool:
                    return bool(
                        pg.pglog.dirty_extents(shard)
                        or pg.pglog.dirty_deletes(shard)
                        or pg.pglog.dirty_xattrs(shard)
                    )

                # the shard lock this PG's client ops serialize
                # under (== d._op_lock at osd_op_num_shards=1)
                with d._op_lock_for(pg.pool, pg.pgid):
                    if _dirty():
                        pg.recovery.recover_from_log(pg.pglog, shard)
                    if not _dirty():
                        pg.backend.recovering.discard(shard)
                        pg.rmw.on_shard_recovered(shard)
                        ok = True
        finally:
            res.append(ok)
            done.set()
