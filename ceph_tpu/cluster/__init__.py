"""Cluster control plane — the OSDMap / monitor / client tier.

The reference's control plane (SURVEY.md §2.4, §3.4): an epoch-
versioned cluster map (src/osd/OSDMap.h) published by a monitor
authority (src/mon/OSDMonitor.cc) and consumed by clients that target
ops via the map (src/osdc/Objecter.cc). This package is the analog:

- ``osdmap``:   OSDMap + Incremental — devices, pools, EC profiles,
                up/down/in/out, pg→acting arithmetic with EC holes.
- ``monitor``:  the map authority — commands, profile validation,
                failure reports, subscriptions, incremental catch-up.
- ``paxos``:    quorum-replicated commit for the monitor store.
- ``osd_daemon`` / ``objecter``: the data-plane daemon serving client
                ops and the map-aware resending client.
- ``peering``:  the explicit per-PG peering state machine
                (PeeringState.cc analog) + crash-point injection.
- ``pgmap``:    the stats plane — per-PG stats reports folded into
                the PGMap aggregate (pg_stats_t / MgrStatMonitor
                analog) behind `status` / `pg dump` / `df`.
"""

from .osdmap import Incremental, OSDInfo, OSDMap, PoolSpec, SHARD_NONE
from .mgr import Manager
from .monitor import CommandError, Monitor
from .objecter import IoCtx, NoPrimary, Objecter, RadosClient
from .osd_daemon import OSDDaemon
from .peering import PgPeeringFsm, crash_points
from .pgmap import OSDStat, PGMap, PGStats
from .striper import StripedIoCtx

__all__ = [
    "Manager",
    "OSDStat",
    "PGMap",
    "PGStats",
    "CommandError",
    "PgPeeringFsm",
    "crash_points",
    "Incremental",
    "IoCtx",
    "Monitor",
    "NoPrimary",
    "OSDDaemon",
    "OSDInfo",
    "OSDMap",
    "Objecter",
    "PoolSpec",
    "RadosClient",
    "StripedIoCtx",
    "SHARD_NONE",
]
