"""Monitor — the cluster-map authority (src/mon/OSDMonitor.cc).

Mirrors the control-plane contract of the reference monitor:

- **Commands** mutate the map through validated proposals:
  ``osd_erasure_code_profile_set`` validates a profile by actually
  instantiating the codec plugin (OSDMonitor::parse_erasure_code_profile,
  mon/OSDMonitor.cc:7714 → ErasureCodePluginRegistry::factory);
  ``osd_pool_create`` binds a pool to a validated profile and derives
  k/m from the live codec (prepare_pool_crush_rule, :7885).
- **Failure detection**: OSDs report peers dead
  (``report_failure``); the monitor marks an OSD down only after
  reports from ``mon_osd_min_down_reporters`` *distinct* reporters
  (OSDMonitor::check_failure semantics), and auto-outs it after
  ``mon_osd_down_out_interval`` seconds down (tick-driven, injected
  clock for tests).
- **Publication**: every committed change produces one
  ``Incremental``; subscribers are notified with the new map, and
  laggards catch up via ``get_incrementals(since)`` — full-map
  fallback when history has been trimmed (the monc subscription
  protocol shape).

Commits go through a pluggable ``commit_fn`` so a Paxos quorum
(``cluster.paxos``) can replicate the incremental stream; standalone,
commits apply locally (a quorum of one).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from collections.abc import Callable

from ceph_tpu.codecs import registry
from ceph_tpu.utils import config

from .osdmap import Incremental, OSDInfo, OSDMap, PoolSpec
from ceph_tpu.utils.lockdep import DebugRLock


class CommandError(Exception):
    """A monitor command was rejected (EINVAL-style)."""


class Monitor:
    """Single map authority (quorum-of-one unless ``commit_fn``)."""

    def __init__(
        self,
        initial: OSDMap | None = None,
        commit_fn: Callable[[Incremental], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
        history: "list[Incremental] | None" = None,
        pool_id_floor: int = 0,
    ) -> None:
        self.osdmap = initial or OSDMap()
        # the stats-plane aggregate (PGMap / MgrStatMonitor role):
        # primaries ship per-PG stats via pg_stats_report; the mgr
        # health model, `cli status`/`pg dump`/`df` and the exporter
        # read the fold instead of rescanning CRUSH
        from .pgmap import PGMap

        self.pgmap = PGMap()
        self._commit_fn = commit_fn
        self._clock = clock
        self._lock = DebugRLock("mon.cmd", rank=10)
        self._subscribers: list[Callable[[OSDMap], None]] = []
        #: incremental history for catch-up, keyed by produced epoch
        self._incrementals: dict[int, Incremental] = {}
        #: target -> set of reporter ids (pending failure evidence)
        self._failure_reports: dict[int, set[int]] = {}
        #: osd id -> monotonic time it went down (for auto-out)
        self._down_since: dict[int, float] = {}
        # resuming from a persisted map: pool ids must keep ascending
        # past every id EVER issued (a removed pool's id must not be
        # reused — stale shard keys on disk encode only the pool id,
        # and a reused id would adopt them into the new pool), so the
        # high-water mark comes from the full history when available
        # pool_id_floor covers history trimmed out of the store: a
        # pool created and deleted before the window must still never
        # have its id reused
        ever = [pool_id_floor]
        ever.extend(p.pool_id for p in self.osdmap.pools.values())
        for incr in history or ():
            ever.extend(p.pool_id for p in incr.new_pools)
        self._next_pool_id = 1 + max(ever, default=0)
        for incr in history or ():
            self._incrementals[incr.epoch] = incr
        #: committed maps awaiting subscriber delivery. Delivery
        #: happens OUTSIDE the monitor lock (``_flush``): subscribers
        #: do real work (an OSD daemon may drive recovery IO on a map
        #: change) and must not stall the control plane or deadlock
        #: by re-entering it.
        self._pending_notify: list[OSDMap] = []
        self._cmd_depth = 0

    @contextmanager
    def _command(self):
        """Lock scope for one public command. On exit of the OUTERMOST
        command (osd_pool_create calls osd_erasure_code_profile_set
        internally), queued map notifications are delivered with the
        lock released."""
        self._lock.acquire()
        self._cmd_depth += 1
        try:
            yield
        finally:
            self._cmd_depth -= 1
            depth = self._cmd_depth
            self._lock.release()
            if depth == 0:
                self._flush()

    # -- commit path ----------------------------------------------------
    def _propose(self, **fields) -> OSDMap:
        """Build + commit one incremental; returns the new map. Caller
        must hold the lock and call ``_flush`` after releasing it.

        Any change that moves CRUSH membership gets pg_temp overrides
        for the affected PGs IN THE SAME EPOCH (old layout keeps
        serving, zero unserved window); primaries backfill and then
        clear them. The reference reaches the same steady state via
        primary-requested pg_temp — committing both atomically removes
        the race where a client reads the new layout before any
        pg_temp lands."""
        incr = Incremental(epoch=self.osdmap.epoch + 1, **fields)
        # only these fields alter CRUSH input (up/down flips and
        # pg_temp edits cannot move membership) — skip the trial map
        # and the O(pools x pg_num) straw2 rescan on every other commit
        crush_moving = any(
            fields.get(f) for f in ("new_osds", "in_", "out")
        )
        if crush_moving:
            trial = self.osdmap.apply(incr)
            temps = []
            for pool, spec in trial.pools.items():
                if pool not in self.osdmap.pools:
                    continue  # new pool: nothing to protect
                for pgid in range(spec.pg_num):
                    if (pool, pgid) in trial.pg_temp:
                        continue
                    old_raw = self.osdmap.pg_to_raw(pool, pgid, True)
                    if old_raw != trial.pg_to_raw(pool, pgid, True):
                        temps.append((pool, pgid, tuple(old_raw)))
            if temps:
                incr = Incremental(
                    epoch=incr.epoch,
                    **{**fields, "new_pg_temp": tuple(
                        list(fields.get("new_pg_temp", ())) + temps
                    )},
                )
        if self._commit_fn is not None:
            self._commit_fn(incr)  # quorum may raise; nothing applied
        self.osdmap = self.osdmap.apply(incr)
        self._incrementals[incr.epoch] = incr
        self._pending_notify.append(self.osdmap)
        return self.osdmap

    def _flush(self) -> None:
        """Deliver queued map notifications without holding the lock.
        Epoch order is preserved by popping under the lock; consumers
        racing on separate threads must tolerate an old epoch arriving
        late (the daemon guards on epoch)."""
        while True:
            with self._lock:
                if not self._pending_notify:
                    return
                m = self._pending_notify.pop(0)
                subs = list(self._subscribers)
            for fn in subs:
                fn(m)

    def apply_committed(self, incr: Incremental) -> None:
        """Learn one externally committed incremental — the replica/
        learner path of a monitor quorum: apply WITHOUT proposing
        (the leader already drove it through Paxos), keep history and
        the pool-id floor, notify local subscribers. Idempotent for
        already-applied epochs; refuses gaps (callers replay the log
        in order)."""
        with self._command():
            if incr.epoch <= self.osdmap.epoch:
                return
            if incr.epoch != self.osdmap.epoch + 1:
                raise ValueError(
                    f"learn gap: at epoch {self.osdmap.epoch}, "
                    f"got {incr.epoch}"
                )
            self.osdmap = self.osdmap.apply(incr)
            self._incrementals[incr.epoch] = incr
            for p in incr.new_pools:
                self._next_pool_id = max(
                    self._next_pool_id, p.pool_id + 1
                )
            self._pending_notify.append(self.osdmap)

    # -- subscriptions (monc analog) ------------------------------------
    def subscribe(self, fn: Callable[[OSDMap], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)
            current = self.osdmap
        fn(current)

    def get_incrementals(self, since: int) -> list[Incremental] | None:
        """Deltas from epoch ``since`` (exclusive) to current; None if
        history no longer reaches back that far (send the full map)."""
        with self._lock:
            out = []
            for e in range(since + 1, self.osdmap.epoch + 1):
                incr = self._incrementals.get(e)
                if incr is None:
                    return None
                out.append(incr)
            return out

    def trim_history(self, keep: int = 500) -> None:
        with self._lock:
            floor = self.osdmap.epoch - keep
            for e in [e for e in self._incrementals if e <= floor]:
                del self._incrementals[e]

    # -- device lifecycle -----------------------------------------------
    def osd_crush_add(
        self,
        osd: int,
        weight: float = 1.0,
        zone: str = "",
        location: dict[str, str] | None = None,
        **loc_kw: str,
    ) -> OSDMap:
        """Register a device in the crush tree (ceph osd crush add).

        ``location`` (or keyword shorthand ``host=.., rack=..``) places
        the device in the bucket hierarchy; rule-based pools
        (osd_pool_create failure_domain/crush_rule) select through it.
        Without a location the device lands directly under the root
        (and the legacy flat ``zone`` placement still applies for
        pools without a rule)."""
        with self._command():
            loc = dict(location or {})
            loc.update({k: v for k, v in loc_kw.items() if v})
            if loc:
                # Reject conflicting topology NOW (a bucket cannot sit
                # under two parents): build a strict trial hierarchy
                # over every REGISTERED device (not just in ones — a
                # conflict must not hide until osd_in).
                from ceph_tpu.crush import CrushHierarchy
                from ceph_tpu.placement import Device as _Dev

                trial = CrushHierarchy(strict=True)
                try:
                    for o in self.osdmap.osds.values():
                        if o.id != osd:
                            trial.add_device(
                                _Dev(o.id, o.weight, o.zone),
                                dict(o.location),
                            )
                    trial.add_device(_Dev(osd, weight, zone), loc)
                except ValueError as e:
                    raise CommandError(str(e)) from e
            prev = self.osdmap.osds.get(osd)
            info = OSDInfo(
                osd, weight, zone,
                up=prev.up if prev else False,
                in_=prev.in_ if prev else False,
                addr=prev.addr if prev else None,
                new=prev.new if prev else True,
                location=tuple(sorted(loc.items()))
                if loc
                else (prev.location if prev else ()),
            )
            return self._propose(new_osds=(info,))

    def osd_crush_rule_create(
        self, name: str, steps: tuple
    ) -> OSDMap:
        """Install a multi-step crush rule (ceph osd crush rule
        create-*; steps per crush.CrushHierarchy.run_rule)."""
        with self._command():
            from ceph_tpu.crush import validate_rule

            try:
                norm = validate_rule(steps)
            except ValueError as e:
                raise CommandError(str(e)) from e
            existing = self.osdmap.crush_rules.get(name)
            if existing is not None:
                if existing != norm:
                    raise CommandError(
                        f"crush rule {name!r} exists with different steps"
                    )
                return self.osdmap
            return self._propose(new_rules=((name, norm),))

    @staticmethod
    def _cluster_event(
        type: str, msg: str, m: OSDMap, severity: str = "INF"
    ) -> None:
        """Health-relevant map changes land in the cluster log (the
        `ceph.log` "osd.N down" lines the reference mon writes)."""
        from ceph_tpu.utils.cluster_log import cluster_log

        cluster_log.log("mon", type, msg, severity=severity,
                        epoch=m.epoch)

    def osd_boot(self, osd: int, addr: tuple[str, int]) -> OSDMap:
        """An OSD came up and announced its address (MOSDBoot). A NEW
        device is auto-marked in (mon_osd_auto_mark_new_in); a device
        an operator marked out stays out until `osd in`."""
        with self._command():
            prev = self.osdmap.osds.get(osd)
            if prev is None:
                raise CommandError(f"osd.{osd} not in crush map")
            info = OSDInfo(
                osd, prev.weight, prev.zone, up=True,
                in_=prev.in_ or prev.new, addr=addr, new=False,
                location=prev.location,
            )
            self._failure_reports.pop(osd, None)
            self._down_since.pop(osd, None)
            m = self._propose(new_osds=(info,))
        self._cluster_event("osd_boot", f"osd.{osd} boot ({addr[0]}:"
                            f"{addr[1]})", m)
        return m

    def osd_down(self, osd: int) -> OSDMap:
        with self._command():
            self._check_osd(osd)
            self._down_since.setdefault(osd, self._clock())
            self._failure_reports.pop(osd, None)
            m = self._propose(down=(osd,))
        self._cluster_event(
            "osd_down", f"osd.{osd} marked down", m, severity="WRN"
        )
        return m

    def osd_out(self, osd: int) -> OSDMap:
        with self._command():
            self._check_osd(osd)
            m = self._propose(out=(osd,))
        self._cluster_event(
            "osd_out", f"osd.{osd} marked out", m, severity="WRN"
        )
        return m

    def osd_in(self, osd: int) -> OSDMap:
        with self._command():
            self._check_osd(osd)
            m = self._propose(in_=(osd,))
        self._cluster_event("osd_in", f"osd.{osd} marked in", m)
        return m

    def osd_reweight(self, osd: int, weight: float) -> OSDMap:
        with self._command():
            prev = self._check_osd(osd)
            if weight < 0:
                raise CommandError("weight must be >= 0")
            from dataclasses import replace

            return self._propose(new_osds=(replace(prev, weight=weight),))

    def _check_osd(self, osd: int) -> OSDInfo:
        info = self.osdmap.osds.get(osd)
        if info is None:
            raise CommandError(f"osd.{osd} does not exist")
        return info

    # -- failure detection (OSDMonitor::check_failure) -------------------
    def report_failure(self, reporter: int, target: int) -> OSDMap | None:
        """Peer-failure evidence. Marks the target down once
        ``mon_osd_min_down_reporters`` distinct reporters agree; a
        report about an already-down or unknown OSD is ignored."""
        with self._command():
            info = self.osdmap.osds.get(target)
            if info is None or not info.up or reporter == target:
                return None
            reporters = self._failure_reports.setdefault(target, set())
            reporters.add(reporter)
            if len(reporters) < config.get("mon_osd_min_down_reporters"):
                return None
            del self._failure_reports[target]
            self._down_since[target] = self._clock()
            return self._propose(down=(target,))

    def tick(self) -> OSDMap | None:
        """Periodic maintenance: auto-out OSDs down longer than
        ``mon_osd_down_out_interval`` (data starts rebalancing)."""
        with self._command():
            horizon = self._clock() - config.get("mon_osd_down_out_interval")
            expired = [
                osd for osd, t in self._down_since.items()
                if t <= horizon and self.osdmap.osds[osd].in_
            ]
            if not expired:
                return None
            for osd in expired:
                del self._down_since[osd]
            from ceph_tpu.utils.log import get_logger

            get_logger("mon").info(
                "auto-out after down-out interval: osds", expired
            )
            return self._propose(out=tuple(expired))

    # -- EC profiles & pools (OSDMonitor::parse_erasure_code_profile) ----
    # -- central config db (ConfigMonitor analog) -----------------------
    # mon/ConfigMonitor.h:15: a Paxos-replicated option store the
    # monitors push to every daemon; daemons overlay it under their
    # local file/env/runtime layers and observers fire on change.
    _CONFIG_WHO_CLASSES = ("", "osd", "mon", "client")

    def _check_config_who(self, who: str) -> None:
        if who in self._CONFIG_WHO_CLASSES:
            return
        cls, _, ident = who.partition(".")
        if cls in self._CONFIG_WHO_CLASSES[1:] and ident.isdigit():
            return
        raise CommandError(
            f"bad config target {who!r}: use '' (global), a daemon "
            f"class {self._CONFIG_WHO_CLASSES[1:]}, or class.id"
        )

    def config_set(self, name: str, value, who: str = "") -> OSDMap:
        """``ceph config set <who> <name> <value>``: validate against
        the option schema, commit through the quorum, push to every
        subscribed daemon via the map channel."""
        from ceph_tpu.utils import config

        self._check_config_who(who)
        opt = config.schema.get(name)
        if opt is None:
            raise CommandError(f"unknown option {name!r}")
        stored = str(value)
        try:
            # validate the STRING that will be stored — daemons parse
            # exactly this form out of the replicated db, so e.g. 8.5
            # for an int option must be rejected here, not silently
            # dropped by every daemon
            opt.parse(stored)
        except Exception as e:
            raise CommandError(
                f"invalid value for {name!r}: {e}"
            ) from None
        with self._command():
            return self._propose(
                new_config=((who, name, stored),)
            )

    def config_rm(self, name: str, who: str = "") -> OSDMap:
        self._check_config_who(who)
        with self._command():
            return self._propose(new_config=((who, name, None),))

    def config_db(self) -> dict:
        """``ceph config dump``: the full replicated db."""
        with self._lock:
            return {
                f"{who or 'global'}/{name}": val
                for (who, name), val in sorted(self.osdmap.config.items())
            }

    def osd_erasure_code_profile_set(
        self, name: str, profile: dict[str, str], force: bool = False
    ) -> OSDMap:
        """Validate by instantiating the plugin, then commit. Changing
        an existing profile requires ``force`` (it would silently
        change placement math for existing pools — same guard as the
        reference)."""
        with self._command():
            if name in self.osdmap.profiles and not force:
                if self.osdmap.profiles[name] != profile:
                    raise CommandError(
                        f"profile {name!r} exists; --force to overwrite"
                    )
                return self.osdmap
            self._validate_profile(profile)
            return self._propose(
                new_profiles=((name, tuple(sorted(profile.items()))),)
            )

    @staticmethod
    def _validate_profile(profile: dict[str, str]):
        plugin = profile.get("plugin", config.get("erasure_code_default_plugin"))
        try:
            codec = registry.factory(plugin, dict(profile))
        except Exception as e:
            raise CommandError(f"invalid erasure-code profile: {e}") from e
        return plugin, codec

    def osd_pool_create(
        self,
        name: str,
        pg_num: int,
        profile_name: str = "",
        distinct_zones: bool = False,
        crush_rule: str = "",
        failure_domain: str = "",
    ) -> OSDMap:
        """Create a pool. ``crush_rule`` binds an installed rule;
        ``failure_domain`` ("host"/"rack"/...) is the shortcut that
        auto-creates the standard EC spread rule for that bucket type
        (ErasureCode::create_rule). An LRC profile with
        ``crush-locality`` gets the two-level locality rule instead
        (ErasureCodeLrc.h): layer groups stay inside one locality
        bucket each."""
        with self._command():
            if name in self.osdmap.pools:
                raise CommandError(f"pool {name!r} already exists")
            if pg_num <= 0:
                raise CommandError("pg_num must be positive")
            if crush_rule and failure_domain:
                raise CommandError(
                    "give crush_rule or failure_domain, not both"
                )
            if not profile_name:
                profile_name = "default"
                if profile_name not in self.osdmap.profiles:
                    prof = dict(
                        kv.split("=")
                        for kv in config.get(
                            "erasure_code_default_profile"
                        ).split()
                    )
                    self.osd_erasure_code_profile_set(profile_name, prof)
            profile = self.osdmap.profiles.get(profile_name)
            if profile is None:
                raise CommandError(f"no such profile: {profile_name!r}")
            plugin, codec = self._validate_profile(profile)
            k = codec.get_data_chunk_count()
            size = codec.get_chunk_count()
            if failure_domain:
                from ceph_tpu.crush import ec_rule, lrc_rule

                locality = dict(profile).get("crush-locality", "")
                if plugin == "lrc" and locality:
                    # kml form: k+m chunks split into groups of l,
                    # one LOCAL parity added per group — total chunks
                    # = k + m + (k+m)/l, each locality group holding
                    # l + 1 chunks (ErasureCodeLrc.cc parse_kml).
                    prof = dict(profile)
                    l = int(prof.get("l", "0") or 0)
                    km = int(prof.get("k", "0") or 0) + int(
                        prof.get("m", "0") or 0
                    )
                    if l <= 0 or km % l or size % (km // l):
                        raise CommandError(
                            "crush-locality needs the kml form with "
                            "l dividing k+m"
                        )
                    groups = km // l
                    per_group = size // groups
                    steps = lrc_rule(
                        groups, per_group, locality, failure_domain
                    )
                    # geometry-keyed name: same layout shares the
                    # rule; a different layout never collides (rules
                    # are not deletable, so a pool-keyed name would
                    # pin the geometry forever)
                    crush_rule = (
                        f"lrc_{locality}_{failure_domain}_"
                        f"{groups}x{per_group}"
                    )
                else:
                    steps = ec_rule(failure_domain)
                    crush_rule = f"ec_{failure_domain}"
                self.osd_crush_rule_create(crush_rule, steps)
            elif crush_rule and crush_rule not in self.osdmap.crush_rules:
                raise CommandError(f"no such crush rule {crush_rule!r}")
            spec = PoolSpec(
                name=name,
                pool_id=self._next_pool_id,
                pg_num=pg_num,
                profile_name=profile_name,
                k=k,
                m=size - k,
                plugin=plugin,
                distinct_zones=distinct_zones,
                crush_rule=crush_rule,
            )
            self._next_pool_id += 1
            return self._propose(new_pools=(spec,))

    def osd_pool_snap_create(self, pool: str, snap: str) -> OSDMap:
        """Pool snapshot (rados_ioctx_snap_create,
        librados/librados_c.cc:1749): commit a new (snapid, name,
        epoch) entry; primaries clone objects copy-on-first-write
        against the newest snap."""
        from dataclasses import replace

        with self._command():
            spec = self.osdmap.pools.get(pool)
            if spec is None:
                raise CommandError(f"no such pool: {pool!r}")
            if any(n == snap for _, n, _ in spec.snaps):
                raise CommandError(f"snap {snap!r} already exists")
            snapid = spec.snap_seq + 1
            new = replace(
                spec,
                snaps=spec.snaps + ((snapid, snap, self.osdmap.epoch + 1),),
                snap_seq=snapid,
            )
            return self._propose(new_pools=(new,))

    def osd_pool_snap_rm(self, pool: str, snap: str) -> OSDMap:
        """Drop a pool snapshot; members garbage-collect its clone
        shards on their next tick."""
        from dataclasses import replace

        with self._command():
            spec = self.osdmap.pools.get(pool)
            if spec is None:
                raise CommandError(f"no such pool: {pool!r}")
            keep = tuple(s for s in spec.snaps if s[1] != snap)
            if len(keep) == len(spec.snaps):
                raise CommandError(f"no such snap: {snap!r}")
            return self._propose(
                new_pools=(replace(spec, snaps=keep),)
            )

    def osd_pool_qos_set(
        self,
        pool: str,
        tenant: str = "",
        res_ops: float = 0.0,
        res_bytes: float = 0.0,
        weight: float = 1.0,
        lim_ops: float = 0.0,
        lim_bytes: float = 0.0,
    ) -> OSDMap:
        """Declare (or replace) one pool/tenant QoS spec — the
        ``osd pool set <pool> qos`` surface of the multi-tenant plane
        (cluster/qos.py).  ``tenant=""`` sets the pool-wide default
        the untagged ``client.<pool>`` class schedules under.  The
        spec rides the map incremental to every OSD, which re-arms
        its mClock class live on the push."""
        from dataclasses import replace

        with self._command():
            spec = self.osdmap.pools.get(pool)
            if spec is None:
                raise CommandError(f"no such pool: {pool!r}")
            if weight <= 0.0:
                raise CommandError("qos weight must be > 0")
            row = (
                str(tenant), float(res_ops), float(res_bytes),
                float(weight), float(lim_ops), float(lim_bytes),
            )
            keep = tuple(q for q in spec.qos if q[0] != row[0])
            new = replace(
                spec, qos=tuple(sorted(keep + (row,))),
            )
            return self._propose(new_pools=(new,))

    def osd_pool_qos_rm(self, pool: str, tenant: str = "") -> OSDMap:
        """Drop one pool/tenant QoS spec: the tenant's class falls
        back to the base ``client`` profile on the next map push."""
        from dataclasses import replace

        with self._command():
            spec = self.osdmap.pools.get(pool)
            if spec is None:
                raise CommandError(f"no such pool: {pool!r}")
            keep = tuple(q for q in spec.qos if q[0] != str(tenant))
            if len(keep) == len(spec.qos):
                raise CommandError(
                    f"no qos spec for tenant {tenant!r}"
                )
            return self._propose(new_pools=(replace(spec, qos=keep),))

    def osd_pool_rm(self, name: str) -> OSDMap:
        with self._command():
            if name not in self.osdmap.pools:
                raise CommandError(f"no such pool: {name!r}")
            m = self._propose(removed_pools=(name,))
        self.pgmap.prune_pools(
            {s.pool_id for s in m.pools.values()}
        )
        return m

    # -- stats ingress (the MPGStats receive path) ----------------------
    def pg_stats_report(
        self, osd: int, epoch: int, pg_stats=(), osd_stat=None
    ) -> int:
        """One daemon's tick-driven stats report. Data-plane traffic:
        folds under the PGMap's own lock, never the command lock (a
        stats flood must not stall map commits). Returns accepted
        per-PG records (stale reports from demoted primaries are
        rejected inside the fold)."""
        return self.pgmap.apply_report(osd, epoch, pg_stats, osd_stat)

    # -- pg_temp (the backfill serving-layout override) -----------------
    def pg_temp_set(
        self, pool: str, pgid: int, acting: list[int]
    ) -> OSDMap:
        """A primary requests serving its PG from ``acting`` while it
        backfills data to the CRUSH layout (OSDMonitor pg_temp)."""
        with self._command():
            if pool not in self.osdmap.pools:
                raise CommandError(f"no such pool: {pool!r}")
            spec = self.osdmap.pools[pool]
            if len(acting) != spec.size:
                raise CommandError(
                    f"pg_temp wants {spec.size} positions, got {len(acting)}"
                )
            for o in acting:
                if o != -1 and o not in self.osdmap.osds:
                    raise CommandError(f"osd.{o} does not exist")
            return self._propose(
                new_pg_temp=((pool, pgid, tuple(acting)),)
            )

    def pg_temp_clear(self, pool: str, pgid: int) -> OSDMap | None:
        """Backfill done: the PG serves from CRUSH again."""
        with self._command():
            if (pool, pgid) not in self.osdmap.pg_temp:
                return None
            return self._propose(del_pg_temp=((pool, pgid),))
