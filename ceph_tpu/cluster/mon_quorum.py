"""Live monitor quorum: N monitor ranks over the Paxos log, with
leader routing and failover (src/mon/Paxos.cc + Elector.cc running in
every mon daemon).

Round-3 had Paxos + election partition-tested but only one Monitor in
the live cluster (VERDICT r3 missing #5). This module puts a real
quorum behind the map service:

- ``MonQuorumService`` owns a ``MonCluster`` (the replicated log) and
  one ``Monitor`` per rank. Exactly ONE rank — the elected leader —
  executes commands; its ``commit_fn`` drives each Incremental
  through Paxos before anything is applied (mon/Paxos.cc: no map
  change without a majority). Replica ranks are learners: committed
  blobs replay into their Monitors (``apply_committed``), so any
  survivor holds the full map history.
- ``QuorumMonitor`` is the handle daemons and clients hold (the
  MonClient analog): it exposes the Monitor command surface, routes
  every call to the current leader, and fails over transparently —
  ``kill(rank)`` severs a rank's transport links and stops routing to
  it; the next command elects a new leader, which first catches up
  from the replicated log (Paxos collect/sync), so NO committed epoch
  is ever lost.
- With a majority dead, commands raise ``QuorumLost`` and the map
  freezes — the reference's "mon quorum lost" stall; OSDs keep
  serving IO on their last map.

Subscriber fan-out is leader-driven and epoch-deduped at the service,
so a daemon subscribed through failover sees each epoch once.
"""

from __future__ import annotations

import threading
from collections.abc import Callable

from .monitor import Monitor
from .osdmap import Incremental, OSDMap
from .paxos import MonCluster, QuorumLost
from ceph_tpu.utils.lockdep import DebugRLock


class MonQuorumService:
    """N monitor ranks sharing one Paxos-replicated map log."""

    def __init__(
        self,
        n: int = 3,
        on_commit: Callable[[int, Incremental], None] | None = None,
        initial: OSDMap | None = None,
        history: "list[Incremental] | None" = None,
        pool_id_floor: int = 0,
    ) -> None:
        self.paxos = MonCluster(n)
        self.n = n
        self.dead: set[int] = set()
        self._lock = DebugRLock("mon.quorum")
        self._subs: list[Callable[[OSDMap], None]] = []
        self._notified_epoch = initial.epoch if initial is not None else 0
        #: durability seam: (rank, incr) for every incremental a rank
        #: applies — vstart points this at per-rank MonStores
        self._on_commit = on_commit
        self.monitors: list[Monitor] = []
        for r in range(n):
            mon = Monitor(
                initial=initial,
                commit_fn=self._make_commit_fn(r),
                history=list(history) if history else None,
                pool_id_floor=pool_id_floor,
            )
            mon.subscribe(self._make_notifier(r))
            self.monitors.append(mon)
        #: per-rank durability high-water mark: the LEADER applies its
        #: own commits through _propose (never apply_committed), so
        #: persistence must track separately from map epoch
        base = initial.epoch if initial is not None else 0
        self._persisted = [base] * n
        #: per-rank replay cursor (highest log slot applied into the
        #: rank's Monitor) — keeps _catch_up incremental instead of
        #: re-decoding the whole committed log every command
        self._applied_slot = [-1] * n
        #: rank -> incremental blob whose propose is in flight (the
        #: at-most-once record for failover retries)
        self._pending_blob: dict[int, bytes] = {}
        self._leader_rank = 0

    # -- commit path (leader-only) -------------------------------------
    def _make_commit_fn(self, rank: int):
        def commit(incr: Incremental) -> None:
            # elect from THIS rank's partition view: a deposed or dead
            # leader cannot reach a majority and fails here, with
            # nothing applied (Monitor applies only after commit_fn).
            if rank in self.dead:
                raise QuorumLost(f"mon.{rank} is dead")
            leader = self.paxos.elect(from_rank=rank)
            if leader.rank != rank:
                # a rank that is not the elected leader must not
                # propose: its epoch numbering could fork the log
                # (the reference forwards commands leader-ward)
                raise QuorumLost(
                    f"mon.{rank} is not the leader (mon.{leader.rank} is)"
                )
            blob = incr.to_bytes()
            # at-most-once bookkeeping: record the blob BEFORE the
            # propose. If the leader dies mid-propose, the value may
            # survive as a minority-accepted orphan that the next
            # leader's sync MUST resurrect (Paxos safety) — the proxy
            # consults this record to avoid re-running a command whose
            # incremental actually committed.
            with self._lock:
                self._pending_blob[rank] = blob
            try:
                self.paxos.commit(blob, leader)
            finally:
                # clear unless the rank died mid-commit — then the
                # record must survive for the failover path's orphan
                # check. Without this finally, a commit() that raised
                # with the rank still alive left a stale blob a LATER
                # failover could misread as that rank's orphan and
                # skip a genuinely uncommitted command.
                if rank not in self.dead:
                    with self._lock:
                        self._pending_blob.pop(rank, None)
            # durable BEFORE the Monitor applies and notifies — the
            # same ordering the single-mon path gets from
            # commit_fn=store.append. Without this, a crash between
            # apply (daemons already acting on the new epoch) and the
            # post-command replicate() would resurrect the old map —
            # and re-issue pool ids whose shard keys survive on disk.
            if self._on_commit is not None and (
                incr.epoch > self._persisted[rank]
            ):
                self._on_commit(rank, incr)
                self._persisted[rank] = incr.epoch

        return commit

    def _make_notifier(self, rank: int):
        def notify(osdmap: OSDMap) -> None:
            subs = []
            with self._lock:
                if osdmap.epoch > self._notified_epoch:
                    self._notified_epoch = osdmap.epoch
                    subs = list(self._subs)
            for fn in subs:
                fn(osdmap)

        return notify

    # -- leadership ----------------------------------------------------
    def leader(self) -> Monitor:
        """The current leader's Monitor, synced to the log tail."""
        with self._lock:
            node = self.paxos.elect(from_rank=self._live_rank())
            self._leader_rank = node.rank
            mon = self.monitors[node.rank]
            self._catch_up(node.rank)
            return mon

    def leader_rank(self) -> int:
        with self._lock:
            self.leader()
            return self._leader_rank

    def _live_rank(self) -> int:
        for r in range(self.n):
            if r not in self.dead:
                return r
        raise QuorumLost("every monitor is dead")

    def _catch_up(self, rank: int) -> None:
        """Replay committed log entries this rank hasn't applied (the
        new-leader sync after ``MonCluster.elect`` already re-drove
        undecided slots; here the rank's MONITOR state catches up) and
        persist anything not yet in its store — including the
        leader's own commits, which apply through _propose."""
        mon = self.monitors[rank]
        node = self.paxos.nodes[rank]
        slot = self._applied_slot[rank] + 1
        while True:
            s = node.slots.get(slot)
            if s is None or s.committed is None:
                break
            incr = Incremental.from_bytes(s.committed)
            if incr.epoch > mon.osdmap.epoch:
                mon.apply_committed(incr)
            if incr.epoch > self._persisted[rank]:
                if self._on_commit is not None:
                    self._on_commit(rank, incr)
                self._persisted[rank] = incr.epoch
            self._applied_slot[rank] = slot
            slot += 1

    def replicate(self) -> None:
        """Push the committed log into every LIVE replica's Monitor —
        called after each proxied command so survivors stay hot (a
        failover needs only the delta since the last command)."""
        with self._lock:
            for r in range(self.n):
                if r not in self.dead:
                    self._catch_up(r)

    # -- chaos surface --------------------------------------------------
    def kill(self, rank: int) -> None:
        """Take a monitor down: transport severed, never routed again.
        Remaining majority keeps serving; a remaining minority means
        QuorumLost on the next command."""
        with self._lock:
            self.dead.add(rank)
            for other in range(self.n):
                if other != rank:
                    self.paxos.transport.cut(rank, other)

    def revive(self, rank: int) -> None:
        with self._lock:
            self.dead.discard(rank)
            self.paxos.transport.heal(rank)
            # learn-catchup: commits made while this rank was cut
            # never reached its acceptor log — replay them from the
            # current leader's committed slots before the monitor
            # replay (the mon store sync phase of Paxos.cc)
            leader = self.paxos.elect(from_rank=self._live_rank())
            mine = self.paxos.nodes[rank]
            for slot, s in sorted(leader.slots.items()):
                if s.committed is not None:
                    mine.on_learn(slot, s.committed)
            self._catch_up(rank)

    # -- subscriber fan-out ---------------------------------------------
    def subscribe(self, fn: Callable[[OSDMap], None]) -> None:
        with self._lock:
            self._subs.append(fn)
            current = self.leader().osdmap
        fn(current)


class QuorumMonitor:
    """The Monitor-API handle over a quorum: every command routes to
    the elected leader and fails over when it dies mid-stream."""

    #: command methods proxied leader-ward (the ``ceph`` command
    #: surface OSD daemons and clients actually use)
    _COMMANDS = (
        "osd_crush_add", "osd_crush_rule_create", "osd_boot",
        "osd_down", "osd_out", "osd_in", "osd_reweight",
        "report_failure", "tick", "osd_erasure_code_profile_set",
        "osd_pool_create", "osd_pool_rm", "osd_pool_snap_create",
        "osd_pool_snap_rm", "pg_temp_set", "pg_temp_clear",
        "trim_history", "config_set", "config_rm",
    )

    def __init__(self, service: MonQuorumService) -> None:
        self.service = service

    def _best_effort_mon(self) -> Monitor:
        """The most advanced live rank's Monitor, no quorum required —
        map READS are monc-cache state (the data plane keeps serving
        on the last committed map when the quorum is gone); only map
        CHANGES need consensus."""
        try:
            return self.service.leader()
        except QuorumLost:
            svc = self.service
            with svc._lock:
                live = [r for r in range(svc.n) if r not in svc.dead]
                # replay each survivor's LOCALLY committed slots first
                # (needs no quorum): a rank can hold epoch N+1 in its
                # acceptor log while its Monitor is still at N if the
                # leader died before the post-command replicate()
                for r in live:
                    svc._catch_up(r)
                candidates = [
                    svc.monitors[r] for r in live
                ] or list(svc.monitors)
                return max(candidates, key=lambda m: m.osdmap.epoch)

    @property
    def osdmap(self) -> OSDMap:
        return self._best_effort_mon().osdmap

    def subscribe(self, fn: Callable[[OSDMap], None]) -> None:
        self.service.subscribe(fn)

    def get_incrementals(self, since: int):
        return self._best_effort_mon().get_incrementals(since)

    def __getattr__(self, name: str):
        if name not in self._COMMANDS:
            raise AttributeError(name)

        def call(*args, **kwargs):
            svc = self.service
            last: Exception | None = None
            for _ in range(svc.n):
                rank = svc.leader_rank()
                mon = svc.monitors[rank]
                try:
                    out = getattr(mon, name)(*args, **kwargs)
                    svc.replicate()
                    return out
                except QuorumLost as e:
                    last = e
                    # leader died between election and commit: if a
                    # DIFFERENT live leader exists, retry there;
                    # otherwise surface the stall
                    if rank not in svc.dead:
                        raise
                    # at-most-once: the dead leader's propose may have
                    # left a minority-accepted value that the NEW
                    # leader's sync resurrects and commits. If that
                    # exact blob is now in the log, the command's
                    # effect landed — re-running it would double-apply.
                    with svc._lock:
                        orphan = svc._pending_blob.pop(rank, None)
                    if orphan is not None:
                        new_leader = svc.leader()  # syncs + catches up
                        node = svc.paxos.nodes[svc._leader_rank]
                        if any(
                            s.committed == orphan
                            for s in node.slots.values()
                        ):
                            svc.replicate()
                            return new_leader.osdmap
                    continue
            raise last if last is not None else QuorumLost("no leader")

        return call
