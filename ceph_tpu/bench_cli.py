"""ceph_erasure_code_benchmark-compatible CLI.

Reproduces the reference tool's interface and output contract
(src/test/erasure-code/ceph_erasure_code_benchmark.cc): encode/decode
workloads over a sized buffer for N iterations, ``--parameter k=v``
profile injection, random or exhaustive erasure generation with decoded
content verified against the original, and the two-column
``<elapsed_seconds>\t<total_KiB>`` output the qa sweep harness parses
(qa/workunits/erasure-code/bench.sh).

Timing contract: like the reference tool, each iteration is a
host-driven dispatch and the clock covers the full per-call path. On
locally attached TPUs that is the honest chip number; through a remote
device tunnel (axon) every iteration pays a ~0.1 s round trip, so
absolute numbers there measure the tunnel unless the per-iteration
payload is large (config 3). ``bench.py`` is the tunnel-honest
throughput tool (on-device loop + trip-count differencing).

Two further workloads cover BASELINE.md configs 4-5 (which the
reference drives through the same tool plus Checksummer):

``repair`` — CLAY MSR single-chunk repair decode: rotate the lost
chunk, read only the fractional sub-chunk helper ranges that
``minimum_to_decode`` plans, and time ``codec.repair``. The KiB
column counts HELPER BYTES READ (the repair-bandwidth story —
(d*chunk)/(d-k+1) instead of k*chunk).

``checksum`` — Checksummer calculate over vmapped blocks
(BlueStore's deep-scrub role): ``--csum-alg``/``--csum-block``
select algorithm and granularity; the KiB column counts bytes
hashed.

Usage:
    python -m ceph_tpu.bench_cli encode --plugin isa -P k=8 -P m=4 \
        --size $((80 * 1024 * 1024)) --iterations 100
    python -m ceph_tpu.bench_cli decode --plugin jerasure \
        -P technique=reed_sol_van -P k=4 -P m=2 --erasures 2 \
        --erasures-generation exhaustive
    python -m ceph_tpu.bench_cli repair --plugin clay \
        -P k=8 -P m=4 -P d=11 --iterations 20
    python -m ceph_tpu.bench_cli checksum --csum-alg crc32c \
        --csum-block 4096 --size $((64 * 1024 * 1024))
"""

from __future__ import annotations

import argparse
import sys
import time
from itertools import combinations

import numpy as np


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="ecbench", description=__doc__.splitlines()[0]
    )
    p.add_argument(
        "workload",
        choices=["encode", "decode", "repair", "checksum", "loadgen"],
    )
    p.add_argument(
        "--plugin", "-p", default=None,
        help="codec plugin (default: isa; repair defaults to clay)",
    )
    p.add_argument(
        "--parameter",
        "-P",
        action="append",
        default=[],
        help="profile key=value (repeatable), e.g. -P k=8 -P m=4",
    )
    p.add_argument("--size", "-s", type=int, default=80 * 1024 * 1024,
                   help="total bytes per iteration (default 80 MiB)")
    p.add_argument("--iterations", "-i", type=int, default=100)
    p.add_argument("--erasures", "-e", type=int, default=1,
                   help="erasures per decode iteration")
    p.add_argument(
        "--erasures-generation",
        "-E",
        choices=["random", "exhaustive"],
        default="random",
    )
    p.add_argument("--batch", type=int, default=8,
                   help="stripes per device dispatch")
    p.add_argument("--csum-alg", default="crc32c",
                   help="checksum workload: algorithm "
                        "(crc32c/crc32c_16/crc32c_8/xxhash32/xxhash64)")
    p.add_argument("--csum-block", type=int, default=4096,
                   help="checksum workload: csum block size in bytes")
    p.add_argument("--verbose", "-v", action="store_true")
    lg = p.add_argument_group(
        "loadgen", "live-cluster workload (radosbench analog): the "
        "two-column contract reports wall seconds and client bytes "
        "moved; the full JSON report goes to stderr"
    )
    lg.add_argument("--preset", default=None,
                    help="canned spec (smoke/mixed/write-heavy/"
                         "read-heavy); flags below override")
    lg.add_argument("--mix", default=None,
                    help='op mix, e.g. "seq_write=2,read=5,'
                         'rmw_overwrite=1"')
    lg.add_argument("--objects", type=int, default=None,
                    help="working-set cap (max objects)")
    lg.add_argument("--object-size", type=int, default=None)
    lg.add_argument("--queue-depth", type=int, default=None,
                    help="closed-loop workers (radosbench -t)")
    lg.add_argument("--ops", type=int, default=None,
                    help="total ops to run")
    lg.add_argument("--warmup", type=int, default=None,
                    help="leading ops excluded from the measurement")
    lg.add_argument("--popularity", default=None,
                    choices=["uniform", "zipfian"])
    lg.add_argument("--zipf-theta", type=float, default=None)
    lg.add_argument("--osds", type=int, default=6)
    lg.add_argument("--pg-num", type=int, default=8)
    lg.add_argument("--chunk-size", type=int, default=4096,
                    help="per-shard chunk bytes on the OSDs")
    lg.add_argument("--fault-at", type=int, default=0,
                    help="kill an OSD once this many ops completed "
                         "(0 = no fault)")
    lg.add_argument("--revive-at", type=int, default=0,
                    help="revive it at this op count (0 = at run end)")
    lg.add_argument("--fault-osd", type=int, default=-1,
                    help="kill victim osd id (-1 = use --victim)")
    lg.add_argument("--victim", default="most_primary",
                    choices=["least_primary", "most_primary"],
                    help="named victim picker when --fault-osd is -1 "
                         "(default most_primary: maximum simultaneous "
                         "primary takeovers — the peering soak path)")
    lg.add_argument("--device-clock", action="store_true",
                    help="report small-op p99 from the device clock "
                         "(tunnel-RTT independent)")
    lg.add_argument("--net-fault", default="none",
                    choices=["none", "flaky", "partition"],
                    help="arm the seeded network-fault plane: 'flaky' "
                         "layers >=2%% drop + dup + ~50 ms p95 delay on "
                         "every inter-OSD link between the fire/settle "
                         "offsets; 'partition' asymmetrically cuts the "
                         "--victim OSD off the data plane and merges it "
                         "back (both deterministic from --seed)")
    lg.add_argument("--net-drop", type=float, default=0.02,
                    help="flaky profile drop probability per frame")
    lg.add_argument("--net-dup", type=float, default=0.02,
                    help="flaky profile duplication probability")
    lg.add_argument("--net-delay-ms", type=float, default=5.0,
                    help="flaky profile base delay (+ jitter to ~50 ms "
                         "p95)")
    lg.add_argument("--seed", type=int, default=0xEC)
    lg.add_argument("--coalesce", choices=["on", "off"], default="on",
                    help="per-OSD-tick op coalescing (A/B flag: run "
                         "the same spec both ways to measure what "
                         "batching buys the live path)")
    lg.add_argument("--trace-capture", type=int, default=0,
                    help="capture the N slowest assembled traces "
                         "(span trees + critical paths + Chrome "
                         "trace JSON) into the report")
    lg.add_argument("--forensics-dir", default=None,
                    help="write a forensics bundle (ops-in-flight + "
                         "assembled traces + cluster-log tail + perf "
                         "dump) into this directory when the run is "
                         "non-green or converges slowly")
    lg.add_argument("--slow-convergence-s", type=float, default=0.0,
                    help="with --forensics-dir: also dump when "
                         "post-kill time_to_recovered_s exceeds this "
                         "(0 = only on non-green)")
    lg.add_argument("--force-forensics", action="store_true",
                    help="treat the run as non-green regardless of "
                         "outcome (the forensics smoke-test hook)")
    lg.add_argument("--lockdep", action="store_true",
                    help="arm the runtime lock-order / blocking-"
                         "under-lock detector for the run "
                         "(utils/lockdep.py): findings land in the "
                         "report + forensics bundle (lockdep.json) "
                         "and fail the run like a verify failure")
    lg.add_argument("--smoke", action="store_true",
                    help="tiny deterministic end-to-end run (CI "
                         "surface): smoke preset, 4 OSDs, one "
                         "kill/revive cycle")
    lg.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant mode: run N identically-shaped "
                         "tenants (t0..tN-1), each its own closed "
                         "loop through a tenant-tagged IoCtx onto the "
                         "OSDs' per-tenant mClock classes; the report "
                         "grows per-tenant sections")
    lg.add_argument("--qos-profile", default=None,
                    choices=["high_client", "balanced",
                             "high_recovery"],
                    help="osd_mclock_profile for the run (the "
                         "recovery-vs-client slosh knob)")
    lg.add_argument("--transport", default=None,
                    choices=["tcp", "shm_ring"],
                    help="messenger lane (msgr_transport): shm_ring "
                         "takes the shared-memory fast path for "
                         "co-located peers, falling back to TCP per "
                         "connection when the peer is out-of-process")
    lg.add_argument("--op-shards", type=int, default=None,
                    help="osd_op_num_shards: split each OSD's op "
                         "worker into N per-PG-hash shards (default "
                         "1 = the classic single worker)")
    return p.parse_args(argv)


def _force(out) -> None:
    """Force completion with a real readback: under a remote device
    tunnel ``block_until_ready`` can resolve before execution finishes
    (see bench.py), so sync on ONE actual element per output leaf
    (sliced on device first — a full-array readback would bill the
    transfer, not the compute)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ndim"):
            np.asarray(leaf[(0,) * leaf.ndim])


def run(args: argparse.Namespace) -> tuple[float, float]:
    """Execute one workload; returns (elapsed_seconds, total_KiB).
    Raises RuntimeError if a decoded chunk differs from the original."""
    from ceph_tpu.utils import honor_platform_env

    honor_platform_env()
    import jax
    import jax.numpy as jnp

    from ceph_tpu.codecs import registry

    if args.workload == "checksum":
        return _run_checksum(args)
    if args.workload == "loadgen":
        return _run_loadgen(args)

    profile = {}
    for kv in args.parameter:
        key, _, val = kv.partition("=")
        profile[key] = val
    if args.plugin is None:
        # Only substitute a default when the flag was omitted — an
        # explicit --plugin must never be silently rebound.
        args.plugin = "clay" if args.workload == "repair" else "isa"
    codec = registry.factory(args.plugin, profile)
    if args.workload == "repair":
        if not hasattr(codec, "repair"):
            raise RuntimeError(
                f"plugin {args.plugin!r} has no fractional repair path "
                "(the repair workload needs an MSR codec, e.g. clay)"
            )
        return _run_repair(args, codec)
    k = codec.get_data_chunk_count()
    m = codec.get_coding_chunk_count()

    # Size -> per-shard chunk bytes across the stripe batch.
    chunk = codec.get_chunk_size(max(args.size // args.batch, k))
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 256, (args.batch, k, chunk)).astype(np.uint8)
    data = {i: jnp.asarray(data_np[:, i, :]) for i in range(k)}

    if args.verbose:
        print(
            f"plugin={args.plugin} profile={profile} k={k} m={m} "
            f"chunk={chunk} batch={args.batch}",
            file=sys.stderr,
        )

    parity = codec.encode_chunks(data)  # compile + warm
    jax.block_until_ready(parity)

    if args.workload == "encode":
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            parity = codec.encode_chunks(data)
        _force(parity)
        elapsed = time.perf_counter() - t0
        total_kib = args.iterations * args.batch * k * chunk / 1024
    else:
        chunks = {**data, **parity}
        originals = {i: np.asarray(c) for i, c in chunks.items()}
        if args.erasures_generation == "exhaustive":
            patterns = list(combinations(range(k + m), args.erasures))
        else:
            pool = list(range(k + m))
            patterns = [
                tuple(rng.choice(pool, args.erasures, replace=False))
                for _ in range(args.iterations)
            ]
        # Warm every pattern once outside the clock: host-side matrix
        # inversion, device upload of the decode table, and first-call
        # compilation all happen here, not in the timed loop (the
        # reference also excludes setup from the timed section).
        for erased in set(patterns):
            have = {i: c for i, c in chunks.items() if i not in erased}
            jax.block_until_ready(codec.decode_chunks(set(erased), have))
        elapsed = 0.0
        total_kib = 0.0
        for it in range(args.iterations):
            erased = patterns[it % len(patterns)]
            have = {i: c for i, c in chunks.items() if i not in erased}
            t0 = time.perf_counter()
            out = codec.decode_chunks(set(erased), have)
            _force(out)
            elapsed += time.perf_counter() - t0
            total_kib += args.batch * k * chunk / 1024
            for e in erased:
                if not (np.asarray(out[e]) == originals[e]).all():
                    raise RuntimeError(f"chunk {e} differs after decode")
    return elapsed, total_kib


def _run_repair(args, codec) -> tuple[float, float]:
    """CLAY (or any sub-chunk codec) single-chunk repair decode —
    BASELINE.md config 4. Reads only the helper sub-chunk ranges the
    repair plan asks for, mirroring what the read pipeline ships over
    the wire (ECCommon.h:85 subchunk selectors)."""
    import jax
    import jax.numpy as jnp

    k = codec.get_data_chunk_count()
    m = codec.get_coding_chunk_count()
    n = k + m
    sub = codec.get_sub_chunk_count()
    chunk = codec.get_chunk_size(max(args.size, k))
    sc = chunk // sub
    rng = np.random.default_rng(0)
    data = {
        i: jnp.asarray(rng.integers(0, 256, (chunk,), np.uint8))
        for i in range(k)
    }
    chunks = {**data, **codec.encode_chunks(data)}
    originals = {i: np.asarray(c) for i, c in chunks.items()}

    def helper_reads(lost: int):
        plan = codec.minimum_to_decode({lost}, set(range(n)) - {lost})
        helper = {}
        read_bytes = 0
        for node, ranges in plan.items():
            parts = [
                chunks[node][idx * sc : (idx + cnt) * sc]
                for idx, cnt in ranges
            ]
            read_bytes += sum(p.shape[0] for p in parts)
            helper[node] = jnp.asarray(np.concatenate(
                [np.asarray(p) for p in parts]
            ))
        return helper, read_bytes

    for lost in range(n):  # warm every rotation outside the clock
        helper, _ = helper_reads(lost)
        jax.block_until_ready(codec.repair({lost}, helper))

    elapsed = 0.0
    total_kib = 0.0
    for it in range(args.iterations):
        lost = it % n
        helper, read_bytes = helper_reads(lost)
        t0 = time.perf_counter()
        out = codec.repair({lost}, helper)
        _force(out)
        elapsed += time.perf_counter() - t0
        total_kib += read_bytes / 1024
        if not (np.asarray(out[lost]) == originals[lost]).all():
            raise RuntimeError(f"chunk {lost} differs after repair")
    return elapsed, total_kib


def _run_loadgen(args) -> tuple[float, float]:
    """Live-cluster load generation (the radosbench workload): boot a
    vstart-analog cluster, drive the spec, verify every op, print the
    JSON report on stderr, and honor the two-column contract with
    (wall seconds, client bytes moved / 1024)."""
    import json

    from ceph_tpu.loadgen import (
        FaultEvent,
        FaultSchedule,
        LoadCluster,
        WorkloadSpec,
        parse_mix,
        preset,
        run_spec,
    )

    if args.smoke:
        spec = preset(
            "smoke", seed=args.seed,
            device_clock=bool(args.device_clock),
            trace_capture=args.trace_capture,
        )
        osds, k, m, chunk = 5, 2, 1, 1024
        fault_at = spec.total_ops // 3
        revive_at = (2 * spec.total_ops) // 3
        args.fault_osd = -1  # named victim, resolved below
    else:
        kw: dict = {}
        if args.mix is not None:
            kw["mix"] = parse_mix(args.mix)
        if args.objects is not None:
            kw["max_objects"] = args.objects
        if args.object_size is not None:
            kw["object_size"] = args.object_size
        if args.queue_depth is not None:
            kw["queue_depth"] = args.queue_depth
        if args.ops is not None:
            kw["total_ops"] = args.ops
        if args.warmup is not None:
            kw["warmup_ops"] = args.warmup
        if args.popularity is not None:
            kw["popularity"] = args.popularity
        if args.zipf_theta is not None:
            kw["zipf_theta"] = args.zipf_theta
        kw["seed"] = args.seed
        kw["device_clock"] = bool(args.device_clock)
        kw["trace_capture"] = args.trace_capture
        spec = (
            preset(args.preset, **kw)
            if args.preset else WorkloadSpec(**kw)
        )
        profile = {}
        for pkv in args.parameter:
            key, _, val = pkv.partition("=")
            profile[key] = val
        k = int(profile.get("k", "3"))
        m = int(profile.get("m", "2"))
        osds, chunk = args.osds, args.chunk_size
        fault_at, revive_at = args.fault_at, args.revive_at
    from ceph_tpu.utils import config as _config

    if getattr(args, "tenants", 0):
        from ceph_tpu.loadgen.spec import default_tenants

        spec.tenants = default_tenants(args.tenants)
    net_fault = getattr(args, "net_fault", "none")
    overrides = dict(osd_op_coalescing=(args.coalesce == "on"))
    if getattr(args, "qos_profile", None):
        overrides["osd_mclock_profile"] = args.qos_profile
    if getattr(args, "transport", None):
        overrides["msgr_transport"] = args.transport
    if getattr(args, "op_shards", None):
        overrides["osd_op_num_shards"] = args.op_shards
    if args.lockdep:
        # arm the runtime lock-order / blocking-under-lock detector
        # for this cluster (locks read the flag at construction);
        # findings land in the report + forensics bundle and fail
        # the run like a verify failure
        from ceph_tpu.utils import lockdep as _lockdep

        _lockdep.reset()
        overrides["lockdep"] = True
    if net_fault != "none":
        # lost frames must resolve inside the client's resend
        # ladder, not a 10 s peer-RPC stall per drop (daemons read
        # these at boot — the override wraps cluster creation); the
        # sub-op retransmit ladder arms so a single lost sub-write
        # ack costs ~0.2 s, not an op park
        overrides["osd_peer_rpc_timeout"] = 1.0
        overrides["osd_subop_resend_interval"] = 0.2
    _override_ctx = _config.override(**overrides)
    _override_ctx.__enter__()
    cluster = LoadCluster(
        n_osds=osds, k=k, m=m,
        pg_num=(args.pg_num if not args.smoke else 4),
        chunk_size=chunk,
    )
    schedule = None
    if fault_at:
        # -1 = a NAMED picker resolved at fire time (the default
        # most_primary targets the takeover path the FSM soaks)
        victim = (
            args.fault_osd if args.fault_osd != -1 else args.victim
        )
        events = [
            FaultEvent(at_op=fault_at, action="kill", osd=victim)
        ]
        if revive_at:
            events.append(
                FaultEvent(at_op=revive_at, action="revive")
            )
        schedule = FaultSchedule(events)
    if net_fault == "flaky":
        net_sched = FaultSchedule.net_flaky(
            spec.total_ops, seed=args.seed, drop=args.net_drop,
            dup=args.net_dup, delay_ms=args.net_delay_ms,
        )
        if schedule is None:
            schedule = net_sched
        else:  # chaos composition: churn x lossy links, one schedule
            schedule = FaultSchedule(
                schedule.events + net_sched.events,
                recovery_timeout=schedule.recovery_timeout,
            )
    elif net_fault == "partition":
        part_victim = (
            args.fault_osd if args.fault_osd != -1 else args.victim
        )
        schedule = FaultSchedule.net_partition(
            spec.total_ops, victim=part_victim, seed=args.seed,
        )
    try:
        report = run_spec(cluster, spec, schedule)
        report["coalesce"] = args.coalesce
        if net_fault != "none":
            from ceph_tpu.msg.messenger import net_faults

            report["net_fault"] = net_fault
            report["net_fault_counters"] = dict(net_faults.counters)
            report["net_dedup_hits"] = sum(
                d.net_pc.get("dedup_hits")
                for d in cluster.daemons.values()
            )
            report["net_resends_absorbed"] = sum(
                d.net_pc.get("resends_absorbed")
                for d in cluster.daemons.values()
            )
        report["op_coalesced"] = sum(
            d.coalesce_pc.get("op_coalesced")
            for d in cluster.daemons.values()
        )
        report["subwrite_batches"] = sum(
            d.coalesce_pc.get("subwrite_batches")
            for d in cluster.daemons.values()
        )
        if args.lockdep:
            from ceph_tpu.utils import lockdep as _lockdep

            report["lockdep"] = _lockdep.findings()
        # forensics BEFORE teardown and before any raise: wedged ops
        # are still live, the cluster log still holds this run's tail
        from ceph_tpu.loadgen.forensics import run_is_green

        green, why = run_is_green(report, args.slow_convergence_s)
        if "status_digest" in report:
            # the one-line `cli status` digest (soak.sh echoes it
            # per lap)
            print(
                f"status digest: {report['status_digest']}",
                file=sys.stderr,
            )
        if not green and report.get("pg_states") is not None:
            # the final PG state histogram, for non-green triage
            hist = ", ".join(
                f"{n} {state}" for state, n in sorted(
                    report["pg_states"].items(),
                    key=lambda kv: (-kv[1], kv[0]),
                )
            ) or "(no reports)"
            print(
                f"final pg states ({why}): {hist}", file=sys.stderr
            )
        if args.forensics_dir:
            from ceph_tpu.loadgen.forensics import write_bundle

            if args.force_forensics:
                green, why = False, "forced (--force-forensics)"
            if not green:
                manifest = write_bundle(
                    args.forensics_dir, report, reason=why,
                    trace_capture=args.trace_capture or 8,
                    cluster=cluster,
                )
                report["forensics"] = manifest
                print(
                    f"forensics bundle: {manifest['dir']} ({why})",
                    file=sys.stderr,
                )
        if not report.get("exactly_once"):
            raise RuntimeError(
                f"op accounting mismatch: issued {report['ops_in']} "
                f"!= accounted {report['ops_accounted']}"
            )
        if report["verify_failures"]:
            raise RuntimeError(
                f"{report['verify_failures']} ops failed "
                "content/checksum verification"
            )
        if args.lockdep and any(report.get("lockdep", {}).values()):
            raise RuntimeError(
                f"lockdep findings: {report['lockdep']} (dump: "
                "admin-socket `lockdep`; bundle: lockdep.json)"
            )
    finally:
        cluster.shutdown()
        _override_ctx.__exit__(None, None, None)
    print(json.dumps(report, sort_keys=True), file=sys.stderr)
    return report["duration_s"], report["bytes"] / 1024


def _run_checksum(args) -> tuple[float, float]:
    """Checksummer calculate over vmapped blocks — BASELINE.md
    config 5 (the BlueStore deep-scrub role, Checksummer.h:196)."""
    from ceph_tpu.checksum import Checksummer

    import jax.numpy as jnp

    summer = Checksummer(args.csum_alg, args.csum_block)
    size = (args.size // args.csum_block) * args.csum_block
    if size == 0:
        raise RuntimeError("--size smaller than one csum block")
    rng = np.random.default_rng(0)
    # Device-resident buffer: the workload measures the checksum
    # kernels, not a host->device upload per iteration.
    buf = jnp.asarray(rng.integers(0, 256, (size,), np.uint8))
    np.asarray(summer.calculate(buf))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(args.iterations):
        csums = summer.calculate(buf)
    np.asarray(csums)
    elapsed = time.perf_counter() - t0
    return elapsed, args.iterations * size / 1024


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    try:
        elapsed, total_kib = run(args)
    except RuntimeError as e:
        print(e, file=sys.stderr)
        return 1
    # The reference's two-column contract: elapsed seconds TAB total KiB.
    print(f"{elapsed:.6f}\t{int(total_kib)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
