"""ceph_erasure_code_benchmark-compatible CLI.

Reproduces the reference tool's interface and output contract
(src/test/erasure-code/ceph_erasure_code_benchmark.cc): encode/decode
workloads over a sized buffer for N iterations, ``--parameter k=v``
profile injection, random or exhaustive erasure generation with decoded
content verified against the original, and the two-column
``<elapsed_seconds>\t<total_KiB>`` output the qa sweep harness parses
(qa/workunits/erasure-code/bench.sh).

Usage:
    python -m ceph_tpu.bench_cli encode --plugin isa -P k=8 -P m=4 \
        --size $((80 * 1024 * 1024)) --iterations 100
    python -m ceph_tpu.bench_cli decode --plugin jerasure \
        -P technique=reed_sol_van -P k=4 -P m=2 --erasures 2 \
        --erasures-generation exhaustive
"""

from __future__ import annotations

import argparse
import sys
import time
from itertools import combinations

import numpy as np


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="ecbench", description=__doc__.splitlines()[0]
    )
    p.add_argument("workload", choices=["encode", "decode"])
    p.add_argument("--plugin", "-p", default="isa")
    p.add_argument(
        "--parameter",
        "-P",
        action="append",
        default=[],
        help="profile key=value (repeatable), e.g. -P k=8 -P m=4",
    )
    p.add_argument("--size", "-s", type=int, default=80 * 1024 * 1024,
                   help="total bytes per iteration (default 80 MiB)")
    p.add_argument("--iterations", "-i", type=int, default=100)
    p.add_argument("--erasures", "-e", type=int, default=1,
                   help="erasures per decode iteration")
    p.add_argument(
        "--erasures-generation",
        "-E",
        choices=["random", "exhaustive"],
        default="random",
    )
    p.add_argument("--batch", type=int, default=8,
                   help="stripes per device dispatch")
    p.add_argument("--verbose", "-v", action="store_true")
    return p.parse_args(argv)


def run(args: argparse.Namespace) -> tuple[float, float]:
    """Execute one workload; returns (elapsed_seconds, total_KiB).
    Raises RuntimeError if a decoded chunk differs from the original."""
    from ceph_tpu.utils import honor_platform_env

    honor_platform_env()
    import jax
    import jax.numpy as jnp

    from ceph_tpu.codecs import registry

    profile = {}
    for kv in args.parameter:
        key, _, val = kv.partition("=")
        profile[key] = val
    codec = registry.factory(args.plugin, profile)
    k = codec.get_data_chunk_count()
    m = codec.get_coding_chunk_count()

    # Size -> per-shard chunk bytes across the stripe batch.
    chunk = codec.get_chunk_size(max(args.size // args.batch, k))
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 256, (args.batch, k, chunk)).astype(np.uint8)
    data = {i: jnp.asarray(data_np[:, i, :]) for i in range(k)}

    if args.verbose:
        print(
            f"plugin={args.plugin} profile={profile} k={k} m={m} "
            f"chunk={chunk} batch={args.batch}",
            file=sys.stderr,
        )

    parity = codec.encode_chunks(data)  # compile + warm
    jax.block_until_ready(parity)

    if args.workload == "encode":
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            parity = codec.encode_chunks(data)
        jax.block_until_ready(parity)
        elapsed = time.perf_counter() - t0
        total_kib = args.iterations * args.batch * k * chunk / 1024
    else:
        chunks = {**data, **parity}
        originals = {i: np.asarray(c) for i, c in chunks.items()}
        if args.erasures_generation == "exhaustive":
            patterns = list(combinations(range(k + m), args.erasures))
        else:
            pool = list(range(k + m))
            patterns = [
                tuple(rng.choice(pool, args.erasures, replace=False))
                for _ in range(args.iterations)
            ]
        # Warm every pattern once outside the clock: host-side matrix
        # inversion, device upload of the decode table, and first-call
        # compilation all happen here, not in the timed loop (the
        # reference also excludes setup from the timed section).
        for erased in set(patterns):
            have = {i: c for i, c in chunks.items() if i not in erased}
            jax.block_until_ready(codec.decode_chunks(set(erased), have))
        elapsed = 0.0
        total_kib = 0.0
        for it in range(args.iterations):
            erased = patterns[it % len(patterns)]
            have = {i: c for i, c in chunks.items() if i not in erased}
            t0 = time.perf_counter()
            out = codec.decode_chunks(set(erased), have)
            jax.block_until_ready(out)
            elapsed += time.perf_counter() - t0
            total_kib += args.batch * k * chunk / 1024
            for e in erased:
                if not (np.asarray(out[e]) == originals[e]).all():
                    raise RuntimeError(f"chunk {e} differs after decode")
    return elapsed, total_kib


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    try:
        elapsed, total_kib = run(args)
    except RuntimeError as e:
        print(e, file=sys.stderr)
        return 1
    # The reference's two-column contract: elapsed seconds TAB total KiB.
    print(f"{elapsed:.6f}\t{int(total_kib)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
