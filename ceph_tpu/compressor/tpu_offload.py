"""Accelerator-offloaded compressor — the QAT/UADK plugin role.

The reference ships hardware-offload compression plugins
(compressor/QatAccel.{h,cc}, UADK) behind the same registry as the
software codecs — SURVEY.md §2.4 calls it "the in-tree precedent for
'accelerator-offloaded codec plugin'". The TPU-native equivalent
offloads the stage an accelerator is actually good at: the batched
zero-block scan. Storage blobs are full of zero pages (sparse writes,
truncate tails, the EC zero-padding convention — the codec flags
ZERO_IN_ZERO_OUT / ZERO_PADDING_EXPECTED exist for the same reason),
and finding them is a bandwidth-bound reduction the device does at
HBM speed while the host would crawl byte-wise.

``TpuZeroElimCompressor``: split into fixed blocks, device-reduce an
any-nonzero mask per block (one dispatch for the whole buffer), emit
``u32 orig_len | bitmap | nonzero blocks``. Optionally the surviving
blocks go through zlib (``tpu_zlib`` — scan offloaded, entropy stage
host-side, exactly the QAT split). Small buffers skip the device (the
same dispatch-threshold discipline as the EC host fast path).

Decompression is pure host reassembly — scatter of stored blocks into
a zero canvas (cheap, and reads must not require an accelerator).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from .compressor import Compressor, registry

BLOCK = 256
_HDR = struct.Struct("<IB")  # original length, flags
_FLAG_ZLIB = 0x01

#: below this, the mask computes on host (device dispatch latency
#: dominates tiny buffers — the ec_host_dispatch_bytes discipline)
DEVICE_THRESHOLD = 1 << 20


def _nonzero_mask(blocks: np.ndarray) -> np.ndarray:
    """[B, BLOCK] -> [B] bool, device-reduced when the buffer is big
    enough and a device is initialized."""
    if blocks.nbytes >= DEVICE_THRESHOLD:
        try:
            import jax.numpy as jnp

            return np.asarray(jnp.any(jnp.asarray(blocks) != 0, axis=1))
        except Exception:
            pass  # device trouble: the host answer is identical
    return blocks.any(axis=1)


class TpuZeroElimCompressor(Compressor):
    """Zero-block elimination with a device-offloaded scan."""

    name = "tpu_zeroelim"
    _zlib_residue = False

    def _compress(self, data: bytes) -> tuple[bytes, int | None]:
        orig_len = len(data)
        arr = np.frombuffer(data, np.uint8)
        aligned = (orig_len // BLOCK) * BLOCK
        # zero-copy view of the aligned prefix; only the ragged tail
        # (< BLOCK bytes) is copy-padded — a full-buffer concatenate
        # would double host traffic in a bandwidth-purposed path
        blocks = arr[:aligned].reshape(-1, BLOCK)
        mask = _nonzero_mask(blocks)
        parts = [blocks[mask]]
        if aligned != orig_len:
            tail = np.zeros(BLOCK, np.uint8)
            tail[: orig_len - aligned] = arr[aligned:]
            tail_nz = bool(tail.any())
            mask = np.concatenate([mask, np.array([tail_nz])])
            if tail_nz:
                parts.append(tail[None, :])
        residue = np.concatenate(parts).tobytes() if parts else b""
        flags = 0
        if self._zlib_residue:
            flags |= _FLAG_ZLIB
            residue = zlib.compress(residue, 5)
        out = bytearray(_HDR.pack(orig_len, flags))
        out += np.packbits(mask).tobytes()
        out += residue
        return bytes(out), None

    def _decompress(self, data: bytes, msg: int | None) -> bytes:
        # every corruption surfaces as ValueError — the contract the
        # whole compressor family honors
        if len(data) < _HDR.size:
            raise ValueError("zeroelim blob shorter than its header")
        orig_len, flags = _HDR.unpack_from(data, 0)
        nblocks = -(-orig_len // BLOCK)
        bitmap_bytes = -(-nblocks // 8)
        pos = _HDR.size
        if len(data) < pos + bitmap_bytes:
            raise ValueError("zeroelim blob truncated in bitmap")
        mask = np.unpackbits(
            np.frombuffer(data, np.uint8, bitmap_bytes, pos)
        )[:nblocks].astype(bool)
        pos += bitmap_bytes
        residue = data[pos:]
        if flags & _FLAG_ZLIB:
            try:
                residue = zlib.decompress(residue)
            except zlib.error as e:
                raise ValueError(f"corrupt zlib residue: {e}") from e
        stored = np.frombuffer(residue, np.uint8)
        if stored.size != int(mask.sum()) * BLOCK:
            raise ValueError("zeroelim residue length mismatch")
        canvas = np.zeros((nblocks, BLOCK), np.uint8)
        canvas[mask] = stored.reshape(-1, BLOCK)
        return canvas.reshape(-1)[:orig_len].tobytes()


class TpuZlibCompressor(TpuZeroElimCompressor):
    """Device scan + host zlib on the surviving blocks — the QAT
    split: offload the bandwidth stage, keep entropy coding where it
    is cheap."""

    name = "tpu_zlib"
    _zlib_residue = True


registry.register("tpu_zeroelim", TpuZeroElimCompressor)
registry.register("tpu_zlib", TpuZlibCompressor)
