"""Compressor contract + registry + stdlib-backed plugins.

Behavioral mirror of compressor/Compressor.{h,cc}: ``compress``
returns compressed bytes plus an optional integer ``compressor_message``
(the zstd/QAT side-channel slot — Compressor.h:85); ``decompress``
takes it back. Plugins register by name with the ABI handshake the EC
registry uses. ``CompressionMode`` + ``should_compress`` reproduce the
hint logic (COMP_NONE/PASSIVE/AGGRESSIVE/FORCE, Compressor.h:62-67);
``maybe_compress`` applies the required-ratio gate BlueStore uses
before keeping a compressed blob.
"""

from __future__ import annotations

import bz2
import enum
import lzma
import threading
import zlib
from collections.abc import Callable

from ceph_tpu import PLUGIN_ABI_VERSION
from ceph_tpu.codecs.registry import PluginLoadError


class CompressionMode(enum.Enum):
    """Compressor.h:62-67."""

    NONE = "none"            # compress never
    PASSIVE = "passive"      # compress if hinted COMPRESSIBLE
    AGGRESSIVE = "aggressive"  # compress unless hinted INCOMPRESSIBLE
    FORCE = "force"          # compress always


class Hint(enum.Enum):
    NONE = "none"
    COMPRESSIBLE = "compressible"
    INCOMPRESSIBLE = "incompressible"


def should_compress(mode: CompressionMode, hint: Hint = Hint.NONE) -> bool:
    if mode is CompressionMode.NONE:
        return False
    if mode is CompressionMode.FORCE:
        return True
    if mode is CompressionMode.PASSIVE:
        return hint is Hint.COMPRESSIBLE
    return hint is not Hint.INCOMPRESSIBLE  # AGGRESSIVE


class Compressor:
    """One algorithm; subclasses implement _compress/_decompress."""

    name = "none"

    def get_type_name(self) -> str:
        return self.name

    def compress(self, data: bytes) -> tuple[bytes, int | None]:
        """-> (compressed, compressor_message)."""
        return self._compress(bytes(data))

    def decompress(
        self, data: bytes, compressor_message: int | None = None
    ) -> bytes:
        return self._decompress(bytes(data), compressor_message)

    # defaults: identity
    def _compress(self, data: bytes) -> tuple[bytes, int | None]:
        return data, None

    def _decompress(self, data: bytes, msg: int | None) -> bytes:
        return data


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5) -> None:
        self.level = level

    def _compress(self, data):
        return zlib.compress(data, self.level), None

    def _decompress(self, data, msg):
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            raise ValueError(f"zlib decompress failed: {e}") from e


class Bz2Compressor(Compressor):
    name = "bz2"

    def _compress(self, data):
        return bz2.compress(data), None

    def _decompress(self, data, msg):
        try:
            return bz2.decompress(data)
        except OSError as e:
            raise ValueError(f"bz2 decompress failed: {e}") from e


class LzmaCompressor(Compressor):
    name = "lzma"

    def _compress(self, data):
        return lzma.compress(data), None

    def _decompress(self, data, msg):
        try:
            return lzma.decompress(data)
        except lzma.LZMAError as e:
            raise ValueError(f"lzma decompress failed: {e}") from e


class NoneCompressor(Compressor):
    name = "none"


class CompressorRegistry:
    """CompressionPlugin registry (same handshake as the EC one)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._factories: dict[str, Callable[[], Compressor]] = {}

    def register(
        self,
        name: str,
        factory: Callable[[], Compressor],
        version: str = PLUGIN_ABI_VERSION,
    ) -> None:
        if version != PLUGIN_ABI_VERSION:
            raise PluginLoadError(
                f"compressor {name!r} ABI {version!r} != "
                f"{PLUGIN_ABI_VERSION!r}"
            )
        with self._lock:
            if name in self._factories:
                raise PluginLoadError(
                    f"compressor {name!r} already registered"
                )
            self._factories[name] = factory

    def create(self, name: str) -> Compressor:
        with self._lock:
            fac = self._factories.get(name)
        if fac is None:
            raise PluginLoadError(f"no compressor {name!r}")
        return fac()

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._factories)


registry = CompressorRegistry()
registry.register("none", NoneCompressor)
registry.register("zlib", ZlibCompressor)
registry.register("bz2", Bz2Compressor)
registry.register("lzma", LzmaCompressor)


def maybe_compress(
    comp: Compressor,
    data: bytes,
    required_ratio: float = 0.875,
    mode: CompressionMode = CompressionMode.AGGRESSIVE,
    hint: Hint = Hint.NONE,
) -> tuple[bytes, bool, int | None]:
    """Compress-if-worth-it (the bluestore_compression_required_ratio
    gate): returns (blob, compressed?, compressor_message). The blob
    is kept compressed only when len(out) <= ratio * len(in)."""
    if not should_compress(mode, hint) or not data:
        return data, False, None
    out, msg = comp.compress(data)
    if len(out) <= required_ratio * len(data):
        return out, True, msg
    return data, False, None
