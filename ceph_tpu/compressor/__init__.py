"""Compression plugin family — the ``src/compressor`` analog.

Mirrors the reference's second codec-plugin registry
(compressor/Compressor.h, CompressionPlugin.h): named algorithms
behind one ``Compressor`` contract (compress/decompress with an
optional compressor_message side-channel), a registry with the same
load/handshake semantics as the EC one, compression MODES
(none/passive/aggressive/force, Compressor.h:62-67) driving the
hint-based should-compress decision BlueStore makes per blob, and a
``maybe_compress`` helper implementing the required-ratio gate
(bluestore_compression_required_ratio semantics: keep the compressed
blob only if it actually saved enough).

Algorithms here are zlib / bz2 / lzma (stdlib-backed — the vendored
snappy/zstd/lz4 role) plus ``none``. The QAT/UADK accelerator-offload
precedent maps to device-batched codecs; the registry is where such a
plugin would slot.
"""

from .compressor import (
    CompressionMode,
    Compressor,
    CompressorRegistry,
    maybe_compress,
    registry,
)
from . import tpu_offload  # noqa: F401  (registers tpu_* plugins)

__all__ = [
    "CompressionMode",
    "Compressor",
    "CompressorRegistry",
    "maybe_compress",
    "registry",
]
