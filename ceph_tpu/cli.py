"""``ceph``/``rados``-style CLI over a persistent dev cluster — the
vstart.sh + src/tools/rados analog (SURVEY.md §4 tier 3: the
standalone-cluster ops surface).

State lives in a directory: ``mon/store.log`` (the persistent monitor
DB — every committed map epoch) and ``osd.N/`` FileStore trees. Each
invocation boots the cluster from that state, executes one command,
and shuts down — like driving a vstart cluster with the ceph CLI:

    python -m ceph_tpu.cli -d /tmp/c vstart --osds 6
    python -m ceph_tpu.cli -d /tmp/c profile-set rs62 plugin=jerasure \\
        technique=reed_sol_van k=4 m=2
    python -m ceph_tpu.cli -d /tmp/c pool-create mypool 16 rs62
    python -m ceph_tpu.cli -d /tmp/c put mypool obj ./file
    python -m ceph_tpu.cli -d /tmp/c get mypool obj ./out
    python -m ceph_tpu.cli -d /tmp/c ls mypool
    python -m ceph_tpu.cli -d /tmp/c status
    python -m ceph_tpu.cli -d /tmp/c osd-down 3
    python -m ceph_tpu.cli -d /tmp/c scrub --repair
    python -m ceph_tpu.cli -d /tmp/c bench mypool --size 65536 --count 32
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ceph_tpu.cluster import Monitor, OSDDaemon, RadosClient
from ceph_tpu.cluster.mon_store import MonStore
from ceph_tpu.store import BlockStore, FileStore


def _open_store(osd_dir: str):
    from ceph_tpu.store import open_store

    return open_store(osd_dir)


def _cluster_backend(root: str) -> str | None:
    """The backend existing OSDs use (None if no OSDs yet) — a
    scale-up without --store follows the cluster, not the default."""
    for name in sorted(os.listdir(root)) if os.path.isdir(root) else []:
        if name.startswith("osd."):
            marker = os.path.join(root, name, "backend")
            if os.path.exists(marker):
                return open(marker).read().strip()
            return (
                "block"
                if os.path.exists(os.path.join(root, name, "block"))
                else "file"
            )
    return None


class Cluster:
    """Boot the persistent dev cluster from a state dir."""

    def __init__(self, root: str, quiet: bool = True) -> None:
        self.root = root
        # keyring (cluster PSK): presence turns on AES-GCM secure mode
        # for every daemon and client link of this cluster
        keyring = os.path.join(root, "keyring")
        self.secret: bytes | None = None
        if os.path.exists(keyring):
            self.secret = open(keyring, "rb").read().strip() or None
        # mon tier: a single authority by default; ``vstart --mons N``
        # records N in root/mons and every later boot runs a real
        # quorum (MonQuorumService: Paxos-committed epochs, leader
        # routing, per-rank durable stores)
        mons_file = os.path.join(root, "mons")
        self.n_mons = 1
        if os.path.exists(mons_file):
            raw = open(mons_file).read().strip()
            try:
                self.n_mons = max(1, int(raw or 1))
            except ValueError:
                # a garbled mons file must not brick every command —
                # infer the quorum size from the rank-store dirs
                ranks = [
                    d for d in os.listdir(root)
                    if d.startswith("mon.") and d[4:].isdigit()
                ]
                self.n_mons = max(1, len(ranks))
                print(
                    f"warning: unreadable {mons_file} ({raw!r}); "
                    f"assuming {self.n_mons} mons from rank stores",
                    file=sys.stderr,
                )
        if self.n_mons > 1:
            self._boot_mon_quorum(root)
        else:
            self.mon_store = MonStore(os.path.join(root, "mon", "store.log"))
            initial, history = self.mon_store.replay()
            # a cluster DOWNGRADED from a quorum: the rank stores may
            # be ahead of the legacy store — abandoning them would
            # silently lose every epoch committed in quorum mode (and
            # regress the pool-id floor into reuse hazards). Seed from
            # the newest store, and take the pool-id floor across ALL
            # stores (a rank store's trimmed history may remember ids
            # the survivor's window no longer does).
            floor = self.mon_store.pool_id_floor()
            for name in sorted(os.listdir(root)) if os.path.isdir(root) else []:
                if not (name.startswith("mon.") and name[4:].isdigit()):
                    continue
                rs = MonStore(os.path.join(root, name, "store.log"))
                floor = max(floor, rs.pool_id_floor())
                rm, rh = rs.replay()
                if rm.epoch > initial.epoch:
                    by_epoch = {i.epoch: i for i in rh}
                    if all(
                        e in by_epoch
                        for e in range(initial.epoch + 1, rm.epoch + 1)
                    ):
                        for e in range(initial.epoch + 1, rm.epoch + 1):
                            self.mon_store.append(by_epoch[e])
                    else:
                        self.mon_store.trim(rm)
                    initial, history = self.mon_store.replay()
            self.mon = Monitor(
                initial=initial, commit_fn=self.mon_store.append,
                history=history,
                pool_id_floor=floor,
            )
            if len(history) > self.mon_store.keep:
                self.mon_store.trim(initial)
        self.daemons: dict[int, OSDDaemon] = {}
        for name in sorted(os.listdir(root)) if os.path.isdir(root) else []:
            if not name.startswith("osd."):
                continue
            osd = int(name.split(".", 1)[1])
            if os.path.exists(os.path.join(root, name, "stopped")):
                continue  # operator stopped it (osd-down marker)
            store = _open_store(os.path.join(root, name))
            d = OSDDaemon(osd, self.mon, store=store, secret=self.secret)
            d.start()
            self.daemons[osd] = d
        # anything in the map but not on disk is gone: mark it down
        for osd in sorted(self.mon.osdmap.up_osds() - set(self.daemons)):
            self.mon.osd_down(osd)
        self.client = RadosClient(self.mon, backoff=0.02, secret=self.secret)

    def _boot_mon_quorum(self, root: str) -> None:
        """N monitor ranks, each with its own durable store; the map
        service is the quorum handle (leader-routed, Paxos-committed).
        Resume takes the highest-epoch rank store as canonical and
        heals laggards (the mon store sync phase)."""
        from ceph_tpu.cluster.mon_quorum import (
            MonQuorumService,
            QuorumMonitor,
        )

        self.mon_stores = [
            MonStore(os.path.join(root, f"mon.{r}", "store.log"))
            for r in range(self.n_mons)
        ]
        replays = [s.replay() for s in self.mon_stores]
        initial, history = max(replays, key=lambda t: t[0].epoch)
        # the canonical seed may live OUTSIDE ranks 0..n-1: the legacy
        # single-mon store (1 -> N growth) or a higher rank's store
        # (shrinking the quorum after its leader sat above the new n).
        # The store DIR is the identity (the KV store lives beside the
        # legacy log-file path, which MonStore removes after import).
        legacy_dir = os.path.join(root, "mon")
        legacy_store = None
        extra_floor = 0
        if os.path.isdir(legacy_dir):
            legacy_store = MonStore(os.path.join(legacy_dir, "store.log"))
            lm, lh = legacy_store.replay()
            if lm.epoch > initial.epoch:
                initial, history = lm, lh
        for name in sorted(os.listdir(root)):
            if not (name.startswith("mon.") and name[4:].isdigit()):
                continue
            if int(name[4:]) < self.n_mons:
                continue  # in-quorum rank, already replayed above
            ds = MonStore(os.path.join(root, name, "store.log"))
            extra_floor = max(extra_floor, ds.pool_id_floor())
            dm, dh = ds.replay()
            if dm.epoch > initial.epoch:
                initial, history = dm, dh
        by_epoch = {i.epoch: i for i in history}
        for r, (m, _h) in enumerate(replays):
            if m.epoch >= initial.epoch:
                continue
            # heal a lagging store: contiguous tail append when the
            # window reaches back far enough, else full-map snapshot
            if all(
                e in by_epoch for e in range(m.epoch + 1, initial.epoch + 1)
            ):
                for e in range(m.epoch + 1, initial.epoch + 1):
                    self.mon_stores[r].append(by_epoch[e])
            else:
                self.mon_stores[r].trim(initial)
        floor = max(s.pool_id_floor() for s in self.mon_stores)
        floor = max(floor, extra_floor)
        if legacy_store is not None:
            floor = max(floor, legacy_store.pool_id_floor())
        self.mon_quorum = MonQuorumService(
            self.n_mons,
            on_commit=lambda r, incr: self.mon_stores[r].append(incr),
            initial=initial,
            history=history,
            pool_id_floor=floor,
        )
        # operator-stopped ranks stay down across invocations (the
        # osd "stopped" marker convention, mon tier). Boot-time clamp:
        # markers that would leave a minority are IGNORED — a wedged
        # quorum cannot serve the commands needed to unwedge it, so
        # the directory would be unrecoverable from the CLI.
        stopped = [
            r for r in range(self.n_mons)
            if os.path.exists(os.path.join(root, f"mon.{r}", "stopped"))
        ]
        if (self.n_mons - len(stopped)) * 2 <= self.n_mons:
            print(
                f"warning: stopped markers for mons {stopped} would "
                "lose quorum; ignoring them (reviving all ranks)",
                file=sys.stderr,
            )
        else:
            for r in stopped:
                self.mon_quorum.kill(r)
        self.mon = QuorumMonitor(self.mon_quorum)

    def add_osd(self, osd: int, zone: str = "", backend: str | None = None) -> None:
        self.mon.osd_crush_add(osd, zone=zone)
        backend = backend or _cluster_backend(self.root) or "file"
        path = os.path.join(self.root, f"osd.{osd}")
        store = BlockStore(path) if backend == "block" else FileStore(path)
        with open(os.path.join(path, "backend"), "w") as f:
            f.write(backend)
        d = OSDDaemon(osd, self.mon, store=store, secret=self.secret)
        d.start()
        self.daemons[osd] = d

    def settle(self, timeout: float = 60.0) -> None:
        """Wait for pending backfills (pg_temp) to clear."""
        end = time.monotonic() + timeout
        while self.mon.osdmap.pg_temp and time.monotonic() < end:
            time.sleep(0.05)

    def shutdown(self) -> None:
        self.settle(timeout=5.0)
        self.client.shutdown()
        for d in self.daemons.values():
            d.stop()
            if hasattr(d.store, "close"):
                d.store.close()


def cmd_vstart(cl: Cluster, args) -> int:
    if getattr(args, "secure", False) and cl.secret is None:
        # generate the keyring; takes effect from the NEXT invocation
        # (this one already booted plaintext)
        import secrets as _secrets

        # hex, not raw bytes: the file is read with a whitespace
        # strip, which must never change the effective key.  0o600:
        # the PSK must not be world-readable on multi-user hosts
        # (ceph treats keyring files the same way).
        fd = os.open(
            os.path.join(cl.root, "keyring"),
            os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
            0o600,
        )
        # O_CREAT's mode only applies to fresh inodes; a pre-existing
        # (e.g. empty) keyring keeps its old perms without this.
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(_secrets.token_hex(32) + "\n")
        print("keyring written: cluster runs AES-GCM secure mode from "
              "the next invocation")
    if getattr(args, "mons", None):
        with open(os.path.join(cl.root, "mons"), "w") as f:
            f.write(str(max(1, args.mons)))
        if args.mons != cl.n_mons:
            print(f"mon quorum size set to {args.mons}: takes effect "
                  "from the next invocation")
    existing = set(cl.daemons)
    for i in range(args.osds):
        if i not in existing:
            cl.add_osd(
                i, zone=f"z{i % max(args.zones, 1)}", backend=args.store
            )
    mons = (f"{cl.n_mons} mons (leader mon."
            f"{cl.mon_quorum.leader_rank()})" if cl.n_mons > 1
            else "1 mon")
    print(f"cluster up: {len(cl.daemons)} osds, {mons}, epoch "
          f"{cl.mon.osdmap.epoch}, dir {cl.root}")
    if getattr(args, "exporter", None) is not None:
        import time as _time

        from ceph_tpu.utils.exporter import Exporter

        exp = Exporter()
        host, port = exp.start(port=args.exporter)
        print(f"metrics: http://{host}:{port}/metrics (ctrl-c to stop)")
        # The CLI is one-command-and-exit; an exporter only makes
        # sense while the cluster process lives, so this invocation
        # blocks and serves until interrupted.
        try:
            while True:
                _time.sleep(1)
        except KeyboardInterrupt:
            pass
        finally:
            exp.stop()
    return 0


def _flush_stats(cl: Cluster) -> None:
    """Force a stats report from every live daemon so the status/pg
    dump/df surfaces read fresh numbers instead of waiting a tick
    (the CLI is one-command-and-exit)."""
    for d in cl.daemons.values():
        try:
            d.report_pg_stats(force=True)
        except Exception:
            pass


def cmd_status(cl: Cluster, args) -> int:
    """The `ceph -s` role: health digest + mon/osd census + PG state
    histogram + client/recovery IO rates, all from the stats plane
    (cluster/pgmap.py)."""
    from ceph_tpu.cluster.pgmap import format_status, status_dict

    _flush_stats(cl)
    st = status_dict(cl.mon)
    if cl.n_mons > 1:
        svc = cl.mon_quorum
        live = sorted(set(range(svc.n)) - svc.dead)
        st["mons"] = (
            f"{svc.n} total, quorum {live} "
            f"(leader mon.{svc.leader_rank()})"
        )
    text = format_status(st)
    if "mons" in st:
        text = text.replace(
            f"    mon: epoch {st['epoch']}",
            f"    mon: {st['mons']}, epoch {st['epoch']}",
        )
    print(text)
    m = cl.mon.osdmap
    for name, spec in sorted(m.pools.items()):
        print(
            f"    pool {name!r}: id {spec.pool_id}, {spec.pg_num} "
            f"pgs, EC {spec.k}+{spec.m} ({spec.plugin}/"
            f"{spec.profile_name})"
        )
    if m.pg_temp:
        print(f"    backfilling: {sorted(m.pg_temp)}")
    return 0


def cmd_pg_dump(cl: Cluster, args) -> int:
    """The `ceph pg dump` role: every PG's stats row + osd stats."""
    from ceph_tpu.cluster.pgmap import format_pg_dump

    _flush_stats(cl)
    dump = cl.mon.pgmap.pg_dump()
    if getattr(args, "json", False):
        print(json.dumps(dump, sort_keys=True, default=str))
    else:
        print(format_pg_dump(dump))
    return 0


def cmd_df(cl: Cluster, args) -> int:
    """The `ceph df` role: cluster capacity + per-pool usage from
    the stats plane's store census."""
    from ceph_tpu.cluster.pgmap import format_df

    _flush_stats(cl)
    df = cl.mon.pgmap.df(cl.mon.osdmap)
    if getattr(args, "json", False):
        print(json.dumps(df, sort_keys=True))
    else:
        print(format_df(df))
    return 0


def cmd_osd_tree(cl: Cluster, args) -> int:
    m = cl.mon.osdmap
    for osd, info in sorted(m.osds.items()):
        state = ("up" if info.up else "down") + "/" + (
            "in" if info.in_ else "out"
        )
        addr = f"{info.addr[0]}:{info.addr[1]}" if info.addr else "-"
        where = (
            " ".join(f"{t}={b}" for t, b in info.location)
            or (f"zone {info.zone}" if info.zone else "-")
        )
        print(
            f"osd.{osd}\tweight {info.weight:.2f}\t{where}\t"
            f"{state}\t{addr}"
        )
    for name, steps in sorted(m.crush_rules.items()):
        rendered = "; ".join(" ".join(str(x) for x in s) for s in steps)
        print(f"rule {name}: {rendered}")
    return 0


def cmd_profile_set(cl: Cluster, args) -> int:
    profile = dict(kv.split("=", 1) for kv in args.kv)
    cl.mon.osd_erasure_code_profile_set(args.name, profile, force=args.force)
    print(f"profile {args.name!r} = {profile}")
    return 0


def cmd_pool_create(cl: Cluster, args) -> int:
    cl.mon.osd_pool_create(
        args.name, args.pg_num, args.profile,
        distinct_zones=args.distinct_zones,
        failure_domain=args.failure_domain,
    )
    spec = cl.mon.osdmap.pools[args.name]
    rule = f", rule {spec.crush_rule!r}" if spec.crush_rule else ""
    print(f"pool {args.name!r} created: EC {spec.k}+{spec.m}, "
          f"{spec.pg_num} pgs{rule}")
    return 0


def cmd_snap(cl: Cluster, args) -> int:
    """pool snapshots: create / rm / ls (rados mksnap/rmsnap/lssnap)."""
    if args.action in ("create", "rm") and not args.snap:
        print(f"snap {args.action} needs a snap name")
        return 1
    if args.action == "create":
        cl.mon.osd_pool_snap_create(args.pool, args.snap)
        print(f"created pool snap {args.snap!r} on {args.pool!r}")
    elif args.action == "rm":
        cl.mon.osd_pool_snap_rm(args.pool, args.snap)
        print(f"removed pool snap {args.snap!r} from {args.pool!r}")
    else:  # ls
        spec = cl.mon.osdmap.pools.get(args.pool)
        if spec is None:
            print(f"no such pool: {args.pool!r}")
            return 1
        for sid, name, epoch in spec.snaps:
            print(f"{sid}\t{name}\t(epoch {epoch})")
    return 0


def cmd_put(cl: Cluster, args) -> int:
    data = (
        sys.stdin.buffer.read() if args.file == "-"
        else open(args.file, "rb").read()
    )
    io = cl.client.open_ioctx(args.pool)
    io.write_full(args.oid, data)
    print(f"wrote {len(data)} bytes to {args.pool}/{args.oid}")
    return 0


def cmd_get(cl: Cluster, args) -> int:
    io = cl.client.open_ioctx(args.pool)
    data = io.read(args.oid)
    if args.file == "-":
        sys.stdout.buffer.write(data)
    else:
        with open(args.file, "wb") as f:
            f.write(data)
        print(f"read {len(data)} bytes from {args.pool}/{args.oid}")
    return 0


def cmd_rm(cl: Cluster, args) -> int:
    cl.client.open_ioctx(args.pool).remove(args.oid)
    print(f"removed {args.pool}/{args.oid}")
    return 0


def cmd_ls(cl: Cluster, args) -> int:
    # the client-visible listing (PGLS through primaries), not a
    # direct store peek
    for oid in cl.client.open_ioctx(args.pool).list_objects():
        print(oid)
    return 0


def cmd_stat(cl: Cluster, args) -> int:
    size = cl.client.open_ioctx(args.pool).stat(args.oid)
    print(f"{args.pool}/{args.oid}: {size} bytes")
    return 0


def cmd_mon_kill(cl: Cluster, args) -> int:
    """Take a monitor rank down durably (the mon-chaos surface).
    Refuses to kill into a lost quorum — a majority-dead quorum
    cannot serve the commands needed to revive it."""
    if cl.n_mons < 2:
        print("single-mon cluster: nothing to kill", file=sys.stderr)
        return 1
    svc = cl.mon_quorum
    r = args.rank
    if r < 0 or r >= svc.n:
        print(f"no such mon rank {r}", file=sys.stderr)
        return 1
    live_after = svc.n - len(svc.dead | {r})
    if live_after * 2 <= svc.n:
        # strictly-more-than-half must survive — for ANY n, odd or
        # even (an earlier >= n+1 pre-check skipped the guard at n=2
        # and wedged the cluster directory)
        print(
            f"refusing: killing mon.{r} would leave {live_after}/"
            f"{svc.n} — quorum lost and unrecoverable from the "
            "CLI", file=sys.stderr,
        )
        return 1
    svc.kill(r)
    open(os.path.join(cl.root, f"mon.{r}", "stopped"), "w").close()
    print(f"mon.{r} killed (leader now mon.{svc.leader_rank()})")
    return 0


def cmd_mon_revive(cl: Cluster, args) -> int:
    if cl.n_mons < 2:
        print("single-mon cluster", file=sys.stderr)
        return 1
    svc = cl.mon_quorum
    if args.rank < 0 or args.rank >= svc.n:
        print(f"no such mon rank {args.rank}", file=sys.stderr)
        return 1
    marker = os.path.join(cl.root, f"mon.{args.rank}", "stopped")
    if os.path.exists(marker):
        os.remove(marker)
    svc.revive(args.rank)
    print(f"mon.{args.rank} revived (caught up from the quorum log)")
    return 0


def cmd_osd_down(cl: Cluster, args) -> int:
    d = cl.daemons.pop(args.osd, None)
    if d is not None:
        d.stop()
        if hasattr(d.store, "close"):
            d.store.close()  # final checkpoint for BlockStore
    open(os.path.join(cl.root, f"osd.{args.osd}", "stopped"), "w").close()
    cl.mon.osd_down(args.osd)
    print(f"osd.{args.osd} stopped + marked down")
    return 0


def cmd_osd_up(cl: Cluster, args) -> int:
    marker = os.path.join(cl.root, f"osd.{args.osd}", "stopped")
    if os.path.exists(marker):
        os.unlink(marker)
    if args.osd not in cl.daemons:
        store = _open_store(os.path.join(cl.root, f"osd.{args.osd}"))
        d = OSDDaemon(args.osd, cl.mon, store=store)
        d.start()
        cl.daemons[args.osd] = d
    cl.settle()
    print(f"osd.{args.osd} restarted")
    return 0


def cmd_osd_out(cl: Cluster, args) -> int:
    cl.mon.osd_out(args.osd)
    cl.settle()
    print(f"osd.{args.osd} marked out; rebalance settled")
    return 0


def cmd_osd_in(cl: Cluster, args) -> int:
    cl.mon.osd_in(args.osd)
    cl.settle()
    print(f"osd.{args.osd} marked in; rebalance settled")
    return 0


def cmd_scrub(cl: Cluster, args) -> int:
    total = bad = repaired = 0
    for d in list(cl.daemons.values()):
        for (pool, pgid), results in d.scrub_all(repair=args.repair).items():
            for r in results:
                total += 1
                if not r.ok:
                    bad += 1
                    print(f"{pool}/{pgid} {r.oid}: "
                          + "; ".join(
                              f"shard {e.shard} {e.kind} {e.detail}"
                              for e in r.errors))
                if r.repaired:
                    repaired += 1
    print(f"scrubbed {total} objects: {bad} inconsistent, "
          f"{repaired} repaired")
    return 1 if (bad and not args.repair) else 0


def cmd_perf(cl: Cluster, args) -> int:
    """The `ceph daemon ... perf dump` role: every pipeline's counters
    (all daemons share this process's collection)."""
    from ceph_tpu.utils import perf_collection

    def active(v) -> bool:
        if isinstance(v, (int, float)):
            return bool(v)
        if isinstance(v, dict):
            if "counts" in v:  # histogram: samples, not bucket edges
                return any(v["counts"])
            return any(active(x) for x in v.values())
        return False

    snap = perf_collection.dump()
    for logger in sorted(snap):
        if args.grep and args.grep not in logger:
            continue
        counters = {k: v for k, v in snap[logger].items() if active(v)}
        if counters:
            print(json.dumps({logger: counters}))
    return 0


def cmd_health(cl: Cluster, args) -> int:
    """The `ceph health detail` role (mgr health model), plus the
    cluster-log digest the reference appends as `ceph -s` recent
    events (slow ops, down-marks, scrub errors, peering stalls)."""
    from ceph_tpu.cluster import Manager
    from ceph_tpu.utils.cluster_log import cluster_log

    _flush_stats(cl)
    report = Manager(cl.mon).health()
    print(report["status"])
    for name, check in sorted(report["checks"].items()):
        print(f"  [{check['severity'].upper()}] {name}: {check['detail']}")
    summary = cluster_log.summary()
    print(
        f"cluster log: {summary['events']} recent events, "
        f"{summary['warnings']} warnings"
    )
    for e in summary["recent_warnings"]:
        print(
            f"  {e['severity']} [{e['daemon']}] {e['type']}: "
            f"{e['message']}"
        )
    return 0 if report["status"] == "HEALTH_OK" else 1


def cmd_autoscale_status(cl: Cluster, args) -> int:
    """The `ceph osd pool autoscale-status` role."""
    from ceph_tpu.cluster import Manager

    for row in Manager(cl.mon).autoscale_status():
        flag = " (warn)" if row["warn"] else ""
        print(
            f"pool {row['pool']!r}: pg_num {row['pg_num']}, "
            f"ideal ~{row['ideal_pg_num']}{flag}"
        )
    return 0


def cmd_balance(cl: Cluster, args) -> int:
    """One balancer run (the `ceph balancer execute` role): reweight
    until the target PG-shard distribution settles, then wait for the
    resulting backfills to finish."""
    from ceph_tpu.cluster import Manager

    mgr = Manager(cl.mon)
    before = mgr.pg_shard_counts()
    rounds = mgr.balance()
    after = mgr.pg_shard_counts()
    cl.settle(timeout=args.timeout)
    print(f"balanced in {rounds} rounds: {before} -> {after}")
    return 0


def cmd_bench(cl: Cluster, args) -> int:
    """The `rados bench` role: parallel writes then reads via aio
    (objects spread over primaries; concurrency is the point)."""
    import numpy as np

    io = cl.client.open_ioctx(args.pool)
    blob = np.random.default_rng(0).integers(
        0, 256, args.size, dtype=np.uint8
    ).tobytes()
    # the shared objecter aio pool bounds real in-flight ops at 16:
    # clamp so the reported depth is the actual one
    depth = min(max(args.concurrency, 1), 16)

    def run_phase(fn) -> float:
        t0 = time.perf_counter()
        pending = []
        for i in range(args.count):
            pending.append(fn(i))
            if len(pending) >= depth:
                pending.pop(0).wait_for_complete()
        for c in pending:
            c.wait_for_complete()
        return time.perf_counter() - t0

    try:
        t_w = run_phase(lambda i: io.aio_write(f"bench_{i}", blob))
        reads: list = []
        t_r = run_phase(
            lambda i: io.aio_read(f"bench_{i}", on_complete=reads.append)
        )
        bad = [c for c in reads if c.reply is not None
               and c.reply.data != blob]
        if bad:
            raise IOError(f"{len(bad)} reads returned wrong bytes")
    finally:
        # bench objects must not survive a failed run
        for i in range(args.count):
            try:
                io.remove(f"bench_{i}")
            except FileNotFoundError:
                pass
    mb = args.size * args.count / 1e6
    print(json.dumps({
        "write_MBps": round(mb / t_w, 2),
        "read_MBps": round(mb / t_r, 2),
        "ops": args.count,
        "object_size": args.size,
        "concurrency": depth,
    }))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ceph_tpu.cli", description=__doc__.splitlines()[0]
    )
    p.add_argument("-d", "--dir", required=True, help="cluster state dir")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("vstart", help="create/boot a dev cluster")
    s.add_argument("--osds", type=int, default=6)
    s.add_argument("--zones", type=int, default=3)
    s.add_argument(
        "--mons", type=int, default=None,
        help="monitor quorum size (>1 boots a Paxos quorum with "
             "leader routing from the next invocation)",
    )
    s.add_argument(
        "--store", choices=("file", "block"), default=None,
        help="OSD backend for NEW osds: FileStore tree or BlockStore "
             "raw device (default: whatever the cluster already uses, "
             "else file)",
    )
    s.add_argument(
        "--secure", action="store_true",
        help="generate a cluster keyring (AES-GCM secure mode for all "
             "links from the next invocation on)",
    )
    s.add_argument(
        "--exporter", type=int, nargs="?", const=0, default=None,
        metavar="PORT",
        help="serve Prometheus /metrics (0 or no value = ephemeral "
             "port; the src/exporter + mgr/prometheus analog)",
    )
    s.set_defaults(fn=cmd_vstart)

    sub.add_parser(
        "status", help="the `ceph -s` shape: health + census + PG "
        "state histogram + IO rates from the stats plane"
    ).set_defaults(fn=cmd_status)
    sub.add_parser("osd-tree").set_defaults(fn=cmd_osd_tree)

    s = sub.add_parser(
        "pg", help="PG-stats surfaces (`pg dump`)"
    )
    s.add_argument("action", choices=["dump"])
    s.add_argument("--json", action="store_true",
                   help="machine-readable dump")
    s.set_defaults(fn=cmd_pg_dump)

    s = sub.add_parser(
        "df", help="cluster + per-pool capacity/usage (`ceph df`)"
    )
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_df)

    s = sub.add_parser("profile-set")
    s.add_argument("name")
    s.add_argument("kv", nargs="+", help="key=value pairs")
    s.add_argument("--force", action="store_true")
    s.set_defaults(fn=cmd_profile_set)

    s = sub.add_parser("pool-create")
    s.add_argument("name")
    s.add_argument("pg_num", type=int)
    s.add_argument("profile", nargs="?", default="")
    s.add_argument("--distinct-zones", action="store_true")
    s.add_argument(
        "--failure-domain", default="",
        help="spread shards across this bucket type (host/rack/...) "
             "via an auto-created crush rule",
    )
    s.set_defaults(fn=cmd_pool_create)

    s = sub.add_parser(
        "snap", help="pool snapshots (rados mksnap/rmsnap/lssnap)"
    )
    s.add_argument("action", choices=["create", "rm", "ls"])
    s.add_argument("pool")
    s.add_argument("snap", nargs="?", default="")
    s.set_defaults(fn=cmd_snap)

    for name, fn, extra in (
        ("put", cmd_put, ["pool", "oid", "file"]),
        ("get", cmd_get, ["pool", "oid", "file"]),
        ("rm", cmd_rm, ["pool", "oid"]),
        ("ls", cmd_ls, ["pool"]),
        ("stat", cmd_stat, ["pool", "oid"]),
    ):
        s = sub.add_parser(name)
        for a in extra:
            s.add_argument(a)
        s.set_defaults(fn=fn)

    for name, fn in (
        ("osd-down", cmd_osd_down),
        ("osd-up", cmd_osd_up),
        ("osd-out", cmd_osd_out),
        ("osd-in", cmd_osd_in),
    ):
        s = sub.add_parser(name)
        s.add_argument("osd", type=int)
        s.set_defaults(fn=fn)

    for name, fn in (
        ("mon-kill", cmd_mon_kill),
        ("mon-revive", cmd_mon_revive),
    ):
        s = sub.add_parser(
            name, help=f"{name.split('-')[1]} a monitor rank "
            "(quorum chaos surface; --mons > 1 clusters)"
        )
        s.add_argument("rank", type=int)
        s.set_defaults(fn=fn)

    s = sub.add_parser("scrub")
    s.add_argument("--repair", action="store_true")
    s.set_defaults(fn=cmd_scrub)

    sub.add_parser(
        "health", help="structured health report (mgr health model)"
    ).set_defaults(fn=cmd_health)
    sub.add_parser(
        "autoscale-status", help="pg_autoscaler recommendations"
    ).set_defaults(fn=cmd_autoscale_status)
    s = sub.add_parser("balance", help="run the balancer (mgr module)")
    s.add_argument("--timeout", type=float, default=60.0)
    s.set_defaults(fn=cmd_balance)

    s = sub.add_parser("perf", help="dump perf counters (perf dump)")
    s.add_argument("--grep", default="", help="substring filter")
    s.set_defaults(fn=cmd_perf)

    s = sub.add_parser("bench")
    s.add_argument("pool")
    s.add_argument("--size", type=int, default=65536)
    s.add_argument("--count", type=int, default=16)
    s.add_argument("--concurrency", type=int, default=8,
                   help="in-flight aio ops (rados bench -t)")
    s.set_defaults(fn=cmd_bench)

    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cl = Cluster(args.dir)
    try:
        return args.fn(cl, args)
    finally:
        cl.shutdown()


if __name__ == "__main__":
    sys.exit(main())
