"""Shared-memory ring transport — the co-located fast lane.

When every daemon of a cluster lives in one process (the loadgen /
bench topology), routing EC sub-write fan-out through loopback TCP
pays kernel socket round-trips for bytes that never leave the
process. This module provides the alternative lane: a pair of
bounded byte rings (native ``ctpu_ring`` slots when the C++ tier
loads, a pure-Python deque ring otherwise) wrapped in a socket
duck-type, so :class:`~ceph_tpu.msg.messenger.Connection` runs over
it UNCHANGED — same framing, same per-segment CRC, same secure
handshake, same reader thread, and crucially the same
``NetFaultPlane`` hooks, which act on logical frames in
``Connection.send`` / ``_read_loop`` *above* the transport (the
acceptance contract: chaos rules apply identically on shm links and
TCP links).

Negotiation happens at connect time, not per frame: when
``msgr_transport = shm_ring`` and the dialed address resolves to an
in-process listener (the bind registry below), ``Messenger.connect``
builds a ring pair and hands the server end to the listener's normal
``_finish_accept`` path. Remote or unresolved addresses fall back to
TCP transparently — the lane is an upgrade, never a requirement.

Teardown mirrors TCP semantics: closing an endpoint closes both
rings; a closed ring still drains buffered chunks before the reader
sees EOF (the FIN-then-drain contract ``_read_loop`` already
handles), and a writer hitting a closed ring gets ``OSError`` like a
send on a reset socket.
"""

from __future__ import annotations

import threading

from ceph_tpu.utils import config as _config
from ceph_tpu.utils.lockdep import DebugLock, DebugRLock

#: ring geometry per direction: chunks of at most SLOT_BYTES travel
#: through a CAPACITY-slot ring (native) or deque (fallback). 32 x
#: 32 KiB = 1 MiB of in-flight bytes per direction per link — enough
#: to stream a full EC sub-write batch without writer stalls, small
#: enough that a fully-meshed loadgen cluster stays tens of MiB.
SLOT_BYTES = 32768
CAPACITY = 32

#: transport stats (the `ss -i` analog for the shm lane); read via
#: snapshot() by the bench A/B legs
_stats_lock = DebugLock("msgr.shm_stats")
_stats = {"connections": 0, "chunks": 0, "bytes": 0}

#: in-process listener registry: bind address -> Messenger. Populated
#: unconditionally at bind() (registration is cheap); consulted by
#: connect() only when the msgr_transport gate selects this lane.
_listeners: dict[tuple, object] = {}
_reg_lock = DebugLock("msgr.shm_registry")


def register(addr, messenger) -> None:
    with _reg_lock:
        _listeners[tuple(addr)] = messenger


def unregister(addr, messenger) -> None:
    with _reg_lock:
        if _listeners.get(tuple(addr)) is messenger:
            del _listeners[tuple(addr)]


def lookup(addr):
    """The connect-time negotiation: the target Messenger when the
    shm lane is configured AND the address resolves in-process (and
    the listener is still accepting), else None -> caller dials TCP."""
    if _config.get("msgr_transport") != "shm_ring":
        return None
    with _reg_lock:
        target = _listeners.get(tuple(addr))
    if target is None or target._stopping:
        return None
    return target


def snapshot() -> dict:
    with _stats_lock:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


class _PyRing:
    """Pure-Python fallback ring: bounded deque of chunks with the
    same timed push/pop/close contract as native.RingBuffer. Return
    codes match: 1 ok, 0 closed (push) / closed-and-drained (pop),
    -2 timeout."""

    def __init__(self, capacity: int) -> None:
        from collections import deque

        self._q = deque()
        self._capacity = capacity
        self._closed = False
        self._cv = threading.Condition(DebugRLock("msgr.shm_pyring"))

    def push_timed(self, data, timeout=None) -> int:
        with self._cv:
            if not self._cv.wait_for(
                lambda: len(self._q) < self._capacity or self._closed,
                timeout,
            ):
                return -2
            if self._closed:
                return 0
            self._q.append(bytes(data))
            self._cv.notify_all()
            return 1

    def pop_timed(self, timeout=None):
        with self._cv:
            if not self._cv.wait_for(
                lambda: self._q or self._closed, timeout
            ):
                return -2, None
            if not self._q:
                return 0, None
            chunk = self._q.popleft()
            self._cv.notify_all()
            return 1, chunk

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


def _make_ring():
    try:
        from ceph_tpu import native

        if native.available():
            return native.RingBuffer(CAPACITY, SLOT_BYTES)
    except Exception:
        pass
    return _PyRing(CAPACITY)


class RingSock:
    """Socket duck-type over a (tx, rx) ring pair — implements the
    exact surface :class:`Connection` touches: ``sendall``, ``recv``,
    ``settimeout``, ``shutdown``, ``close``. Byte-stream semantics:
    ``recv(n)`` may return fewer bytes (one buffered chunk at a
    time); ``b""`` means EOF; a closed tx ring raises ``OSError``."""

    def __init__(self, tx, rx) -> None:
        self._tx = tx
        self._rx = rx
        self._timeout = None
        # leftover bytes from a popped chunk larger than the last recv
        self._rbuf = b""
        self._rpos = 0

    def settimeout(self, t) -> None:
        self._timeout = t

    def sendall(self, data) -> None:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data)
        view = memoryview(data)
        total = len(view)
        sent = 0
        while sent < total:
            chunk = bytes(view[sent : sent + SLOT_BYTES])
            rc = self._tx.push_timed(chunk, self._timeout)
            if rc == 0:
                raise OSError("shm ring closed by peer")
            if rc == -2:
                import socket as _socket

                raise _socket.timeout("shm ring send timed out")
            sent += len(chunk)
        with _stats_lock:
            _stats["bytes"] += total
            _stats["chunks"] += (total + SLOT_BYTES - 1) // SLOT_BYTES

    def recv(self, n: int) -> bytes:
        if self._rpos < len(self._rbuf):
            out = self._rbuf[self._rpos : self._rpos + n]
            self._rpos += len(out)
            return out
        rc, chunk = self._rx.pop_timed(self._timeout)
        if rc == -2:
            import socket as _socket

            raise _socket.timeout("shm ring recv timed out")
        if rc != 1 or not chunk:
            return b""  # closed and drained: EOF
        if len(chunk) <= n:
            return chunk
        self._rbuf = chunk
        self._rpos = n
        return chunk[:n]

    def shutdown(self, how=None) -> None:
        self._tx.close()
        self._rx.close()

    def close(self) -> None:
        self._tx.close()
        self._rx.close()


def socketpair() -> tuple[RingSock, RingSock]:
    """Build a connected pair of ring sockets (one ring per
    direction), client end first."""
    c2s = _make_ring()
    s2c = _make_ring()
    with _stats_lock:
        _stats["connections"] += 1
    return RingSock(tx=c2s, rx=s2c), RingSock(tx=s2c, rx=c2s)
