"""AES-GCM secure mode — the ProtocolV2 rev-1 crypto_onwire analog.

Mirrors the design of msg/async/crypto_onwire.{h,cc}: after an
in-the-clear nonce exchange, each direction of a connection gets its
own AES-128-GCM key and a 96-bit nonce split into a fixed 4-byte salt
plus an 8-byte counter that increments per sealed frame
(crypto_onwire.cc nonce_t). Integrity comes from the AEAD tag — secure
mode REPLACES per-segment CRC, exactly as ProtocolV2's secure mode
supersedes crc mode (frames_v2.h rev-1 "secure mode").

Key derivation differs deliberately: the reference runs CephX tickets;
here a cluster pre-shared secret (the keyring role) is stretched with
HKDF-SHA256 over both peers' fresh nonces, so session keys are unique
per connection and the PSK never crosses the wire. A tampered
handshake yields mismatched keys and the first frame fails AEAD open —
the same failure surface as a forged CephX authorizer.

Replay is rejected by requiring the peer's counter to be strictly
increasing (the reference gets this from its per-session nonce
discipline).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

try:  # cryptography ships in the base image; gate anyway
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover
    AESGCM = None

    class InvalidTag(Exception):
        pass


KEY_BYTES = 16       # AES-128, matching the reference's AES_GCM_128
SALT_BYTES = 4
COUNTER_BYTES = 8
NONCE_BYTES = 32     # per-peer handshake nonce


class SecurityError(Exception):
    """Authentication/decryption failure — the connection must drop."""


def available() -> bool:
    return AESGCM is not None


def _hkdf(key_material: bytes, info: bytes, length: int) -> bytes:
    """HKDF-SHA256 (RFC 5869) — extract with a fixed salt, then expand."""
    prk = hmac.new(b"ceph_tpu-hkdf-v1", key_material, hashlib.sha256).digest()
    out, block, counter = b"", b"", 1
    while len(out) < length:
        block = hmac.new(
            prk, block + info + bytes([counter]), hashlib.sha256
        ).digest()
        out += block
        counter += 1
    return out[:length]


def fresh_nonce() -> bytes:
    return os.urandom(NONCE_BYTES)


def derive_session(
    psk: bytes, nonce_c: bytes, nonce_s: bytes, is_client: bool
) -> tuple["SecureSession", "SecureSession"]:
    """(tx_session, rx_session) for this side of the connection.

    Each direction gets an independent key+salt; both peers derive the
    same material and pick tx/rx by role."""
    material = _hkdf(
        psk + nonce_c + nonce_s,
        b"connection-keys",
        2 * (KEY_BYTES + SALT_BYTES),
    )
    cs = material[: KEY_BYTES + SALT_BYTES]          # client -> server
    sc = material[KEY_BYTES + SALT_BYTES :]          # server -> client
    sess_cs = SecureSession(cs[:KEY_BYTES], cs[KEY_BYTES:])
    sess_sc = SecureSession(sc[:KEY_BYTES], sc[KEY_BYTES:])
    return (sess_cs, sess_sc) if is_client else (sess_sc, sess_cs)


class SecureSession:
    """One direction's AEAD state: key, nonce salt, frame counter."""

    def __init__(self, key: bytes, salt: bytes) -> None:
        if AESGCM is None:  # pragma: no cover
            raise SecurityError("cryptography library unavailable")
        assert len(key) == KEY_BYTES and len(salt) == SALT_BYTES
        self._aead = AESGCM(key)
        self._salt = salt
        self._tx_counter = 0
        self._rx_counter = 0

    def _nonce(self, counter: int) -> bytes:
        return self._salt + struct.pack("<Q", counter)

    def seal(self, aad: bytes, plaintext: bytes) -> tuple[int, bytes]:
        """Encrypt+authenticate; returns (counter, ciphertext||tag)."""
        self._tx_counter += 1
        ct = self._aead.encrypt(self._nonce(self._tx_counter), plaintext, aad)
        return self._tx_counter, ct

    def open(self, aad: bytes, counter: int, ciphertext: bytes) -> bytes:
        """Verify+decrypt; enforces a strictly increasing counter so a
        recorded frame cannot be replayed into the stream."""
        if counter <= self._rx_counter:
            raise SecurityError(
                f"replayed or reordered frame: counter {counter} <= "
                f"{self._rx_counter}"
            )
        try:
            pt = self._aead.decrypt(self._nonce(counter), ciphertext, aad)
        except InvalidTag as e:
            raise SecurityError("AEAD authentication failed") from e
        self._rx_counter = counter
        return pt
