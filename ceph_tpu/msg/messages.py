"""Typed, versioned sub-op messages — the MOSDECSubOp* analog.

Mirrors the message vocabulary of the EC fan-out
(src/messages/MOSDECSubOpWrite.h / MOSDECSubOpRead.h and their
replies; payload structs osd/ECMsgTypes.{h,cc}): a write carries the
target shard's transaction (+ the op tid for the in-order commit
protocol); a read carries per-object extent lists and optional
sub-chunk selectors; replies carry ack / buffers / per-object errors.

Each message encodes as wire-frame segments: segment 0 is a compact
header (json — these are tiny), further segments carry bulk bytes
(transaction payloads, read buffers) so big data is never re-encoded.
The version byte in the header follows the reference's
versioned-message pattern (msg/Message.h HEAD_VERSION/COMPAT_VERSION).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ceph_tpu.store import Transaction

# Frame type ids.
MSG_EC_SUB_WRITE = 108        # MOSDECSubOpWrite
MSG_EC_SUB_WRITE_REPLY = 109  # MOSDECSubOpWriteReply
MSG_EC_SUB_READ = 110         # MOSDECSubOpRead
MSG_EC_SUB_READ_REPLY = 111   # MOSDECSubOpReadReply
MSG_PING = 112                # MOSDPing analog (heartbeats)
MSG_PONG = 113
MSG_OSD_OP = 114              # MOSDOp (client op to the primary)
MSG_OSD_OP_REPLY = 115        # MOSDOpReply
MSG_PG_LIST = 116             # backfill object discovery
MSG_PG_LIST_REPLY = 117
MSG_GET_ATTRS = 118           # per-shard attr fetch (scrub consensus)
MSG_GET_ATTRS_REPLY = 119
MSG_WATCH_NOTIFY = 120        # MWatchNotify (daemon -> watcher push)
MSG_NOTIFY_ACK = 121          # watcher ack back to the primary
MSG_DCN_HELLO = 122           # DCN worker-host handshake
MSG_DCN_CMD = 123             # DCN control-plane op broadcast
MSG_DCN_REPLY = 124           # DCN per-host op result
MSG_PG_INFO = 125             # peering info exchange (MOSDPGInfo)
MSG_PG_INFO_REPLY = 126
MSG_PG_ACTIVATE = 127         # interval activation (les push)
MSG_PG_ACTIVATE_ACK = 128
MSG_BACKFILL_RESERVE = 129    # MBackfillReserve (request/release)
MSG_BACKFILL_RESERVE_REPLY = 130
MSG_EC_SUB_WRITE_BATCH = 131        # one frame, many sub-writes
MSG_EC_SUB_WRITE_BATCH_REPLY = 132

VERSION = 1


def _header(kind: str, fields: dict) -> bytes:
    return json.dumps({"v": VERSION, "kind": kind, **fields}).encode()


def _parse(seg: bytes, kind: str) -> dict:
    obj = json.loads(seg.decode())
    if obj.get("v", 0) > VERSION:
        raise ValueError(f"{kind} from the future: v{obj['v']}")
    if obj.get("kind") != kind:
        raise ValueError(f"expected {kind}, got {obj.get('kind')!r}")
    return obj


@dataclass
class ECSubWrite:
    """Per-shard write sub-op (ECSubWrite, osd/ECMsgTypes.h).

    ``epoch``/``from_osd`` carry the sender's map interval for the
    replica-side fence (the MOSDECSubOpWrite map_epoch role): a
    superseded primary whose map lags must not commit through
    replicas that already serve a newer interval — the replica
    rejects, the stale op never acks, and the client's resend lands
    on the real primary (OSD::require_same_or_newer_map)."""

    tid: int
    shard: int
    txn: Transaction
    trace_id: str | None = None
    parent_span: str | None = None
    epoch: int = 0
    from_osd: int = -1

    def encode(self) -> list[bytes]:
        h = {"tid": self.tid, "shard": self.shard}
        if self.trace_id is not None:  # keep untraced wire bytes lean
            h["trace"] = [self.trace_id, self.parent_span]
        if self.epoch:
            h["e"] = [self.epoch, self.from_osd]
        return [_header("sub_write", h), self.txn.to_bytes()]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubWrite":
        h = _parse(segments[0], "sub_write")
        trace = h.get("trace") or [None, None]
        e = h.get("e") or [0, -1]
        return cls(
            h["tid"], h["shard"], Transaction.from_bytes(segments[1]),
            trace[0], trace[1], e[0], e[1],
        )


@dataclass
class ECSubWriteReply:
    tid: int
    shard: int
    committed: bool = True

    def encode(self) -> list[bytes]:
        return [
            _header(
                "sub_write_reply",
                {"tid": self.tid, "shard": self.shard,
                 "committed": self.committed},
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubWriteReply":
        h = _parse(segments[0], "sub_write_reply")
        return cls(h["tid"], h["shard"], h["committed"])


@dataclass
class ECSubWriteBatch:
    """A tick's worth of sub-writes for ONE peer OSD in one framed
    message (the round-10 fan-out batching): the primary's coalesced
    op batch stages every sub-write destined for a peer and flushes
    them together, so N concurrent client ops cost one frame per peer
    instead of N. Each item keeps its own tid, logical shard, and
    interval stamp — the receiver fences and applies items
    INDEPENDENTLY (one stale item must not poison its batch-mates)
    and answers with per-item outcomes in one reply frame.

    ``tid`` is the batch's own wire id (reply routing only); item
    tids are the sub-write tids the sender's pending table knows."""

    tid: int
    shard: int  # echo key for reply routing (the peer's osd id)
    #: (tid, shard, epoch, from_osd, txn) per sub-write
    items: list = field(default_factory=list)

    def encode(self) -> list[bytes]:
        blobs = [txn.to_bytes() for *_m, txn in self.items]
        return [
            _header(
                "sub_write_batch",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "items": [
                        list(meta) for *meta, _txn in self.items
                    ],
                    "lens": [len(b) for b in blobs],
                },
            ),
            b"".join(blobs),
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubWriteBatch":
        h = _parse(segments[0], "sub_write_batch")
        blob, pos, items = segments[1], 0, []
        for meta, ln in zip(h["items"], h["lens"]):
            txn = Transaction.from_bytes(blob[pos : pos + ln])
            pos += ln
            items.append(tuple(meta) + (txn,))
        return cls(h["tid"], h["shard"], items)


@dataclass
class ECSubWriteBatchReply:
    """Per-item outcomes for one ECSubWriteBatch: (tid, committed)
    pairs. Items the receiver never acked (injected drop, abort) are
    simply absent — the sender's pending entries expire exactly like
    a lost single-sub-write ack."""

    tid: int
    shard: int
    results: list = field(default_factory=list)  # (tid, committed)

    def encode(self) -> list[bytes]:
        return [
            _header(
                "sub_write_batch_reply",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "results": [list(r) for r in self.results],
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubWriteBatchReply":
        h = _parse(segments[0], "sub_write_batch_reply")
        return cls(
            h["tid"], h["shard"], [tuple(r) for r in h["results"]]
        )


@dataclass
class ECSubRead:
    """Per-shard read sub-op: oid -> extent list (+ sub-chunk
    selectors, the CLAY plumbing of ECCommon.h:85)."""

    tid: int
    shard: int
    oid: str
    extents: list[tuple[int, int]]  # (start, end) pairs
    subchunks: list[tuple[int, int]] | None = None
    #: logical EC shard index the caller believes this store holds;
    #: the server cross-checks it against the stored SI attr so a
    #: CRUSH remap can't serve misplaced bytes (None = don't check).
    logical: int | None = None
    trace_id: str | None = None
    parent_span: str | None = None

    def encode(self) -> list[bytes]:
        h = {
            "tid": self.tid,
            "shard": self.shard,
            "oid": self.oid,
            "extents": self.extents,
            "subchunks": self.subchunks,
            "logical": self.logical,
        }
        if self.trace_id is not None:
            h["trace"] = [self.trace_id, self.parent_span]
        return [_header("sub_read", h)]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubRead":
        h = _parse(segments[0], "sub_read")
        sub = h["subchunks"]
        trace = h.get("trace") or [None, None]
        return cls(
            h["tid"],
            h["shard"],
            h["oid"],
            [tuple(e) for e in h["extents"]],
            [tuple(s) for s in sub] if sub is not None else None,
            h.get("logical"),
            trace[0],
            trace[1],
        )


@dataclass
class ECSubReadReply:
    """Buffers (offset-keyed) or an error for one sub-read."""

    tid: int
    shard: int
    offsets: list[int] = field(default_factory=list)
    buffers: list[bytes] = field(default_factory=list)
    error: str = ""  # "" | "eio" | "missing"

    def encode(self) -> list[bytes]:
        segs = [
            _header(
                "sub_read_reply",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "offsets": self.offsets,
                    "error": self.error,
                },
            )
        ]
        # One bulk segment: per-segment crc covers all buffers; the
        # header's offsets + lengths let the receiver re-split.
        segs.append(
            json.dumps([len(b) for b in self.buffers]).encode()
        )
        segs.append(b"".join(self.buffers))
        return segs

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubReadReply":
        h = _parse(segments[0], "sub_read_reply")
        lengths = json.loads(segments[1].decode())
        blob = segments[2]
        buffers, pos = [], 0
        for ln in lengths:
            buffers.append(blob[pos : pos + ln])
            pos += ln
        return cls(h["tid"], h["shard"], h["offsets"], buffers, h["error"])


@dataclass
class Ping:
    """Heartbeat probe (the OSD::handle_osd_ping analog)."""

    tid: int
    shard: int

    def encode(self) -> list[bytes]:
        return [_header("ping", {"tid": self.tid, "shard": self.shard})]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "Ping":
        h = _parse(segments[0], "ping")
        return cls(h["tid"], h["shard"])


@dataclass
class Pong:
    tid: int
    shard: int

    def encode(self) -> list[bytes]:
        return [_header("pong", {"tid": self.tid, "shard": self.shard})]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "Pong":
        h = _parse(segments[0], "pong")
        return cls(h["tid"], h["shard"])


@dataclass
class OSDOp:
    """Client op to the object's primary OSD (MOSDOp,
    src/messages/MOSDOp.h). ``epoch`` is the client's map epoch — a
    primary that disagrees about who owns the object answers
    ``eagain`` + its epoch and the client re-targets (the
    resend-on-map-change contract, osdc/Objecter.cc:2127)."""

    tid: int
    epoch: int
    pool: str
    oid: str
    op: str  # write | read | stat | remove | pgls | *xattr*
    offset: int = 0
    length: int = 0
    data: bytes = b""
    name: str = ""  # xattr name for the *xattr ops
    #: stable across resends (osd_reqid_t analog): the primary dedups
    #: re-applied mutations by replaying the completed op's result
    reqid: str = ""
    #: snapshot id a read targets (0 = head); the primary resolves
    #: the clone (rados_ioctx_snap_set_read role)
    snap: int = 0
    #: distributed-trace context (ZTracer/blkin role: the reference
    #: threads trace handles through op messages); optional and
    #: version-tolerant
    trace_id: str | None = None
    parent_span: str | None = None
    #: QoS identity (the MOSDOp entity/client role): the OSD front end
    #: schedules the op under the dmClock class ``client.<tenant>``,
    #: falling back to ``client.<pool>`` when empty (cluster/qos.py)
    tenant: str = ""

    def encode(self) -> list[bytes]:
        return [
            _header(
                "osd_op",
                {
                    "tid": self.tid,
                    "epoch": self.epoch,
                    "pool": self.pool,
                    "oid": self.oid,
                    "op": self.op,
                    "offset": self.offset,
                    "length": self.length,
                    "name": self.name,
                    "reqid": self.reqid,
                    "snap": self.snap,
                    **(
                        {"trace": [self.trace_id, self.parent_span]}
                        if self.trace_id is not None else {}
                    ),
                    **({"tenant": self.tenant} if self.tenant else {}),
                },
            ),
            self.data,
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "OSDOp":
        h = _parse(segments[0], "osd_op")
        trace = h.get("trace") or [None, None]
        return cls(
            h["tid"], h["epoch"], h["pool"], h["oid"], h["op"],
            h["offset"], h["length"], segments[1], h.get("name", ""),
            h.get("reqid", ""), h.get("snap", 0),
            trace[0], trace[1], h.get("tenant", ""),
        )


@dataclass
class OSDOpReply:
    """MOSDOpReply: result + data, or a retryable/terminal error.
    ``error`` ∈ {"", "eagain", "enoent", "eio"}; eagain carries the
    primary's (newer) epoch so the client refreshes before resending."""

    tid: int
    epoch: int
    error: str = ""
    size: int = 0
    data: bytes = b""

    def encode(self) -> list[bytes]:
        return [
            _header(
                "osd_op_reply",
                {
                    "tid": self.tid,
                    "epoch": self.epoch,
                    "error": self.error,
                    "size": self.size,
                },
            ),
            self.data,
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "OSDOpReply":
        h = _parse(segments[0], "osd_op_reply")
        return cls(h["tid"], h["epoch"], h["error"], h["size"], segments[1])


@dataclass
class PGList:
    """Ask a peer which objects of one PG it holds (the backfill
    scan — the reference's backfill interval scan over the PG
    collection). Placement params travel in the message so the peer
    answers correctly even with a lagging map."""

    tid: int
    shard: int  # echo key for reply routing (the peer's osd id)
    pool_id: int
    pg_num: int
    pgid: int

    def encode(self) -> list[bytes]:
        return [
            _header(
                "pg_list",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "pool_id": self.pool_id,
                    "pg_num": self.pg_num,
                    "pgid": self.pgid,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "PGList":
        h = _parse(segments[0], "pg_list")
        return cls(h["tid"], h["shard"], h["pool_id"], h["pg_num"], h["pgid"])


@dataclass
class PGListReply:
    """Oids this peer holds for the PG, with the logical shard index
    each one's bytes belong to (the SI attr) and the stored ro size."""

    tid: int
    shard: int
    oids: list[tuple[str, int, int]] = field(default_factory=list)
    # (oid, held_shard_index or -1 if unknown, ro_size or -1)

    def encode(self) -> list[bytes]:
        return [
            _header(
                "pg_list_reply",
                {"tid": self.tid, "shard": self.shard, "oids": self.oids},
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "PGListReply":
        h = _parse(segments[0], "pg_list_reply")
        return cls(
            h["tid"], h["shard"], [tuple(o) for o in h["oids"]]
        )


@dataclass
class PGInfo:
    """Ask a peer for its pg_info_t analog for one PG: the interval
    ledger (last_epoch_started) plus its log head (last_update = max
    committed eversion over its shard copies). The peering info
    exchange (MOSDPGInfo / PeeringState::proc_replica_info) that
    feeds authoritative-log election (find_best_info,
    osd/PeeringState.cc:1565). Answered from the peer's STORE, not
    its in-memory PG (the peer may not have instantiated one)."""

    tid: int
    shard: int  # echo key for reply routing (the peer's osd id)
    pool_id: int
    pg_num: int
    pgid: int
    #: the querying election's map epoch: answering FENCES the member
    #: against sub-writes from older intervals of this PG (the
    #: MOSDPGQuery epoch role) -- see OSDDaemon._sub_write_interval_ok
    epoch: int = 0

    def encode(self) -> list[bytes]:
        return [
            _header(
                "pg_info",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "pool_id": self.pool_id,
                    "pg_num": self.pg_num,
                    "pgid": self.pgid,
                    "epoch": self.epoch,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "PGInfo":
        h = _parse(segments[0], "pg_info")
        return cls(
            h["tid"], h["shard"], h["pool_id"], h["pg_num"], h["pgid"],
            h.get("epoch", 0),
        )


@dataclass
class PGInfoReply:
    """(last_epoch_started, last_update) for one PG on one peer."""

    tid: int
    shard: int
    les: int
    lu_epoch: int
    lu_tid: int

    def encode(self) -> list[bytes]:
        return [
            _header(
                "pg_info_reply",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "les": self.les,
                    "lu_epoch": self.lu_epoch,
                    "lu_tid": self.lu_tid,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "PGInfoReply":
        h = _parse(segments[0], "pg_info_reply")
        return cls(
            h["tid"], h["shard"], h["les"], h["lu_epoch"], h["lu_tid"]
        )


@dataclass
class PGActivate:
    """Interval activation push: after the elected primary finishes
    peering at map epoch E, every up member records
    last_epoch_started = E in its own durable pgmeta — the
    PeeringState::activate / MOSDPGLog activation role. A member that
    misses this push (partitioned) keeps its old les, which is
    exactly what makes a later election rank it non-authoritative."""

    tid: int
    shard: int
    pool_id: int
    pgid: int
    epoch: int

    def encode(self) -> list[bytes]:
        return [
            _header(
                "pg_activate",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "pool_id": self.pool_id,
                    "pgid": self.pgid,
                    "epoch": self.epoch,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "PGActivate":
        h = _parse(segments[0], "pg_activate")
        return cls(
            h["tid"], h["shard"], h["pool_id"], h["pgid"], h["epoch"]
        )


@dataclass
class PGActivateAck:
    tid: int
    shard: int

    def encode(self) -> list[bytes]:
        return [
            _header(
                "pg_activate_ack", {"tid": self.tid, "shard": self.shard}
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "PGActivateAck":
        h = _parse(segments[0], "pg_activate_ack")
        return cls(h["tid"], h["shard"])


@dataclass
class BackfillReserve:
    """The MBackfillReserve analog (backfill_reservation.rst): a
    backfill primary asks each target OSD for a remote slot before
    moving data; ``action`` is "request" or "release". The reply to a
    request may be DELAYED — the target's remote AsyncReserver grants
    it when a slot frees, so a busy target throttles the primary
    instead of rejecting it."""

    tid: int
    shard: int
    action: str  # NOT "kind": that key frames the message envelope
    pool_id: int
    pgid: int
    prio: int = 0

    def encode(self) -> list[bytes]:
        return [
            _header(
                "backfill_reserve",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "action": self.action,
                    "pool_id": self.pool_id,
                    "pgid": self.pgid,
                    "prio": self.prio,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "BackfillReserve":
        h = _parse(segments[0], "backfill_reserve")
        return cls(
            h["tid"], h["shard"], h["action"], h["pool_id"], h["pgid"],
            h["prio"],
        )


@dataclass
class BackfillReserveReply:
    tid: int
    shard: int
    granted: bool = True

    def encode(self) -> list[bytes]:
        return [
            _header(
                "backfill_reserve_reply",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "granted": self.granted,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "BackfillReserveReply":
        h = _parse(segments[0], "backfill_reserve_reply")
        return cls(h["tid"], h["shard"], h["granted"])


@dataclass
class GetAttrs:
    """Fetch named attrs from one shard's store — the getattr sub-op
    (the extension point deep scrub needs to vote on HashInfo copies
    instead of trusting the primary's own)."""

    tid: int
    shard: int
    oid: str          # full store key (shard_key applied by caller)
    names: list[str]

    def encode(self) -> list[bytes]:
        return [
            _header(
                "get_attrs",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "oid": self.oid,
                    "names": self.names,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "GetAttrs":
        h = _parse(segments[0], "get_attrs")
        return cls(h["tid"], h["shard"], h["oid"], list(h["names"]))


@dataclass
class GetAttrsReply:
    """Requested attrs as raw bytes (hex on the wire); absent names
    map to None, a missing object sets error."""

    tid: int
    shard: int
    attrs: dict = field(default_factory=dict)  # name -> bytes | None
    error: str | None = None

    def encode(self) -> list[bytes]:
        return [
            _header(
                "get_attrs_reply",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "attrs": {
                        k: (v.hex() if v is not None else None)
                        for k, v in self.attrs.items()
                    },
                    "error": self.error,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "GetAttrsReply":
        h = _parse(segments[0], "get_attrs_reply")
        return cls(
            h["tid"],
            h["shard"],
            {
                k: (bytes.fromhex(v) if v is not None else None)
                for k, v in h["attrs"].items()
            },
            h.get("error"),
        )


def serve_get_attrs(store, shard_id: int, conn, msg: "GetAttrs") -> None:
    """Serve one GetAttrs against a local store — shared by the
    shard-server and OSD-daemon dispatchers (one source of truth for
    the absent-name/enoent semantics)."""
    try:
        attrs = store.getattrs(msg.oid)
        conn.send(GetAttrsReply(
            msg.tid, shard_id, {n: attrs.get(n) for n in msg.names},
        ))
    except FileNotFoundError:
        conn.send(GetAttrsReply(msg.tid, shard_id, error="enoent"))


@dataclass
class WatchNotify:
    """Primary -> watcher event push (MWatchNotify,
    src/messages/MWatchNotify.h): carries the notify payload to every
    registered watcher of the object; the watcher answers with
    NotifyAck so the notifier learns who saw it."""

    notify_id: int
    cookie: str   # the watcher's registration cookie
    pool: str
    oid: str
    payload: bytes = b""

    def encode(self) -> list[bytes]:
        return [
            _header(
                "watch_notify",
                {
                    "notify_id": self.notify_id,
                    "cookie": self.cookie,
                    "pool": self.pool,
                    "oid": self.oid,
                },
            ),
            self.payload,
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "WatchNotify":
        h = _parse(segments[0], "watch_notify")
        return cls(
            h["notify_id"], h["cookie"], h["pool"], h["oid"],
            segments[1],
        )


@dataclass
class NotifyAck:
    """Watcher -> primary completion of one notify delivery."""

    notify_id: int
    cookie: str

    def encode(self) -> list[bytes]:
        return [
            _header(
                "notify_ack",
                {"notify_id": self.notify_id, "cookie": self.cookie},
            ),
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "NotifyAck":
        h = _parse(segments[0], "notify_ack")
        return cls(h["notify_id"], h["cookie"])


@dataclass
class DcnHello:
    """DCN host-process handshake: which rank this is and what slice
    of the global device mesh it owns (the multi-controller analog of
    the messenger's peer identification)."""

    rank: int
    n_processes: int
    local_devices: int
    global_devices: int

    def encode(self) -> list[bytes]:
        return [_header("dcn_hello", {
            "rank": self.rank, "n": self.n_processes,
            "local": self.local_devices, "global": self.global_devices,
        })]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "DcnHello":
        h = _parse(segments[0], "dcn_hello")
        return cls(h["rank"], h["n"], h["local"], h["global"])


@dataclass
class DcnCmd:
    """One DCN control-plane op. Every host receives the SAME op
    metadata (the multi-controller SPMD discipline: identical program
    on every host) with its OWN shard-slice payload — the sub-op
    shard fan-out of MOSDECSubOpWrite mapped onto hosts."""

    tid: int
    kind: str          # "encode" | "decode" | "shutdown"
    meta: dict         # json-serializable op parameters
    payload: bytes = b""   # this host's shard-slice bytes

    def encode(self) -> list[bytes]:
        return [
            _header("dcn_cmd", {
                "tid": self.tid, "op": self.kind, "meta": self.meta,
            }),
            self.payload,
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "DcnCmd":
        h = _parse(segments[0], "dcn_cmd")
        return cls(h["tid"], h["op"], h["meta"], segments[1])


@dataclass
class DcnReply:
    tid: int
    rank: int
    meta: dict
    payload: bytes = b""

    def encode(self) -> list[bytes]:
        return [
            _header("dcn_reply", {
                "tid": self.tid, "rank": self.rank, "meta": self.meta,
            }),
            self.payload,
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "DcnReply":
        h = _parse(segments[0], "dcn_reply")
        return cls(h["tid"], h["rank"], h["meta"], segments[1])


_DECODERS = {
    MSG_EC_SUB_WRITE: ECSubWrite.decode,
    MSG_EC_SUB_WRITE_REPLY: ECSubWriteReply.decode,
    MSG_EC_SUB_READ: ECSubRead.decode,
    MSG_EC_SUB_READ_REPLY: ECSubReadReply.decode,
    MSG_PING: Ping.decode,
    MSG_PONG: Pong.decode,
    MSG_OSD_OP: OSDOp.decode,
    MSG_OSD_OP_REPLY: OSDOpReply.decode,
    MSG_PG_LIST: PGList.decode,
    MSG_PG_LIST_REPLY: PGListReply.decode,
    MSG_GET_ATTRS: GetAttrs.decode,
    MSG_GET_ATTRS_REPLY: GetAttrsReply.decode,
    MSG_WATCH_NOTIFY: WatchNotify.decode,
    MSG_NOTIFY_ACK: NotifyAck.decode,
    MSG_DCN_HELLO: DcnHello.decode,
    MSG_DCN_CMD: DcnCmd.decode,
    MSG_DCN_REPLY: DcnReply.decode,
    MSG_PG_INFO: PGInfo.decode,
    MSG_PG_INFO_REPLY: PGInfoReply.decode,
    MSG_PG_ACTIVATE: PGActivate.decode,
    MSG_PG_ACTIVATE_ACK: PGActivateAck.decode,
    MSG_BACKFILL_RESERVE: BackfillReserve.decode,
    MSG_BACKFILL_RESERVE_REPLY: BackfillReserveReply.decode,
    MSG_EC_SUB_WRITE_BATCH: ECSubWriteBatch.decode,
    MSG_EC_SUB_WRITE_BATCH_REPLY: ECSubWriteBatchReply.decode,
}

_TYPE_OF = {
    ECSubWrite: MSG_EC_SUB_WRITE,
    ECSubWriteReply: MSG_EC_SUB_WRITE_REPLY,
    ECSubRead: MSG_EC_SUB_READ,
    ECSubReadReply: MSG_EC_SUB_READ_REPLY,
    Ping: MSG_PING,
    Pong: MSG_PONG,
    OSDOp: MSG_OSD_OP,
    OSDOpReply: MSG_OSD_OP_REPLY,
    PGList: MSG_PG_LIST,
    PGListReply: MSG_PG_LIST_REPLY,
    GetAttrs: MSG_GET_ATTRS,
    GetAttrsReply: MSG_GET_ATTRS_REPLY,
    WatchNotify: MSG_WATCH_NOTIFY,
    NotifyAck: MSG_NOTIFY_ACK,
    DcnHello: MSG_DCN_HELLO,
    DcnCmd: MSG_DCN_CMD,
    DcnReply: MSG_DCN_REPLY,
    PGInfo: MSG_PG_INFO,
    PGInfoReply: MSG_PG_INFO_REPLY,
    PGActivate: MSG_PG_ACTIVATE,
    PGActivateAck: MSG_PG_ACTIVATE_ACK,
    BackfillReserve: MSG_BACKFILL_RESERVE,
    BackfillReserveReply: MSG_BACKFILL_RESERVE_REPLY,
    ECSubWriteBatch: MSG_EC_SUB_WRITE_BATCH,
    ECSubWriteBatchReply: MSG_EC_SUB_WRITE_BATCH_REPLY,
}


def message_type(msg) -> int:
    return _TYPE_OF[type(msg)]


def decode_message(msg_type: int, segments: list[bytes]):
    dec = _DECODERS.get(msg_type)
    if dec is None:
        raise ValueError(f"unknown message type {msg_type}")
    return dec(segments)
