"""Typed, versioned sub-op messages — the MOSDECSubOp* analog.

Mirrors the message vocabulary of the EC fan-out
(src/messages/MOSDECSubOpWrite.h / MOSDECSubOpRead.h and their
replies; payload structs osd/ECMsgTypes.{h,cc}): a write carries the
target shard's transaction (+ the op tid for the in-order commit
protocol); a read carries per-object extent lists and optional
sub-chunk selectors; replies carry ack / buffers / per-object errors.

Each message encodes as wire-frame segments: segment 0 is a compact
header (json — these are tiny), further segments carry bulk bytes
(transaction payloads, read buffers) so big data is never re-encoded.
The version byte in the header follows the reference's
versioned-message pattern (msg/Message.h HEAD_VERSION/COMPAT_VERSION).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ceph_tpu.store import Transaction

# Frame type ids.
MSG_EC_SUB_WRITE = 108        # MOSDECSubOpWrite
MSG_EC_SUB_WRITE_REPLY = 109  # MOSDECSubOpWriteReply
MSG_EC_SUB_READ = 110         # MOSDECSubOpRead
MSG_EC_SUB_READ_REPLY = 111   # MOSDECSubOpReadReply
MSG_PING = 112                # MOSDPing analog (heartbeats)
MSG_PONG = 113

VERSION = 1


def _header(kind: str, fields: dict) -> bytes:
    return json.dumps({"v": VERSION, "kind": kind, **fields}).encode()


def _parse(seg: bytes, kind: str) -> dict:
    obj = json.loads(seg.decode())
    if obj.get("v", 0) > VERSION:
        raise ValueError(f"{kind} from the future: v{obj['v']}")
    if obj.get("kind") != kind:
        raise ValueError(f"expected {kind}, got {obj.get('kind')!r}")
    return obj


@dataclass
class ECSubWrite:
    """Per-shard write sub-op (ECSubWrite, osd/ECMsgTypes.h)."""

    tid: int
    shard: int
    txn: Transaction

    def encode(self) -> list[bytes]:
        return [
            _header("sub_write", {"tid": self.tid, "shard": self.shard}),
            self.txn.to_bytes(),
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubWrite":
        h = _parse(segments[0], "sub_write")
        return cls(h["tid"], h["shard"], Transaction.from_bytes(segments[1]))


@dataclass
class ECSubWriteReply:
    tid: int
    shard: int
    committed: bool = True

    def encode(self) -> list[bytes]:
        return [
            _header(
                "sub_write_reply",
                {"tid": self.tid, "shard": self.shard,
                 "committed": self.committed},
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubWriteReply":
        h = _parse(segments[0], "sub_write_reply")
        return cls(h["tid"], h["shard"], h["committed"])


@dataclass
class ECSubRead:
    """Per-shard read sub-op: oid -> extent list (+ sub-chunk
    selectors, the CLAY plumbing of ECCommon.h:85)."""

    tid: int
    shard: int
    oid: str
    extents: list[tuple[int, int]]  # (start, end) pairs
    subchunks: list[tuple[int, int]] | None = None

    def encode(self) -> list[bytes]:
        return [
            _header(
                "sub_read",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "oid": self.oid,
                    "extents": self.extents,
                    "subchunks": self.subchunks,
                },
            )
        ]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubRead":
        h = _parse(segments[0], "sub_read")
        sub = h["subchunks"]
        return cls(
            h["tid"],
            h["shard"],
            h["oid"],
            [tuple(e) for e in h["extents"]],
            [tuple(s) for s in sub] if sub is not None else None,
        )


@dataclass
class ECSubReadReply:
    """Buffers (offset-keyed) or an error for one sub-read."""

    tid: int
    shard: int
    offsets: list[int] = field(default_factory=list)
    buffers: list[bytes] = field(default_factory=list)
    error: str = ""  # "" | "eio" | "missing"

    def encode(self) -> list[bytes]:
        segs = [
            _header(
                "sub_read_reply",
                {
                    "tid": self.tid,
                    "shard": self.shard,
                    "offsets": self.offsets,
                    "error": self.error,
                },
            )
        ]
        # One bulk segment: per-segment crc covers all buffers; the
        # header's offsets + lengths let the receiver re-split.
        segs.append(
            json.dumps([len(b) for b in self.buffers]).encode()
        )
        segs.append(b"".join(self.buffers))
        return segs

    @classmethod
    def decode(cls, segments: list[bytes]) -> "ECSubReadReply":
        h = _parse(segments[0], "sub_read_reply")
        lengths = json.loads(segments[1].decode())
        blob = segments[2]
        buffers, pos = [], 0
        for ln in lengths:
            buffers.append(blob[pos : pos + ln])
            pos += ln
        return cls(h["tid"], h["shard"], h["offsets"], buffers, h["error"])


@dataclass
class Ping:
    """Heartbeat probe (the OSD::handle_osd_ping analog)."""

    tid: int
    shard: int

    def encode(self) -> list[bytes]:
        return [_header("ping", {"tid": self.tid, "shard": self.shard})]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "Ping":
        h = _parse(segments[0], "ping")
        return cls(h["tid"], h["shard"])


@dataclass
class Pong:
    tid: int
    shard: int

    def encode(self) -> list[bytes]:
        return [_header("pong", {"tid": self.tid, "shard": self.shard})]

    @classmethod
    def decode(cls, segments: list[bytes]) -> "Pong":
        h = _parse(segments[0], "pong")
        return cls(h["tid"], h["shard"])


_DECODERS = {
    MSG_EC_SUB_WRITE: ECSubWrite.decode,
    MSG_EC_SUB_WRITE_REPLY: ECSubWriteReply.decode,
    MSG_EC_SUB_READ: ECSubRead.decode,
    MSG_EC_SUB_READ_REPLY: ECSubReadReply.decode,
    MSG_PING: Ping.decode,
    MSG_PONG: Pong.decode,
}

_TYPE_OF = {
    ECSubWrite: MSG_EC_SUB_WRITE,
    ECSubWriteReply: MSG_EC_SUB_WRITE_REPLY,
    ECSubRead: MSG_EC_SUB_READ,
    ECSubReadReply: MSG_EC_SUB_READ_REPLY,
    Ping: MSG_PING,
    Pong: MSG_PONG,
}


def message_type(msg) -> int:
    return _TYPE_OF[type(msg)]


def decode_message(msg_type: int, segments: list[bytes]):
    dec = _DECODERS.get(msg_type)
    if dec is None:
        raise ValueError(f"unknown message type {msg_type}")
    return dec(segments)
