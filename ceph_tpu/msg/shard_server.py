"""Shard daemon + networked shard backend — the mini-OSD tier.

``ShardServer`` is the remote end of the EC fan-out: it owns one
shard's store and serves ECSubWrite/ECSubRead exactly like the
reference's ``handle_sub_write``/``handle_sub_read``
(osd/ECBackend.cc:912,998) by delegating to the same local
``ShardBackend`` the in-process pipelines use (one source of truth for
zero-padding and ECInject consultation), over the framed wire protocol.

``NetShardBackend`` is a drop-in for ``pipeline.rmw.ShardBackend``
whose sub-ops travel over sockets. Sub-op sends are asynchronous (the
whole k+m fan-out goes out before any reply is awaited — one RTT per
op, not per shard); replies are queued and executed on the CALLER's
thread via ``drain_until``, so pipeline state stays single-threaded
(the crimson run-to-completion stance, not reader-thread reentrancy).
RPC timeouts and connection failures mark the shard down (the
failure-detection seam), so degraded reads and recovery route around a
dead daemon automatically; a lost sub-write ack parks its op exactly
like the reference until recovery intervenes.

Deep scrub currently requires local stores (it reads attrs directly);
a getattr sub-op is the natural extension point.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections.abc import Callable

from ceph_tpu.store import MemStore, Transaction
from ceph_tpu.utils import tracer

from .messages import (
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteBatch,
    ECSubWriteBatchReply,
    ECSubWriteReply,
    BackfillReserve,
    BackfillReserveReply,
    GetAttrs,
    GetAttrsReply,
    PGActivate,
    PGActivateAck,
    PGInfo,
    PGInfoReply,
    PGList,
    PGListReply,
    Ping,
    Pong,
)
from .messenger import Connection, Messenger
from ceph_tpu.utils import lockdep
from ceph_tpu.utils.lockdep import DebugLock, DebugRLock


class ShardServer:
    """One shard's daemon: store + messenger + sub-op handlers."""

    def __init__(
        self,
        shard: int,
        store: MemStore | None = None,
        secret: bytes | None = None,
    ) -> None:
        from ceph_tpu.pipeline.rmw import ShardBackend

        self.shard = shard
        self.store = store or MemStore(f"osd.{shard}")
        # Delegate sub-op semantics (zero-pad reads, inject hooks) to
        # the same backend the in-process pipelines use.
        self._local = ShardBackend({shard: self.store})
        self.messenger = Messenger(f"osd.{shard}", secret=secret)
        self.messenger.set_dispatcher(self._dispatch)
        self.addr: tuple[str, int] | None = None

    def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self.addr = self.messenger.bind(host, port)
        return self.addr

    def stop(self) -> None:
        self.messenger.shutdown()

    # -- sub-op handlers (handle_sub_write / handle_sub_read) ----------
    def _dispatch(self, conn: Connection, msg) -> None:
        if isinstance(msg, Ping):
            conn.send(Pong(msg.tid, self.shard))
        elif isinstance(msg, GetAttrs):
            from .messages import serve_get_attrs

            serve_get_attrs(self.store, self.shard, conn, msg)
        elif isinstance(msg, ECSubWrite):
            with tracer.continue_trace(msg.trace_id, msg.parent_span):
                with tracer.span(
                    "sub_write", shard=self.shard, tid=msg.tid,
                ):
                    self._local.submit_shard_txn(
                        self.shard,
                        msg.txn,
                        lambda: conn.send(
                            ECSubWriteReply(msg.tid, self.shard)
                        ),
                    )
        elif isinstance(msg, ECSubWriteBatch):
            results = []
            for tid, shard, _epoch, _from, txn in msg.items:
                acked: list[bool] = []
                with tracer.span(
                    "sub_write", shard=self.shard, tid=tid,
                ):
                    self._local.submit_shard_txn(
                        self.shard, txn, lambda a=acked: a.append(True)
                    )
                if acked:  # injected drops stay un-acked (parked)
                    results.append((tid, True))
            conn.send(
                ECSubWriteBatchReply(msg.tid, self.shard, results)
            )
        elif isinstance(msg, ECSubRead):
            from ceph_tpu.pipeline.extents import ExtentSet

            def reply(shard: int, result) -> None:
                if isinstance(result, Exception):
                    kind = getattr(result, "kind", "eio")
                    conn.send(
                        ECSubReadReply(msg.tid, shard, error=kind)
                    )
                else:
                    offsets = sorted(result)
                    conn.send(
                        ECSubReadReply(
                            msg.tid,
                            shard,
                            offsets,
                            [bytes(result[o]) for o in offsets],
                        )
                    )

            with tracer.continue_trace(msg.trace_id, msg.parent_span), \
                    tracer.span(
                        "sub_read", shard=self.shard, tid=msg.tid,
                    ):
                self._local.read_shard_async(
                    self.shard,
                    msg.oid,
                    ExtentSet((s, e) for s, e in msg.extents),
                    reply,
                )


class _Pending:
    __slots__ = (
        "shard", "oid", "on_reply", "deadline", "is_read", "soft",
        "resend", "retry_at", "tries", "tracked",
    )

    def __init__(self, shard, oid, on_reply, deadline, is_read,
                 soft=False, resend=None, retry_at=None,
                 tracked=None):
        from ceph_tpu.utils.optracker import NULL_OP

        self.shard = shard
        self.oid = oid
        self.on_reply = on_reply
        self.deadline = deadline
        self.is_read = is_read
        #: live-op handle: a wedged peer RPC (lost frame, dead peer)
        #: shows in dump_ops_in_flight with how long it has waited
        self.tracked = tracked if tracked is not None else NULL_OP
        #: soft RPCs are EXPECTED to wait (delayed reservation
        #: grants): expiry wakes the waiter but must not mark the
        #: merely-busy peer down
        self.soft = soft
        #: sub-op retransmit (the lossless-messenger replay collapsed
        #: to idempotent re-send; armed only on lossy-link runs via
        #: ``osd_subop_resend_interval``): re-fires the frame on a
        #: doubling ladder until the reply lands or the deadline
        #: expires. Safe because sub-writes carry absolute extents +
        #: attrs (re-apply = same bytes), the interval fence rejects
        #: cross-interval staleness, and a duplicate ack is absorbed
        #: by the pending-entry pop exactly-once.
        self.resend = resend
        self.retry_at = retry_at
        self.tries = 0


class NetShardBackend:
    """ShardBackend over the wire: same surface the pipelines consume
    (avail_shards / read_shard / read_shard_async / submit_shard_txn).

    Callbacks are NEVER invoked from reader threads: replies queue into
    an inbox that ``drain_until`` executes on the calling thread.
    """

    def __init__(
        self,
        addrs: dict[int, tuple[str, int]],
        timeout: float = 10.0,
        secret: bytes | None = None,
        name: str = "client",
    ) -> None:
        from ceph_tpu.utils.log import get_logger

        from ceph_tpu.utils import config as _cfg

        self.addrs = dict(addrs)
        self.timeout = timeout
        #: seconds before an un-replied sub-op is re-sent (0 = never,
        #: the default: TCP is lossless, parked semantics stand).
        #: Lossy-link runs (the injected fault plane) arm it so a lost
        #: frame resolves in fractions of the RPC deadline.
        self.resend_interval = float(
            _cfg.get("osd_subop_resend_interval")
        )
        self.down_shards: set[int] = set()
        #: shard -> monotonic stamp of its LAST down-marking (the
        #: recheck probe only clears a mark once liveness evidence —
        #: a Pong — postdates it)
        self._down_at: dict[int, float] = {}
        self._log = get_logger("msgr")
        # ``name`` identifies this endpoint on the fault plane's link
        # rules (an OSD daemon passes its own name so inter-OSD links
        # read as osd.i -> osd.j, not client -> osd.j)
        self.messenger = Messenger(name, secret=secret)
        self.messenger.set_dispatcher(self._dispatch)
        self._conns: dict[int, Connection] = {}
        self._tids = itertools.count(1)
        self._lock = DebugLock("msgr.shard_sessions")
        self._waiting: dict[tuple[int, int], _Pending] = {}
        self._inbox: "queue.Queue[Callable[[], None]]" = queue.Queue()
        # Serializes reply-callback execution (and predicate checks)
        # across concurrent drainers: client-op workers, backfill and
        # catch-up recovery threads all drain the one inbox, and the
        # RMW/read pipelines assume their callbacks never run
        # concurrently (crimson run-to-completion stance). RLock: a
        # callback may itself drain (sync read inside a recovery step).
        self._cb_lock = DebugRLock("msgr.shard_cb")
        self._last_seen: dict[int, float] = {}
        self._hb_stop: threading.Event | None = None
        self._hb_thread: threading.Thread | None = None
        # -- sub-write batching (round-10 fan-out coalescing): inside
        # a ``subwrite_batching`` scope, sub-writes stage per peer and
        # flush as ONE ECSubWriteBatch frame each. Flush points:
        # scope exit, and the top of every drain_until loop — every
        # submitter drains right after its fan-out, so a staged txn
        # is never more than one drain iteration from the wire (and
        # any concurrent thread's drain carries it along).
        self._stage_depth = 0
        self._staged: dict[int, list] = {}
        #: observability hook the owning daemon points at its
        #: coalesce counters: called with the item count of every
        #: multi-sub-write frame sent
        self.on_subwrite_batch: Callable[[int], None] | None = None

    # -- plumbing ------------------------------------------------------
    def _conn(self, shard: int) -> Connection:
        with self._lock:
            conn = self._conns.get(shard)
        if conn is not None and conn.alive:
            return conn
        conn = self.messenger.connect(self.addrs[shard])
        with self._lock:
            self._conns[shard] = conn
        return conn

    def _dispatch(self, conn: Connection, msg) -> None:
        """Reader thread: queue the reply for the caller to drain.
        Pongs update liveness directly (no pipeline state touched)."""
        if isinstance(msg, Pong):
            self._last_seen[msg.shard] = time.monotonic()
            return
        if isinstance(msg, ECSubWriteBatchReply):
            # demux the batch into its items' pending entries: each
            # staged sub-write registered under its OWN tid, so the
            # ack path below it is indistinguishable from a solo
            # ECSubWriteReply (parked items simply stay registered)
            for tid, committed in msg.results:
                with self._lock:
                    entry = self._waiting.pop((tid, msg.shard), None)
                if entry is not None:
                    entry.tracked.finish(
                        "replied" if committed else "fenced"
                    )
                    self._inbox.put(
                        lambda e=entry, t=tid, c=committed: e.on_reply(
                            ECSubWriteReply(t, msg.shard, c)
                        )
                    )
                else:
                    self._absorbed()
            return
        if not isinstance(
            msg,
            (ECSubWriteReply, ECSubReadReply, PGListReply, GetAttrsReply,
             PGInfoReply, PGActivateAck, BackfillReserveReply),
        ):
            return  # a reflected request must never satisfy an RPC
        with self._lock:
            entry = self._waiting.pop((msg.tid, msg.shard), None)
        if entry is not None:
            entry.tracked.finish("replied")
            self._inbox.put(lambda: entry.on_reply(msg))
        elif isinstance(msg, (ECSubWriteReply, ECSubWriteBatchReply)):
            self._absorbed()

    def _absorbed(self) -> None:
        """A write ack with no pending entry: a duplicated frame's
        second copy, or a straggler ack that outlived its RPC deadline
        — either way the commit path already consumed (or re-sent) the
        op, so the ack is absorbed exactly-once. Observable on the
        owning daemon's ``osd.N.net`` counter set."""
        pc = self.messenger.net_pc
        if pc is not None:
            pc.inc("resends_absorbed")

    def _register(
        self, tid, shard, oid, on_reply, is_read,
        deadline=None, soft=False, resend=None,
    ) -> None:
        retry_at = None
        if resend is not None and self.resend_interval > 0:
            retry_at = time.monotonic() + self.resend_interval
        tracked = None
        if not soft:
            # soft RPCs (delayed reservation grants) are EXPECTED to
            # wait — tracking them would feed false slow-op complaints
            from ceph_tpu.utils.optracker import op_tracker

            tracked = op_tracker.register(
                "peer_subop", daemon=self.messenger.name,
                to=f"osd.{shard}", tid=tid,
                kind="read" if is_read else "write", oid=oid,
            )
        with self._lock:
            self._waiting[(tid, shard)] = _Pending(
                shard, oid, on_reply,
                deadline if deadline is not None
                else time.monotonic() + self.timeout,
                is_read, soft, resend=resend, retry_at=retry_at,
                tracked=tracked,
            )

    def _send(self, shard: int, msg, tid: int) -> bool:
        try:
            self._conn(shard).send(msg)
            return True
        except (ConnectionError, OSError, KeyError):
            with self._lock:
                entry = self._waiting.pop((tid, shard), None)
            if entry is not None:
                entry.tracked.finish("send_failed")
            self._mark_down(shard, "send failed")
            return False

    def _mark_down(self, shard: int, why: str) -> None:
        if shard not in self.down_shards:
            self._log.info("shard", shard, f"marked down ({why})")
        self.down_shards.add(shard)
        self._down_at[shard] = time.monotonic()

    def recheck_down(self, shards=None) -> None:
        """Re-probe locally down-marked peers (callers pass only ones
        the OSDMap still says are up): a mark earned on a LOSSY link
        — one lost ack tripping the RPC deadline — must not exclude a
        healthy peer until the next map change. Evidence-based: a
        Pong that postdates the down-mark clears it; otherwise a
        fresh Ping goes out and a later recheck consumes its Pong. A
        genuinely dead or partitioned peer never pongs, so its mark
        stands (one-way marking is preserved for real failures)."""
        now = time.monotonic()
        for shard in list(self.down_shards):
            if shards is not None and shard not in shards:
                continue
            if self._last_seen.get(shard, 0.0) > self._down_at.get(
                shard, now
            ):
                self.down_shards.discard(shard)
                self._down_at.pop(shard, None)
                self._log.info(
                    "shard", shard, "back up (pong after down-mark)"
                )
                continue
            try:
                self._conn(shard).send(Ping(next(self._tids), shard))
            except (ConnectionError, OSError, KeyError):
                pass

    def _expire(self) -> None:
        """Timed-out RPCs: mark the shard down; reads get an error
        callback, writes stay parked (lost-ack semantics). Before the
        deadline, entries with a retransmit ladder re-fire on their
        doubling schedule (lossy-link runs only; see _Pending)."""
        now = time.monotonic()
        expired = []
        resends = []
        with self._lock:
            for key, entry in list(self._waiting.items()):
                if entry.deadline <= now:
                    expired.append((key, entry))
                    del self._waiting[key]
                elif (
                    entry.retry_at is not None and entry.retry_at <= now
                ):
                    entry.tries += 1
                    entry.retry_at = now + self.resend_interval * (
                        2 ** entry.tries
                    )
                    entry.tracked.mark_event("resent", tries=entry.tries)
                    resends.append(entry.resend)
        for fire in resends:  # outside the lock: sends can block
            try:
                fire()
            except (ConnectionError, OSError, KeyError):
                pass  # dead link: the deadline path judges it
        for (tid, shard), entry in expired:
            entry.tracked.finish("rpc_timeout")
            if not entry.soft:
                self._mark_down(shard, "rpc timeout")
            if entry.is_read:
                from ceph_tpu.pipeline.read import ShardReadError

                self._inbox.put(
                    lambda e=entry: e.on_reply(
                        ShardReadError(e.shard, e.oid)
                    )
                )

    # -- caller-thread event loop --------------------------------------
    def drain_until(
        self, pred: Callable[[], bool], timeout: float = 30.0
    ) -> None:
        """Run queued reply callbacks on this thread until ``pred``
        holds. Raises TimeoutError if it never does. Any thread may
        drain; pipeline callbacks stay mutually serialized under
        ``_cb_lock`` (a drainer may execute another waiter's thunk —
        the state change it was waiting on is shared, so its own
        predicate pass sees it)."""
        with lockdep.blocking_region("peers.drain_until"):
            self._drain_until(pred, timeout)

    def _drain_until(
        self, pred: Callable[[], bool], timeout: float
    ) -> None:
        end = time.monotonic() + timeout
        while True:
            with self._cb_lock:
                if pred():
                    return
            self._expire()
            self._flush_staged()
            try:
                thunk = self._inbox.get(timeout=0.05)
            except queue.Empty:
                if time.monotonic() > end:
                    raise TimeoutError("drain_until: condition never held")
                continue
            # Execute only if no other thread is mid-callback: blocking
            # here would park this thunk — possibly the very reply the
            # lock holder's nested drain is waiting on — on our stack
            # and starve it into a spurious TimeoutError. Re-queue and
            # let the holder's own (re-entrant) drain loop pop it.
            if self._cb_lock.acquire(blocking=False):
                try:
                    thunk()
                finally:
                    self._cb_lock.release()
            else:
                self._inbox.put(thunk)
                time.sleep(0.001)

    # -- ShardBackend surface ------------------------------------------
    def set_addr(self, shard: int, addr: tuple[str, int]) -> None:
        """Point a shard at a replacement daemon and mark it up (the
        osdmap-update analog after an OSD is replaced)."""
        with self._lock:
            self.addrs[shard] = addr
            conn = self._conns.pop(shard, None)
        if conn is not None:
            conn.close()
        self._last_seen[shard] = time.monotonic()
        self.down_shards.discard(shard)
        self._down_at.pop(shard, None)

    def avail_shards(self) -> set[int]:
        return set(self.addrs) - self.down_shards

    def read_shard_async(
        self,
        shard: int,
        oid: str,
        extents,
        cb: Callable[[int, object], None],
        logical: int | None = None,
    ) -> None:
        from ceph_tpu.pipeline.read import ShardReadError

        tid = next(self._tids)

        def on_reply(reply) -> None:
            if isinstance(reply, Exception):
                cb(shard, reply)
            elif reply.error:
                cb(shard, ShardReadError(shard, oid, kind=reply.error))
            else:
                cb(shard, dict(zip(reply.offsets, reply.buffers)))

        t_id, t_span = tracer.current()
        msg = ECSubRead(
            tid, shard, oid, [(s, e) for s, e in extents], logical=logical,
            trace_id=t_id, parent_span=t_span,
        )
        self._register(
            tid, shard, oid, on_reply, is_read=True,
            resend=lambda: self._conn(shard).send(msg),
        )
        if not self._send(shard, msg, tid):
            self._inbox.put(lambda: cb(shard, ShardReadError(shard, oid)))

    def read_shard(
        self, shard: int, oid: str, extents, logical: int | None = None
    ) -> dict[int, bytes]:
        """Synchronous single-shard read (drains inline)."""
        out: dict[str, object] = {}
        self.read_shard_async(
            shard, oid, extents, lambda s, r: out.update(r=r),
            logical=logical,
        )
        self.drain_until(lambda: "r" in out, timeout=self.timeout + 5)
        result = out["r"]
        if isinstance(result, Exception):
            raise result
        return result

    def list_pg(
        self, shard: int, pool_id: int, pg_num: int, pgid: int
    ) -> list[tuple[str, int, int]]:
        """Synchronous backfill scan: which objects of this PG does the
        peer hold, as (oid, held_shard_index, ro_size) tuples."""
        tid = next(self._tids)
        out: dict[str, object] = {}
        self._register(
            tid, shard, "", lambda r: out.update(r=r), is_read=True
        )
        if not self._send(
            shard, PGList(tid, shard, pool_id, pg_num, pgid), tid
        ):
            raise ConnectionError(f"osd.{shard} unreachable for pg list")
        self.drain_until(lambda: "r" in out, timeout=self.timeout + 5)
        result = out["r"]
        if isinstance(result, Exception):
            raise result
        return result.oids

    def get_pg_info(
        self, shard: int, pool_id: int, pg_num: int, pgid: int,
        epoch: int = 0,
    ) -> tuple[int, tuple[int, int]]:
        """Synchronous peering info fetch: the peer's
        (last_epoch_started, last_update) for one PG, answered from
        its durable store (proc_replica_info's data source).
        ``epoch`` fences the answering member against sub-writes from
        older intervals of this PG before it answers."""
        tid = next(self._tids)
        out: dict[str, object] = {}
        self._register(
            tid, shard, "", lambda r: out.update(r=r), is_read=True
        )
        if not self._send(
            shard, PGInfo(tid, shard, pool_id, pg_num, pgid, epoch), tid
        ):
            raise ConnectionError(f"osd.{shard} unreachable for pg info")
        self.drain_until(lambda: "r" in out, timeout=self.timeout + 5)
        result = out["r"]
        if isinstance(result, Exception):
            raise result
        return result.les, (result.lu_epoch, result.lu_tid)

    def activate_pg(
        self, shard: int, pool_id: int, pgid: int, epoch: int
    ) -> bool:
        """Push an interval activation (les=epoch) to one member;
        waits for the ack so the les write is durable before the
        primary starts serving. Returns False when the member is
        unreachable (it keeps its stale les — by design)."""
        tid = next(self._tids)
        out: dict[str, object] = {}
        self._register(
            tid, shard, "", lambda r: out.update(r=r), is_read=True
        )
        if not self._send(
            shard, PGActivate(tid, shard, pool_id, pgid, epoch), tid
        ):
            return False
        try:
            self.drain_until(lambda: "r" in out, timeout=self.timeout)
        except TimeoutError:
            return False
        return not isinstance(out.get("r"), Exception)

    def reserve_backfill(
        self, shard: int, pool_id: int, pgid: int, prio: int,
        timeout: float,
    ) -> bool:
        """Ask a backfill target for a remote reservation slot. The
        grant may be DELAYED while the target's remote reserver is
        full — ``timeout`` bounds the wait; False means unreachable
        or not granted in time (the caller backs off and retries)."""
        tid = next(self._tids)
        out: dict[str, object] = {}
        # soft + per-call deadline: a full target DELAYS its grant by
        # design, so the generic RPC expiry must neither cut the wait
        # short nor mark the healthy-but-busy peer down
        self._register(
            tid, shard, "", lambda r: out.update(r=r), is_read=True,
            deadline=time.monotonic() + timeout, soft=True,
        )
        if not self._send(
            shard,
            BackfillReserve(tid, shard, "request", pool_id, pgid, prio),
            tid,
        ):
            return False
        try:
            self.drain_until(lambda: "r" in out, timeout=timeout)
        except TimeoutError:
            return False
        r = out.get("r")
        return (
            not isinstance(r, Exception)
            and getattr(r, "granted", False)
        )

    def release_backfill(self, shard: int, pool_id: int, pgid: int) -> None:
        """Fire-and-forget remote-slot release (acked, but the caller
        has nothing to do with the ack)."""
        tid = next(self._tids)
        self._register(tid, shard, "", lambda r: None, is_read=True)
        self._send(
            shard,
            BackfillReserve(tid, shard, "release", pool_id, pgid),
            tid,
        )

    def get_attrs_async(
        self, shard: int, oid: str, names: list[str], cb
    ) -> bool:
        """Async attr fetch (the read_shard_async pattern): ``cb`` gets
        a GetAttrsReply, an Exception, or is never called when the
        send itself fails (returns False so the caller can count)."""
        tid = next(self._tids)
        self._register(tid, shard, oid, cb, is_read=True)
        return self._send(shard, GetAttrs(tid, shard, oid, names), tid)

    def get_attrs(
        self, shard: int, oid: str, names: list[str]
    ) -> dict:
        """Synchronous attr fetch from one shard's store (the getattr
        sub-op): name -> bytes | None. Raises on enoent/unreachable."""
        out: dict[str, object] = {}
        if not self.get_attrs_async(
            shard, oid, names, lambda r: out.update(r=r)
        ):
            raise ConnectionError(f"osd.{shard} unreachable for attrs")
        self.drain_until(lambda: "r" in out, timeout=self.timeout)
        result = out["r"]
        if isinstance(result, Exception):
            raise result
        if result.error:
            raise FileNotFoundError(oid)
        return result.attrs

    #: set by the owning OSD daemon: () -> (map_epoch, osd_id), the
    #: sender interval stamped into every sub-write for the replica
    #: fence (standalone pipeline tests leave it None: no fencing)
    interval_fn = None

    # -- sub-write batching scope --------------------------------------
    def subwrite_batching(self):
        """Scope within which sub-writes stage per peer instead of
        going out one frame each; nesting-safe, flushes on exit."""
        import contextlib

        @contextlib.contextmanager
        def scope():
            with self._lock:
                self._stage_depth += 1
            try:
                yield
            finally:
                with self._lock:
                    self._stage_depth -= 1
                self._flush_staged()

        return scope()

    def _flush_staged(self) -> None:
        """Ship every staged sub-write: one ECSubWriteBatch per peer
        with >= 2 items, plain ECSubWrite for singletons (the wire
        stays byte-identical when nothing actually coalesced)."""
        with self._lock:
            if not self._staged:
                return
            staged, self._staged = self._staged, {}
        for shard, items in staged.items():
            if len(items) == 1:
                tid, epoch, from_osd, txn = items[0]
                self._send(
                    shard,
                    ECSubWrite(
                        tid, shard, txn, epoch=epoch, from_osd=from_osd
                    ),
                    tid,
                )
                continue
            batch_tid = next(self._tids)
            msg = ECSubWriteBatch(
                batch_tid, shard,
                [(tid, shard, epoch, from_osd, txn)
                 for tid, epoch, from_osd, txn in items],
            )
            try:
                self._conn(shard).send(msg)
                if self.on_subwrite_batch is not None:
                    self.on_subwrite_batch(len(items))
            except (ConnectionError, OSError, KeyError):
                # the whole frame is lost: drop every item's pending
                # entry and mark the peer down, exactly like a failed
                # solo send (writes park; recovery's problem)
                dropped = []
                with self._lock:
                    for tid, *_rest in items:
                        e = self._waiting.pop((tid, shard), None)
                        if e is not None:
                            dropped.append(e)
                for e in dropped:
                    e.tracked.finish("send_failed")
                self._mark_down(shard, "send failed")

    def submit_shard_txn(
        self, shard: int, txn: Transaction, ack: Callable[[], None]
    ) -> None:
        tid = next(self._tids)

        def on_reply(reply) -> None:
            if not isinstance(reply, Exception) and reply.committed:
                ack()
            # else parked: ack never fires, recovery's problem

        epoch, from_osd = (
            self.interval_fn() if self.interval_fn else (0, -1)
        )
        t_id, t_span = tracer.current()
        msg = ECSubWrite(
            tid, shard, txn, trace_id=t_id, parent_span=t_span,
            epoch=epoch, from_osd=from_osd,
        )
        # retransmits always go out SOLO (even for batch-staged
        # items): the receiver path is identical and the frame is
        # self-contained
        self._register(
            tid, shard, "", on_reply, is_read=False,
            resend=lambda: self._conn(shard).send(msg),
        )
        with self._lock:
            if self._stage_depth > 0:
                self._staged.setdefault(shard, []).append(
                    (tid, epoch, from_osd, txn)
                )
                return
        self._send(shard, msg, tid)

    # -- heartbeats (OSD::handle_osd_ping / stale-ping culling) --------
    def start_heartbeat(
        self, period: float = 0.5, grace: float = 2.0
    ) -> None:
        """Ping every shard each ``period`` seconds; a shard silent for
        ``grace`` seconds (or unreachable) is marked down so the
        planners route around it BEFORE any IO trips over the failure
        (osd/OSD.cc:5854 heartbeat + :6148 stale-ping culling).
        Down-marking is one-way: a replaced daemon comes back via
        ``set_addr`` (the osdmap-update path), never silently."""
        self.stop_heartbeat()
        self._hb_stop = threading.Event()
        now = time.monotonic()
        for shard in self.addrs:
            self._last_seen.setdefault(shard, now)

        def loop() -> None:
            while not self._hb_stop.wait(period):
                for shard in list(self.addrs):
                    if shard in self.down_shards:
                        continue
                    try:
                        self._conn(shard).send(
                            Ping(next(self._tids), shard)
                        )
                    except (ConnectionError, OSError):
                        self._mark_down(shard, "ping failed")
                        continue
                    age = time.monotonic() - self._last_seen.get(shard, 0)
                    if age > grace:
                        self._mark_down(shard, "ping silence")

        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
            if self._hb_thread is not None:
                self._hb_thread.join(timeout=2.0)
        self._hb_stop = None
        self._hb_thread = None

    def shutdown(self) -> None:
        self.stop_heartbeat()
        with self._lock:
            pending = list(self._waiting.values())
            self._waiting.clear()
        for entry in pending:
            # a stopped backend's RPCs died with it — the live tracker
            # must not carry (and complain about) them forever
            entry.tracked.finish("backend_shutdown")
        self.messenger.shutdown()
