"""Socket messenger — the AsyncMessenger analog (host/DCN tier).

Mirrors the roles of msg/async/AsyncMessenger.{h,cc}: a ``Messenger``
binds a listening address and dispatches inbound typed messages to its
dispatcher (the ``ms_fast_dispatch`` seam, osd/OSD.cc:7686);
``Connection`` objects carry framed messages (wire.py) over TCP with a
reader thread per connection. Event-loop sophistication (epoll worker
pools, lossy/lossless policies with replay) is intentionally replaced
by one thread per connection — connection counts here are k+m, not
thousands; the wire format, per-segment CRC, and dispatch contract are
the load-bearing parts.

Network-fault plane (the tc/netem analog, qa thrasher msgr-failures
role): :data:`net_faults` is a process-global, seeded registry of
per-link (src name → dst name) rules — drop probability, delay
distribution, duplication, reordering, and full/asymmetric partitions.
Faults apply to LOGICAL frames above TCP, at the connection-initiating
end, which knows both endpoint names (outbound requests in ``send``,
inbound replies after decode in the read loop) — each direction of a
link is therefore faulted exactly once, and a delayed outbound frame
is re-sent through the normal seal-under-lock path so secure-mode
counters stay consistent with socket order. Every decision comes from
a per-link ``random.Random`` seeded from (plane seed, src, dst): the
same seed replays the same per-link firing sequence, which is what
makes a chaos run a regression test instead of a dice roll. When
nothing is armed the cost is one attribute check per frame.
"""

from __future__ import annotations

import fnmatch
import heapq
import itertools
import socket
import threading
import time
import zlib
from collections.abc import Callable

from . import secure as secure_mod
from . import shm_ring
from .messages import decode_message, message_type
from .wire import BadFrame, decode_frame, encode_frame
from ceph_tpu.utils import lockdep
from ceph_tpu.utils.lockdep import DebugLock


#: listening addr -> messenger name, registered at bind() — how a
#: connecting end resolves the PEER's name so the fault plane can key
#: its link rules on (src, dst) daemon names (in-process clusters
#: only; a cross-host deployment would carry names in a hello frame)
_addr_names: dict[tuple[str, int], str] = {}
_addr_lock = DebugLock("msgr.addr_registry")


class LinkRule:
    """One link's injection profile. Probabilities are per logical
    frame per direction; ``delay_ms`` + uniform ``delay_jitter_ms``
    is the netem delay/jitter pair (p95 = delay + 0.95·jitter);
    ``reorder`` holds a frame until the next one on the link passes
    it; ``partition`` drops everything (compose two asymmetric rules
    for a full partition)."""

    __slots__ = (
        "drop", "dup", "delay_ms", "delay_jitter_ms", "reorder",
        "partition",
    )

    def __init__(
        self,
        drop: float = 0.0,
        dup: float = 0.0,
        delay_ms: float = 0.0,
        delay_jitter_ms: float = 0.0,
        reorder: float = 0.0,
        partition: bool = False,
    ) -> None:
        for name, p in (("drop", drop), ("dup", dup), ("reorder", reorder)):
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be a probability, got {p}")
        if delay_ms < 0 or delay_jitter_ms < 0:
            raise ValueError("delays must be >= 0")
        self.drop = drop
        self.dup = dup
        self.delay_ms = delay_ms
        self.delay_jitter_ms = delay_jitter_ms
        self.reorder = reorder
        self.partition = partition

    def __repr__(self) -> str:  # the `tc qdisc show` analog
        parts = []
        if self.partition:
            parts.append("partition")
        if self.drop:
            parts.append(f"drop={self.drop}")
        if self.dup:
            parts.append(f"dup={self.dup}")
        if self.delay_ms or self.delay_jitter_ms:
            parts.append(
                f"delay={self.delay_ms}ms+{self.delay_jitter_ms}ms"
            )
        if self.reorder:
            parts.append(f"reorder={self.reorder}")
        return f"LinkRule({' '.join(parts) or 'clean'})"


class _Lane:
    """Per-(src, dst) state: the resolved rule, a deterministic RNG,
    and the held-frame slot the reorder fault uses."""

    __slots__ = ("rule", "rng", "held", "lock")

    def __init__(self, rule: "LinkRule | None", seed: int) -> None:
        import random

        self.rule = rule
        self.rng = random.Random(seed)
        self.held: "Callable[[], None] | None" = None
        self.lock = DebugLock("msgr.net_lane")


#: counters the plane keeps (process totals; per-daemon slices ride
#: the owning messenger's ``net_pc`` perf set when one is attached)
FAULT_COUNTERS = (
    "frames_dropped", "frames_delayed", "frames_duped",
    "frames_reordered",
)


class NetFaultPlane:
    """Process-global seeded link-fault registry (see module doc).

    Arm with :meth:`add_rule` / :meth:`partition`; every armed plane
    change bumps a generation so lanes re-resolve their rule lazily.
    ``clear()`` disarms and FLUSHES in-flight delayed/held frames
    (delivered immediately — a cleared plane must not keep eating
    frames), so a fault window has a crisp settle edge."""

    #: failsafe: a reorder-held frame is force-flushed after this many
    #: seconds even if no follow-up frame ever crosses the lane
    REORDER_FLUSH_S = 0.1

    def __init__(self) -> None:
        self._lock = DebugLock("msgr.net_faults")
        self._rules: list[tuple[str, str, LinkRule]] = []
        self._lanes: dict[tuple[str, int], _Lane] = {}
        self._gen = 0
        self.seed = 0
        self.active = False
        self.counters = dict.fromkeys(FAULT_COUNTERS, 0)
        # delayed-delivery timer machinery (lazy daemon thread)
        self._timers: list[tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count(1)
        self._timer_cv = threading.Condition()
        self._timer_thread: threading.Thread | None = None

    # -- operator surface (the `tc qdisc add` analog) -------------------
    def configure(self, seed: int) -> "NetFaultPlane":
        """Set the plane seed and reset lane RNGs — call once per run
        BEFORE arming rules; same seed => same per-link firings."""
        with self._lock:
            self.seed = int(seed)
            self._lanes.clear()
            self._gen += 1
        return self

    def add_rule(self, src: str, dst: str, rule: LinkRule) -> None:
        """Arm ``rule`` for frames src→dst (fnmatch patterns, e.g.
        ``("osd.*", "osd.*")``). First matching rule wins. The
        ``msgr_fault_plane`` config gate (evaluated at arm time) is
        the operator escape hatch that keeps armed rules inert."""
        from ceph_tpu.utils import config
        from ceph_tpu.utils.cluster_log import cluster_log

        with self._lock:
            self._rules.append((src, dst, rule))
            self._gen += 1
            self.active = bool(config.get("msgr_fault_plane"))
        # the arm lands in the cluster log so a chaos run's fallout
        # (slow ops, down-marks) lines up against its cause
        cluster_log.log(
            "net", "net_fault_armed",
            f"link rule armed {src} -> {dst}: {rule!r}"
            + ("" if self.active else " (inert: msgr_fault_plane=false)"),
            severity="WRN", seed=self.seed,
        )

    def partition(
        self, names, peers: str = "*", asymmetric: bool = False
    ) -> None:
        """Partition every name in ``names`` from ``peers``:
        symmetric by default; ``asymmetric=True`` cuts only the
        INBOUND direction (peers → victim), the half-partition that
        makes a victim keep talking into a void — the peering
        re-election torture case."""
        if isinstance(names, str):
            names = [names]
        for name in names:
            self.add_rule(peers, name, LinkRule(partition=True))
            if not asymmetric:
                self.add_rule(name, peers, LinkRule(partition=True))

    def clear(self) -> None:
        """Disarm everything and flush held/delayed frames NOW."""
        with self._lock:
            had_rules = bool(self._rules)
            self._rules.clear()
            self._gen += 1
            self.active = False
            lanes = list(self._lanes.values())
        if had_rules:
            from ceph_tpu.utils.cluster_log import cluster_log

            cluster_log.log(
                "net", "net_fault_cleared",
                "fault plane cleared (held/delayed frames flushed)",
            )
        held = []
        for lane in lanes:
            with lane.lock:
                if lane.held is not None:
                    held.append(lane.held)
                    lane.held = None
        with self._timer_cv:
            pending = [fn for _w, _s, fn in self._timers]
            self._timers.clear()
            self._timer_cv.notify()
        for fn in held + pending:
            try:
                fn()
            except Exception:
                pass  # the link may have died while the frame was held
        with self._lock:
            self._lanes.clear()

    def reset_counters(self) -> None:
        with self._lock:
            self.counters = dict.fromkeys(FAULT_COUNTERS, 0)

    # -- plumbing -------------------------------------------------------
    def _resolve(self, src: str, dst: str) -> "LinkRule | None":
        for pat_s, pat_d, rule in self._rules:
            if fnmatch.fnmatchcase(src, pat_s) and fnmatch.fnmatchcase(
                dst, pat_d
            ):
                return rule
        return None

    def _lane(self, src: str, dst: str) -> _Lane:
        key = (f"{src}>{dst}", self._gen)
        with self._lock:
            lane = self._lanes.get(key)
            if lane is None:
                # prune lanes from superseded generations (their rule
                # resolution is stale; the RNG restarts per arming,
                # which keeps a configure+arm block deterministic)
                for old in [k for k in self._lanes if k[1] != self._gen]:
                    del self._lanes[old]
                lane = self._lanes[key] = _Lane(
                    self._resolve(src, dst),
                    zlib.crc32(f"{self.seed}|{src}>{dst}".encode()),
                )
            return lane

    def _count(self, kind: str, owner: "Messenger | None") -> None:
        with self._lock:
            self.counters[kind] += 1
        pc = getattr(owner, "net_pc", None)
        if pc is not None:
            pc.inc(kind)

    def _at(self, when: float, fn: Callable[[], None]) -> None:
        with self._timer_cv:
            heapq.heappush(
                self._timers, (when, next(self._timer_seq), fn)
            )
            if self._timer_thread is None or not self._timer_thread.is_alive():
                self._timer_thread = threading.Thread(
                    target=self._timer_loop, daemon=True,
                    name="net-fault-timer",
                )
                self._timer_thread.start()
            self._timer_cv.notify()

    def _timer_loop(self) -> None:
        while True:
            with self._timer_cv:
                if not self._timers:
                    if not self._timer_cv.wait(5.0) and not self._timers:
                        self._timer_thread = None
                        return
                    continue
                when = self._timers[0][0]
                now = time.monotonic()
                if when > now:
                    self._timer_cv.wait(min(when - now, 0.5))
                    continue
                _w, _s, fn = heapq.heappop(self._timers)
            try:
                fn()
            except Exception:
                pass  # a dead link eats the frame, like a real drop

    # -- the per-frame decision (the netem hook) ------------------------
    def process(
        self,
        src: str,
        dst: str,
        deliver: Callable[[], None],
        owner: "Messenger | None" = None,
    ) -> None:
        """Run one frame src→dst through the link's rule. ``deliver``
        performs the actual send/dispatch; it may run synchronously
        (clean frame — exceptions propagate to the caller exactly as
        without the plane), later on the timer thread (delay/reorder/
        dup copies; exceptions there are swallowed, the frame is
        simply lost like any fault), or never (drop/partition)."""
        lane = self._lane(src, dst)
        rule = lane.rule
        if rule is None:
            deliver()
            return
        with lane.lock:
            rng = lane.rng
            # one draw per fault class per frame, in a FIXED order, so
            # the per-link decision sequence is a pure function of
            # (seed, frame index on the link)
            p_drop = rng.random()
            p_dup = rng.random()
            p_delay = rng.random()
            p_reorder = rng.random()
            dropped = rule.partition or (
                rule.drop > 0.0 and p_drop < rule.drop
            )
            dup = rule.dup > 0.0 and p_dup < rule.dup
            delay = 0.0
            if rule.delay_ms or rule.delay_jitter_ms:
                delay = (
                    rule.delay_ms + rule.delay_jitter_ms * p_delay
                ) / 1000.0
            reorder = rule.reorder > 0.0 and p_reorder < rule.reorder
            released, lane.held = lane.held, None
        if dropped:
            self._count("frames_dropped", owner)
            if released is not None:
                self._guarded(released)
            return
        if dup:
            self._count("frames_duped", owner)

        def emit() -> None:
            deliver()
            if dup:
                self._guarded(deliver)

        if reorder and released is None:
            # hold THIS frame; the next frame on the lane (or the
            # failsafe timer) releases it behind itself
            self._count("frames_reordered", owner)
            hold = (
                emit if delay == 0.0
                else lambda: self._later(delay, emit, owner, count=False)
            )
            with lane.lock:
                if lane.held is None:
                    lane.held = hold
                    self._at(
                        time.monotonic() + delay + self.REORDER_FLUSH_S,
                        lambda: self._flush_lane(lane),
                    )
                    if delay:
                        self._count("frames_delayed", owner)
                    return
            # lost the slot to a racing frame: fall through, deliver
        if delay:
            self._count("frames_delayed", owner)
            self._later(delay, emit, owner, count=False)
        else:
            emit()
        if released is not None:
            self._guarded(released)

    def _later(self, delay, fn, owner, count=True) -> None:
        if count:
            self._count("frames_delayed", owner)
        self._at(time.monotonic() + delay, lambda: self._guarded(fn))

    def _flush_lane(self, lane: _Lane) -> None:
        with lane.lock:
            held, lane.held = lane.held, None
        if held is not None:
            self._guarded(held)

    @staticmethod
    def _guarded(fn: Callable[[], None]) -> None:
        try:
            fn()
        except Exception:
            pass  # faulted copy on a dead link: just lost


#: the process-global fault plane (tests and loadgen arm it)
net_faults = NetFaultPlane()

# In-the-clear handshake frame type for secure-mode nonce exchange
# (outside the normal message-type space; auth_none + CephX roles).
HANDSHAKE_TYPE = 0x7FFF


class Connection:
    """One peer link; ``send(msg)`` frames and writes atomically.

    With a cluster secret configured, the connection runs the secure
    handshake (nonce exchange -> per-direction AES-GCM sessions)
    synchronously before the reader thread starts, so no payload
    message ever travels in the clear."""

    def __init__(
        self,
        sock: socket.socket,
        messenger: "Messenger",
        is_client: bool = False,
        peer_name: "str | None" = None,
    ) -> None:
        self.sock = sock
        self.messenger = messenger
        #: the remote messenger's name when known (client-initiated
        #: conns resolve it from the bind registry). The fault plane
        #: only acts where BOTH names are known — i.e. once per
        #: logical direction, at the connection-initiating end.
        self.peer_name = peer_name
        self._send_lock = DebugLock("msgr.send")
        self._seq = 0
        self.alive = True
        self._tx = self._rx = None
        if messenger.secret is not None:
            try:
                self._handshake(is_client)
            except Exception:
                self.alive = False
                try:
                    self.sock.close()
                except OSError:
                    pass
                raise
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _handshake(self, is_client: bool) -> None:
        # Bounded: a peer that connects and goes silent must not wedge
        # the accept loop (the reference bounds auth exchanges too).
        self.sock.settimeout(5)
        try:
            self._do_handshake(is_client)
        finally:
            self.sock.settimeout(None)

    def _do_handshake(self, is_client: bool) -> None:
        my_nonce = secure_mod.fresh_nonce()
        hello = encode_frame(HANDSHAKE_TYPE, 0, [my_nonce])
        try:
            if is_client:
                self.sock.sendall(hello)
                peer_nonce = self._read_handshake()
                nonce_c, nonce_s = my_nonce, peer_nonce
            else:
                peer_nonce = self._read_handshake()
                self.sock.sendall(hello)
                nonce_c, nonce_s = peer_nonce, my_nonce
        except (EOFError, BadFrame, socket.timeout) as e:
            # A clear-mode or garbage-speaking peer must look like any
            # other dead link (callers map ConnectionError to a down
            # shard), not raise EOFError/BadFrame out of the op path.
            raise ConnectionError(f"secure handshake failed: {e!r}") from e
        self._tx, self._rx = secure_mod.derive_session(
            self.messenger.secret, nonce_c, nonce_s, is_client
        )

    def _read_handshake(self) -> bytes:
        msg_type, _seq, segments = decode_frame(self._read_exact)
        if msg_type != HANDSHAKE_TYPE or len(segments) != 1:
            raise ConnectionError("peer did not offer secure handshake")
        return segments[0]

    def send(self, msg) -> None:
        # lockdep checkpoint: a socket write is a blocking call —
        # executing one while an op-serializing lock is held is only
        # legitimate on the op's own (bounded) commit path, which the
        # "messenger.send" waiver documents
        with lockdep.blocking_region("messenger.send"):
            self._send_faulted(msg)

    def _send_faulted(self, msg) -> None:
        if net_faults.active and self.peer_name is not None:
            # outbound half of the link: the plane may drop the frame
            # (caller sees success — exactly a lost frame), defer it
            # (re-enters _send_now on the timer thread; sealing order
            # still matches socket order because encode happens at
            # delivery time under the send lock), or duplicate it.
            net_faults.process(
                self.messenger.name,
                self.peer_name,
                lambda m=msg: self._send_now(m),
                owner=self.messenger,
            )
            return
        self._send_now(msg)

    def _send_now(self, msg) -> None:
        with self._send_lock:
            self._seq += 1
            # Sealing must happen under the send lock: the AEAD tx
            # counter and the socket write have to agree on order.
            frame = encode_frame(
                message_type(msg),
                self._seq,
                msg.encode(),
                compress=self.messenger.compress,
                secure=self._tx,
            )
            try:
                self.sock.sendall(frame)
            except OSError as e:
                self.alive = False
                raise ConnectionError(str(e)) from e

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise EOFError
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        try:
            while True:
                msg_type, _seq, segments = decode_frame(
                    self._read_exact, secure=self._rx
                )
                msg = decode_message(msg_type, segments)
                if net_faults.active and self.peer_name is not None:
                    # inbound half of the link (peer → me): replies on
                    # a client-initiated conn are faulted HERE, after
                    # decode — the server end never needs to know our
                    # name, and secure frames are already opened
                    net_faults.process(
                        self.peer_name,
                        self.messenger.name,
                        lambda m=msg: self.messenger.dispatch(self, m),
                        owner=self.messenger,
                    )
                else:
                    self.messenger.dispatch(self, msg)
        except (EOFError, OSError):
            pass
        except Exception:
            # Decode/dispatch failure (bad frame, unknown type, handler
            # bug): drop the connection loudly-at-the-socket so the
            # peer sees EOF and fails fast instead of waiting out RPC
            # timeouts on a wedged link.
            pass
        finally:
            self.alive = False
            try:
                self.sock.close()
            except OSError:
                pass
            self.messenger._conn_closed(self)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class Messenger:
    """Bind/connect endpoint + dispatcher registry."""

    def __init__(
        self,
        name: str,
        compress: bool = False,
        secret: bytes | None = None,
    ) -> None:
        self.name = name
        # On-wire compression for frames WE send (receivers auto-detect
        # via the frame flags — compression_onwire.cc role).
        self.compress = compress
        # Cluster pre-shared secret (keyring role): non-None enables
        # AES-GCM secure mode on every connection of this messenger.
        # Both ends must agree — a secure peer rejects clear frames
        # and vice versa (mode is per-connection, negotiated up front).
        self.secret = secret
        #: per-daemon net-fault counter set (``osd.N.net``): the
        #: owning daemon attaches one; the fault plane increments it
        #: for frames it drops/delays/dupes/reorders on this
        #: messenger's links
        self.net_pc = None
        self.dispatcher: Callable[[Connection, object], None] | None = None
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = False
        self._conns: set[Connection] = set()
        self._lock = DebugLock("msgr.conns")
        self.addr: tuple[str, int] | None = None

    def set_dispatcher(self, fn: Callable[[Connection, object], None]) -> None:
        self.dispatcher = fn

    def dispatch(self, conn: Connection, msg) -> None:
        if self.dispatcher is not None:
            self.dispatcher(conn, msg)

    # -- server side ---------------------------------------------------
    def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(64)
        # Poll with a timeout: closing a listener out from under a
        # thread blocked in accept() does NOT close the kernel-side
        # open file description — the old accept keeps serving the
        # port. The flag + timeout loop is the portable shutdown.
        s.settimeout(0.2)
        self._stopping = False
        self._listener = s
        self.addr = s.getsockname()
        with _addr_lock:
            _addr_names[self.addr] = self.name
        # shm-ring lane registration (always cheap; the msgr_transport
        # gate decides at connect() time whether anyone upgrades)
        shm_ring.register(self.addr, self)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        return self.addr

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                sock, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Finish connection setup off the accept thread: the secure
            # handshake blocks up to its 5 s timeout, and one silent
            # connector must not starve other peers' accepts.
            threading.Thread(
                target=self._finish_accept, args=(sock,), daemon=True
            ).start()
        try:
            self._listener.close()
        except OSError:
            pass

    def _finish_accept(self, sock: socket.socket) -> None:
        try:
            conn = Connection(sock, self, is_client=False)
        except Exception:
            return  # failed handshake drops the socket, not us
        with self._lock:
            if self._stopping:
                conn.close()
                return
            self._conns.add(conn)

    # -- client side ---------------------------------------------------
    def connect(self, addr: tuple[str, int]) -> Connection:
        # Transport negotiation: when the shm-ring lane is configured
        # and the peer listens in-process, skip the kernel socket
        # entirely — the Connection (framing, CRC, secure handshake,
        # fault-plane hooks) runs unchanged over the ring pair.
        target = shm_ring.lookup(addr)
        if target is not None:
            client_sock, server_sock = shm_ring.socketpair()
            # the server end rides the normal accept path, off-thread
            # (the secure handshake blocks, exactly like TCP accepts)
            threading.Thread(
                target=target._finish_accept,
                args=(server_sock,),
                daemon=True,
            ).start()
            conn = Connection(
                client_sock, self, is_client=True, peer_name=target.name
            )
            with self._lock:
                self._conns.add(conn)
            return conn
        sock = socket.create_connection(addr, timeout=10)
        if sock.getsockname() == sock.getpeername():
            # TCP self-connect: the kernel picked the (freed) target
            # port as our ephemeral source port — the peer is gone.
            sock.close()
            raise ConnectionError(f"self-connect to dead peer {addr}")
        sock.settimeout(None)  # connect timeout must not become a
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)  # recv timeout
        with _addr_lock:
            peer_name = _addr_names.get(tuple(addr))
        conn = Connection(sock, self, is_client=True, peer_name=peer_name)
        with self._lock:
            self._conns.add(conn)
        return conn

    def _conn_closed(self, conn: Connection) -> None:
        with self._lock:
            self._conns.discard(conn)

    def shutdown(self) -> None:
        self._stopping = True
        if self.addr is not None:
            shm_ring.unregister(self.addr, self)
            with _addr_lock:
                if _addr_names.get(self.addr) == self.name:
                    del _addr_names[self.addr]
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=1.0)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            conn.close()
