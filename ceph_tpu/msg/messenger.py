"""Socket messenger — the AsyncMessenger analog (host/DCN tier).

Mirrors the roles of msg/async/AsyncMessenger.{h,cc}: a ``Messenger``
binds a listening address and dispatches inbound typed messages to its
dispatcher (the ``ms_fast_dispatch`` seam, osd/OSD.cc:7686);
``Connection`` objects carry framed messages (wire.py) over TCP with a
reader thread per connection. Event-loop sophistication (epoll worker
pools, lossy/lossless policies with replay) is intentionally replaced
by one thread per connection — connection counts here are k+m, not
thousands; the wire format, per-segment CRC, and dispatch contract are
the load-bearing parts.
"""

from __future__ import annotations

import socket
import threading
from collections.abc import Callable

from .messages import decode_message, message_type
from .wire import decode_frame, encode_frame


class Connection:
    """One peer link; ``send(msg)`` frames and writes atomically."""

    def __init__(self, sock: socket.socket, messenger: "Messenger") -> None:
        self.sock = sock
        self.messenger = messenger
        self._send_lock = threading.Lock()
        self._seq = 0
        self.alive = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def send(self, msg) -> None:
        frame = encode_frame(
            message_type(msg),
            self._next_seq(),
            msg.encode(),
            compress=self.messenger.compress,
        )
        with self._send_lock:
            try:
                self.sock.sendall(frame)
            except OSError as e:
                self.alive = False
                raise ConnectionError(str(e)) from e

    def _next_seq(self) -> int:
        with self._send_lock:
            self._seq += 1
            return self._seq

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise EOFError
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        try:
            while True:
                msg_type, _seq, segments = decode_frame(self._read_exact)
                msg = decode_message(msg_type, segments)
                self.messenger.dispatch(self, msg)
        except (EOFError, OSError):
            pass
        except Exception:
            # Decode/dispatch failure (bad frame, unknown type, handler
            # bug): drop the connection loudly-at-the-socket so the
            # peer sees EOF and fails fast instead of waiting out RPC
            # timeouts on a wedged link.
            pass
        finally:
            self.alive = False
            try:
                self.sock.close()
            except OSError:
                pass
            self.messenger._conn_closed(self)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class Messenger:
    """Bind/connect endpoint + dispatcher registry."""

    def __init__(self, name: str, compress: bool = False) -> None:
        self.name = name
        # On-wire compression for frames WE send (receivers auto-detect
        # via the frame flags — compression_onwire.cc role).
        self.compress = compress
        self.dispatcher: Callable[[Connection, object], None] | None = None
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = False
        self._conns: set[Connection] = set()
        self._lock = threading.Lock()
        self.addr: tuple[str, int] | None = None

    def set_dispatcher(self, fn: Callable[[Connection, object], None]) -> None:
        self.dispatcher = fn

    def dispatch(self, conn: Connection, msg) -> None:
        if self.dispatcher is not None:
            self.dispatcher(conn, msg)

    # -- server side ---------------------------------------------------
    def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(64)
        # Poll with a timeout: closing a listener out from under a
        # thread blocked in accept() does NOT close the kernel-side
        # open file description — the old accept keeps serving the
        # port. The flag + timeout loop is the portable shutdown.
        s.settimeout(0.2)
        self._stopping = False
        self._listener = s
        self.addr = s.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        return self.addr

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                sock, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(Connection(sock, self))
        try:
            self._listener.close()
        except OSError:
            pass

    # -- client side ---------------------------------------------------
    def connect(self, addr: tuple[str, int]) -> Connection:
        sock = socket.create_connection(addr, timeout=10)
        if sock.getsockname() == sock.getpeername():
            # TCP self-connect: the kernel picked the (freed) target
            # port as our ephemeral source port — the peer is gone.
            sock.close()
            raise ConnectionError(f"self-connect to dead peer {addr}")
        sock.settimeout(None)  # connect timeout must not become a
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)  # recv timeout
        conn = Connection(sock, self)
        with self._lock:
            self._conns.add(conn)
        return conn

    def _conn_closed(self, conn: Connection) -> None:
        with self._lock:
            self._conns.discard(conn)

    def shutdown(self) -> None:
        self._stopping = True
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=1.0)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            conn.close()
