"""Socket messenger — the AsyncMessenger analog (host/DCN tier).

Mirrors the roles of msg/async/AsyncMessenger.{h,cc}: a ``Messenger``
binds a listening address and dispatches inbound typed messages to its
dispatcher (the ``ms_fast_dispatch`` seam, osd/OSD.cc:7686);
``Connection`` objects carry framed messages (wire.py) over TCP with a
reader thread per connection. Event-loop sophistication (epoll worker
pools, lossy/lossless policies with replay) is intentionally replaced
by one thread per connection — connection counts here are k+m, not
thousands; the wire format, per-segment CRC, and dispatch contract are
the load-bearing parts.
"""

from __future__ import annotations

import socket
import threading
from collections.abc import Callable

from . import secure as secure_mod
from .messages import decode_message, message_type
from .wire import BadFrame, decode_frame, encode_frame

# In-the-clear handshake frame type for secure-mode nonce exchange
# (outside the normal message-type space; auth_none + CephX roles).
HANDSHAKE_TYPE = 0x7FFF


class Connection:
    """One peer link; ``send(msg)`` frames and writes atomically.

    With a cluster secret configured, the connection runs the secure
    handshake (nonce exchange -> per-direction AES-GCM sessions)
    synchronously before the reader thread starts, so no payload
    message ever travels in the clear."""

    def __init__(
        self,
        sock: socket.socket,
        messenger: "Messenger",
        is_client: bool = False,
    ) -> None:
        self.sock = sock
        self.messenger = messenger
        self._send_lock = threading.Lock()
        self._seq = 0
        self.alive = True
        self._tx = self._rx = None
        if messenger.secret is not None:
            try:
                self._handshake(is_client)
            except Exception:
                self.alive = False
                try:
                    self.sock.close()
                except OSError:
                    pass
                raise
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _handshake(self, is_client: bool) -> None:
        # Bounded: a peer that connects and goes silent must not wedge
        # the accept loop (the reference bounds auth exchanges too).
        self.sock.settimeout(5)
        try:
            self._do_handshake(is_client)
        finally:
            self.sock.settimeout(None)

    def _do_handshake(self, is_client: bool) -> None:
        my_nonce = secure_mod.fresh_nonce()
        hello = encode_frame(HANDSHAKE_TYPE, 0, [my_nonce])
        try:
            if is_client:
                self.sock.sendall(hello)
                peer_nonce = self._read_handshake()
                nonce_c, nonce_s = my_nonce, peer_nonce
            else:
                peer_nonce = self._read_handshake()
                self.sock.sendall(hello)
                nonce_c, nonce_s = peer_nonce, my_nonce
        except (EOFError, BadFrame, socket.timeout) as e:
            # A clear-mode or garbage-speaking peer must look like any
            # other dead link (callers map ConnectionError to a down
            # shard), not raise EOFError/BadFrame out of the op path.
            raise ConnectionError(f"secure handshake failed: {e!r}") from e
        self._tx, self._rx = secure_mod.derive_session(
            self.messenger.secret, nonce_c, nonce_s, is_client
        )

    def _read_handshake(self) -> bytes:
        msg_type, _seq, segments = decode_frame(self._read_exact)
        if msg_type != HANDSHAKE_TYPE or len(segments) != 1:
            raise ConnectionError("peer did not offer secure handshake")
        return segments[0]

    def send(self, msg) -> None:
        with self._send_lock:
            self._seq += 1
            # Sealing must happen under the send lock: the AEAD tx
            # counter and the socket write have to agree on order.
            frame = encode_frame(
                message_type(msg),
                self._seq,
                msg.encode(),
                compress=self.messenger.compress,
                secure=self._tx,
            )
            try:
                self.sock.sendall(frame)
            except OSError as e:
                self.alive = False
                raise ConnectionError(str(e)) from e

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise EOFError
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        try:
            while True:
                msg_type, _seq, segments = decode_frame(
                    self._read_exact, secure=self._rx
                )
                msg = decode_message(msg_type, segments)
                self.messenger.dispatch(self, msg)
        except (EOFError, OSError):
            pass
        except Exception:
            # Decode/dispatch failure (bad frame, unknown type, handler
            # bug): drop the connection loudly-at-the-socket so the
            # peer sees EOF and fails fast instead of waiting out RPC
            # timeouts on a wedged link.
            pass
        finally:
            self.alive = False
            try:
                self.sock.close()
            except OSError:
                pass
            self.messenger._conn_closed(self)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class Messenger:
    """Bind/connect endpoint + dispatcher registry."""

    def __init__(
        self,
        name: str,
        compress: bool = False,
        secret: bytes | None = None,
    ) -> None:
        self.name = name
        # On-wire compression for frames WE send (receivers auto-detect
        # via the frame flags — compression_onwire.cc role).
        self.compress = compress
        # Cluster pre-shared secret (keyring role): non-None enables
        # AES-GCM secure mode on every connection of this messenger.
        # Both ends must agree — a secure peer rejects clear frames
        # and vice versa (mode is per-connection, negotiated up front).
        self.secret = secret
        self.dispatcher: Callable[[Connection, object], None] | None = None
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = False
        self._conns: set[Connection] = set()
        self._lock = threading.Lock()
        self.addr: tuple[str, int] | None = None

    def set_dispatcher(self, fn: Callable[[Connection, object], None]) -> None:
        self.dispatcher = fn

    def dispatch(self, conn: Connection, msg) -> None:
        if self.dispatcher is not None:
            self.dispatcher(conn, msg)

    # -- server side ---------------------------------------------------
    def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(64)
        # Poll with a timeout: closing a listener out from under a
        # thread blocked in accept() does NOT close the kernel-side
        # open file description — the old accept keeps serving the
        # port. The flag + timeout loop is the portable shutdown.
        s.settimeout(0.2)
        self._stopping = False
        self._listener = s
        self.addr = s.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        return self.addr

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                sock, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Finish connection setup off the accept thread: the secure
            # handshake blocks up to its 5 s timeout, and one silent
            # connector must not starve other peers' accepts.
            threading.Thread(
                target=self._finish_accept, args=(sock,), daemon=True
            ).start()
        try:
            self._listener.close()
        except OSError:
            pass

    def _finish_accept(self, sock: socket.socket) -> None:
        try:
            conn = Connection(sock, self, is_client=False)
        except Exception:
            return  # failed handshake drops the socket, not us
        with self._lock:
            if self._stopping:
                conn.close()
                return
            self._conns.add(conn)

    # -- client side ---------------------------------------------------
    def connect(self, addr: tuple[str, int]) -> Connection:
        sock = socket.create_connection(addr, timeout=10)
        if sock.getsockname() == sock.getpeername():
            # TCP self-connect: the kernel picked the (freed) target
            # port as our ephemeral source port — the peer is gone.
            sock.close()
            raise ConnectionError(f"self-connect to dead peer {addr}")
        sock.settimeout(None)  # connect timeout must not become a
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)  # recv timeout
        conn = Connection(sock, self, is_client=True)
        with self._lock:
            self._conns.add(conn)
        return conn

    def _conn_closed(self, conn: Connection) -> None:
        with self._lock:
            self._conns.discard(conn)

    def shutdown(self) -> None:
        self._stopping = True
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=1.0)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            conn.close()
