"""Framed wire protocol with per-segment CRC — the ProtocolV2 analog.

Mirrors the frame shape of msg/async/frames_v2.{h,cc}: a fixed header
(magic, message type, sequence, segment count) followed by a segment
table (length + crc32c per segment) and the segment payloads. Every
segment's crc32c is verified on decode — a flipped bit anywhere raises
``BadFrame``, the on-wire integrity contract ProtocolV2 provides
(SURVEY.md section 5.8; the reference seeds crc32c with -1).

On-wire compression is flag bit 0 (the compression_onwire.cc analog):
segments are zlib-deflated before framing and the per-segment CRC
covers the compressed bytes, so corruption is still caught before any
decompressor touches the data.

AES-GCM secure mode is flag bit 1 (the crypto_onwire.cc analog — see
secure.py): the segment table and payloads are sealed into one AEAD
blob with the frame header as associated data, and the GCM tag
REPLACES per-segment CRC (ProtocolV2 rev-1 secure mode likewise
drops crc protection in favor of the auth tag). Layout:

    header | counter u64 | ct_len u32 | ciphertext+tag

Compression composes: segments deflate first, then the whole frame
body seals. Tampering with header or body raises ``BadFrame`` via the
AEAD check; replayed frames are rejected by the session counter.

Clear-mode (CRC) frames have a native fast path: header + segment
table + per-segment crc32c assemble/verify in one C call each
(native/src/ceph_tpu_native.cc frame codec), gated on
``msgr_native_codec`` and ``CEPH_TPU_NO_NATIVE``, bit-identical to
the pure-Python path kept below as the fallback and oracle.
"""

from __future__ import annotations

import struct
import zlib

from ceph_tpu.checksum import crc32c_wire as _crc32c_host
from ceph_tpu.utils.config import config as _config

MAGIC = b"CTv2"
_HDR = struct.Struct("<4sHBBQ")  # magic, type, flags, nseg, seq
_SEG = struct.Struct("<II")      # length, crc32c
_SLEN = struct.Struct("<I")      # secure mode: plain length table entry
_SECHDR = struct.Struct("<QI")   # secure mode: counter, ciphertext len
CRC_SEED = 0xFFFFFFFF

FLAG_COMPRESSED = 0x01
FLAG_SECURE = 0x02

MAX_SEGMENTS = 8
MAX_SEGMENT_BYTES = 1 << 30


class BadFrame(Exception):
    pass


def _crc(data: bytes) -> int:
    return _crc32c_host(CRC_SEED, data)


# Native frame codec (ceph_tpu_native.cc frame_encode/frame_verify):
# the clear-mode header+table+CRC assembly runs as one C call instead
# of per-segment struct.pack / bytes churn. The module probe is cached;
# the config gate (msgr_native_codec) is read per frame so bench A/B
# legs can flip it with config.override. CEPH_TPU_NO_NATIVE disables
# the probe entirely; the pure-Python path below stays bit-identical
# (pinned by tests/test_wire_native.py).
_native_mod = None
_native_probed = False


def _native():
    global _native_mod, _native_probed
    if not _native_probed:
        _native_probed = True
        try:
            from ceph_tpu import native as _n

            if _n.available():
                _native_mod = _n
        except Exception:
            _native_mod = None
    return _native_mod


def _codec():
    """The native codec module when loaded AND enabled, else None."""
    mod = _native()
    if mod is None:
        return None
    return mod if _config.get("msgr_native_codec") else None


def encode_frame(
    msg_type: int,
    seq: int,
    segments: list[bytes],
    compress: bool = False,
    secure=None,
) -> bytes:
    """Frame ``segments``; ``secure`` is a secure.SecureSession for
    AES-GCM sealing (tx direction) or None for crc mode."""
    if not 0 < len(segments) <= MAX_SEGMENTS:
        raise ValueError(f"1..{MAX_SEGMENTS} segments, got {len(segments)}")
    flags = 0
    if compress:
        flags |= FLAG_COMPRESSED
        segments = [zlib.compress(seg, 1) for seg in segments]
    if secure is not None:
        flags |= FLAG_SECURE
        hdr = _HDR.pack(MAGIC, msg_type, flags, len(segments), seq)
        body = bytearray()
        for seg in segments:
            body += _SLEN.pack(len(seg))
        for seg in segments:
            body += seg
        counter, ct = secure.seal(hdr, bytes(body))
        return hdr + _SECHDR.pack(counter, len(ct)) + ct
    codec = _codec()
    if codec is not None:
        return codec.frame_encode(msg_type, flags, seq, segments)
    out = bytearray(_HDR.pack(MAGIC, msg_type, flags, len(segments), seq))
    for seg in segments:
        out += _SEG.pack(len(seg), _crc(seg))
    for seg in segments:
        out += seg
    return bytes(out)


def decode_frame(read_exact, secure=None) -> tuple[int, int, list[bytes]]:
    """Parse one frame from ``read_exact(n) -> bytes`` (raises
    ``EOFError`` at stream end). Returns (msg_type, seq, segments).
    Compressed frames are transparently inflated AFTER CRC (or AEAD)
    checks. ``secure`` is the rx-direction secure.SecureSession; a
    secure frame arriving without one (or vice versa) is rejected —
    mode is negotiated per connection, not per frame."""
    hdr = read_exact(_HDR.size)
    magic, msg_type, flags, nseg, seq = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise BadFrame(f"bad magic {magic!r}")
    if flags & ~(FLAG_COMPRESSED | FLAG_SECURE):
        raise BadFrame(f"unsupported flags {flags:#x}")
    if not 0 < nseg <= MAX_SEGMENTS:
        raise BadFrame(f"bad segment count {nseg}")
    if bool(flags & FLAG_SECURE) != (secure is not None):
        raise BadFrame(
            "secure-mode mismatch: frame "
            + ("sealed" if flags & FLAG_SECURE else "clear")
            + " but session "
            + ("clear" if secure is None else "secure")
        )
    if secure is not None:
        from .secure import SecurityError

        counter, ct_len = _SECHDR.unpack(read_exact(_SECHDR.size))
        if ct_len > MAX_SEGMENT_BYTES:
            raise BadFrame(f"ciphertext too large: {ct_len}")
        try:
            body = secure.open(hdr, counter, read_exact(ct_len))
        except SecurityError as e:
            raise BadFrame(str(e)) from e
        pos = nseg * _SLEN.size
        lengths = [
            _SLEN.unpack_from(body, i * _SLEN.size)[0] for i in range(nseg)
        ]
        if pos + sum(lengths) != len(body):
            raise BadFrame("secure body length mismatch")
        segments = []
        for length in lengths:
            seg = body[pos : pos + length]
            pos += length
            if flags & FLAG_COMPRESSED:
                try:
                    seg = zlib.decompress(seg)
                except zlib.error as e:
                    raise BadFrame(f"segment inflate failed: {e}") from e
            segments.append(seg)
        return msg_type, seq, segments
    # Clear mode: one read for the whole segment table, one for the
    # concatenated payloads (fewer recv round-trips than the old
    # entry-at-a-time loop), then a single native batch CRC verify
    # when the codec is armed — per-segment Python CRC otherwise.
    table_raw = read_exact(nseg * _SEG.size)
    table = []
    total = 0
    for length, crc in _SEG.iter_unpack(table_raw):
        if length > MAX_SEGMENT_BYTES:
            raise BadFrame(f"segment too large: {length}")
        table.append((length, crc))
        total += length
    payload = read_exact(total)
    codec = _codec()
    if codec is not None:
        bad = codec.frame_verify(table_raw, payload)
        if bad == -2:
            raise BadFrame("segment table/payload length mismatch")
        if bad >= 0:
            raise BadFrame(
                f"segment crc mismatch: segment {bad}"
                f" want {table[bad][1]:#x}"
            )
    segments = []
    pos = 0
    for length, crc in table:
        seg = payload[pos : pos + length]
        pos += length
        if codec is None and _crc(seg) != crc:
            raise BadFrame(
                f"segment crc mismatch: got {_crc(seg):#x} want {crc:#x}"
            )
        if flags & FLAG_COMPRESSED:
            try:
                seg = zlib.decompress(seg)
            except zlib.error as e:
                raise BadFrame(f"segment inflate failed: {e}") from e
        segments.append(seg)
    return msg_type, seq, segments


def frame_from_buffer(buf: bytes, secure=None) -> tuple[int, int, list[bytes]]:
    """Decode a frame held fully in memory (tests / datagram use)."""
    pos = 0

    def read_exact(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(buf):
            raise EOFError
        out = buf[pos : pos + n]
        pos += n
        return out

    return decode_frame(read_exact, secure=secure)
