"""Framed wire protocol with per-segment CRC — the ProtocolV2 analog.

Mirrors the frame shape of msg/async/frames_v2.{h,cc}: a fixed header
(magic, message type, sequence, segment count) followed by a segment
table (length + crc32c per segment) and the segment payloads. Every
segment's crc32c is verified on decode — a flipped bit anywhere raises
``BadFrame``, the on-wire integrity contract ProtocolV2 provides
(SURVEY.md section 5.8; the reference seeds crc32c with -1).

On-wire compression is flag bit 0 (the compression_onwire.cc analog):
segments are zlib-deflated before framing and the per-segment CRC
covers the compressed bytes, so corruption is still caught before any
decompressor touches the data. AES-GCM secure mode remains reserved.
"""

from __future__ import annotations

import struct
import zlib

from ceph_tpu.checksum.host import crc32c as _crc32c_host

MAGIC = b"CTv2"
_HDR = struct.Struct("<4sHBBQ")  # magic, type, flags, nseg, seq
_SEG = struct.Struct("<II")      # length, crc32c
CRC_SEED = 0xFFFFFFFF

FLAG_COMPRESSED = 0x01

MAX_SEGMENTS = 8
MAX_SEGMENT_BYTES = 1 << 30


class BadFrame(Exception):
    pass


def _crc(data: bytes) -> int:
    return _crc32c_host(CRC_SEED, data)


def encode_frame(
    msg_type: int, seq: int, segments: list[bytes], compress: bool = False
) -> bytes:
    if not 0 < len(segments) <= MAX_SEGMENTS:
        raise ValueError(f"1..{MAX_SEGMENTS} segments, got {len(segments)}")
    flags = 0
    if compress:
        flags |= FLAG_COMPRESSED
        segments = [zlib.compress(seg, 1) for seg in segments]
    out = bytearray(_HDR.pack(MAGIC, msg_type, flags, len(segments), seq))
    for seg in segments:
        out += _SEG.pack(len(seg), _crc(seg))
    for seg in segments:
        out += seg
    return bytes(out)


def decode_frame(read_exact) -> tuple[int, int, list[bytes]]:
    """Parse one frame from ``read_exact(n) -> bytes`` (raises
    ``EOFError`` at stream end). Returns (msg_type, seq, segments).
    Compressed frames are transparently inflated AFTER CRC checks."""
    hdr = read_exact(_HDR.size)
    magic, msg_type, flags, nseg, seq = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise BadFrame(f"bad magic {magic!r}")
    if flags & ~FLAG_COMPRESSED:
        raise BadFrame(f"unsupported flags {flags:#x}")
    if not 0 < nseg <= MAX_SEGMENTS:
        raise BadFrame(f"bad segment count {nseg}")
    table = []
    for _ in range(nseg):
        length, crc = _SEG.unpack(read_exact(_SEG.size))
        if length > MAX_SEGMENT_BYTES:
            raise BadFrame(f"segment too large: {length}")
        table.append((length, crc))
    segments = []
    for length, crc in table:
        seg = read_exact(length)
        if _crc(seg) != crc:
            raise BadFrame(
                f"segment crc mismatch: got {_crc(seg):#x} want {crc:#x}"
            )
        if flags & FLAG_COMPRESSED:
            try:
                seg = zlib.decompress(seg)
            except zlib.error as e:
                raise BadFrame(f"segment inflate failed: {e}") from e
        segments.append(seg)
    return msg_type, seq, segments


def frame_from_buffer(buf: bytes) -> tuple[int, int, list[bytes]]:
    """Decode a frame held fully in memory (tests / datagram use)."""
    pos = 0

    def read_exact(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(buf):
            raise EOFError
        out = buf[pos : pos + n]
        pos += n
        return out

    return decode_frame(read_exact)
