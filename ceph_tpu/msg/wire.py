"""Framed wire protocol with per-segment CRC — the ProtocolV2 analog.

Mirrors the frame shape of msg/async/frames_v2.{h,cc}: a fixed header
(magic, message type, sequence, segment count) followed by a segment
table (length + crc32c per segment) and the segment payloads. Every
segment's crc32c is verified on decode — a flipped bit anywhere raises
``BadFrame``, the on-wire integrity contract ProtocolV2 provides
(SURVEY.md section 5.8; the reference seeds crc32c with -1).

AES-GCM secure mode and on-wire compression are out of scope for now;
the header reserves a flags byte for both.
"""

from __future__ import annotations

import struct

from ceph_tpu.checksum.host import crc32c as _crc32c_host

MAGIC = b"CTv2"
_HDR = struct.Struct("<4sHBBQ")  # magic, type, flags, nseg, seq
_SEG = struct.Struct("<II")      # length, crc32c
CRC_SEED = 0xFFFFFFFF

MAX_SEGMENTS = 8
MAX_SEGMENT_BYTES = 1 << 30


class BadFrame(Exception):
    pass


def _crc(data: bytes) -> int:
    return _crc32c_host(CRC_SEED, data)


def encode_frame(msg_type: int, seq: int, segments: list[bytes]) -> bytes:
    if not 0 < len(segments) <= MAX_SEGMENTS:
        raise ValueError(f"1..{MAX_SEGMENTS} segments, got {len(segments)}")
    out = bytearray(_HDR.pack(MAGIC, msg_type, 0, len(segments), seq))
    for seg in segments:
        out += _SEG.pack(len(seg), _crc(seg))
    for seg in segments:
        out += seg
    return bytes(out)


def decode_frame(read_exact) -> tuple[int, int, list[bytes]]:
    """Parse one frame from ``read_exact(n) -> bytes`` (raises
    ``EOFError`` at stream end). Returns (msg_type, seq, segments)."""
    hdr = read_exact(_HDR.size)
    magic, msg_type, flags, nseg, seq = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise BadFrame(f"bad magic {magic!r}")
    if flags != 0:
        raise BadFrame(f"unsupported flags {flags:#x}")
    if not 0 < nseg <= MAX_SEGMENTS:
        raise BadFrame(f"bad segment count {nseg}")
    table = []
    for _ in range(nseg):
        length, crc = _SEG.unpack(read_exact(_SEG.size))
        if length > MAX_SEGMENT_BYTES:
            raise BadFrame(f"segment too large: {length}")
        table.append((length, crc))
    segments = []
    for length, crc in table:
        seg = read_exact(length)
        if _crc(seg) != crc:
            raise BadFrame(
                f"segment crc mismatch: got {_crc(seg):#x} want {crc:#x}"
            )
        segments.append(seg)
    return msg_type, seq, segments


def frame_from_buffer(buf: bytes) -> tuple[int, int, list[bytes]]:
    """Decode a frame held fully in memory (tests / datagram use)."""
    pos = 0

    def read_exact(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(buf):
            raise EOFError
        out = buf[pos : pos + n]
        pos += n
        return out

    return decode_frame(read_exact)
