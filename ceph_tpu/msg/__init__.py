"""Host-level distributed communication — the ``src/msg`` analog.

The reference fans EC sub-ops to remote OSDs through AsyncMessenger's
ProtocolV2 framed wire protocol (msg/async/ProtocolV2.h: segmented
frames, per-segment crc32c). The TPU framework splits that role in two
(SURVEY.md section 5.8):

- intra-slice shard fan-out rides ICI as XLA collectives
  (``ceph_tpu.parallel``) — no host messaging at all;
- host-to-host (the DCN tier) uses this package: the same framed,
  crc-protected wire protocol carrying typed, versioned sub-op
  messages between shard servers.

``NetShardBackend`` is a drop-in ``ShardBackend`` whose sub-ops travel
over sockets, so the whole RMW/read/recovery pipeline runs unchanged
against remote shard daemons — the standalone-cluster test tier
(qa/standalone/erasure-code) boots exactly that topology in-process.
"""

from .wire import BadFrame, decode_frame, encode_frame
from .messages import (
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteReply,
    decode_message,
)
from .messenger import Connection, Messenger
from .shard_server import NetShardBackend, ShardServer

__all__ = [
    "BadFrame",
    "decode_frame",
    "encode_frame",
    "ECSubRead",
    "ECSubReadReply",
    "ECSubWrite",
    "ECSubWriteReply",
    "decode_message",
    "Connection",
    "Messenger",
    "NetShardBackend",
    "ShardServer",
]
