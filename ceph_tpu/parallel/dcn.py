"""Multi-process DCN tier: jax multi-controller hosts under the
socket messenger's control plane.

The reference scales past one host with AsyncMessenger carrying
MOSDECSubOpWrite/Read between OSD processes over the data-center
network (msg/async/AsyncMessenger.h:95, ProtocolV2.h:13; SURVEY.md
§5.8 maps that stack to ICI + DCN). The TPU-native equivalent built
here:

- N OS processes ("hosts"), each owning a slice of ONE global
  ``jax.sharding.Mesh`` via ``jax.distributed.initialize`` (the jax
  multi-controller model, CPU backend + gloo collectives for CI; the
  same code is what a real multi-host TPU pod runs).
- The mesh is laid out so ``dp`` (stripe batch) is intra-host and
  ``sp`` (the EC shard axis) SPANS hosts: the XOR-reduction that
  combines parity — ring reduce-scatter + all-gather in
  parallel/collectives.ring_parity — runs its ppermute hops ACROSS
  host boundaries, i.e. the shard fan-out travels as XLA collectives
  over DCN, not as application-level sends.
- The repo's framed socket messenger carries the CONTROL plane: the
  coordinator broadcasts identical op metadata to every host (the
  SPMD multi-controller discipline) with each host's own shard-slice
  payload — the per-shard sub-op fan-out of MOSDECSubOpWrite mapped
  onto hosts — and hosts answer with their locally-addressable result
  shards plus their ``ec_dispatch`` counter deltas, so the mesh route
  stays counter-verified end to end.

Coordinator (``DcnCluster``) runs in the caller's process and does
NOT join the jax cluster; workers are spawned as subprocesses running
``python -m ceph_tpu.parallel.dcn``. CI drives a 2-host x 2-device
cluster (tests/test_dcn.py); ``__graft_entry__.dryrun_multichip``
runs the same pass and reports ``hosts>1`` in its tail line.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np

HELLO_TIMEOUT = 90.0
OP_TIMEOUT = 180.0


# ---------------------------------------------------------------- worker
def _worker_main(argv: list[str]) -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--coord", required=True)   # jax coordinator addr
    ap.add_argument("--devices", type=int, required=True)  # per host
    ap.add_argument("--ctrl", required=True)    # messenger host:port
    args = ap.parse_args(argv)

    # Platform pinning BEFORE any backend initializes. The axon
    # sitecustomize hook sets the jax_platforms CONFIG key, which
    # beats the env var — override at the config level (the conftest
    # lesson).
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=args.coord,
        num_processes=args.nprocs,
        process_id=args.rank,
    )
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ceph_tpu.codecs.matrix_codec import _dispatch_counters
    from ceph_tpu.codecs.registry import registry
    from ceph_tpu.msg.messages import DcnCmd, DcnHello, DcnReply
    from ceph_tpu.msg.messenger import Messenger
    from ceph_tpu.parallel import dispatch as mesh_dispatch
    from ceph_tpu.utils import config

    devs = jax.devices()
    # sp SPANS processes: global device list is process-major, so the
    # transpose puts one device of EVERY process in each mesh row —
    # column j == host j. dp stays intra-host.
    mesh = Mesh(
        np.array(devs).reshape(args.nprocs, args.devices).T,
        ("dp", "sp"),
    )
    config.set("ec_use_mesh", True)
    mesh_dispatch.set_mesh(mesh)

    msgr = Messenger(f"dcn-host-{args.rank}")
    done = threading.Event()

    def snap():
        pc = _dispatch_counters()
        return {kk: pc.get(kk) for kk in pc.dump()}

    codecs: dict[tuple, object] = {}

    def get_codec(meta: dict):
        """One codec instance per (plugin, profile) for the worker's
        lifetime — keeps the DecodeTableCache warm across commands
        (rebuilding per op would re-invert decode matrices every
        time, the exact cost the ISA TableCache precedent avoids)."""
        key = (meta["plugin"], tuple(sorted(meta["profile"].items())))
        if key not in codecs:
            codecs[key] = registry.factory(
                meta["plugin"], dict(meta["profile"])
            )
        return codecs[key]

    def run_cmd(cmd: DcnCmd) -> DcnReply:
        from ceph_tpu.codecs.bitmatrix_codec import BitMatrixCodec

        meta = cmd.meta
        if cmd.kind == "shutdown":
            done.set()
            return DcnReply(cmd.tid, args.rank, {"ok": True})
        if cmd.kind == "apply":
            return _run_apply(cmd)
        codec = get_codec(meta)
        b, c, n = meta["shape"]
        sp = mesh.shape["sp"]
        local = np.frombuffer(cmd.payload, np.uint8).reshape(
            b, c // sp, n
        )
        # Packet codes (liberation family) dispatch at PACKET
        # granularity: each host packetizes its own chunk block (a
        # chunk's w packets stay host-local, so the sp split is
        # preserved: c_blk chunks -> c_blk*w packets).
        packets = isinstance(codec, BitMatrixCodec)
        if packets:
            w = codec.w
            local = local.reshape(b, (c // sp) * w, n // w)
            gshape = (b, c * w, n // w)
        else:
            gshape = (b, c, n)
        sharding = NamedSharding(mesh, P("dp", "sp", None))
        stacked = jax.make_array_from_process_local_data(
            sharding, local, gshape
        )
        before = snap()
        # the bitmatrix goes in as HOST numpy: under multi-controller,
        # identical numpy inputs are valid replicated operands, while
        # a jnp array committed to one process's device 0 is not a
        # legal input for a mesh spanning processes
        if cmd.kind == "encode":
            bm_np = codec._encode_bmat_np
        elif cmd.kind == "decode":
            present = list(meta["present"])
            want = list(meta["want"])
            key = (tuple(present), tuple(want))
            if packets:
                dec01 = codec._host_tables.get(
                    key,
                    lambda: codec._build_decode_bitmatrix(present, want),
                )
                bm_np, _key = codec._host_bits(dec01)
            else:
                bm_np = codec._tables.get(
                    key, lambda: codec._build_decode_bmat(present, want)
                )
        else:
            raise ValueError(f"unknown DCN op {cmd.kind!r}")
        out = codec._dispatch_bitmatrix(bm_np, bm_np, stacked, cmd.kind)
        delta = {
            kk: v - before.get(kk, 0)
            for kk, v in snap().items()
            if v != before.get(kk, 0)
        }
        # The output is replicated over sp (out_specs P("dp", ...)):
        # this host's addressable shards cover the WHOLE result — but
        # the coordinator reads only rank 0's copy, so nonzero ranks
        # ACK with metadata after syncing (the _run_apply discipline;
        # shipping (n_hosts-1)x the output bytes bought nothing).
        if args.rank == 0:
            full = _assemble_addressable(out)
            if packets:  # de-packetize on the host copy
                full = full.reshape(b, full.shape[1] // codec.w, n)
            return DcnReply(
                cmd.tid, args.rank,
                {"ok": True, "counters": delta,
                 "shape": list(full.shape), "hosts": args.nprocs},
                full.tobytes(),
            )
        out.block_until_ready()
        oshape = [b, out.shape[1] // codec.w, n] if packets else [
            b, out.shape[1], out.shape[2]
        ]
        return DcnReply(
            cmd.tid, args.rank,
            {"ok": True, "counters": delta, "shape": oshape,
             "hosts": args.nprocs},
        )

    def _run_apply(cmd: DcnCmd) -> DcnReply:
        """Raw bitmatrix application — the generic engine op the codec
        dispatch route ships over DCN (encode, decode and delta all
        reduce to it; the payload is bitmatrix bytes + this host's
        shard-slice)."""
        meta = cmd.meta
        r8, c8 = meta["bm_shape"]
        bm_bytes = c8 * r8
        bm_np = np.frombuffer(
            cmd.payload[:bm_bytes], np.uint8
        ).reshape(r8, c8)
        b, c, n = meta["shape"]
        sp = mesh.shape["sp"]
        local = np.frombuffer(
            cmd.payload[bm_bytes:], np.uint8
        ).reshape(b, c // sp, n)
        sharding = NamedSharding(mesh, P("dp", "sp", None))
        stacked = jax.make_array_from_process_local_data(
            sharding, local, (b, c, n)
        )
        out = mesh_dispatch.mesh_apply_bitmatrix(mesh, bm_np, stacked)
        # every rank holds the full (sp-replicated) output, but the
        # coordinator reads only rank 0's copy — the others ACK with
        # metadata so (n_hosts-1) x output bytes never cross the wire
        if args.rank == 0:
            full = _assemble_addressable(out)
            return DcnReply(
                cmd.tid, args.rank,
                {"ok": True, "shape": list(full.shape),
                 "hosts": args.nprocs, "counters": {}},
                full.tobytes(),
            )
        out.block_until_ready()
        return DcnReply(
            cmd.tid, args.rank,
            {"ok": True, "shape": list(out.shape),
             "hosts": args.nprocs, "counters": {}},
        )

    def dispatch(c, msg) -> None:
        if isinstance(msg, DcnCmd):
            try:
                reply = run_cmd(msg)
            except Exception as e:  # surfaced to the coordinator
                reply = DcnReply(
                    msg.tid, args.rank,
                    {"ok": False, "error": f"{type(e).__name__}: {e}"},
                )
            c.send(reply)

    # dispatcher installed BEFORE connecting: the coordinator may send
    # the first command the moment it sees the hello
    msgr.set_dispatcher(dispatch)
    host, port = args.ctrl.rsplit(":", 1)
    conn = msgr.connect((host, int(port)))
    conn.send(DcnHello(
        args.rank, args.nprocs, len(jax.local_devices()), len(devs)
    ))
    while not done.wait(0.2):
        pass
    time.sleep(0.2)  # let the shutdown reply flush
    msgr.shutdown()


def _assemble_addressable(arr) -> np.ndarray:
    """Reassemble a global jax.Array from THIS process's addressable
    shards (valid when the process's shards cover every global index,
    e.g. outputs replicated over the cross-host axis)."""
    out = np.zeros(arr.shape, arr.dtype)
    seen = np.zeros(arr.shape, bool)
    for shard in arr.addressable_shards:
        out[shard.index] = np.asarray(shard.data)
        seen[shard.index] = True
    if not seen.all():
        raise ValueError(
            "output not fully addressable on this host — cross-host "
            "sharding left gaps"
        )
    return out


# ------------------------------------------------------------ coordinator
class DcnCluster:
    """Spawn + drive N jax multi-controller host processes.

    The coordinator stays OUTSIDE the jax cluster (it may already own
    a different backend — the axon TPU, a test's CPU mesh); it talks
    to the hosts purely over the messenger control plane.
    """

    def __init__(self, n_hosts: int = 2, devices_per_host: int = 2) -> None:
        self.n_hosts = n_hosts
        self.devices_per_host = devices_per_host
        self.procs: list[subprocess.Popen] = []
        self._errfiles: list = []
        #: tids with a waiter: replies for anything else (stragglers
        #: after a timeout) are dropped at arrival instead of
        #: accumulating payload bytes forever
        self._awaiting: set[int] = set()
        self.conns: dict[int, object] = {}
        self.hellos: dict[int, object] = {}
        self._replies: dict[tuple[int, int], object] = {}
        self._lock = threading.Lock()
        #: serializes WHOLE ops (send fan-out through reply wait):
        #: every op bottoms out in a cross-host SPMD collective, which
        #: requires all hosts to execute ops in the SAME order —
        #: interleaved sends from concurrent threads give the hosts
        #: divergent orders and their collectives pair wrongly (hangs
        #: observed under a 12-thread stress test). Workers execute
        #: serially anyway, so this lock costs no real parallelism.
        self._op_lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._tid = 0
        self.msgr = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "DcnCluster":
        from ceph_tpu.msg.messages import DcnHello, DcnReply
        from ceph_tpu.msg.messenger import Messenger

        self.msgr = Messenger("dcn-coordinator")
        addr = self.msgr.bind("127.0.0.1", 0)

        def dispatch(conn, msg) -> None:
            with self._cv:
                if isinstance(msg, DcnHello):
                    self.hellos[msg.rank] = msg
                    self.conns[msg.rank] = conn
                elif isinstance(msg, DcnReply):
                    if msg.tid in self._awaiting:
                        self._replies[(msg.tid, msg.rank)] = msg
                    # else: straggler after a timeout — drop it
                self._cv.notify_all()

        self.msgr.set_dispatcher(dispatch)

        import tempfile

        coord_port = _free_port()
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # workers pin their own
        for rank in range(self.n_hosts):
            # worker stderr lands in a temp file so a startup failure
            # (gloo/jax.distributed init, port clash) keeps its
            # traceback — DEVNULL made those undiagnosable
            errf = tempfile.NamedTemporaryFile(
                mode="w+", prefix=f"dcn-host{rank}-", suffix=".err",
                delete=False,
            )
            self._errfiles.append(errf)
            self.procs.append(subprocess.Popen(
                [
                    sys.executable, "-m", "ceph_tpu.parallel.dcn",
                    "--rank", str(rank),
                    "--nprocs", str(self.n_hosts),
                    "--coord", f"127.0.0.1:{coord_port}",
                    "--devices", str(self.devices_per_host),
                    "--ctrl", f"{addr[0]}:{addr[1]}",
                ],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=errf,
            ))
        deadline = time.monotonic() + HELLO_TIMEOUT
        failed = False
        with self._cv:
            while len(self.hellos) < self.n_hosts:
                left = deadline - time.monotonic()
                if left <= 0 or any(
                    p.poll() is not None for p in self.procs
                ):
                    failed = True
                    break
                self._cv.wait(min(left, 0.5))
        if failed:
            # OUTSIDE the cv: stop() -> _wait() re-acquires it (a
            # plain Lock — calling under the cv deadlocked forever on
            # partial startup)
            got = len(self.hellos)
            tails = self._stderr_tails()
            self.stop()
            raise RuntimeError(
                f"DCN hosts failed to start ({got}/{self.n_hosts} "
                f"hellos); worker stderr tails: {tails}"
            )
        return self

    def _stderr_tails(self, limit: int = 800) -> dict[int, str]:
        tails = {}
        for rank, f in enumerate(self._errfiles):
            try:
                f.flush()
                with open(f.name) as fh:
                    tails[rank] = fh.read()[-limit:]
            except Exception:
                pass
        return tails

    def stop(self) -> None:
        from ceph_tpu.msg.messages import DcnCmd

        try:
            if self.conns:
                tid = self._next_tid()
                for conn in self.conns.values():
                    conn.send(DcnCmd(tid, "shutdown", {}))
                self._wait(tid, timeout=5.0, strict=False)
        except Exception:
            pass
        for p in self.procs:
            try:
                p.terminate()
            except Exception:
                pass
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        if self.msgr is not None:
            self.msgr.shutdown()
        for f in self._errfiles:
            try:
                f.close()
                os.unlink(f.name)
            except Exception:
                pass
        self._errfiles = []

    def __enter__(self) -> "DcnCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- ops -----------------------------------------------------------
    def _next_tid(self) -> int:
        # under the lock: OSD daemons dispatch from multiple reader
        # threads — a raced tid would cross-deliver replies. The tid
        # registers as awaited HERE, before any send, so a fast reply
        # can never race past the filter in the dispatcher.
        with self._lock:
            self._tid += 1
            self._awaiting.add(self._tid)
            return self._tid

    def _wait(self, tid: int, timeout: float = OP_TIMEOUT,
              strict: bool = True) -> dict[int, object]:
        deadline = time.monotonic() + timeout
        with self._cv:
            try:
                while True:
                    got = {
                        r: self._replies[(tid, r)]
                        for r in range(self.n_hosts)
                        if (tid, r) in self._replies
                    }
                    if len(got) == self.n_hosts:
                        return got
                    left = deadline - time.monotonic()
                    if left <= 0:
                        if strict:
                            raise TimeoutError(
                                f"DCN op {tid}: {len(got)}/"
                                f"{self.n_hosts} replies"
                            )
                        return got
                    self._cv.wait(min(left, 0.5))
            finally:
                # consume on EVERY exit (complete, timeout, raise):
                # replies carry whole output payloads — leaking them
                # per-op would grow without bound on the codec
                # dispatch hot path, and un-awaited stragglers are
                # dropped at arrival
                self._awaiting.discard(tid)
                for r in range(self.n_hosts):
                    self._replies.pop((tid, r), None)

    def _run(self, kind: str, plugin: str, profile: dict,
             data: np.ndarray, meta_extra: dict | None = None):
        """Broadcast one op: identical metadata to every host, each
        host carrying its own sp-block of the shard axis."""
        with self._op_lock:
            return self._run_locked(kind, plugin, profile, data, meta_extra)

    def _run_locked(self, kind, plugin, profile, data, meta_extra=None):
        from ceph_tpu.msg.messages import DcnCmd

        b, c, n = data.shape
        sp = self.n_hosts
        if c % sp:
            raise ValueError(f"shard axis {c} must divide hosts {sp}")
        tid = self._next_tid()
        meta = {
            "plugin": plugin, "profile": profile,
            "shape": [b, c, n], **(meta_extra or {}),
        }
        blk = c // sp
        for rank, conn in self.conns.items():
            slice_ = np.ascontiguousarray(
                data[:, rank * blk : (rank + 1) * blk, :]
            )
            conn.send(DcnCmd(tid, kind, meta, slice_.tobytes()))
        replies = self._wait(tid)
        for r, rep in sorted(replies.items()):
            if not rep.meta.get("ok"):
                raise RuntimeError(
                    f"DCN host {r}: {rep.meta.get('error')}"
                )
        rep0 = replies[0]
        out = np.frombuffer(rep0.payload, np.uint8).reshape(
            rep0.meta["shape"]
        )
        counters = {
            r: rep.meta["counters"] for r, rep in replies.items()
        }
        return out, counters

    def supported(self, bm_shape, data_shape) -> bool:
        """Divisibility contract for the generic apply route: the
        shard axis must split across hosts, the bitmatrix must match
        it, and the stripe batch must split over each host's devices
        — directly or by folding the lane axis (the same exactness
        argument as mesh_apply_bitmatrix: the GF(2) apply is
        independent per lane)."""
        if len(data_shape) != 3:
            return False
        b, c, n = data_shape
        dp = self.devices_per_host
        return (
            c % self.n_hosts == 0
            and bm_shape[1] == c * 8
            and (b % dp == 0 or n % dp == 0)
        )

    def apply_bitmatrix(
        self, bm_np: np.ndarray, data: np.ndarray,
        timeout: float = 60.0,
    ):
        """Generic [R*8, C*8] bitmatrix over [B, C, N] host data,
        fanned across hosts (the engine-route op: encode, decode and
        parity delta all arrive here when the codec dispatch routes
        over DCN). Shorter timeout than the command ops: this sits on
        the data path, where a dead host should fail fast into the
        dispatcher's fallback."""
        with self._op_lock:
            return self._apply_bitmatrix_locked(bm_np, data, timeout)

    def _apply_bitmatrix_locked(self, bm_np, data, timeout):
        from ceph_tpu.msg.messages import DcnCmd

        b0, c, n0 = data.shape
        dp = self.devices_per_host
        fold = b0 % dp != 0
        if fold:
            # batch-1 deltas and odd stripe batches: fold the lane
            # axis into the batch so dp divides it (exact — the
            # bitmatrix apply is lane-independent)
            if n0 % dp:
                raise ValueError(
                    f"batch {b0} and lanes {n0} both unsplittable by "
                    f"dp={dp}"
                )
            data = (
                data.reshape(b0, c, dp, n0 // dp)
                .transpose(0, 2, 1, 3)
                .reshape(b0 * dp, c, n0 // dp)
            )
        b, c, n = data.shape
        sp = self.n_hosts
        if c % sp:
            raise ValueError(f"shard axis {c} must divide hosts {sp}")
        tid = self._next_tid()
        meta = {
            "bm_shape": [int(bm_np.shape[0]), int(bm_np.shape[1])],
            "shape": [b, c, n],
        }
        bm_bytes = np.ascontiguousarray(bm_np, np.uint8).tobytes()
        blk = c // sp
        for rank, conn in self.conns.items():
            slice_ = np.ascontiguousarray(
                data[:, rank * blk : (rank + 1) * blk, :]
            )
            conn.send(DcnCmd(
                tid, "apply", meta, bm_bytes + slice_.tobytes()
            ))
        replies = self._wait(tid, timeout=timeout)
        for r, rep in sorted(replies.items()):
            if not rep.meta.get("ok"):
                raise RuntimeError(
                    f"DCN host {r}: {rep.meta.get('error')}"
                )
        rep0 = replies[0]
        out = np.frombuffer(rep0.payload, np.uint8).reshape(
            rep0.meta["shape"]
        )
        if fold:
            r_out = out.shape[1]
            out = (
                out.reshape(b0, dp, r_out, n)
                .transpose(0, 2, 1, 3)
                .reshape(b0, r_out, n0)
            )
        return out

    def encode(self, plugin: str, profile: dict, data: np.ndarray):
        """[B, k, N] data -> ([B, m, N] parity, per-host counters)."""
        return self._run("encode", plugin, profile, data)

    def decode(self, plugin: str, profile: dict, present: list[int],
               want: list[int], survivors: np.ndarray):
        """[B, len(present), N] survivors -> [B, len(want), N]."""
        return self._run(
            "decode", plugin, profile, survivors,
            {"present": list(present), "want": list(want)},
        )


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


if __name__ == "__main__":
    _worker_main(sys.argv[1:])
