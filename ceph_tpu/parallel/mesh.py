"""Distributed EC: shard fan-out as XLA collectives over a device mesh.

The reference fans per-shard sub-ops to k+m-1 remote OSDs over
AsyncMessenger/ProtocolV2 (MOSDECSubOpWrite — SURVEY.md section 5.8).
The TPU-native design replaces that with SPMD over a Mesh:

- axis ``dp`` — stripe batch (data parallel): independent stripes on
  different devices, no communication.
- axis ``sp`` — shard axis (the tensor-parallel analog): each device
  holds a subset of data shards; parity is an XOR-reduction across
  devices, expressed as an integer ``psum`` over bit-plane counts
  followed by mod 2. XLA lowers the psum onto ICI; on multi-host
  meshes the same program spans DCN with no code change — that IS the
  framework's distributed communication backend.

GF(2) trick making the collective cheap: parity bits are (sum of
per-device partial bit-counts) mod 2, and psum-of-int32 is exact, so
the cross-device combine is a single standard all-reduce.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ceph_tpu.ops.bitplane import pack_bits, unpack_bits


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the jax API window this repo spans:
    new jax exports it top-level (replication check kwarg
    ``check_vma``), 0.4.x keeps it in ``jax.experimental.shard_map``
    (kwarg ``check_rep``). One seam so every collective call site
    works on both — without it the whole mesh/DCN tier dies with
    AttributeError on 0.4.x."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def make_ec_mesh(n_devices: int | None = None, k: int = 8) -> Mesh:
    """Mesh over (dp, sp): sp divides both n_devices and k so the shard
    axis splits evenly; prefer using both axes when possible."""
    avail = jax.devices()
    n = n_devices or len(avail)
    if n > len(avail):
        raise ValueError(
            f"requested {n} devices but only {len(avail)} available; "
            "a degenerate mesh would silently skip the collective path"
        )
    devs = avail[:n]
    # sp must divide BOTH n (for the reshape) and k (for even shard
    # split); prefer the largest such sp that still leaves dp > 1 so
    # both axes are exercised, else fall back to sp = gcd(n, k).
    divisors = [d for d in range(1, n + 1) if n % d == 0 and k % d == 0]
    proper = [d for d in divisors if d < n]
    sp = max(proper) if proper else max(divisors)
    dp = n // sp
    return Mesh(np.array(devs).reshape(dp, sp), ("dp", "sp"))


def partial_parity_counts(
    bmat_cols: jax.Array, shards: jax.Array
) -> jax.Array:
    """One device's contribution to the parity bit counts:
    [m*8, k_local*8] x [b, k_local, N] -> [b, m*8, N] int32 (mod 2
    pending). The shared local body of every parity collective."""
    bits = unpack_bits(shards)
    return jnp.einsum(
        "rc,bcn->brn",
        bmat_cols.astype(jnp.int8),
        bits.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    )


def sharded_encode(
    mesh: Mesh, bitmatrix: jax.Array, data: jax.Array
) -> jax.Array:
    """Encode [B, k, N] uint8 -> [B, m, N] parity, stripes sharded over
    ``dp`` and shards over ``sp`` (XOR-allreduce for the parity combine).

    ``bitmatrix`` is the [m*8, k*8] GF(2) coding matrix; its column
    blocks are sharded over ``sp`` alongside the data shards.
    """
    def local(bmat_cols: jax.Array, shards: jax.Array) -> jax.Array:
        acc = partial_parity_counts(bmat_cols, shards)
        acc = jax.lax.psum(acc, "sp")  # XOR-allreduce (mod 2 below)
        return pack_bits((acc & 1).astype(jnp.uint8))

    # bitmatrix columns follow the shard axis: [m*8, k*8] -> sp-sharded.
    fn = shard_map_compat(
        local,
        mesh,
        in_specs=(P(None, "sp"), P("dp", "sp", None)),
        out_specs=P("dp", None, None),
    )
    return fn(bitmatrix, data)


def sharded_decode(
    mesh: Mesh, dec_bitmatrix: jax.Array, survivors: jax.Array
) -> jax.Array:
    """Distributed reconstruct: decode is the same mod-2 matmul as
    encode with the inverted-submatrix rows, so the survivor axis
    shards over ``sp`` and the partial products combine with the same
    XOR-allreduce. ``survivors`` is [B, k, N] (any k survivors, rows
    matching the decode matrix columns); returns the missing shards.
    """
    return sharded_encode(mesh, dec_bitmatrix, survivors)


def sharded_pipeline_step(
    mesh: Mesh, bitmatrix: jax.Array, data: jax.Array
) -> dict[str, jax.Array]:
    """One full distributed EC step — the framework's "training step":

    encode (sp-XOR-allreduce across the shard axis) followed by the
    real per-chunk Checksummer CRC32C fold (the HashInfo/deep-scrub
    integrity word, computed on device). Jit-able under the mesh; the
    driver dry-runs this over N virtual devices and separately
    verifies a degraded-read reconstruct
    (see __graft_entry__.dryrun_multichip).
    """
    from ceph_tpu.checksum.crc32c import crc32c_device

    parity = sharded_encode(mesh, bitmatrix, data)
    csum = crc32c_device(parity)  # [B, m] uint32, one per parity chunk
    return {"parity": parity, "csum": csum}
