"""Multi-chip shard fan-out over a jax.sharding.Mesh."""

from .collectives import (  # noqa: F401
    ring_parity,
    sharded_crc32c,
)
from .mesh import (  # noqa: F401
    make_ec_mesh,
    sharded_decode,
    sharded_encode,
    sharded_pipeline_step,
)
