"""Multi-chip shard fan-out over a jax.sharding.Mesh."""

from .collectives import (  # noqa: F401
    ring_parity,
    sharded_crc32c,
)
from .dispatch import (  # noqa: F401
    get_mesh,
    mesh_apply_bitmatrix,
    mesh_supported,
    set_mesh,
    use_mesh,
)
from .mesh import (  # noqa: F401
    make_ec_mesh,
    sharded_decode,
    sharded_encode,
    sharded_pipeline_step,
)
