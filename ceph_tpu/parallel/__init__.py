"""Multi-chip shard fan-out over a jax.sharding.Mesh."""

from .mesh import (  # noqa: F401
    make_ec_mesh,
    sharded_decode,
    sharded_encode,
    sharded_pipeline_step,
)
