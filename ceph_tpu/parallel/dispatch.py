"""Mesh dispatch context: the seam that makes the multi-chip tier a
SYSTEM component instead of a standalone demo.

The reference's distributed backend is the per-shard sub-op fan-out
over AsyncMessenger (MOSDECSubOpWrite,
msg/async/AsyncMessenger.h:95 — SURVEY.md §5.8 maps it to an ICI
all-to-all of shard slices). Here the equivalent seam is a process-
wide active ``jax.sharding.Mesh``: when one is configured (and the
``ec_use_mesh`` option is on), every bitmatrix dispatch in the codec
tier — encode, decode, parity delta — shards the stripe batch over
``dp`` and the shard axis over ``sp`` and combines parity with the
ring XOR collective (parallel/collectives.ring_parity), with the
same dispatch-counter visibility the single-chip routes have
(``mesh_encode`` / ``mesh_decode`` / ``mesh_delta`` /
``mesh_fallback`` in ``perf dump``).

The RMW and read pipelines need no code of their own for this: their
device work flows through ``codec.encode_chunks`` /
``decode_chunks`` / ``apply_delta``, all of which land in
``MatrixErasureCodec._dispatch_bitmatrix`` — the one router this
module feeds. ``__graft_entry__.dryrun_multichip`` drives a full
RMW write and a reconstruct read through this route on the virtual
8-device mesh; ``tests/test_mesh_pipeline.py`` forces it on for a
cluster round trip.
"""

from __future__ import annotations

import contextlib

from jax.sharding import Mesh

# Process-wide, NOT thread-local: OSD daemons dispatch codec work from
# their connection-reader threads, and those must see the mesh the
# operator installed.
_mesh: Mesh | None = None

#: the installed multi-HOST cluster (parallel/dcn.DcnCluster): when
#: present, host-staged codec dispatches fan out across OS-process
#: hosts — the operator installing it IS the opt-in, mirroring the
#: reference where configuring the messenger's peer map turns a
#: single-daemon build into a cluster member
_dcn = None


def set_mesh(mesh: Mesh | None) -> None:
    """Install (or clear) the process-wide EC dispatch mesh."""
    global _mesh
    _mesh = mesh


def get_mesh() -> Mesh | None:
    return _mesh


def set_dcn(cluster) -> None:
    """Install (or clear) the process-wide DCN dispatch cluster."""
    global _dcn
    _dcn = cluster


def get_dcn():
    return _dcn


@contextlib.contextmanager
def use_mesh(mesh: Mesh | None):
    """Scoped mesh activation (tests, dryruns)."""
    prev = get_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


@contextlib.contextmanager
def use_dcn(cluster):
    """Scoped DCN-cluster activation (tests, dryruns)."""
    prev = get_dcn()
    set_dcn(cluster)
    try:
        yield cluster
    finally:
        set_dcn(prev)


def mesh_supported(
    mesh: Mesh, bitmatrix_shape, data_shape
) -> bool:
    """Divisibility contract for the sharded route: stripes split
    over ``dp`` (directly, or by folding the lane axis into the
    batch — the bitmatrix apply is lane-independent, so any exact
    lane split is free parallelism; parity-delta dispatches always
    arrive with batch 1), and bitmatrix columns (= input shards)
    over ``sp``. The residual lane axis need not split — ring_parity
    falls back to the psum schedule internally when it doesn't."""
    if len(data_shape) != 3:
        return False
    batch, c, n = data_shape
    if bitmatrix_shape[1] != c * 8:
        return False
    dp = mesh.shape.get("dp", 1)
    # The shard axis pads with zero shards up to sp (exact in GF(2)),
    # so only the stripe/lane split can disqualify a dispatch.
    return batch % dp == 0 or n % dp == 0


def mesh_apply_bitmatrix(mesh: Mesh, bitmatrix, data):
    """[R*8, C*8] GF(2) bitmatrix over [B, C, N] uint8 shards, stripe
    batch over ``dp``, shard/survivor axis over ``sp``, ring-XOR
    parity combine. Same contract as the single-chip kernel routes.

    When the batch does not divide ``dp``, the lane axis is folded
    into the batch (transpose + reshape) before the shard_map and
    unfolded after — exact, because the GF(2) apply is independent
    per lane. When the shard count does not divide ``sp`` (a
    parity-delta touching few columns, or an odd survivor set), zero
    shards pad it out — zeros contribute nothing in GF(2)."""
    import jax.numpy as jnp

    from .collectives import ring_parity

    b, c, n = data.shape
    sp = mesh.shape.get("sp", 1)
    pad = (-c) % sp
    if pad:
        data = jnp.concatenate(
            [data, jnp.zeros((b, pad, n), data.dtype)], axis=1
        )
        bitmatrix = jnp.concatenate(
            [
                bitmatrix,
                jnp.zeros(
                    (bitmatrix.shape[0], pad * 8), bitmatrix.dtype
                ),
            ],
            axis=1,
        )
        c += pad
    dp = mesh.shape.get("dp", 1)
    if b % dp == 0:
        return ring_parity(mesh, bitmatrix, data)
    folded = (
        data.reshape(b, c, dp, n // dp)
        .transpose(0, 2, 1, 3)
        .reshape(b * dp, c, n // dp)
    )
    out = ring_parity(mesh, bitmatrix, folded)
    r = out.shape[1]
    return (
        out.reshape(b, dp, r, n // dp)
        .transpose(0, 2, 1, 3)
        .reshape(b, r, n)
    )
