"""Alternative collective schedules for the EC fan-out: ring parity
accumulation and sequence-parallel CRC.

Two distributed patterns beyond mesh.py's all-reduce encode, mirroring
the scaling-book playbook (pick a mesh, annotate shardings, let XLA
place collectives on ICI):

**Ring parity** (`ring_parity`): the XOR-reduction across the shard
axis as an explicit ring of ``lax.ppermute`` steps — the ring-allreduce
schedule (and the ring-attention communication shape: a rotating
accumulator passes around the ring while every device folds in its
local partial). The accumulator travels PACKED ([b, m, N] uint8 —
XOR commutes with bit packing), so each hop moves exactly the parity
bytes. Bit-exact with ``sharded_encode``'s psum; the explicit schedule
is the form to reach for when the shard axis spans links where psum's
tree placement is suboptimal.

**Sequence-parallel CRC32C** (`sharded_crc32c`): the long-object axis
(SURVEY.md §5.7 — object size is this framework's sequence length)
sharded across devices. CRC is position-dependent, so naive sharding
breaks; linearity saves it: with per-device fold tensors pre-composed
with the zero-gap transition for the device's suffix length
(crc32c.zero_gap_matrix), each device folds its local bytes and the
combine is a single 32-bit-per-block XOR-allreduce:

    crc(block) = mod2( Σ_d  A_{suffix(d)} @ fold(bytes_d) )

One object of any length (left-padded with zero bytes to the mesh
granularity — a no-op for the fold, since zeros from the zero register
stay zero, while the init contribution uses the true length) hashes
with one psum of [B, 32] ints — the deep-scrub integrity pass for
objects too large for one chip's HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ceph_tpu.ops.bitplane import pack_bits

from .mesh import partial_parity_counts

#: fixed fold granularity for the sequence-parallel CRC scan: keeps
#: the fold-tensor constant bounded (<= 16 MiB) no matter how long
#: the object is — a monolithic per-segment tensor would be 256x the
#: segment size and OOM exactly on the large objects this op exists for
FOLD_BLOCK_MAX = 65536


def ring_parity(
    mesh: Mesh, bitmatrix: jax.Array, data: jax.Array
) -> jax.Array:
    """[B, k, N] uint8 -> [B, m, N] parity; XOR-reduction over the
    ``sp`` axis scheduled as an explicit ring instead of psum."""
    sp = mesh.shape["sp"]

    def local(bmat_cols: jax.Array, shards: jax.Array) -> jax.Array:
        acc = partial_parity_counts(bmat_cols, shards)
        # pack BEFORE the ring: per-hop traffic is the parity bytes,
        # not the 8x bit expansion
        partial = pack_bits((acc & 1).astype(jnp.uint8))  # [b, m, N]

        def hop(_i, carry):
            moved = jax.lax.ppermute(
                carry, "sp",
                [(d, (d + 1) % sp) for d in range(sp)],
            )
            return jnp.bitwise_xor(moved, partial)

        # after sp-1 hops every device's accumulator has folded every
        # partial exactly once: a ring all-reduce in GF(2)
        return jax.lax.fori_loop(0, sp - 1, hop, partial)

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, "sp"), P("dp", "sp", None)),
        out_specs=P("dp", None, None),
        check_vma=False,
    )
    return fn(bitmatrix, data)


def _suffix_transforms(n_shards: int, local_bytes: int) -> np.ndarray:
    """[D, 32, 32] with row d = A_{(D-1-d)*local}: the zero-gap
    transition carrying device d's local remainder across everything
    to its right."""
    from ceph_tpu.checksum.crc32c import zero_gap_matrix

    out = np.empty((n_shards, 32, 32), dtype=np.int8)
    for d in range(n_shards):
        out[d] = np.frombuffer(
            zero_gap_matrix((n_shards - 1 - d) * local_bytes),
            dtype=np.uint8,
        ).reshape(32, 32)
    return out


_const_cache: dict = {}


def _pick_fold_block(local_bytes: int) -> int:
    """Largest divisor of the local segment <= FOLD_BLOCK_MAX that is
    a multiple of 64 (the chunk-fold granularity)."""
    best = 64
    d = 64
    while d <= min(FOLD_BLOCK_MAX, local_bytes):
        if local_bytes % d == 0:
            best = d
        d += 64
    return best


def _sharded_crc_consts(padded: int, n_dev: int):
    """Device-resident (K_fb, A_fb, suffix stack) for the scan fold —
    cached per (padded, n_dev) geometry unless under a trace (the
    _device_fold discipline: tracer leaks poison caches; re-upload
    through the tunnel is 10x). The true-length init transform is NOT
    here: it varies per object length and is a tiny 32x32."""
    from ceph_tpu.checksum.crc32c import (
        _pick_chunk,
        fold_tensor,
        zero_gap_matrix,
    )

    local_bytes = padded // n_dev
    fb = _pick_fold_block(local_bytes)
    c = _pick_chunk(fb)

    def build():
        return (
            jnp.asarray(fold_tensor(fb, c), jnp.int8),
            jnp.asarray(
                np.frombuffer(
                    zero_gap_matrix(fb), dtype=np.uint8
                ).reshape(32, 32),
                jnp.int32,
            ),
            jnp.asarray(_suffix_transforms(n_dev, local_bytes)),
        )

    from ceph_tpu.utils.platform import trace_state_clean

    if not trace_state_clean():
        return build()
    key = (padded, n_dev)
    if key not in _const_cache:
        _const_cache[key] = build()
    return _const_cache[key]


def sharded_crc32c(
    mesh: Mesh,
    data: jax.Array,  # [B, L] uint8, L sharded over ``axes``
    init: int = 0xFFFFFFFF,
    axes: tuple[str, ...] = ("dp", "sp"),
) -> jax.Array:
    """Per-block CRC32C with the BLOCK axis sharded across the WHOLE
    mesh (both axes by default — this op has no stripe axis to give
    ``dp``, so anything less duplicates data and FLOPs). Each device
    scans its segment in FOLD_BLOCK-bounded pieces

        r <- (r @ A_fb^T) xor fold(piece)      (remainder chaining)

    so the fold-tensor constant stays <= 16 MiB for any object length.
    Returns [B] uint32."""
    from ceph_tpu.checksum.crc32c import (
        acc_to_crc32,
        fold_blocks_bits,
        init_bits32,
        zero_gap_matrix,
    )

    nblocks, total = data.shape
    n_dev = 1
    for a in axes:
        n_dev *= mesh.shape[a]
    # Left-pad with zero bytes to the mesh granularity: a no-op for
    # the zero-init fold; the init contribution below uses TRUE length.
    pad = (-total) % (n_dev * 64)  # 64 keeps the chunk fold aligned
    if pad:
        data = jnp.pad(data, ((0, 0), (pad, 0)))
    k_fb, a_fb, suffix = _sharded_crc_consts(total + pad, n_dev)
    fb = k_fb.shape[0] * (k_fb.shape[2] // 8)
    local_bytes = (total + pad) // n_dev
    npieces = local_bytes // fb

    def local(kf, afb, sfx, blocks):
        pieces = blocks.reshape(blocks.shape[0], npieces, fb)

        def step(r, piece):
            folded = fold_blocks_bits(kf, piece) & 1
            r = ((r @ afb.T) + folded) & 1
            return r, None

        r0 = jnp.zeros((blocks.shape[0], 32), jnp.int32)
        local_bits, _ = jax.lax.scan(
            step, r0, jnp.swapaxes(pieces, 0, 1)
        )
        d = jax.lax.axis_index(axes)
        a_sfx = jax.lax.dynamic_index_in_dim(
            sfx, d, axis=0, keepdims=False
        ).astype(jnp.int32)
        carried = local_bits @ a_sfx.T  # [B, 32] suffix-shifted
        return jax.lax.psum(carried, axes)  # one 32-int all-reduce

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(None, axes)),
        out_specs=P(),
        check_vma=False,
    )
    acc = fn(k_fb, a_fb, suffix, data)
    a_true = jnp.asarray(
        np.frombuffer(
            zero_gap_matrix(total), dtype=np.uint8
        ).reshape(32, 32),
        jnp.int32,
    )
    acc = acc + (a_true @ init_bits32(init).astype(jnp.int32))
    return acc_to_crc32(acc)
