"""Alternative collective schedules for the EC fan-out: ring parity
accumulation and sequence-parallel CRC.

Two distributed patterns beyond mesh.py's all-reduce encode, mirroring
the scaling-book playbook (pick a mesh, annotate shardings, let XLA
place collectives on ICI):

**Ring parity** (`ring_parity`): the XOR-reduction across the shard
axis as the canonical bandwidth-optimal ring all-reduce — a
reduce-scatter phase (each of sp-1 hops moves ONE 1/sp slice of the
packed parity; after them device d owns the fully-reduced slice) then
an all-gather phase (sp-1 more one-slice hops) — ~2(sp-1)/sp times
the parity bytes per link, the schedule large-model training uses
over ICI. The accumulator travels PACKED (XOR commutes with bit
packing). Bit-exact with ``sharded_encode``'s psum; falls back to
psum when the lane axis doesn't split into sp slices.

**Sequence-parallel CRC32C** (`sharded_crc32c`): the long-object axis
(SURVEY.md §5.7 — object size is this framework's sequence length)
sharded across devices. CRC is position-dependent, so naive sharding
breaks; linearity saves it: with per-device fold tensors pre-composed
with the zero-gap transition for the device's suffix length
(crc32c.zero_gap_matrix), each device folds its local bytes and the
combine is a single 32-bit-per-block XOR-allreduce:

    crc(block) = mod2( Σ_d  A_{suffix(d)} @ fold(bytes_d) )

One object of any length (left-padded with zero bytes to the mesh
granularity — a no-op for the fold, since zeros from the zero register
stay zero, while the init contribution uses the true length) hashes
with one psum of [B, 32] ints — the deep-scrub integrity pass for
objects too large for one chip's HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ceph_tpu.ops.bitplane import pack_bits

from .mesh import partial_parity_counts

#: fixed fold granularity for the sequence-parallel CRC scan: keeps
#: the fold-tensor constant bounded (<= 16 MiB) no matter how long
#: the object is — a monolithic per-segment tensor would be 256x the
#: segment size and OOM exactly on the large objects this op exists for
FOLD_BLOCK_MAX = 65536


def ring_parity(
    mesh: Mesh, bitmatrix: jax.Array, data: jax.Array
) -> jax.Array:
    """[B, k, N] uint8 -> [B, m, N] parity; XOR-reduction over the
    ``sp`` axis as ring reduce-scatter + all-gather."""
    sp = mesh.shape["sp"]
    n = data.shape[-1]
    if sp == 1 or n % sp:
        # no ring to run / lane axis unsliceable: psum is the schedule
        from .mesh import sharded_encode

        return sharded_encode(mesh, bitmatrix, data)
    w = n // sp
    fwd = [(d, (d + 1) % sp) for d in range(sp)]

    def local(bmat_cols: jax.Array, shards: jax.Array) -> jax.Array:
        acc = partial_parity_counts(bmat_cols, shards)
        # pack BEFORE the ring: hop traffic is parity bytes, not the
        # 8x bit expansion
        partial = pack_bits((acc & 1).astype(jnp.uint8))  # [b, m, n]
        d = jax.lax.axis_index("sp")

        def slice_at(x, j):
            return jax.lax.dynamic_slice_in_dim(x, j * w, w, axis=-1)

        # -- reduce-scatter: at step t device d sends its accumulated
        # slice (d - t) mod sp and folds its own contribution into the
        # slice arriving from d-1. After sp-1 steps it owns the FULLY
        # reduced slice (d + 1) mod sp.
        def rs_step(t, carry):
            recv = jax.lax.ppermute(carry, "sp", fwd)
            return jnp.bitwise_xor(
                recv, slice_at(partial, (d - t - 1) % sp)
            )

        # carry starts as this device's own slice d: at step t the
        # carry IS the partially-reduced slice (d - t) mod sp
        mine = jax.lax.fori_loop(
            0, sp - 1, rs_step, slice_at(partial, d)
        )
        my_slice = (d + 1) % sp

        # -- all-gather: circulate the reduced slices; each device
        # scatters every arriving slice into its output at the slice
        # index it belongs to ((d + 1 - t) mod sp at step t).
        out = jax.lax.dynamic_update_slice_in_dim(
            jnp.zeros_like(partial), mine, my_slice * w, axis=-1
        )

        def ag_step(t, carry):
            out, moving = carry
            moving = jax.lax.ppermute(moving, "sp", fwd)
            src = (d - t) % sp  # slice index the arrival carries
            out = jax.lax.dynamic_update_slice_in_dim(
                out, moving, src * w, axis=-1
            )
            return out, moving

        out, _ = jax.lax.fori_loop(0, sp - 1, ag_step, (out, mine))
        return out

    from .mesh import shard_map_compat

    fn = shard_map_compat(
        local,
        mesh,
        in_specs=(P(None, "sp"), P("dp", "sp", None)),
        out_specs=P("dp", None, None),
    )
    return fn(bitmatrix, data)


def _suffix_transforms(n_shards: int, local_bytes: int) -> np.ndarray:
    """[D, 32, 32] with row d = A_{(D-1-d)*local}: the zero-gap
    transition carrying device d's local remainder across everything
    to its right."""
    from ceph_tpu.checksum.crc32c import mat32, zero_gap_matrix

    out = np.empty((n_shards, 32, 32), dtype=np.int8)
    for d in range(n_shards):
        out[d] = mat32(zero_gap_matrix((n_shards - 1 - d) * local_bytes))
    return out


_fold_cache: dict = {}
_suffix_cache: dict = {}


def _pick_geometry(total: int, n_dev: int) -> tuple[int, int, int]:
    """(fb, npieces, padded): fold-block chosen FIRST (padding with
    zeros is free), so awkward lengths never degenerate into tiny
    folds — the object pads up to n_dev * npieces * fb."""
    local = -(-total // n_dev)
    fb = min(FOLD_BLOCK_MAX, max(64, ((local + 63) // 64) * 64))
    npieces = -(-local // fb)
    return fb, npieces, n_dev * npieces * fb


def _fold_consts(fb: int):
    """(K_fb, A_fb), cached per fold-block size ONLY — the big tensor
    (fb*256 bytes) has a handful of distinct sizes, never one per
    object length. Trace guard per the _device_fold discipline."""
    from ceph_tpu.checksum.crc32c import (
        _pick_chunk,
        fold_tensor,
        mat32,
        zero_gap_matrix,
    )
    from ceph_tpu.utils.platform import trace_state_clean

    def build():
        return (
            jnp.asarray(fold_tensor(fb, _pick_chunk(fb)), jnp.int8),
            jnp.asarray(mat32(zero_gap_matrix(fb)), jnp.int32),
        )

    if not trace_state_clean():
        return build()
    if fb not in _fold_cache:
        _fold_cache[fb] = build()
    return _fold_cache[fb]


def _suffix_consts(n_dev: int, local_bytes: int):
    """Suffix transform stack — [D, 32, 32] int8, tiny; cached per
    geometry."""
    from ceph_tpu.utils.platform import trace_state_clean

    if not trace_state_clean():
        return jnp.asarray(_suffix_transforms(n_dev, local_bytes))
    key = (n_dev, local_bytes)
    if key not in _suffix_cache:
        _suffix_cache[key] = jnp.asarray(
            _suffix_transforms(n_dev, local_bytes)
        )
    return _suffix_cache[key]


def sharded_crc32c(
    mesh: Mesh,
    data: jax.Array,  # [B, L] uint8, L sharded over ``axes``
    init: int = 0xFFFFFFFF,
    axes: tuple[str, ...] = ("dp", "sp"),
) -> jax.Array:
    """Per-block CRC32C with the BLOCK axis sharded across the WHOLE
    mesh (both axes by default — this op has no stripe axis to give
    ``dp``, so anything less duplicates data and FLOPs). Each device
    scans its segment in FOLD_BLOCK-bounded pieces

        r <- (r @ A_fb^T) xor fold(piece)      (remainder chaining)

    so the fold-tensor constant stays <= 16 MiB for any object length.
    Returns [B] uint32."""
    from ceph_tpu.checksum.crc32c import (
        acc_to_crc32,
        fold_blocks_bits,
        init_bits32,
        zero_gap_matrix,
    )

    nblocks, total = data.shape
    n_dev = 1
    for a in axes:
        n_dev *= mesh.shape[a]
    fb, npieces, padded = _pick_geometry(total, n_dev)
    # Left-pad with zero bytes to the fold geometry: a no-op for the
    # zero-init fold; the init contribution below uses TRUE length.
    if padded != total:
        data = jnp.pad(data, ((0, 0), (padded - total, 0)))
    k_fb, a_fb = _fold_consts(fb)
    local_bytes = padded // n_dev
    suffix = _suffix_consts(n_dev, local_bytes)

    def local(kf, afb, sfx, blocks):
        pieces = blocks.reshape(blocks.shape[0], npieces, fb)

        def step(r, piece):
            folded = fold_blocks_bits(kf, piece) & 1
            r = ((r @ afb.T) + folded) & 1
            return r, None

        r0 = jnp.zeros((blocks.shape[0], 32), jnp.int32)
        local_bits, _ = jax.lax.scan(
            step, r0, jnp.swapaxes(pieces, 0, 1)
        )
        d = jax.lax.axis_index(axes)
        a_sfx = jax.lax.dynamic_index_in_dim(
            sfx, d, axis=0, keepdims=False
        ).astype(jnp.int32)
        carried = local_bits @ a_sfx.T  # [B, 32] suffix-shifted
        return jax.lax.psum(carried, axes)  # one 32-int all-reduce

    from .mesh import shard_map_compat

    fn = shard_map_compat(
        local,
        mesh,
        in_specs=(P(), P(), P(), P(None, axes)),
        out_specs=P(),
    )
    acc = fn(k_fb, a_fb, suffix, data)
    a_true = jnp.asarray(
        np.frombuffer(
            zero_gap_matrix(total), dtype=np.uint8
        ).reshape(32, 32),
        jnp.int32,
    )
    acc = acc + (a_true @ init_bits32(init).astype(jnp.int32))
    return acc_to_crc32(acc)
