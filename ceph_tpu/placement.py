"""Deterministic placement — the CRUSH analog (straw2 selection).

Mirrors the behavioral contract of src/crush (mapper.c
``crush_do_rule``, straw2 buckets; OSDMap::pg_to_up_acting_osds):
object -> PG by stable hash; PG -> N distinct devices by straw2
draws — every device computes ``ln(hash01(pg, device, trial)) /
weight`` and the max wins, which gives weight-proportional placement
and CRUSH's key property: adding/removing/reweighting a device only
moves the PGs that now draw higher for it (minimal data movement).
The hash is a fixed 64-bit mixer, NOT bit-compatible with rjenkins on
purpose — the contract is determinism-forever within THIS framework,
frozen by tests.

Failure domains: devices carry a ``zone``; selection can require
distinct zones first (the chooseleaf host/rack rule analog), falling
back to distinct devices when zones run out.

Deployment wiring: a pool maps each PG's acting set to k+m shard
daemons, then orders the messenger tier's address map by it — shard i
of a stripe lives on acting[i] (the ECSwitch ctor wiring role,
osd/ECSwitch.h:36-48).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

_MASK = 0xFFFFFFFFFFFFFFFF


def _mix(x: int) -> int:
    """splitmix64 finalizer — frozen forever (placement stability)."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK
    return x ^ (x >> 31)


def stable_hash(*parts: int | str) -> int:
    h = 0x5EED0FCE
    for p in parts:
        if isinstance(p, str):
            for ch in p.encode():
                h = _mix(h ^ ch)
        else:
            h = _mix(h ^ (p & _MASK))
    return h


def _hash01(*parts) -> float:
    """(0, 1] uniform from the stable hash."""
    return (stable_hash(*parts) + 1) / 2.0**64


@dataclass(frozen=True)
class Device:
    id: int
    weight: float = 1.0
    zone: str = ""


class CrushMap:
    """Weighted device set + straw2 selection."""

    def __init__(self, devices: list[Device]) -> None:
        if len({d.id for d in devices}) != len(devices):
            raise ValueError("duplicate device ids")
        self.devices = {d.id: d for d in devices}

    def _draw(self, key: tuple, dev: Device) -> float:
        """straw2: ln(u)/w — max over devices is weight-proportional."""
        if dev.weight <= 0:
            return -math.inf
        u = _hash01(*key, dev.id)
        return math.log(u) / dev.weight

    def select(
        self, pg: int, n: int, distinct_zones: bool = False
    ) -> list[int]:
        """N distinct devices for a PG, ordered by draw rank (the
        acting set). With ``distinct_zones``, no two picks share a
        zone until zones are exhausted (chooseleaf semantics)."""
        live = [d for d in self.devices.values() if d.weight > 0]
        if n > len(live):
            raise ValueError(f"want {n} devices, have {len(live)}")
        ranked = sorted(
            live, key=lambda d: self._draw((pg,), d), reverse=True
        )
        if not distinct_zones:
            return [d.id for d in ranked[:n]]
        out: list[int] = []
        used_zones: set[str] = set()
        skipped: list[Device] = []
        for d in ranked:
            if len(out) >= n:
                break
            if d.zone and d.zone in used_zones:
                skipped.append(d)
                continue
            out.append(d.id)
            used_zones.add(d.zone)
        for d in skipped:  # zones exhausted: fill with best remaining
            if len(out) >= n:
                break
            out.append(d.id)
        return out


class PGMap:
    """Object -> PG -> acting set (the OSDMap/pg_to_up_acting path)."""

    def __init__(
        self,
        crush: CrushMap,
        pg_num: int,
        pool: str = "default",
    ) -> None:
        if pg_num <= 0:
            raise ValueError("pg_num must be positive")
        self.crush = crush
        self.pg_num = pg_num
        self.pool = pool

    def object_to_pg(self, oid: str) -> int:
        return stable_hash(self.pool, oid) % self.pg_num

    def pg_to_acting(self, pg: int, n: int, **kw) -> list[int]:
        return self.crush.select(stable_hash(self.pool, pg), n, **kw)

    def object_to_acting(self, oid: str, n: int, **kw) -> list[int]:
        return self.pg_to_acting(self.object_to_pg(oid), n, **kw)
