"""GF(2^8) arithmetic for erasure coding.

Host side (numpy): tables, generator-matrix construction, inversion
(``tables``, ``matrices``). Device side (JAX): bit-plane formulation where
multiply-by-constant is an 8x8 GF(2) matrix, so RS encode becomes one
binary matmul on the MXU (``ceph_tpu.ops.bitplane``).

Polynomial: x^8 + x^4 + x^3 + x^2 + 1 (0x11D), the field used by both
ISA-L and gf-complete's default w=8 field (the two SIMD GF backends the
reference vendors — SURVEY.md section 2.1).
"""

from .tables import (  # noqa: F401
    GF_POLY,
    gf_exp,
    gf_log,
    gf_inv_table,
    gf_mul,
    gf_div,
    gf_inv,
    gf_pow,
    gf_apply_bytes_host,
    gf_mul_bytes,
    mul_bitmatrix,
    MUL_BITMATRIX,
)
from .matrices import (  # noqa: F401
    identity,
    vandermonde_rs_matrix,
    isa_rs_matrix,
    isa_cauchy_matrix,
    cauchy_original_matrix,
    cauchy_good_matrix,
    raid6_matrix,
    gf_matmul_np,
    gf_invert_matrix,
    decode_matrix,
)
from .bitmatrix import (  # noqa: F401
    gf_matrix_to_bitmatrix,
    bitmatrix_invert,
    bitmatrix_matmul,
)
