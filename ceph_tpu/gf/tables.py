"""GF(2^8) scalar arithmetic tables (host-side numpy).

These are the semantics the reference gets from its vendored SIMD GF
libraries (gf-complete / ISA-L — SURVEY.md section 2.1, "Vendored native
libs"): exp/log tables over the 0x11D field, multiply, divide, inverse.
On TPU we never use byte-granular table lookups (no pshufb analog);
instead ``mul_bitmatrix`` lowers multiply-by-constant to an 8x8 GF(2)
matrix, which is what the device kernels consume.

Bit convention: bit i of a byte is the coefficient of x^i (LSB-first),
matching how ISA-L / gf-complete represent field elements.
"""

from __future__ import annotations

import functools

import numpy as np

# x^8 + x^4 + x^3 + x^2 + 1 — ISA-L's and gf-complete's default w=8 field.
GF_POLY = 0x11D


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = -1  # undefined
    return exp, log


gf_exp, gf_log = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Scalar GF(2^8) multiply."""
    if a == 0 or b == 0:
        return 0
    return int(gf_exp[gf_log[a] + gf_log[b]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(gf_exp[(gf_log[a] - gf_log[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(gf_exp[255 - gf_log[a]])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(gf_exp[(gf_log[a] * n) % 255])


gf_inv_table = np.array([0] + [gf_inv(i) for i in range(1, 256)], dtype=np.uint8)


def gf_mul_bytes(c: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of ``data`` by constant ``c`` (numpy reference)."""
    data = np.asarray(data, dtype=np.uint8)
    if c == 0:
        return np.zeros_like(data)
    if c == 1:
        return data.copy()
    lc = gf_log[c]
    out = np.zeros_like(data)
    nz = data != 0
    out[nz] = gf_exp[lc + gf_log[data[nz].astype(np.int32)]]
    return out


@functools.lru_cache(maxsize=None)
def _mul_bitmatrix_cached(c: int) -> bytes:
    # Column j of the matrix is c * x^j; row i is bit i of those products.
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = gf_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (prod >> i) & 1
    return m.tobytes()


def mul_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix M with bits(c*v) = M @ bits(v) (bit i = coeff of x^i).

    This is the lowering that turns GF(2^8) matrix codes into pure
    XOR networks — the formulation the TPU kernels execute (SURVEY.md
    section 7, "Design stance").
    """
    return np.frombuffer(_mul_bitmatrix_cached(c), dtype=np.uint8).reshape(8, 8).copy()


# [256, 8, 8] — all multiply-by-constant bit matrices.
MUL_BITMATRIX = np.stack([mul_bitmatrix(c) for c in range(256)])


def gf_apply_bytes_host(mat: np.ndarray, stacked: np.ndarray) -> np.ndarray:
    """Apply a GF(2^8) byte matrix on the HOST: out[..., r, :] =
    XOR_c mat[r, c] * stacked[..., c, :].

    The small-op fast path (the reference's ec_encode_data on CPU):
    device dispatch costs more than the math below ~1 MiB, especially
    through a remote-device tunnel. Uses the native SIMD region kernel
    when built, the log/exp tables otherwise — both bit-identical to
    the device bit-plane path (verified in tests).
    """
    from ceph_tpu import native

    mat = np.asarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(stacked, dtype=np.uint8)
    lead = data.shape[:-2]
    flat = data.reshape((-1,) + data.shape[-2:])
    b, c_count, n = flat.shape
    r_count = mat.shape[0]
    if native.available():
        # one native call per batch item (the C kernel runs the whole
        # mat x data application; per-call ctypes overhead would
        # otherwise dominate exactly the small ops this path serves)
        out = np.stack(
            [native.gf_matrix_encode(mat, flat[i]) for i in range(b)]
        )
    else:
        out = np.zeros((b, r_count, n), dtype=np.uint8)
        for r in range(r_count):
            for c in range(c_count):
                g = int(mat[r, c])
                if g:
                    out[:, r, :] ^= gf_mul_bytes(g, flat[:, c, :])
    return out.reshape(lead + (r_count, n))
