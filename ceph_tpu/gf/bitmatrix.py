"""GF(2) bit-matrix operations.

Two uses, mirroring the reference:

1. Lowering a GF(2^8) generator matrix to one (m*8) x (k*8) binary matrix
   so encode is a single mod-2 matmul — the TPU replacement for jerasure's
   ``jerasure_matrix_to_bitmatrix`` + XOR schedules.
2. Native bit-matrix codes (cauchy_good schedules, liberation family,
   blaum_roth, liber8tion — ErasureCodeJerasure.h:188-324) whose
   generators are defined directly over GF(2) with word size w.
"""

from __future__ import annotations

import numpy as np

from .tables import MUL_BITMATRIX


def gf_matrix_to_bitmatrix(m: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix [r, c] to its GF(2) form [r*8, c*8].

    Block (i, j) is the 8x8 multiply-by-m[i,j] matrix, so
    bits(out_i) = XOR_j block(i,j) @ bits(in_j) with LSB-first bit order.
    """
    m = np.asarray(m, dtype=np.uint8)
    r, c = m.shape
    blocks = MUL_BITMATRIX[m]  # [r, c, 8, 8]
    return blocks.transpose(0, 2, 1, 3).reshape(r * 8, c * 8).astype(np.uint8)


def plane_major_cols(m: np.ndarray, pad: int = 0) -> np.ndarray:
    """Reindex bit COLUMNS from shard-major to plane-major, padded.

    Input columns are shard-major (col i*8 + b = bit b of shard i, the
    ``gf_matrix_to_bitmatrix`` layout); output columns are plane-major
    (col b*F + i with F = C + pad), matching the contraction order the
    packed bit-plane unpack produces on device: all shards' bit-b
    planes are contiguous, with ``pad`` all-zero shard slots per plane
    (the int32-sublane alignment columns — the ONLY structural zeros
    the zero-waste kernel packing has left). Vectorized: the round-5
    builders walked an r*c*64 Python loop per cached matrix, which the
    wide packet-code matrices (C up to k*w) paid at every cache miss.
    """
    m = np.asarray(m, dtype=np.uint8)
    rows, c8 = m.shape
    assert c8 % 8 == 0, c8
    c = c8 // 8
    x = m.reshape(rows, c, 8).transpose(0, 2, 1)  # [rows, 8, c]
    if pad:
        x = np.concatenate(
            [x, np.zeros((rows, 8, pad), np.uint8)], axis=2
        )
    return np.ascontiguousarray(x.reshape(rows, 8 * (c + pad)))


def bitmatrix_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2)."""
    return (a.astype(np.int64) @ b.astype(np.int64) % 2).astype(np.uint8)


def bitmatrix_invert(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2) matrix; ValueError if singular.

    Used for decode of native bit-matrix codes (liberation family), where
    the decode transform is the inverse of the surviving (k*w) x (k*w)
    sub-bitmatrix — jerasure_invert_bitmatrix's role in the reference.
    """
    m = np.asarray(m, dtype=np.uint8).copy()
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError(f"not square: {m.shape}")
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if m[row, col]:
                pivot = row
                break
        if pivot is None:
            raise ValueError("singular GF(2) matrix")
        if pivot != col:
            m[[col, pivot]] = m[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        for row in range(n):
            if row != col and m[row, col]:
                m[row, :] ^= m[col, :]
                inv[row, :] ^= inv[col, :]
    return inv
