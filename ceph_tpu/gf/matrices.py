"""Generator-matrix construction and GF(2^8) linear algebra (host-side).

Mirrors the matrix generators of the reference's plugins:

- ``vandermonde_rs_matrix`` — jerasure ``reed_sol_van`` (systematized
  Vandermonde; reference src/erasure-code/jerasure/ErasureCodeJerasure.h:124).
- ``isa_rs_matrix`` — ISA-L ``gf_gen_rs_matrix`` semantics (identity top,
  parity rows p[j] = gen_i^j with gen_i = 2^(i-k), so parity row 0 is
  all-ones; only MDS inside the envelope documented at
  src/erasure-code/isa/README:23-24).
- ``isa_cauchy_matrix`` — ISA-L ``gf_gen_cauchy1_matrix``
  (reference src/erasure-code/isa/ErasureCodeIsa.cc:598-600).
- ``cauchy_original_matrix`` / ``cauchy_good_matrix`` — jerasure
  ``cauchy_orig`` / ``cauchy_good`` techniques.
- ``raid6_matrix`` — jerasure ``reed_sol_r6_op`` (P = XOR, Q = powers of 2).

Matrix inversion is tiny (<=32x32 — isa/ErasureCodeIsa.h:48-49 caps) and
sequential, so it stays host-side; decode kernels stay erasure-pattern
agnostic and consume the cached inverted matrix (the TableCache precedent,
isa/ErasureCodeIsaTableCache.cc — SURVEY.md section 7 "Hard parts").
"""

from __future__ import annotations

import numpy as np

from .tables import gf_div, gf_inv, gf_mul, gf_pow

MAX_K = 32  # isa/ErasureCodeIsa.h:48
MAX_M = 32  # isa/ErasureCodeIsa.h:49


def identity(k: int) -> np.ndarray:
    return np.eye(k, dtype=np.uint8)


def gf_matmul_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (numpy reference; small matrices only)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[0]):
        for j in range(b.shape[1]):
            acc = 0
            for t in range(a.shape[1]):
                acc ^= gf_mul(int(a[i, t]), int(b[t, j]))
            out[i, j] = acc
    return out


def gf_invert_matrix(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination.

    Raises ValueError if singular (the caller treats that as "erasure
    pattern not decodable", e.g. SHEC's determinant search).
    """
    m = np.asarray(m, dtype=np.uint8).copy()
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError(f"not square: {m.shape}")
    inv = identity(n)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if m[row, col]:
                pivot = row
                break
        if pivot is None:
            raise ValueError("singular GF(2^8) matrix")
        if pivot != col:
            m[[col, pivot]] = m[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pv = gf_inv(int(m[col, col]))
        for j in range(n):
            m[col, j] = gf_mul(int(m[col, j]), pv)
            inv[col, j] = gf_mul(int(inv[col, j]), pv)
        for row in range(n):
            if row != col and m[row, col]:
                f = int(m[row, col])
                for j in range(n):
                    m[row, j] ^= gf_mul(f, int(m[col, j]))
                    inv[row, j] ^= gf_mul(f, int(inv[col, j]))
    return inv


def vandermonde_rs_matrix(k: int, m: int) -> np.ndarray:
    """jerasure reed_sol_van: systematized (k+m) x k Vandermonde.

    Build V[i, j] = i^j over GF(2^8) for i in [0, k+m), then right-multiply
    by inv(top k x k block) so the top becomes identity — algebraically the
    distribution matrix jerasure's reed_sol_vandermonde_coding_matrix
    produces by column elimination. Rows k.. are the parity (coding) rows.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for GF(2^8) Vandermonde")
    v = np.zeros((k + m, k), dtype=np.uint8)
    for i in range(k + m):
        for j in range(k):
            v[i, j] = gf_pow(i, j) if i > 0 else (1 if j == 0 else 0)
    top_inv = gf_invert_matrix(v[:k, :])
    return gf_matmul_np(v, top_inv)


def isa_rs_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L gf_gen_rs_matrix semantics: identity + geometric parity rows.

    Parity row i (0-based among parities) is the geometric sequence
    p[j] = gen_i^j with gen_i = 2^i: row 0 is all-ones, the base
    doubles per row. MDS only
    within (k<=21,m<=4)/(k<=32,m<=3) envelope (isa/README:23-24); callers
    must respect that envelope exactly as the reference does.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for GF(2^8)")
    a = np.zeros((k + m, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    gen = 1
    for i in range(m):
        p = 1
        for j in range(k):
            a[k + i, j] = p
            p = gf_mul(gen, p)
        gen = gf_mul(gen, 2)
    return a


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L gf_gen_cauchy1_matrix: identity top, then 1/(i ^ j) rows.

    Reference call site: isa/ErasureCodeIsa.cc:598-600 (matrixtype
    kVandermonde vs kCauchy). Always MDS for k+m <= 256.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for GF(2^8)")
    a = np.zeros((k + m, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    for i in range(k, k + m):
        for j in range(k):
            a[i, j] = gf_inv(i ^ j)  # i >= k > j so i^j != 0
    return a


def cauchy_original_matrix(k: int, m: int) -> np.ndarray:
    """jerasure cauchy_original_coding_matrix: C[i][j] = 1/(i ^ (m+j)).

    Points x_i = i (parities) and y_j = m+j (data) are disjoint, so every
    minor is nonsingular (classic Cauchy MDS property). Returns the full
    systematic (k+m) x k matrix (identity on top).
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256")
    a = np.zeros((k + m, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    for i in range(m):
        for j in range(k):
            a[k + i, j] = gf_inv(i ^ (m + j))
    return a


def _ones_in_bitmatrix_row(c: int) -> int:
    from .tables import mul_bitmatrix

    return int(mul_bitmatrix(c).sum())


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """jerasure cauchy_good: original Cauchy improved to minimize XOR count.

    jerasure's improve_coding_matrix: (1) scale each column so parity row 0
    becomes all ones, (2) for each later parity row, try scaling the row by
    the inverse of each of its elements and keep the scaling with the
    fewest total ones across the row's 8x8 mul bitmatrices. Row/column
    scaling by nonzero constants preserves the Cauchy MDS property.
    """
    a = cauchy_original_matrix(k, m)
    p = a[k:, :].copy()
    for j in range(k):
        f = gf_inv(int(p[0, j]))
        for i in range(m):
            p[i, j] = gf_mul(int(p[i, j]), f)
    for i in range(1, m):
        best_row = p[i, :].copy()
        best_cost = sum(_ones_in_bitmatrix_row(int(c)) for c in best_row)
        for divisor in sorted({int(c) for c in p[i, :] if c > 1}):
            cand = np.array(
                [gf_div(int(c), divisor) for c in p[i, :]], dtype=np.uint8
            )
            cost = sum(_ones_in_bitmatrix_row(int(c)) for c in cand)
            if cost < best_cost:
                best_cost = cost
                best_row = cand
        p[i, :] = best_row
    out = a.copy()
    out[k:, :] = p
    return out


def raid6_matrix(k: int) -> np.ndarray:
    """jerasure reed_sol_r6_op layout: P = XOR of data, Q = sum 2^j * d_j."""
    a = np.zeros((k + 2, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    a[k, :] = 1
    for j in range(k):
        a[k + 1, j] = gf_pow(2, j)
    return a


def decode_matrix(
    generator: np.ndarray, k: int, present_rows: list[int]
) -> np.ndarray:
    """Rows that reconstruct ALL k data chunks from ``present_rows`` chunks.

    ``generator`` is the full (k+m) x k systematic matrix; ``present_rows``
    selects >= k surviving chunk indices (data rows are identity rows).
    Returns a k x len(present_rows) matrix D with data = D @ survivors.
    Equivalent to the invert-submatrix step of ISA-L decode
    (isa/ErasureCodeIsa.cc:504-516). Raises ValueError if the pattern is
    undecodable (non-MDS codes like isa Vandermonde outside its envelope,
    or SHEC with too many erasures).
    """
    if len(present_rows) < k:
        raise ValueError(f"need >= {k} chunks, have {len(present_rows)}")
    rows = sorted(present_rows)[: generator.shape[0]]
    # Choose k linearly independent survivor rows by greedy rank extension
    # (incremental Gaussian elimination) — O(len(rows) * k^2), needed for
    # non-MDS codes where the first k survivors may be dependent.
    chosen: list[int] = []
    echelon: list[np.ndarray] = []  # reduced rows mirroring `chosen`
    for r in rows:
        if len(chosen) == k:
            break
        v = generator[r].astype(np.uint8).copy()
        for e in echelon:
            lead = int(np.argmax(e != 0))
            if v[lead]:
                f = gf_div(int(v[lead]), int(e[lead]))
                for j in range(k):
                    v[j] ^= gf_mul(f, int(e[j]))
        if v.any():
            chosen.append(r)
            echelon.append(v)
    if len(chosen) < k:
        raise ValueError("erasure pattern not decodable")
    inv = gf_invert_matrix(np.stack([generator[r] for r in chosen]))
    d = np.zeros((k, len(rows)), dtype=np.uint8)
    for out_col, r in enumerate(rows):
        if r in chosen:
            d[:, out_col] = inv[:, chosen.index(r)]
    return d
