"""ceph_tpu — a TPU-native erasure-coding and data-integrity framework.

Implements the behavioral contract of Ceph's erasure-code subsystem
(reference: /root/reference/src/erasure-code/ErasureCodeInterface.h:182)
as an idiomatic JAX/XLA/Pallas framework:

- GF(2^8) math as bit-sliced MXU matmuls (``ceph_tpu.gf``, ``ceph_tpu.ops``)
- Code families: Reed-Solomon (Vandermonde / RAID6), Cauchy, the
  Liberation XOR-schedule family, LRC, SHEC, CLAY (``ceph_tpu.codecs``)
- The OSD EC stripe pipeline semantics — stripe geometry, extent maps,
  read-modify-write planning, reconstruct reads, recovery, deep scrub
  (``ceph_tpu.pipeline``)
- Block checksumming (CRC32C family, xxhash32/64) (``ceph_tpu.checksum``)
- Multi-chip shard fan-out over a jax.sharding.Mesh (``ceph_tpu.parallel``)
- Native C++ host runtime (ring buffer, scalar validation paths)
  (``ceph_tpu.runtime``)
"""

__version__ = "0.1.0"

# Interface generation implemented: the 2025 "optimized EC" path
# (reference: src/osd/ECSwitch.h:6-18). Mirrors __erasure_code_version
# handshake in src/erasure-code/ErasureCodePlugin.cc:30-33.
PLUGIN_ABI_VERSION = "ceph_tpu-ec-2.0"
