"""Golden-chunk corpus — the non-regression harness.

Mirrors src/test/erasure-code/ceph_erasure_code_non_regression.cc +
the ceph-erasure-code-corpus archive (SURVEY.md §2.1 "EC on-disk
corpus"): encoded chunks for each plugin/profile are frozen on disk;
``check`` re-encodes the archived payload and demands byte equality
(encode must be deterministic forever — the cross-version
bit-compatibility guarantee), then decodes every 1- and 2-erasure
combination back to the archived content.

Layout: ``<base>/<version>/<plugin>/<slug>/`` holding ``payload.bin``,
``profile.json``, and ``chunk.<i>``.

The payload generator is SHA-256 chaining — intentionally NOT a PRNG
library whose stream could change across releases; the corpus must be
reproducible from (seed, size) forever.

CLI:
    python -m ceph_tpu.corpus create --base tests/corpus/v0
    python -m ceph_tpu.corpus check  --base tests/corpus/v0
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from itertools import combinations

# The default suite frozen at v0: one profile per plugin family plus
# the headline configs from BASELINE.md.
DEFAULT_SUITE: list[tuple[str, dict[str, str]]] = [
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "8", "m": "4"}),
    ("jerasure", {"technique": "reed_sol_r6_op", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "cauchy_orig", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "cauchy_good", "k": "4", "m": "2"}),
    # construction=v0 pins the round-1 matrices: re-creating the v0
    # tree must reproduce the ORIGINAL archive, not today's defaults
    ("jerasure", {"technique": "liberation", "k": "4", "m": "2",
                  "construction": "v0"}),
    ("jerasure", {"technique": "blaum_roth", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "liber8tion", "k": "4", "m": "2",
                  "construction": "v0"}),
    ("isa", {"technique": "reed_sol_van", "k": "8", "m": "3"}),
    ("isa", {"technique": "cauchy", "k": "4", "m": "2"}),
    ("lrc", {"k": "4", "m": "2", "l": "3"}),
    ("shec", {"k": "4", "m": "3", "c": "2"}),
    ("clay", {"k": "4", "m": "2", "d": "5"}),
]

# v1 (round 5): the packet bit-matrix techniques under their
# reference-derived constructions (liberation = Plank FAST'08 port,
# blaum_roth = Blaum-Roth 1993 ring form, liber8tion = frozen
# minimal-density search) — the v0 entries for these pin
# construction=v0, so both matrix generations stay covered forever.
#
# Round 6 adds the byte-matrix families (reed_sol_van, cauchy_orig,
# cauchy_good, isa RS) at geometries the v0 suite does not cover —
# including the non-power-of-two k the zero-waste kernel pads and the
# cauchy k=10 bench geometry. Their chunks are additionally pinned
# against a from-scratch host GF apply of the gf/matrices.py ported
# constructions (tests/test_zero_waste_packing.py), so the repacked
# kernels regress against reference-derived vectors, not a v0 freeze
# of the engine under test.
V1_SUITE: list[tuple[str, dict[str, str]]] = [
    ("jerasure", {"technique": "liberation", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "liberation", "k": "6", "m": "2",
                  "w": "7"}),
    ("jerasure", {"technique": "blaum_roth", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "liber8tion", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "liber8tion", "k": "8", "m": "2"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "5", "m": "3"}),
    ("jerasure", {"technique": "cauchy_orig", "k": "5", "m": "3"}),
    ("jerasure", {"technique": "cauchy_good", "k": "10", "m": "4"}),
    ("isa", {"technique": "reed_sol_van", "k": "6", "m": "3"}),
]

# v2 (round 8): CLAY breadth (VERDICT #7 remainder) — the (8,4,d=10)
# profile (d < k+m-1: helper planes span fewer nodes than the d=11
# default, a distinct repair-plan shape) and a SHORTENED geometry
# ((4,3,d=6): q=3 does not divide k+m=7, so nu=2 virtual zero chunks
# pad the inner code — the ErasureCodeClay.cc:330 shortening path the
# v0 (4,2,d=5) entry never exercises).
#
# Round 9 adds the general-d kernel-path profiles: (6,3,d=7) is
# ALOOF + SHORTENED at once (one aloof node, nu=1 virtual chunk —
# the B1/B2 split with virtual members in the aloof row), and the
# (4,2,d=5) @ 516 KiB entry pins a chunk whose
# ``SB * sub_chunk_no * sc`` (2 Mi lanes at sc=16512) overflowed the
# retired round-7 whole-chunk scatter budget — the plane-blocked
# kernels must keep re-encoding/repairing it bit-identically
# (tests/test_clay_general_d.py runs repair-vs-archive through the
# kernels in interpret mode).  An optional third tuple element is the
# payload size (default PAYLOAD_SIZE).
V2_SUITE: list[tuple] = [
    ("clay", {"k": "8", "m": "4", "d": "10"}),
    ("clay", {"k": "4", "m": "3", "d": "6"}),
    ("clay", {"k": "6", "m": "3", "d": "7"}),
    ("clay", {"k": "4", "m": "2", "d": "5"}, 4 * 132096),
]

SUITES = {"v0": DEFAULT_SUITE, "v1": V1_SUITE, "v2": V2_SUITE}

PAYLOAD_SIZE = 31 * 1024 + 17  # ragged on purpose: exercises padding


def deterministic_payload(size: int, seed: str) -> bytes:
    """SHA-256 counter-mode byte stream: stable across releases."""
    out = bytearray()
    counter = 0
    while len(out) < size:
        out += hashlib.sha256(f"{seed}:{counter}".encode()).digest()
        counter += 1
    return bytes(out[:size])


def profile_slug(plugin: str, profile: dict[str, str]) -> str:
    parts = [plugin] + [
        f"{k}={profile[k]}" for k in sorted(profile)
    ]
    return "_".join(parts).replace("/", "-")


def _codec(plugin: str, profile: dict[str, str]):
    from ceph_tpu.codecs import registry

    return registry.factory(plugin, dict(profile))


def run_create(
    base: str, plugin: str, profile: dict[str, str],
    size: int = PAYLOAD_SIZE,
) -> str:
    """Archive payload + encoded chunks for one plugin/profile."""
    slug = profile_slug(plugin, profile)
    path = os.path.join(base, plugin, slug)
    os.makedirs(path, exist_ok=True)
    payload = deterministic_payload(size, seed=slug)
    codec = _codec(plugin, profile)
    chunks = codec.encode(payload)
    with open(os.path.join(path, "payload.bin"), "wb") as f:
        f.write(payload)
    with open(os.path.join(path, "profile.json"), "w") as f:
        json.dump({"plugin": plugin, "profile": profile, "size": size}, f,
                  indent=1, sort_keys=True)
    for i, chunk in sorted(chunks.items()):
        with open(os.path.join(path, f"chunk.{i}"), "wb") as f:
            f.write(chunk)
    return path


def run_check(path: str, max_erasures: int = 2) -> list[str]:
    """Verify one archived corpus entry; returns a list of failures."""
    errors: list[str] = []
    with open(os.path.join(path, "profile.json")) as f:
        meta = json.load(f)
    plugin, profile = meta["plugin"], meta["profile"]
    with open(os.path.join(path, "payload.bin"), "rb") as f:
        payload = f.read()
    if len(payload) != meta["size"]:
        errors.append(f"payload size {len(payload)} != {meta['size']}")
    codec = _codec(plugin, profile)
    n = codec.get_chunk_count()
    stored: dict[int, bytes] = {}
    for i in range(n):
        with open(os.path.join(path, f"chunk.{i}"), "rb") as f:
            stored[i] = f.read()

    # 1. Bit-compatibility: today's encode == the archived chunks.
    now = codec.encode(payload)
    for i in range(n):
        if now[i] != stored[i]:
            errors.append(f"chunk {i} re-encodes differently")

    # 2. Every 1..max_erasures erasure combination decodes to the
    #    archived chunks (the decode_erasures recursion of the
    #    reference tool).
    m = codec.get_coding_chunk_count()
    for count in range(1, min(max_erasures, m) + 1):
        for erased in combinations(range(n), count):
            have = {i: c for i, c in stored.items() if i not in erased}
            try:
                out = codec.decode(set(erased), have)
            except ValueError:
                # Non-MDS families (SHEC trades decodability for
                # recovery cost) legitimately reject some patterns.
                if plugin in ("shec",):
                    continue
                errors.append(f"decode refused erasure {erased}")
                continue
            for e in erased:
                if bytes(out[e]) != stored[e]:
                    errors.append(f"erasure {erased}: chunk {e} differs")
    return errors


def iter_entries(base: str):
    for plugin in sorted(os.listdir(base)):
        pdir = os.path.join(base, plugin)
        if not os.path.isdir(pdir):
            continue
        for slug in sorted(os.listdir(pdir)):
            entry = os.path.join(pdir, slug)
            if os.path.isfile(os.path.join(entry, "profile.json")):
                yield entry


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="ceph_tpu.corpus")
    p.add_argument("action", choices=["create", "check"])
    p.add_argument("--base", default="tests/corpus/v0")
    p.add_argument("--size", type=int, default=PAYLOAD_SIZE)
    args = p.parse_args(argv)

    from ceph_tpu.utils import honor_platform_env

    honor_platform_env()

    if args.action == "create":
        version = os.path.basename(os.path.normpath(args.base))
        suite = SUITES.get(version)
        if suite is None:
            p.error(
                f"--base must end in a known corpus version "
                f"({sorted(SUITES)}), got {version!r}"
            )
        for entry in suite:
            plugin, profile = entry[0], entry[1]
            size = entry[2] if len(entry) > 2 else args.size
            path = run_create(args.base, plugin, profile, size)
            print(f"created {path}")
        return 0

    failed = 0
    for entry in iter_entries(args.base):
        errors = run_check(entry)
        status = "ok" if not errors else "FAIL"
        print(f"{status}  {entry}")
        for e in errors:
            print(f"      {e}")
        failed += bool(errors)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
