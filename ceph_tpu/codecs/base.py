"""Shared codec behavior — the ``ErasureCode`` base-class analog.

Default implementations mirroring src/erasure-code/ErasureCode.{h,cc}:
profile parsing helpers (``to_int``/``to_bool`` — ErasureCode.h:136-152),
padded data preparation (``encode_prepare`` — ErasureCode.cc), byte-level
``encode``/``decode`` wrappers over the chunk APIs, chunk remapping, and
availability-based ``minimum_to_decode``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .interface import ErasureCodeProfile, Flag, SubChunkPlan

# TPU lane width; chunk sizes are padded to a multiple of this so the
# byte axis tiles cleanly (the SIMD_ALIGN analog, ErasureCode.h).
CHUNK_ALIGN = 128


def to_int(name: str, profile: ErasureCodeProfile, default: int) -> int:
    v = profile.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except (TypeError, ValueError):
        raise ValueError(f"profile key {name}={v!r} is not an integer")


def to_bool(name: str, profile: ErasureCodeProfile, default: bool) -> bool:
    v = profile.get(name)
    if v is None or v == "":
        return default
    return str(v).lower() in ("1", "true", "yes", "on")


class ErasureCodeBase:
    """Concrete shared machinery; code families subclass this."""

    def __init__(self) -> None:
        self.k = 0
        self.m = 0
        self.profile: ErasureCodeProfile = {}
        self.chunk_mapping: list[int] = []

    # -- geometry -----------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_sub_chunk_count(self) -> int:
        return 1

    def get_chunk_size(self, stripe_width: int) -> int:
        """ceil(stripe_width / k) rounded up to CHUNK_ALIGN bytes."""
        per = -(-stripe_width // self.k)
        return -(-per // CHUNK_ALIGN) * CHUNK_ALIGN

    def get_flags(self) -> Flag:
        return Flag.NONE

    def get_chunk_mapping(self) -> list[int]:
        return self.chunk_mapping or list(range(self.get_chunk_count()))

    # -- planning -----------------------------------------------------
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> SubChunkPlan:
        """Default: any k available shards, whole chunks.

        Mirrors ErasureCode::_minimum_to_decode — prefer the wanted
        shards themselves, fill with other survivors up to k.
        """
        if want_to_read <= available:
            return {s: [(0, self.get_sub_chunk_count())] for s in want_to_read}
        chosen = sorted(want_to_read & available)
        for s in sorted(available - want_to_read):
            if len(chosen) >= self.k:
                break
            chosen.append(s)
        if len(chosen) < self.k:
            raise ValueError(
                f"cannot decode {sorted(want_to_read)} from "
                f"{sorted(available)}: need {self.k} shards"
            )
        return {s: [(0, self.get_sub_chunk_count())] for s in chosen[: self.k]}

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int]
    ) -> set[int]:
        """Pick the cheapest k-cover (ErasureCodeInterface.h:346): widen
        a cheapest-first candidate window until a plan exists."""
        ordered = sorted(available, key=lambda s: (available[s], s))
        for cut in range(self.k, len(ordered)):
            try:
                plan = self.minimum_to_decode(
                    want_to_read, set(ordered[:cut])
                )
                return set(plan)
            except ValueError:
                continue
        return set(self.minimum_to_decode(want_to_read, set(ordered)))

    # -- shared shard plumbing ----------------------------------------
    def _shard_list_xp(self, data: dict[int, jax.Array]):
        """(k shard arrays in index order, array namespace); absent
        shards are zero (the shared zero-buffer convention of the
        reference's encode_chunks). All-numpy inputs stay on the host
        so small ops can take the host GF path without a device
        round-trip; anything already on device fills with device
        zeros."""
        sample = next(iter(data.values()))
        xp = (
            np
            if all(isinstance(v, np.ndarray) for v in data.values())
            else jnp
        )
        return [
            data.get(i, xp.zeros_like(sample)) for i in range(self.k)
        ], xp

    def _shard_list(self, data: dict[int, jax.Array]) -> list:
        return self._shard_list_xp(data)[0]

    def _stack_data(self, data: dict[int, jax.Array]) -> jax.Array:
        """dict -> [..., k, N] via _shard_list_xp's zero-fill rule."""
        shards, xp = self._shard_list_xp(data)
        return xp.stack(shards, axis=-2)

    # -- byte-level wrappers (legacy-interface parity) ----------------
    def encode_prepare(self, data: bytes) -> jax.Array:
        """Pad + split a flat byte string into [k, chunk_size] on device.

        The encode() front half of ErasureCode.cc (zero-pad the tail so
        every chunk is full and aligned — ZERO_PADDING_EXPECTED).
        """
        cs = self.get_chunk_size(len(data))
        buf = np.zeros(self.k * cs, dtype=np.uint8)
        buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
        return jnp.asarray(buf.reshape(self.k, cs))

    def encode(self, data: bytes) -> dict[int, bytes]:
        """Whole-object encode returning all k+m chunks as bytes
        (the legacy encode() contract, ErasureCodeInterface.h:403)."""
        shards = self.encode_prepare(data)
        data_map = {i: shards[i] for i in range(self.k)}
        parity = self.encode_chunks(data_map)
        out = {}
        for i in range(self.k):
            out[i] = bytes(np.asarray(shards[i]))
        for i, p in parity.items():
            out[i] = bytes(np.asarray(p))
        return out

    def decode(
        self, want_to_read: set[int], chunks: dict[int, bytes]
    ) -> dict[int, bytes]:
        """Byte-level decode wrapper (ErasureCodeInterface.h:539)."""
        arrs = {
            i: jnp.asarray(np.frombuffer(c, dtype=np.uint8))
            for i, c in chunks.items()
        }
        out = self.decode_chunks(want_to_read, arrs)
        return {i: bytes(np.asarray(a)) for i, a in out.items()}
