"""Generic GF(2^8) matrix erasure codec on the bit-plane MXU engine.

The shared engine under every matrix-style family (jerasure
reed_sol_van/reed_sol_r6_op/cauchy_*, ISA-L RS) — the role
``jerasure_matrix_encode`` / ``ec_encode_data`` play in the reference,
re-designed so one jitted dispatch encodes an arbitrary stripe batch.

Decode matrices are computed host-side (tiny <=32x32 inversions) and
cached in an LRU keyed by the erasure signature — the TableCache
precedent (isa/ErasureCodeIsaTableCache.cc; SURVEY.md section 7
"Hard parts").
"""

from __future__ import annotations

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf import (
    decode_matrix,
    gf_matrix_to_bitmatrix,
)
from ceph_tpu.ops import xor_schedule
from ceph_tpu.ops.bitplane import gf_encode_bitplane, xor_bytes

from .base import ErasureCodeBase
from .interface import Flag


@jax.jit
def _apply_bitmatrix(bmat: jax.Array, shards: jax.Array) -> jax.Array:
    return gf_encode_bitplane(bmat, shards)


@functools.lru_cache(maxsize=1)
def _dispatch_counters():
    """Kernel-path visibility: which engine served each bit-matrix
    application (Pallas MXU kernel / XLA einsum / host GF tables) and
    how often an enabled Pallas path had to fall back on an
    untileable shape. Served by ``perf dump`` as ``ec_dispatch``."""
    from ceph_tpu.utils.perf_counters import (
        PerfCountersBuilder,
        perf_collection,
    )

    b = PerfCountersBuilder(perf_collection, "ec_dispatch")
    for op in ("encode", "decode", "delta"):
        b.add_u64_counter(f"dcn_{op}", f"{op}s fanned across DCN hosts")
        b.add_u64_counter(f"mesh_{op}", f"{op}s sharded over the mesh")
        b.add_u64_counter(f"pallas_{op}", f"{op}s served by the Pallas kernel")
        b.add_u64_counter(f"einsum_{op}", f"{op}s served by the einsum engine")
        b.add_u64_counter(f"host_{op}", f"{op}s served by host GF tables")
        b.add_u64_counter(
            f"sched_{op}",
            f"{op}s served by the schedule-native XOR kernel "
            "(sparse packet bit-matrices)",
        )
    b.add_u64_counter(
        "fused_encode",
        "encodes served by the fused encode+checksum kernel (parity "
        "AND per-block crc32c in one device pass)",
    )
    b.add_u64_counter(
        "fused_fallback",
        "fused encode+csum requests the kernel could not serve "
        "(untileable shape / non-TPU without interpret) — parity "
        "encoded normally, csums fell back to the host tier",
    )
    b.add_u64_counter(
        "sched_rejected_density",
        "sched-eligible dispatches that fell back to the MXU engine "
        "because even the post-CSE schedule stayed over the op-count "
        "gate (dense matrix); counted once per dispatch at the "
        "terminal schedule probe",
    )
    b.add_u64_counter(
        "sched_rejected_shape",
        "sched-eligible dispatches that fell back because no "
        "schedule kernel form could tile the shape (packet axis not "
        "lane-tileable / VMEM-oversized shard blocks)",
    )
    b.add_u64_counter(
        "pallas_fallback",
        "dispatches where Pallas was enabled on TPU but the shape "
        "could not tile (chunk axis % LANE_TILE != 0)",
    )
    b.add_u64_counter(
        "mesh_fallback",
        "dispatches where a mesh was installed but neither the stripe "
        "batch nor the lane axis divided dp (the shard axis always "
        "zero-pads to sp) and a single-chip route served the op",
    )
    b.add_u64_counter(
        "dcn_fallback",
        "dispatches where the DCN cluster failed mid-op (host death / "
        "timeout): the cluster is uninstalled, a single-host route "
        "serves the op, and the operator re-installs after repair",
    )
    return b.create_perf_counters()


def dev_bmat(
    cache: "DecodeTableCache", key: tuple, np_mat: np.ndarray,
    traced: bool,
) -> jax.Array:
    """Device copy of a host matrix. Under a trace the copy is a
    TRACE-LOCAL constant — caching an array created while tracing
    stores that trace's tracer and poisons every later call with the
    same key (UnexpectedTracerError; the round-3 lru_cache lesson,
    re-hit by the traced CLAY repair's inner decode). Eager callers
    get an LRU-cached concrete upload."""
    if traced:
        return jnp.asarray(np_mat)
    return cache.get(("dev",) + key, lambda: jnp.asarray(np_mat))


class DecodeTableCache:
    """LRU of device bit-matrices keyed by (present-shards, wanted-shards).

    The ISA plugin caches inverted decode tables because inversion is the
    sequential hot-path cost under churny erasure patterns
    (ErasureCodeIsaTableCache.cc, 327 LoC). Same idea; the cached value
    here is the expanded GF(2) matrix, host-side and on device (both
    forms: the Pallas kernel folds the host copy, einsum uses the
    device copy).
    """

    def __init__(self, maxsize: int = 256) -> None:
        self.maxsize = maxsize
        # Values are whatever the builder returns — (np bitmatrix,
        # device bitmatrix) pairs here; codecs may cache richer tuples.
        self._cache: OrderedDict[tuple, object] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple, build):
        if key in self._cache:
            self._cache.move_to_end(key)
            self.hits += 1
            return self._cache[key]
        self.misses += 1
        val = build()
        self._cache[key] = val
        if len(self._cache) > self.maxsize:
            self._cache.popitem(last=False)
        return val


class BitplaneDispatchMixin:
    """The device-dispatch engine shared by every bit-plane codec
    family: route one bitmatrix application to host GF tables (small
    numpy inputs), the mesh (when installed), the Pallas MXU kernel
    (on TPU, tileable shapes), or the XLA einsum engine — with every
    route visible in the ``ec_dispatch`` counters. The byte matrix
    families (jerasure RS/Cauchy, ISA) and the packet bit-matrix
    families (liberation/blaum_roth/liber8tion) both dispatch here;
    the reference splits these across jerasure_matrix_encode vs
    jerasure_schedule_encode, but on TPU they are one engine."""

    @staticmethod
    def _host_sized(*arrays) -> bool:
        """Small host-side inputs skip device dispatch entirely: below
        the threshold, tunnel/launch latency dwarfs the GF math."""
        from ceph_tpu.utils import config

        limit = config.get("ec_host_dispatch_bytes")
        return (
            limit > 0
            and all(isinstance(a, np.ndarray) for a in arrays)
            and sum(a.nbytes for a in arrays) <= limit
        )

    @staticmethod
    def _active_mesh():
        """The configured dispatch mesh, or None. Mesh routing wins
        over every single-chip path (including the host small-op
        shortcut) — when the operator installs a mesh, shard fan-out
        IS the system's dispatch, the way the reference's sub-op
        fan-out is its distributed backend (SURVEY.md §5.8)."""
        from ceph_tpu.utils import config

        if not config.get("ec_use_mesh"):
            return None
        from ceph_tpu.parallel import dispatch as mesh_dispatch

        return mesh_dispatch.get_mesh()

    def _mesh_routable(self, stacked) -> bool:
        return self._mesh_routable_shape(stacked.shape)

    def _mesh_routable_shape(self, shape) -> bool:
        """True when a mesh is active AND this dispatch shape will
        actually ride it — the host small-op shortcut stays available
        for shapes that would only hit mesh_fallback (device launch
        latency dwarfs the GF math there, same as without a mesh).
        ``shape`` is the stacked [..., n_shards, chunk] form; the
        sched-shards route probes with its would-be stacked shape."""
        mesh = self._active_mesh()
        if mesh is None:
            return False
        from ceph_tpu.parallel import dispatch as mesh_dispatch

        c = shape[-2]
        flat_shape = (
            int(np.prod(shape[:-2], initial=1)),
            c,
            shape[-1],
        )
        return mesh_dispatch.mesh_supported(mesh, (0, c * 8), flat_shape)

    @staticmethod
    def _stack(vals: list):
        """Stack shard buffers along the shard axis, KEEPING host
        arrays host-side (np): the DCN route ships bytes, and the
        host GF shortcut reads them in place — converting to device
        arrays here would bar both. One policy for every family."""
        if all(isinstance(v, np.ndarray) for v in vals):
            return np.stack(vals, axis=-2)
        return jnp.stack(vals, axis=-2)

    def _dcn_routable(self, stacked) -> bool:
        return self._dcn_routable_shape(
            stacked.shape, isinstance(stacked, np.ndarray)
        )

    def _dcn_routable_shape(self, shape, host_staged: bool) -> bool:
        """True when a DCN cluster is installed AND this host-staged
        shape will ride it — like _mesh_routable, this must outrank
        the host small-op shortcut, or default-config dispatches
        (< ec_host_dispatch_bytes) would silently never leave the
        host."""
        from ceph_tpu.parallel import dispatch as mesh_dispatch

        dcn = mesh_dispatch.get_dcn()
        if dcn is None or not host_staged:
            return False
        c = shape[-2]
        flat_shape = (
            int(np.prod(shape[:-2], initial=1)),
            c,
            shape[-1],
        )
        return dcn.supported((0, c * 8), flat_shape)

    def _dispatch_bitmatrix(
        self,
        bmat_np: np.ndarray,
        bmat_dev: jax.Array,
        stacked: jax.Array,
        op: str,
    ) -> jax.Array:
        """Route one device bit-matrix application. Decode and delta
        ride the same fused kernel as encode — the kernel is generic
        over [R*8, C*8] bitmatrices, so reconstruct is a first-class
        on-chip path (the reference treats decode as equally hot:
        osd/ECUtil.cc:648-729, isa/ErasureCodeIsa.cc:504-516)."""
        from ceph_tpu.ops import pallas_encode as pe
        from ceph_tpu.utils import config

        # DCN outranks every single-host route: with a multi-host
        # cluster installed, host-staged dispatches fan out across OS
        # processes (the AsyncMessenger sub-op fan-out over the data-
        # center network). Device-resident inputs stay on this chip —
        # shipping them through the control plane would force a sync.
        from ceph_tpu.parallel import dispatch as mesh_dispatch

        dcn = mesh_dispatch.get_dcn()
        if dcn is not None and isinstance(stacked, np.ndarray):
            flat = stacked.reshape((-1,) + stacked.shape[-2:])
            if dcn.supported(bmat_np.shape, flat.shape):
                try:
                    out = dcn.apply_bitmatrix(bmat_np, flat)
                    _dispatch_counters().inc(f"dcn_{op}")
                    return out.reshape(
                        stacked.shape[:-2] + out.shape[-2:]
                    )
                except Exception as e:
                    # a dead/hung host must not wedge the data path:
                    # uninstall the cluster (every later op would pay
                    # the timeout again) and serve this op on a
                    # single-host route. The operator re-installs
                    # after repairing the cluster.
                    _dispatch_counters().inc("dcn_fallback")
                    mesh_dispatch.set_dcn(None)
                    from ceph_tpu.utils.log import get_logger

                    get_logger("ec-dcn").error(
                        "DCN dispatch failed; cluster uninstalled:",
                        type(e).__name__, str(e)[:200],
                    )
        mesh = self._active_mesh()
        if mesh is not None:
            flat = stacked.reshape((-1,) + stacked.shape[-2:])
            if mesh_dispatch.mesh_supported(
                mesh, bmat_np.shape, flat.shape
            ):
                _dispatch_counters().inc(f"mesh_{op}")
                out = mesh_dispatch.mesh_apply_bitmatrix(
                    mesh, bmat_dev, flat
                )
                return out.reshape(stacked.shape[:-2] + out.shape[-2:])
            _dispatch_counters().inc("mesh_fallback")
        if config.get("ec_use_pallas") and pe.on_tpu():
            if pe.supported((1,) + stacked.shape[-2:]):
                _dispatch_counters().inc(f"pallas_{op}")
                flat = stacked.reshape((-1,) + stacked.shape[-2:])
                out = pe.gf_encode_bitplane_pallas(bmat_np, flat)
                return out.reshape(stacked.shape[:-2] + out.shape[-2:])
            _dispatch_counters().inc("pallas_fallback")
        _dispatch_counters().inc(f"einsum_{op}")
        return _apply_bitmatrix(bmat_dev, stacked)

    def _sched_shards_route(
        self,
        mat01: np.ndarray,
        shards: list,
        w: int,
        op: str,
        count_reject: bool = False,
    ):
        """Shared schedule-engine shards dispatch for a 0/1 packet
        matrix (w packets per chunk; w=1 means whole-chunk byte
        rows). Builds the route's schedule — CSE-optimized multi-
        level program under ``ec_sched_opt`` (default), the pinned
        selection form otherwise — gates it on post-CSE op count /
        raw density respectively, and serves the op through the
        multi-operand schedule kernel: shard arrays in, shard arrays
        out, no stack relayout. Returns the output shard list, or
        None when any precondition fails (each of those keeps its
        existing route).

        ``count_reject`` marks the TERMINAL schedule probe for an op:
        only that site increments ``sched_rejected_density`` /
        ``sched_rejected_shape``, so one logical dispatch counts one
        rejection even when several kernel forms probe it. Rejections
        are only counted for ops the schedule engine would otherwise
        have owned — host-sized and mesh/DCN-routed shapes bail first
        (those routes outrank the schedule the same way they outrank
        Pallas)."""
        from ceph_tpu.utils import config

        if not config.get("ec_use_sched") or not xor_schedule.on_tpu():
            return None
        shape = shards[0].shape
        if any(s.shape != shape for s in shards[1:]):
            return None
        if self._host_sized(*shards):
            return None
        # mesh/DCN routing operates on the stacked form and outranks
        # single-chip paths; probe with the would-be stacked shape
        probe = shape[:-1] + (len(shards) * w, shape[-1] // w)
        if self._mesh_routable_shape(probe) or self._dcn_routable_shape(
            probe, all(isinstance(s, np.ndarray) for s in shards)
        ):
            return None
        sched = xor_schedule.routable_schedule(
            mat01, config.get("ec_sched_opt")
        )
        if sched is None:
            if count_reject:
                _dispatch_counters().inc("sched_rejected_density")
            return None
        n_slots = 0
        if isinstance(sched, xor_schedule.Schedule):
            n_slots = xor_schedule._linearize(sched)[1]
        if not xor_schedule.shards_supported(
            len(shards), xor_schedule._n_rows(sched) // w, w, shape,
            n_slots,
        ):
            if count_reject:
                _dispatch_counters().inc("sched_rejected_shape")
            return None
        _dispatch_counters().inc(f"sched_{op}")
        return xor_schedule.xor_schedule_apply_shards(sched, shards, w)

    def _try_sched_bytes(
        self, mat: np.ndarray, shards: list, op: str
    ):
        """w=1 schedule route for GF(2^8) BYTE matrices whose entries
        are all 0/1: over the subfield {0,1} each output chunk is a
        pure XOR of input chunks, so the packet engine applies with
        packet == chunk. This is how LRC xor-local-parity repair (a
        single all-ones decode row) and the xor plugin's parity ride
        the schedule engine. Generic GF coefficient rows never
        qualify and bail on the cheap max() probe with no counter —
        they are not schedule-eligible, not rejected. This is the
        byte codecs' terminal schedule probe, so rejections count."""
        mat = np.asarray(mat)
        if mat.size == 0 or int(mat.max()) > 1:
            return None
        return self._sched_shards_route(
            np.ascontiguousarray(mat, dtype=np.uint8), shards, 1, op,
            count_reject=True,
        )

    def _shards_host_route(self, shards: list, host_staged: bool) -> bool:
        """One gate for every per-shard dispatch site: small host-
        staged inputs take the host GF tables UNLESS a mesh/DCN wants
        the shape (those routes outrank the host shortcut — see
        _active_mesh)."""
        if not host_staged:
            return False
        shape = shards[0].shape[:-1] + (
            len(shards), shards[0].shape[-1]
        )
        return (
            not self._mesh_routable_shape(shape)
            and not self._dcn_routable_shape(shape, True)
            and self._host_sized(*shards)
        )

    def _dispatch_bitmatrix_shards(
        self,
        bmat_np: np.ndarray,
        bmat_dev: jax.Array,
        shards: list,
        op: str,
    ) -> list:
        """Per-shard-operand route: device inputs that fit the
        shards-form Pallas kernel skip the [.., C, N] stack entirely
        (the stack is a relayout copy that measured 3.5x the kernel's
        own cost on the LRC/SHEC bench geometry — the same finding
        that shaped the XOR-schedule engine's shards form,
        ops/xor_schedule.py). The zero-waste packing widened this
        route to any c <= pallas_encode.SHARDS_MAX_C: cauchy k=10
        encode and wide SHEC survivor sets now ride it, where the
        round-5 block-diagonal rule (s*c <= 16) forced them through
        the stacked path. DCN/mesh routes and the einsum fallback
        still take the stacked tensor. Returns one array per output
        row-group (R = bitmatrix rows / 8)."""
        from ceph_tpu.ops import pallas_encode as pe
        from ceph_tpu.utils import config

        c = len(shards)
        shape = shards[0].shape[:-1] + (c, shards[0].shape[-1])
        host_staged = all(isinstance(v, np.ndarray) for v in shards)
        if (
            not host_staged
            and config.get("ec_use_pallas")
            and pe.on_tpu()
            and pe.shards_supported(c, shards[0].shape)
            and not self._mesh_routable_shape(shape)
            and not self._dcn_routable_shape(shape, host_staged)
        ):
            _dispatch_counters().inc(f"pallas_{op}")
            return pe.gf_encode_bitplane_pallas_shards(bmat_np, shards)
        stacked = self._stack(list(shards))
        out = self._dispatch_bitmatrix(bmat_np, bmat_dev, stacked, op)
        return [out[..., j, :] for j in range(out.shape[-2])]


class MatrixErasureCodec(BitplaneDispatchMixin, ErasureCodeBase):
    """Codec defined by a systematic (k+m) x k GF(2^8) generator matrix."""

    def __init__(self) -> None:
        super().__init__()
        self.generator: np.ndarray | None = None  # [(k+m), k] uint8
        self._encode_bmat: jax.Array | None = None
        self._tables = DecodeTableCache()
        self._host_tables = DecodeTableCache()  # byte matrices

    # Subclasses set self.k/self.m then call this from init().
    def _set_generator(self, generator: np.ndarray) -> None:
        self.generator = np.asarray(generator, dtype=np.uint8)
        assert self.generator.shape == (self.k + self.m, self.k)
        self._encode_bmat_np = gf_matrix_to_bitmatrix(
            self.generator[self.k :, :]
        )
        self._encode_bmat = jnp.asarray(self._encode_bmat_np)

    def get_flags(self) -> Flag:
        return (
            Flag.OPTIMIZED_SUPPORTED
            | Flag.PARITY_DELTA_OPTIMIZATION
            | Flag.ZERO_INPUT_ZERO_OUTPUT
            | Flag.ZERO_PADDING_EXPECTED
            | Flag.PARTIAL_READ_OPTIMIZATION
            | Flag.PARTIAL_WRITE_OPTIMIZATION
        )

    # -- encode -------------------------------------------------------
    def encode_chunks(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        shards, xp = self._shard_list_xp(data)
        parity = self._encode_shards(shards, xp)
        return {self.k + i: parity[i] for i in range(self.m)}

    def encode_chunks_with_csums(
        self, data: dict[int, jax.Array], csum_block: int
    ):
        """Fused encode+checksum dispatch: (parity dict, csums) where
        ``csums`` is ``[..., k+m, nblocks]`` uint32 ZERO-INIT per-block
        crc32c (row i = shard i; seed conversion is a constant XOR,
        checksum.crc32c.crc32c_seed_shift). Returns ``(None, None)``
        when no fused kernel route can serve the shape — callers then
        encode normally and keep their host csum fallback. The fused
        route runs on TPU, or off-TPU in Pallas interpreter mode when
        ``ec_fused_csum_interpret`` is set (tests/CI)."""
        from ceph_tpu.ops import pallas_encode as pe
        from ceph_tpu.utils import config

        if not (
            config.get("ec_fused_csum") and config.get("ec_use_pallas")
        ):
            return None, None
        interpret = None
        if not pe.on_tpu():
            if not config.get("ec_fused_csum_interpret"):
                return None, None
            interpret = True
        shards, _xp = self._shard_list_xp(data)
        c = len(shards)
        shape = shards[0].shape[:-1] + (c, shards[0].shape[-1])
        if self._mesh_routable_shape(shape) or self._dcn_routable_shape(
            shape, all(isinstance(v, np.ndarray) for v in shards)
        ):
            return None, None  # multi-chip routes own those shapes
        if pe.fused_csum_shards_supported(
            c, shards[0].shape, csum_block
        ) and not all(isinstance(v, np.ndarray) for v in shards):
            # device-resident per-shard inputs skip the stack relayout
            _dispatch_counters().inc("fused_encode")
            parity, csums = pe.gf_encode_csum_bitplane_pallas_shards(
                self._encode_bmat_np, shards, csum_block,
                interpret=interpret,
            )
            return (
                {self.k + j: parity[j] for j in range(self.m)},
                csums,
            )
        stacked_shape = (
            (int(np.prod(shards[0].shape[:-1], initial=1)),)
            + (c, shards[0].shape[-1])
        )
        if not pe.fused_csum_supported(stacked_shape, csum_block):
            _dispatch_counters().inc("fused_fallback")
            return None, None
        _dispatch_counters().inc("fused_encode")
        stacked = self._stack(list(shards))
        lead = stacked.shape[:-2]
        flat = stacked.reshape(stacked_shape)
        parity, csums = pe.gf_encode_csum_bitplane_pallas(
            self._encode_bmat_np, jnp.asarray(flat), csum_block,
            interpret=interpret,
        )
        n = shards[0].shape[-1]
        parity = parity.reshape(lead + (self.m, n))
        csums = csums.reshape(lead + (c + self.m, n // csum_block))
        return (
            {self.k + j: parity[..., j, :] for j in range(self.m)},
            csums,
        )

    def _encode_shards(self, shards: list, xp) -> list:
        """Dispatch the parity matmul: host GF tables for small numpy
        inputs, the schedule engine for 0/1 parity rows (the xor
        plugin / LRC xor-local layers), the shards-form Pallas MXU
        kernel on TPU for per-shard device arrays, the stacked routes
        otherwise."""
        if self._shards_host_route(shards, xp is np):
            from ceph_tpu.gf import gf_apply_bytes_host

            _dispatch_counters().inc("host_encode")
            out = gf_apply_bytes_host(
                self.generator[self.k :, :], np.stack(shards, axis=-2)
            )
            return [out[..., j, :] for j in range(self.m)]
        outs = self._try_sched_bytes(
            self.generator[self.k :, :], shards, "encode"
        )
        if outs is not None:
            return outs
        return self._dispatch_bitmatrix_shards(
            self._encode_bmat_np, self._encode_bmat, shards, "encode"
        )

    # -- decode -------------------------------------------------------
    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        present = sorted(chunks)
        # Only reconstruct what is actually missing: wanted-but-present
        # shards pass through, keeping decode tables (and the LRU keys)
        # erasure-pattern-minimal.
        want = sorted(w for w in want_to_read if w not in chunks)
        if not want:
            return {w: chunks[w] for w in want_to_read}
        key = (tuple(present), tuple(want))
        shards = [chunks[i] for i in present]
        host_staged = all(isinstance(v, np.ndarray) for v in shards)
        if self._shards_host_route(shards, host_staged):
            from ceph_tpu.gf import gf_apply_bytes_host

            _dispatch_counters().inc("host_decode")
            mat = self._host_tables.get(
                key, lambda: self._build_decode_bytes(present, want)
            )
            out = gf_apply_bytes_host(mat, np.stack(shards, axis=-2))
            outs = [out[..., j, :] for j in range(len(want))]
        else:
            # 0/1 decode rows (XOR-parity local groups: the common
            # LRC local repair) ride the schedule engine as w=1
            # whole-chunk packets — _build_decode_bytes is the same
            # host matrix the host route caches, so the probe shares
            # its table
            mat = self._host_tables.get(
                key, lambda: self._build_decode_bytes(present, want)
            )
            outs = self._try_sched_bytes(mat, shards, "decode")
            if outs is None:
                bmat_np = self._tables.get(
                    key, lambda: self._build_decode_bmat(present, want)
                )
                traced = any(
                    isinstance(v, jax.core.Tracer) for v in shards
                )
                outs = self._dispatch_bitmatrix_shards(
                    bmat_np,
                    dev_bmat(self._tables, key, bmat_np, traced),
                    shards, "decode",
                )
        result = {w: chunks[w] for w in want_to_read if w in chunks}
        for idx, w in enumerate(want):
            result[w] = outs[idx]
        return result

    def _build_decode_bytes(
        self, present: list[int], want: list[int]
    ) -> np.ndarray:
        """Byte-matrix rows producing each wanted shard from the
        present shards. Data shards come from the inverted-submatrix
        rows; wanted parity shards are re-encoded as G_parity_row @
        (decode rows) — the decode-of-data + re-encode-of-parity split
        of shard_extent_map_t::decode (osd/ECUtil.cc:648-729)."""
        from ceph_tpu.gf import gf_matmul_np

        d = decode_matrix(self.generator, self.k, present)  # [k, len(present)]
        rows = []
        for w in want:
            if w < self.k:
                rows.append(d[w, :])
            else:
                rows.append(gf_matmul_np(self.generator[w : w + 1, :], d)[0])
        return np.stack(rows)

    def _build_decode_bmat(
        self, present: list[int], want: list[int]
    ) -> np.ndarray:
        """HOST bitmatrix only — the device copy goes through
        dev_bmat so a trace never caches its own tracer."""
        return gf_matrix_to_bitmatrix(
            self._build_decode_bytes(present, want)
        )

    # -- parity delta (RMW) -------------------------------------------
    def encode_delta(
        self, old_data: jax.Array, new_data: jax.Array
    ) -> jax.Array:
        return xor_bytes(old_data, new_data)

    def apply_delta(
        self,
        delta: dict[int, jax.Array],
        parity: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        """parity'_j = parity_j XOR sum_i G[j, i] * delta_i.

        The matrix_apply_delta analog (ErasureCodeJerasure.h:110-119):
        one small matmul over just the changed columns.
        """
        cols = sorted(delta)
        shards = [delta[c] for c in cols]
        host_staged = all(isinstance(v, np.ndarray) for v in shards)
        if self._shards_host_route(shards, host_staged):
            from ceph_tpu.gf import gf_apply_bytes_host

            _dispatch_counters().inc("host_delta")
            contrib = gf_apply_bytes_host(
                self.generator[self.k :, cols],
                np.stack(shards, axis=-2),
            )
            return {
                pid: np.bitwise_xor(
                    np.asarray(p), contrib[..., pid - self.k, :]
                )
                for pid, p in parity.items()
            }

        key = ("delta", tuple(cols))
        # 0/1 delta columns (xor plugin / LRC xor-local layers): the
        # parity-delta contribution is a pure XOR program — the
        # schedule engine's w=1 form
        contribs = self._try_sched_bytes(
            self.generator[self.k :, cols], shards, "delta"
        )
        if contribs is None:
            bmat_np = self._tables.get(
                key,
                lambda: gf_matrix_to_bitmatrix(
                    self.generator[self.k :, cols]
                ),
            )
            traced = any(
                isinstance(v, jax.core.Tracer) for v in shards
            )
            contribs = self._dispatch_bitmatrix_shards(
                bmat_np,
                dev_bmat(self._tables, key, bmat_np, traced),
                shards, "delta",
            )
        return {
            pid: xor_bytes(p, contribs[pid - self.k])
            for pid, p in parity.items()
        }
