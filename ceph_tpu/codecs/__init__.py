"""Erasure-code families behind one codec protocol.

The TPU-native equivalent of the reference's plugin subsystem
(src/erasure-code/ — SURVEY.md section 2.1): a registry of codec
factories (``registry``), the abstract contract (``interface``), shared
default behavior (``base``), and the code families:

- ``jerasure``: reed_sol_van, reed_sol_r6_op, cauchy_orig, cauchy_good,
  liberation, blaum_roth, liber8tion
- ``isa``: Reed-Solomon Vandermonde + Cauchy with decode-table cache
- ``lrc``: locally repairable layered codes
- ``shec``: shingled erasure code
- ``clay``: coupled-layer MSR regenerating code
- ``xor``: single-parity XOR (Azure-LRC-style local parity; the
  schedule-engine fast path for LRC ``local_parity=xor`` layers)
"""

from .interface import (  # noqa: F401
    ErasureCodec,
    ErasureCodeProfile,
    Flag,
    SubChunkPlan,
)
from .registry import (  # noqa: F401
    ErasureCodePluginRegistry,
    registry,
    create_codec,
)

# Register in-tree plugins (the analog of osd_erasure_code_plugins
# preload — global.yaml.in:2638).
from . import jerasure as _jerasure  # noqa: E402,F401
from . import isa as _isa  # noqa: E402,F401
from . import lrc as _lrc  # noqa: E402,F401
from . import shec as _shec  # noqa: E402,F401
from . import clay as _clay  # noqa: E402,F401
from . import xor_codec as _xor  # noqa: E402,F401
