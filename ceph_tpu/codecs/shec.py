"""Shingled Erasure Code (SHEC) — the shec plugin.

Behavioral mirror of src/erasure-code/shec/ErasureCodeShec.{h,cc}
(Fujitsu): parameters (k, m, c) where c is the "durability" — every
data chunk is covered by c parity chunks, but each parity only covers a
*shingle* (circular window) of the data, so single-failure recovery
reads fewer chunks than k. Non-MDS by design: recoverability of a given
erasure pattern is decided by a determinant search over parity subsets
(shec_make_decoding_matrix, ErasureCodeShec.cc:745-973), whose result —
a minimal invertible reconstruction system — is cached per
(want, avails) signature (the ShecTableCache analog).

Technique ``multiple`` splits (m, c) into two shingle bands (m1, c1) +
(m2, c2) chosen to minimize expected single-failure recovery reads
(shec_calc_recovery_efficiency1); ``single`` keeps one band.

The coding matrix is jerasure's Vandermonde RS coding matrix with the
out-of-shingle entries zeroed (shec_reedsolomon_coding_matrix,
ErasureCodeShec.cc:675-742). Encode/decode bulk math rides the same
bit-plane MXU engine as the other matrix codes.
"""

from __future__ import annotations

import jax
import numpy as np

from ceph_tpu import PLUGIN_ABI_VERSION
from ceph_tpu.gf import (
    gf_invert_matrix,
    gf_matmul_np,
    gf_matrix_to_bitmatrix,
    vandermonde_rs_matrix,
)

from .base import to_int
from .interface import ErasureCodeProfile, Flag, SubChunkPlan
from .matrix_codec import MatrixErasureCodec, dev_bmat
from .registry import registry


def _shingle_bands(k: int, m: int, c: int, single: bool) -> tuple[int, int, int, int]:
    """(m1, c1, m2, c2): the shingle-band split. ``multiple`` minimizes
    recovery efficiency r_e1 over valid splits (ErasureCodeShec.cc
    shec_reedsolomon_coding_matrix)."""
    if single:
        return 0, 0, m, c
    best = (None, None)
    min_r_e1 = 100.0
    for c1 in range(c // 2 + 1):
        for m1 in range(m + 1):
            c2, m2 = c - c1, m - m1
            if m1 < c1 or m2 < c2:
                continue
            if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
                continue
            if (m1 != 0 and c1 == 0) or (m2 != 0 and c2 == 0):
                continue
            r_e1 = _recovery_efficiency1(k, m1, m2, c1, c2)
            if min_r_e1 - r_e1 > np.finfo(float).eps and r_e1 < min_r_e1:
                min_r_e1 = r_e1
                best = (m1, c1)
    m1, c1 = best
    return m1, c1, m - m1, c - c1


def _recovery_efficiency1(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """Expected single-failure recovery read cost
    (shec_calc_recovery_efficiency1, ErasureCodeShec.cc)."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [100000000] * k
    r_e1 = 0.0
    for band_m, band_c, _row0 in ((m1, c1, 0), (m2, c2, m1)):
        for rr in range(band_m):
            start = ((rr * k) // band_m) % k
            end = (((rr + band_c) * k) // band_m) % k
            width = ((rr + band_c) * k) // band_m - (rr * k) // band_m
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc], width)
                cc = (cc + 1) % k
            r_e1 += width
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_coding_matrix(k: int, m: int, c: int, single: bool) -> np.ndarray:
    """[m, k] GF(2^8) coding matrix: Vandermonde RS rows with entries
    outside each row's shingle window zeroed."""
    m1, c1, m2, c2 = _shingle_bands(k, m, c, single)
    mat = vandermonde_rs_matrix(k, m)[k:, :].copy()
    for band_m, band_c, row0 in ((m1, c1, 0), (m2, c2, m1)):
        for rr in range(band_m):
            end = ((rr * k) // band_m) % k
            start = (((rr + band_c) * k) // band_m) % k
            cc = start
            while cc != end:
                mat[row0 + rr, cc] = 0
                cc = (cc + 1) % k
    return mat


class ShecCodec(MatrixErasureCodec):
    """shec ReedSolomonVandermonde (single|multiple)."""

    technique = "multiple"
    MAX_K = 12       # ErasureCodeShec.cc parse: k <= 12
    MAX_KM = 20      # k + m <= 20

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        t = profile.get("technique", "multiple")
        if t not in ("single", "multiple"):
            raise ValueError(
                f"technique={t} is not a valid coding technique"
            )
        self.technique = t
        has_any = any(x in profile for x in ("k", "m", "c"))
        has_all = all(x in profile for x in ("k", "m", "c"))
        if has_any and not has_all:
            raise ValueError("(k, m, c) must all be chosen or none")
        self.k = to_int("k", profile, 4)
        self.m = to_int("m", profile, 3)
        self.c = to_int("c", profile, 2)
        self.w = to_int("w", profile, 8)
        if self.k <= 0 or self.m <= 0 or self.c <= 0:
            raise ValueError(
                f"k={self.k}, m={self.m}, c={self.c} must be positive"
            )
        if self.m < self.c:
            raise ValueError(f"c={self.c} must be <= m={self.m}")
        if self.k > self.MAX_K:
            raise ValueError(f"k={self.k} must be <= {self.MAX_K}")
        if self.k + self.m > self.MAX_KM:
            raise ValueError(f"k+m={self.k + self.m} must be <= {self.MAX_KM}")
        if self.k < self.m:
            raise ValueError(f"m={self.m} must be <= k={self.k}")
        if self.w not in (8, 16, 32):
            self.w = 8  # the reference warns and falls back to default
        if self.w != 8:
            # TPU engine is GF(2^8); reference default is also 8.
            raise ValueError("shec on TPU supports w=8 only")
        self.coding = shec_coding_matrix(
            self.k, self.m, self.c, self.technique == "single"
        )
        full = np.zeros((self.k + self.m, self.k), dtype=np.uint8)
        full[: self.k] = np.eye(self.k, dtype=np.uint8)
        full[self.k :] = self.coding
        self._set_generator(full)

    def get_flags(self) -> Flag:
        # ErasureCodeShec.h get_supported_optimizations
        return (
            Flag.PARTIAL_READ_OPTIMIZATION
            | Flag.PARTIAL_WRITE_OPTIMIZATION
            | Flag.ZERO_INPUT_ZERO_OUTPUT
            | Flag.PARITY_DELTA_OPTIMIZATION
        )

    # -- the shingled decoding search ---------------------------------
    def _search(
        self, want: list[int], avails: list[int]
    ) -> tuple[list[int], list[int], np.ndarray | None, list[int]]:
        """Port of shec_make_decoding_matrix's subset search.

        Returns (dm_row, dm_column, inv, minimum): chunk ids whose
        values feed the solve, the data columns treated as unknowns,
        the inverted system (None when nothing is erased), and the
        minimum chunk-id set to read. Raises ValueError when no parity
        subset recovers the pattern.
        """
        k, m = self.k, self.m
        mat = self.coding
        want = list(want)
        # A wanted-but-missing parity needs its contributing data.
        for i in range(m):
            if want[k + i] and not avails[k + i]:
                for j in range(k):
                    if mat[i, j]:
                        want[j] = 1
        mindup, minp = k + 1, k + 1
        best: tuple | None = None
        for pp in range(1 << m):
            p = [i for i in range(m) if pp & (1 << i)]
            if len(p) > minp:
                continue
            if any(not avails[k + i] for i in p):
                continue
            tmprow = [0] * (k + m)
            tmpcol = [0] * k
            for i in range(k):
                if want[i] and not avails[i]:
                    tmpcol[i] = 1
            for i in p:
                tmprow[k + i] = 1
                for j in range(k):
                    if mat[i, j]:
                        tmpcol[j] = 1
                        if avails[j]:
                            tmprow[j] = 1
            dup_row = sum(tmprow)
            dup_col = sum(tmpcol)
            if dup_row != dup_col:
                continue
            dup = dup_row
            if dup == 0:
                mindup = 0
                best = ([], [], None, len(p))
                break
            if dup >= mindup:
                continue
            rows = [i for i in range(k + m) if tmprow[i]]
            cols = [j for j in range(k) if tmpcol[j]]
            sysmat = np.zeros((dup, dup), dtype=np.uint8)
            for ri, i in enumerate(rows):
                for ci, j in enumerate(cols):
                    sysmat[ri, ci] = (
                        1 if (i < k and i == j)
                        else (0 if i < k else mat[i - k, j])
                    )
            try:
                inv = gf_invert_matrix(sysmat)
            except ValueError:
                continue  # det == 0
            mindup = dup
            minp = len(p)
            best = (rows, cols, inv, len(p))
        if best is None:
            raise ValueError(
                f"cannot find recover matrix for want={want} avails={avails}"
            )
        rows, cols, inv, _ = best
        minimum = [0] * (k + m)
        for i in rows:
            minimum[i] = 1
        for i in range(k):
            if want[i] and avails[i]:
                minimum[i] = 1
        for i in range(m):
            if want[k + i] and avails[k + i] and not minimum[k + i]:
                if any(mat[i, j] and not want[j] for j in range(k)):
                    minimum[k + i] = 1
        return rows, cols, inv, [i for i in range(k + m) if minimum[i]]

    # -- interface -----------------------------------------------------
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> SubChunkPlan:
        if set(want_to_read) <= set(available):
            return {s: [(0, 1)] for s in want_to_read}
        n = self.k + self.m
        want = [1 if i in want_to_read else 0 for i in range(n)]
        avails = [1 if i in available else 0 for i in range(n)]
        *_, minimum = self._search(want, avails)
        return {s: [(0, 1)] for s in minimum}

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        k, m = self.k, self.m
        n = k + m
        missing = sorted(s for s in want_to_read if s not in chunks)
        if not missing:
            return {s: chunks[s] for s in want_to_read}
        key = ("shec", tuple(sorted(chunks)), tuple(missing))
        inputs, bmat_np = self._tables.get(
            key, lambda: self._build_reconstruction(set(chunks), missing)
        )
        # shards-form dispatch: the survivors feed the kernel as
        # per-shard operands (k+m <= 20 always fits the zero-waste
        # shards form), so shingled repair skips the [.., C, N] stack
        # relayout the round-5 path paid; the LRU keeps only HOST
        # matrices and the device copy goes through dev_bmat so a
        # traced decode never caches its own tracer.
        shard_list = [chunks[i] for i in inputs]
        traced = any(isinstance(v, jax.core.Tracer) for v in shard_list)
        outs = self._dispatch_bitmatrix_shards(
            bmat_np,
            dev_bmat(self._tables, key, bmat_np, traced),
            shard_list, "decode",
        )
        result = {s: chunks[s] for s in want_to_read if s in chunks}
        for idx, s in enumerate(missing):
            result[s] = outs[idx]
        return result

    def _build_reconstruction(
        self, available: set[int], missing: list[int]
    ) -> tuple[list[int], np.ndarray]:
        """One GF matrix mapping survivor chunks -> all missing wanted
        shards: erased data via the inverted shingle system, erased
        parity re-encoded by composition (shec_matrix_decode)."""
        k, m = self.k, self.m
        n = k + m
        want = [0] * n
        for s in missing:
            want[s] = 1
        avails = [1 if i in available else 0 for i in range(n)]
        rows, cols, inv, _minimum = self._search(want, avails)
        # Unknown data column cols[j] = sum_i inv[j, i] * chunk[rows[i]].
        # Inputs: the solve's rows plus only the available data columns
        # a wanted parity row actually references — stacking all
        # survivors would widen the dispatch and the cache key for
        # nothing (shingle locality is the point of SHEC).
        referenced: set[int] = set(rows)
        for s in missing:
            if s >= k:
                for j in range(k):
                    if self.coding[s - k, j] and avails[j]:
                        referenced.add(j)
        col_solution: dict[int, np.ndarray] = {}
        inputs = sorted(referenced)
        in_idx = {s: i for i, s in enumerate(inputs)}
        if inv is not None:
            for j, coljd in enumerate(cols):
                vec = np.zeros(len(inputs), dtype=np.uint8)
                for i, r in enumerate(rows):
                    vec[in_idx[r]] ^= inv[j, i]
                col_solution[coljd] = vec
        out_rows = []
        for s in missing:
            if s < k:
                out_rows.append(col_solution[s])
            else:
                # parity s: row over data columns, substituting solved
                # columns for erased data.
                vec = np.zeros(len(inputs), dtype=np.uint8)
                for j in range(k):
                    coeff = int(self.coding[s - k, j])
                    if not coeff:
                        continue
                    if avails[j]:
                        base = np.zeros(len(inputs), dtype=np.uint8)
                        base[in_idx[j]] = 1
                        contrib = base
                    else:
                        contrib = col_solution[j]
                    vec ^= gf_matmul_np(
                        np.array([[coeff]], dtype=np.uint8),
                        contrib[None, :],
                    )[0]
                out_rows.append(vec)
        return inputs, gf_matrix_to_bitmatrix(np.stack(out_rows))


registry.register("shec", ShecCodec, PLUGIN_ABI_VERSION)
