"""The ISA plugin equivalent: Reed-Solomon with matrix-type selection.

Mirrors isa/ErasureCodeIsa.{h,cc}: profile key ``technique`` chooses
``reed_sol_van`` (gf_gen_rs_matrix — MDS only inside the envelope
documented at isa/README:23-24, enforced here) or ``cauchy``
(gf_gen_cauchy1_matrix). Hard caps MAX_K=32 / MAX_M=32
(isa/ErasureCodeIsa.h:48-49). Decode tables are LRU-cached per erasure
signature (ErasureCodeIsaTableCache semantics — shared DecodeTableCache).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import PLUGIN_ABI_VERSION
from ceph_tpu.gf import isa_cauchy_matrix, isa_rs_matrix
from ceph_tpu.gf.matrices import MAX_K, MAX_M

from .base import to_int
from .interface import ErasureCodeProfile
from .matrix_codec import MatrixErasureCodec
from .registry import registry


def _vandermonde_envelope_ok(k: int, m: int) -> bool:
    """isa/README:23-24: RS-Vandermonde verified MDS up to (21,4)/(32,3)."""
    if m <= 1:
        return True
    if m == 2:
        return k <= 32
    if m == 3:
        return k <= 32
    if m == 4:
        return k <= 21
    return False


class ErasureCodeIsa(MatrixErasureCodec):
    DEFAULT_K = 7   # isa plugin defaults (k=7, m=3 upstream)
    DEFAULT_M = 3

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        technique = profile.get("technique", "reed_sol_van")
        if self.k < 1 or self.m < 1:
            raise ValueError(f"k={self.k}, m={self.m} must be >= 1")
        if self.k > MAX_K or self.m > MAX_M:
            raise ValueError(
                f"k={self.k} m={self.m} exceed ISA caps ({MAX_K},{MAX_M})"
            )
        if technique == "reed_sol_van":
            if not _vandermonde_envelope_ok(self.k, self.m):
                raise ValueError(
                    f"(k={self.k}, m={self.m}) outside the RS-Vandermonde "
                    "MDS envelope (max (21,4)/(32,3)); use technique=cauchy"
                )
            gen = isa_rs_matrix(self.k, self.m)
        elif technique == "cauchy":
            gen = isa_cauchy_matrix(self.k, self.m)
        else:
            raise ValueError(
                f"unknown isa technique {technique!r}; "
                "choose reed_sol_van or cauchy"
            )
        self._set_generator(np.asarray(gen))


registry.register("isa", ErasureCodeIsa, PLUGIN_ABI_VERSION)
