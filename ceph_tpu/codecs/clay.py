"""Coupled-Layer (CLAY) MSR regenerating code — the clay plugin.

Behavioral mirror of src/erasure-code/clay/ErasureCodeClay.{h,cc}
(IISc): parameters (k, m, d) with k+1 <= d <= k+m-1. Derived geometry
(ErasureCodeClay.cc:316-348): q = d-k+1, nu pads k+m to a multiple of q
(shortened zero chunks), t = (k+m+nu)/q, and every chunk consists of
``sub_chunk_no = q^t`` sub-chunks ("planes"). Nodes live on a q x t
grid; plane z has a base-q digit vector z_vec[t]; node (x, y) is a
"dot" in plane z when x == z_vec[y], else it pairs with node
(z_vec[y], y) in the companion plane z_sw (digit y swapped to x).

Stored ("coupled") values C and intermediate ("uncoupled") values U are
linked pairwise by an invertible 2x2 GF(2^8) transform — the reference
realizes it as an RS(2,2) pairwise-forward-transform codec (pft); here
it is explicit algebra: (U_hi, U_lo) = P @ (C_hi, C_lo) where "hi" is
the pair member with the larger x. Across nodes, each plane of U is a
codeword of an inner scalar MDS code (k+nu data, m parity — the mds
member, default jerasure reed_sol_van).

Encode = decode with all parity erased (ErasureCodeClay.cc:141-169).
Single-chunk repair reads only sub_chunk_no/q sub-chunks from each of d
helpers — the MSR property (repair*, ErasureCodeClay.cc:454-699).

TPU-first deltas from the reference:

- Planes of equal "intersection score" are independent; the per-plane
  inner-MDS decodes are batched into ONE device dispatch per score
  group (the plane axis becomes a batch dim of the bit-plane MXU
  kernel) instead of q^t sequential 4KB calls.
- Pair transforms are closed-form 2-coefficient GF combinations
  (host-cached), not recursive codec calls.
- ``is_repair`` is genuinely enabled (the reference currently disables
  it pending its new-EC refactor, ErasureCodeClay.cc:356-368; we
  implement the documented pre-refactor semantics).
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from ceph_tpu import PLUGIN_ABI_VERSION
from ceph_tpu.gf import vandermonde_rs_matrix
from ceph_tpu.gf.matrices import gf_invert_matrix, gf_matmul_np
from ceph_tpu.gf.tables import gf_mul_bytes

from .base import ErasureCodeBase, to_int
from .interface import ErasureCodeProfile, Flag, SubChunkPlan
from .registry import registry


def _pow_int(a: int, x: int) -> int:
    return a**x


@functools.lru_cache(maxsize=256)
def _mul_table_np(c: int) -> np.ndarray:
    """[256] uint8 host table for GF mul-by-constant ``c``. The cache
    holds NUMPY only — caching a device array built inside a jit
    trace would leak that trace's tracer into every later call
    (UnexpectedTracerError); jnp.asarray at the call site turns it
    into a per-trace constant instead."""
    return np.array(
        [gf_mul_bytes(c, np.array([v], np.uint8))[0] for v in range(256)],
        np.uint8,
    )


def _gf_mul_traced(c: int, x):
    """GF(2^8) multiply-by-constant as a shift/mask/xor chain (the
    carry-less "peasant" ladder): ~8 fused VPU ops. Replaces the
    256-entry ``jnp.take`` gather, which serializes on TPU — the
    gather formulation measured 0.08 GB/s through the whole CLAY
    repair; this chain is what makes the traced repair stream."""
    import jax.numpy as jnp

    if c == 0:
        return jnp.zeros_like(x)
    if c == 1:
        return x
    acc = None
    xt = x
    cc = c
    while cc:
        if cc & 1:
            acc = xt if acc is None else acc ^ xt
        cc >>= 1
        if cc:
            hi = (xt >> jnp.uint8(7)).astype(jnp.uint8)
            xt = ((xt << jnp.uint8(1)) ^ (hi * jnp.uint8(0x1D))).astype(
                jnp.uint8
            )
    return acc


def _gf_mul2(x):
    """x * 2 in GF(2^8)/0x11D: one shift step (3 VPU ops)."""
    import jax.numpy as jnp

    return (
        (x << jnp.uint8(1))
        ^ ((x >> jnp.uint8(7)) * jnp.uint8(0x1D))
    ).astype(jnp.uint8)


def _gf_div2(x):
    """x * inv(2) = x * 142: the inverse shift step."""
    import jax.numpy as jnp

    return (
        (x >> jnp.uint8(1))
        ^ ((x & jnp.uint8(1)) * jnp.uint8(0x8E))
    ).astype(jnp.uint8)


def _gf_mul_planes(cs: np.ndarray, x):
    """GF constant multiply with a PER-PLANE constant: ``x`` is
    [..., P, sc], ``cs`` [P] uint8 broadcast over the plane axis.
    The shift/xor ladder of _gf_mul_vec_traced, shaped for whole-
    helper-tensor transforms and truncated to the constants' actual
    bit length (the pair-transform coefficients are tiny)."""
    import jax.numpy as jnp

    cs = np.asarray(cs, np.uint8)
    nbits = max(int(v).bit_length() for v in cs) or 1
    c = jnp.asarray(cs).reshape(-1, 1)
    acc = jnp.zeros_like(x)
    xt = x
    for j in range(nbits):
        bit = ((c >> jnp.uint8(j)) & jnp.uint8(1)).astype(jnp.uint8)
        acc = acc ^ (xt * bit)
        if j < nbits - 1:
            xt = _gf_mul2(xt)
    return acc


def _gf_mul_vec_traced(cs: np.ndarray, x):
    """Per-row GF constant multiply: ``x`` [P, ...], ``cs`` [P] uint8.
    One 8-step shift/xor ladder over the WHOLE stack — this is the op
    that lets a plane-group's pair transforms run as a single fused
    dispatch instead of one kernel per (plane, node)."""
    import jax.numpy as jnp

    c = jnp.asarray(np.asarray(cs, np.uint8)).reshape(
        (-1,) + (1,) * (x.ndim - 1)
    )
    acc = jnp.zeros_like(x)
    xt = x
    for j in range(8):
        bit = ((c >> jnp.uint8(j)) & jnp.uint8(1)).astype(jnp.uint8)
        acc = acc ^ (xt * bit)
        if j < 7:
            hi = (xt >> jnp.uint8(7)).astype(jnp.uint8)
            xt = ((xt << jnp.uint8(1)) ^ (hi * jnp.uint8(0x1D))).astype(
                jnp.uint8
            )
    return acc


class ClayCodec(ErasureCodeBase):
    SCALAR_MDS = ("jerasure", "isa", "shec")

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        self.k = to_int("k", profile, 4)
        self.m = to_int("m", profile, 2)
        self.d = to_int("d", profile, self.k + self.m - 1)
        self.w = to_int("w", profile, 8)
        if self.k < 2 or self.m < 1:
            raise ValueError(f"k={self.k} must be >= 2 and m={self.m} >= 1")
        if not (self.k + 1 <= self.d <= self.k + self.m - 1):
            raise ValueError(
                f"value of d {self.d} must be within "
                f"[{self.k + 1},{self.k + self.m - 1}]"
            )
        scalar_mds = profile.get("scalar_mds") or "jerasure"
        self.scalar_mds = scalar_mds
        if scalar_mds not in self.SCALAR_MDS:
            raise ValueError(
                f"scalar_mds {scalar_mds!r} is not supported, use one of "
                f"{self.SCALAR_MDS}"
            )
        technique = profile.get("technique") or (
            "reed_sol_van" if scalar_mds in ("jerasure", "isa") else "single"
        )
        self.q = self.d - self.k + 1
        self.nu = (
            0
            if (self.k + self.m) % self.q == 0
            else self.q - (self.k + self.m) % self.q
        )
        if self.k + self.m + self.nu > 254:
            raise ValueError("k + m + nu must be <= 254")
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = _pow_int(self.q, self.t)
        mds_profile = {
            "k": str(self.k + self.nu),
            "m": str(self.m),
            "technique": technique,
            "w": "8",
        }
        if scalar_mds == "shec":
            mds_profile["c"] = "2"
        self.mds = registry.factory(scalar_mds, mds_profile)
        # Pairwise transform: G4 maps (C_hi, C_lo) -> (C_hi, C_lo,
        # U_hi, U_lo); any 2 of the 4 determine the rest (RS(2,2) MDS).
        self._g4 = vandermonde_rs_matrix(2, 2)  # [4, 2]
        self._pair_cache: dict[tuple, tuple[int, int]] = {}
        #: static kernel-repair plans keyed by (lost_node, aloof set):
        #: digit strides, member kinds, pair coefficients, score
        #: groups and B2 patch items — all host-side planning shared
        #: by every traced repair of the same erasure pattern (the
        #: device decode matrices ride mds._tables / dev_bmat).
        self._kernel_plans: dict[tuple, dict] = {}

    # -- geometry ------------------------------------------------------
    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, stripe_width: int) -> int:
        # Chunks must split into q^t sub-chunks, each lane-aligned
        # (the sub_chunk_no * k * scalar-alignment rule of
        # ErasureCodeClay.cc:95-101).
        from .base import CHUNK_ALIGN

        align = self.sub_chunk_no * CHUNK_ALIGN
        per = -(-stripe_width // self.k)
        return -(-per // align) * align

    def get_flags(self) -> Flag:
        flags = Flag.PARTIAL_READ_OPTIMIZATION | Flag.REQUIRE_SUB_CHUNKS
        if self.m == 1:
            flags |= Flag.PARTIAL_WRITE_OPTIMIZATION
        return flags

    # -- plane arithmetic ---------------------------------------------
    def _plane_vector(self, z: int) -> list[int]:
        vec = [0] * self.t
        for i in range(self.t):
            vec[self.t - 1 - i] = z % self.q
            z //= self.q
        return vec

    def _z_sw(self, z: int, x: int, y: int, z_vec: list[int]) -> int:
        return z + (x - z_vec[y]) * _pow_int(self.q, self.t - 1 - y)

    # -- pair algebra --------------------------------------------------
    def _pair_coeffs(self, known: tuple[int, int], want: int) -> tuple[int, int]:
        """v[want] = c0*v[known[0]] + c1*v[known[1]] in the 4-tuple
        (C_hi, C_lo, U_hi, U_lo)."""
        key = (known, want)
        if key not in self._pair_cache:
            msub = self._g4[list(known), :]  # [2, 2]
            inv = gf_invert_matrix(msub)
            row = gf_matmul_np(self._g4[want : want + 1, :], inv)[0]
            self._pair_cache[key] = (int(row[0]), int(row[1]))
        return self._pair_cache[key]

    def _pair_solve(
        self,
        known: tuple[int, int],
        a,
        b,
        want: int,
    ):
        c0, c1 = self._pair_coeffs(known, want)
        if isinstance(a, np.ndarray):
            return gf_mul_bytes(c0, a) ^ gf_mul_bytes(c1, b)
        return _gf_mul_traced(c0, a) ^ _gf_mul_traced(c1, b)

    def _pair_idx(self, x: int, x_other: int) -> tuple[int, int]:
        """(C index, U index) of the member with coordinate ``x`` in the
        canonical tuple: larger-x member is (0, 2), smaller is (1, 3)."""
        return (0, 2) if x > x_other else (1, 3)

    # -- repair planning (the MSR read-savings surface) ----------------
    def is_repair(self, want_to_read: set[int], available: set[int]) -> bool:
        """True when the fractional-read repair path applies: a single
        lost chunk, all other members of its x-group available, and at
        least d helpers (the documented semantics of
        ErasureCodeClay.cc:356-382 before the upstream disable)."""
        if set(want_to_read) <= set(available):
            return False
        if len(want_to_read) != 1:
            return False
        lost = next(iter(want_to_read))
        lost_node = self._to_node(lost)
        for x in range(self.q):
            node = (lost_node // self.q) * self.q + x
            if self.k <= node < self.k + self.nu:
                continue  # shortened (virtual) node — always "available"
            chunk = self._from_node(node)
            if chunk != lost and chunk not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> list[tuple[int, int]]:
        """(index, count) runs of the planes where the lost node is a
        dot: digit y_lost == x_lost (ErasureCodeClay.cc:422-436)."""
        y_lost, x_lost = lost_node // self.q, lost_node % self.q
        seq = _pow_int(self.q, self.t - 1 - y_lost)
        num_seq = _pow_int(self.q, y_lost)
        out = []
        index = x_lost * seq
        for _ in range(num_seq):
            out.append((index, seq))
            index += self.q * seq
        return out

    def get_repair_sub_chunk_count(self, want_to_read: set[int]) -> int:
        weights = [0] * self.t
        for node in want_to_read:
            weights[node // self.q] += 1
        remaining = 1
        for y in range(self.t):
            remaining *= self.q - weights[y]
        return self.sub_chunk_no - remaining

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> SubChunkPlan:
        if self.is_repair(want_to_read, available):
            return self._minimum_to_repair(want_to_read, available)
        return super().minimum_to_decode(want_to_read, available)

    def _minimum_to_repair(
        self, want_to_read: set[int], available: set[int]
    ) -> SubChunkPlan:
        lost = next(iter(want_to_read))
        lost_node = lost if lost < self.k else lost + self.nu
        sub_ind = self.get_repair_subchunks(lost_node)
        minimum: SubChunkPlan = {}
        # Same x-group members first (they are mandatory helpers).
        for j in range(self.q):
            node = (lost_node // self.q) * self.q + j
            if j != lost_node % self.q:
                if node < self.k:
                    minimum[node] = list(sub_ind)
                elif node >= self.k + self.nu:
                    minimum[node - self.nu] = list(sub_ind)
        for chunk in sorted(available):
            if len(minimum) >= self.d:
                break
            if chunk not in minimum and chunk != lost:
                minimum[chunk] = list(sub_ind)
        if len(minimum) != self.d:
            raise ValueError(
                f"cannot repair {lost}: need {self.d} helpers from "
                f"{sorted(available)}"
            )
        return minimum

    # -- node-id mapping (shortening) ---------------------------------
    def _to_node(self, chunk: int) -> int:
        return chunk if chunk < self.k else chunk + self.nu

    def _from_node(self, node: int) -> int:
        return node if node < self.k else node - self.nu

    # -- encode --------------------------------------------------------
    def encode_chunks(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        # encode = decode with all parity erased; see _is_traced for
        # the traced/host split rationale
        traced = self._is_traced(data.values())
        xp = jax.numpy if traced else np
        sample = xp.asarray(next(iter(data.values())))
        nbytes = sample.shape[-1]
        if nbytes % self.sub_chunk_no:
            raise ValueError(
                f"chunk bytes {nbytes} not divisible by sub_chunk_no "
                f"{self.sub_chunk_no}"
            )
        sc = nbytes // self.sub_chunk_no
        n = self.q * self.t
        shape = sample.shape[:-1] + (self.sub_chunk_no, sc)
        C = {}
        for i in range(self.k):
            arr = xp.asarray(data[i]) if i in data else None
            C[i] = (
                xp.zeros(shape, np.uint8)
                if arr is None
                else self._reshaped(arr, shape, xp)
            )
        for i in range(self.k, n):
            C[i] = xp.zeros(shape, np.uint8)
        erased = set(range(self.k + self.nu, n))
        self._decode_layered(erased, C, traced)
        return {
            self.k + j: jax.numpy.asarray(
                C[self.k + self.nu + j].reshape(sample.shape[:-1] + (nbytes,))
            )
            for j in range(self.m)
        }

    @staticmethod
    def _reshaped(arr, shape, xp):
        # astype always copies (even same-dtype), so the host path's
        # in-place mutation never aliases caller data
        return arr.reshape(shape).astype(np.uint8)

    # -- full decode ---------------------------------------------------
    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        missing = [s for s in want_to_read if s not in chunks]
        if not missing:
            return {s: chunks[s] for s in want_to_read}
        if len(chunks) < self.k:
            raise ValueError(
                f"cannot decode: {len(chunks)} < k={self.k} chunks"
            )
        traced = self._is_traced(chunks.values())
        xp = jax.numpy if traced else np
        sample = xp.asarray(next(iter(chunks.values())))
        nbytes = sample.shape[-1]
        if nbytes % self.sub_chunk_no:
            raise ValueError(
                f"chunk bytes {nbytes} not divisible by sub_chunk_no "
                f"{self.sub_chunk_no}"
            )
        sc = nbytes // self.sub_chunk_no
        n = self.q * self.t
        shape = sample.shape[:-1] + (self.sub_chunk_no, sc)
        C = {}
        erased = set()
        for chunk_id in range(self.k + self.m):
            node = self._to_node(chunk_id)
            if chunk_id in chunks:
                C[node] = self._reshaped(
                    xp.asarray(chunks[chunk_id]), shape, xp
                )
            else:
                C[node] = xp.zeros(shape, np.uint8)
                erased.add(node)
        for i in range(self.k, self.k + self.nu):
            C[i] = xp.zeros(shape, np.uint8)
        self._decode_layered(erased, C, traced)
        out = {s: chunks[s] for s in want_to_read if s in chunks}
        for s in missing:
            out[s] = jax.numpy.asarray(
                C[self._to_node(s)].reshape(sample.shape[:-1] + (nbytes,))
            )
        return out

    # -- the layered engine -------------------------------------------
    @staticmethod
    def _is_traced(values) -> bool:
        """True when any input is a jax tracer: the engines then
        build ONE functional device program (jit over a fixed erasure
        pattern). Eager callers keep the host path — an un-jitted run
        of the traced body would be hundreds of per-op device round
        trips."""
        return any(isinstance(v, jax.core.Tracer) for v in values)

    @staticmethod
    def _setz(arr, z: int, val, traced: bool):
        """arr[..., z, :] = val — in place (host) or functional."""
        if traced:
            return arr.at[..., z, :].set(val)
        arr[..., z, :] = val
        return arr

    def _decode_layered(
        self,
        erased_chunks: set[int],
        C: dict[int, np.ndarray],
        traced: bool = False,
    ) -> None:
        """Recover coupled values of ``erased_chunks`` (node ids) in
        ``C`` (decode_layered, ErasureCodeClay.cc:702-767). TRACE-
        GENERIC like repair: host numpy mutates in place; tracer
        inputs build one functional device program (jit over a fixed
        erasure pattern), which is what makes CLAY encode AND full
        decode usable on device — encode is decode with all parity
        erased."""
        q, t, n = self.q, self.t, self.q * self.t
        erased = set(erased_chunks)
        for i in range(self.k + self.nu, n):
            if len(erased) >= self.m:
                break
            erased.add(i)
        if len(erased) > self.m:
            raise ValueError(
                f"too many erasures {sorted(erased_chunks)} for m={self.m}"
            )
        shape = next(iter(C.values())).shape
        if traced:
            import jax.numpy as jnp

            U = {i: jnp.zeros(shape, np.uint8) for i in range(n)}
        else:
            U = {i: np.zeros(shape, np.uint8) for i in range(n)}

        # order[z] = number of erased nodes that are dots in plane z.
        order: dict[int, list[int]] = {}
        for z in range(self.sub_chunk_no):
            z_vec = self._plane_vector(z)
            sc_order = sum(1 for i in erased if i % q == z_vec[i // q])
            order.setdefault(sc_order, []).append(z)

        for iscore in sorted(order):
            planes = order[iscore]
            # Step a: uncoupled values of non-erased nodes, plane by
            # plane (pair reads touch companion planes of other groups,
            # already final).
            for z in planes:
                self._compute_uncoupled(erased, z, C, U, traced)
            # Step b: ONE batched inner-MDS decode across this score
            # group (TPU delta: the reference dispatches per plane).
            self._decode_uncoupled_batch(erased, planes, U, traced)
            # Step c: uncoupled -> coupled for erased nodes.
            for z in planes:
                z_vec = self._plane_vector(z)
                for node in sorted(erased):
                    x, y = node % q, node // q
                    node_sw = y * q + z_vec[y]
                    z_sw = self._z_sw(z, x, y, z_vec)
                    if z_vec[y] == x:  # dot: C = U
                        C[node] = self._setz(
                            C[node], z, U[node][..., z, :], traced
                        )
                    elif node_sw not in erased:
                        # recover_type1: C_xy from (C_sw, U_xy).
                        ci, ui = self._pair_idx(x, z_vec[y])
                        cj, _ = self._pair_idx(z_vec[y], x)
                        C[node] = self._setz(
                            C[node], z,
                            self._pair_solve(
                                (cj, ui),
                                C[node_sw][..., z_sw, :],
                                U[node][..., z, :],
                                ci,
                            ),
                            traced,
                        )
                    elif z_vec[y] < x:
                        # Both pair members erased: invert the full
                        # pair transform from (U_xy, U_sw).
                        u_xy = U[node][..., z, :]
                        u_sw = U[node_sw][..., z_sw, :]
                        C[node] = self._setz(
                            C[node], z,
                            self._pair_solve((2, 3), u_xy, u_sw, 0),
                            traced,
                        )
                        C[node_sw] = self._setz(
                            C[node_sw], z_sw,
                            self._pair_solve((2, 3), u_xy, u_sw, 1),
                            traced,
                        )

    def _compute_uncoupled(
        self,
        erased: set[int],
        z: int,
        C: dict[int, np.ndarray],
        U: dict[int, np.ndarray],
        traced: bool = False,
    ) -> None:
        """U values of non-erased nodes in plane z (decode_erasures,
        ErasureCodeClay.cc:769-796)."""
        q, t = self.q, self.t
        z_vec = self._plane_vector(z)
        for x in range(q):
            for y in range(t):
                node = q * y + x
                if node in erased:
                    continue
                node_sw = q * y + z_vec[y]
                z_sw = self._z_sw(z, x, y, z_vec)
                if z_vec[y] == x:
                    U[node] = self._setz(
                        U[node], z, C[node][..., z, :], traced
                    )
                elif z_vec[y] < x or node_sw in erased:
                    # Forward transform of the coupled pair fills the
                    # U of both members.
                    node_c, node_u = self._pair_idx(x, z_vec[y])
                    sw_c, sw_u = self._pair_idx(z_vec[y], x)
                    a = C[node][..., z, :]
                    b = C[node_sw][..., z_sw, :]
                    U[node] = self._setz(
                        U[node], z,
                        self._pair_solve((node_c, sw_c), a, b, node_u),
                        traced,
                    )
                    U[node_sw] = self._setz(
                        U[node_sw], z_sw,
                        self._pair_solve((node_c, sw_c), a, b, sw_u),
                        traced,
                    )

    def _decode_uncoupled_batch(
        self,
        erased: set[int],
        planes: list[int],
        U: dict[int, np.ndarray],
        traced: bool = False,
    ) -> None:
        """Inner-MDS decode of erased nodes' U over a batch of planes
        in one device dispatch (decode_uncoupled,
        ErasureCodeClay.cc:798-816)."""
        import jax.numpy as jnp

        n = self.q * self.t
        zsel = np.asarray(planes)
        known = {
            node: jnp.asarray(U[node][..., zsel, :])
            for node in range(n)
            if node not in erased
        }
        out = self.mds.decode_chunks(set(erased), known)
        for node in erased:
            if traced:
                U[node] = U[node].at[..., zsel, :].set(out[node])
            else:
                U[node][..., zsel, :] = np.asarray(out[node])

    # -- fractional repair ---------------------------------------------
    def repair(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        """Single-chunk repair from d helpers' repair sub-chunks
        (repair + repair_one_lost_chunk, ErasureCodeClay.cc:454-699).

        ``chunks`` maps helper chunk id -> the CONCATENATED repair
        sub-chunks selected by minimum_to_decode (in plane order).
        Returns the full lost chunk.

        The whole body is TRACE-GENERIC: numpy inputs run the host
        path with in-place updates; jax inputs (or tracers) build a
        single functional device program — ``jax.jit`` over a fixed
        erasure pattern turns repair into ONE dispatch, which is what
        makes batched MSR repair usable through a remote-device
        tunnel (round-3; the plane planning is all static Python
        either way).
        """
        if len(want_to_read) != 1 or len(chunks) != self.d:
            raise ValueError(
                f"repair wants 1 chunk from exactly d={self.d} helpers"
            )
        lost = next(iter(want_to_read))
        lost_node = self._to_node(lost)
        q, t, n = self.q, self.t, self.q * self.t

        # Traced ONLY under an enclosing jit (tracer inputs): the
        # functional device program then compiles to one dispatch.
        # Eager callers — including the read pipeline handing over
        # concrete jax arrays — keep the host path (coerce to numpy):
        # an UN-jitted run of the traced body would be hundreds of
        # per-op device round trips, the exact cost this split exists
        # to avoid. Mixed input dicts are normalized either way.
        traced = any(
            isinstance(v, jax.core.Tracer) for v in chunks.values()
        )
        if traced:
            import jax.numpy as jnp

            zeros = jnp.zeros
            chunks = {i: jnp.asarray(v) for i, v in chunks.items()}
        else:
            zeros = np.zeros
            chunks = {i: np.asarray(v) for i, v in chunks.items()}

        def setz(arr, z, val):
            return self._setz(arr, z, val, traced)

        repair_planes: list[int] = []
        for index, count in self.get_repair_subchunks(lost_node):
            repair_planes.extend(range(index, index + count))
        plane_ind = {z: i for i, z in enumerate(repair_planes)}
        r = len(repair_planes)

        sample = next(iter(chunks.values()))
        if sample.shape[-1] % r:
            raise ValueError(
                f"helper bytes {sample.shape[-1]} not divisible by "
                f"{r} repair planes"
            )
        sc = sample.shape[-1] // r
        lead = tuple(sample.shape[:-1])
        helper = {}
        aloof = set()
        for chunk_id in range(self.k + self.m):
            node = self._to_node(chunk_id)
            if chunk_id in chunks:
                helper[node] = (
                    chunks[chunk_id]
                    .reshape(lead + (r, sc))
                    .astype(np.uint8)
                )
            elif chunk_id != lost:
                aloof.add(node)
        for i in range(self.k, self.k + self.nu):
            helper[i] = zeros(lead + (r, sc), np.uint8)

        if traced:
            # Plane-blocked Pallas kernels: general d (aloof nodes
            # enter the per-group uncoupled solves as decoded known
            # rows) at any sub_chunk_no — HBM sees each helper byte
            # once in, each recovered byte once out.
            kout = self._repair_kernels(
                lost_node, helper, aloof, sc
            )
            if kout is not None:
                out = kout.reshape(lead + (self.sub_chunk_no * sc,))
                return {lost: out}
        if traced and not aloof:
            # d = k+m-1 (no aloof nodes): every repair plane has
            # intersection score 1 and the whole repair collapses to
            # three whole-tensor stages — the XLA fast path when the
            # kernels are gated off or the geometry does not fit (the
            # itemized stacked path below gathers hundreds of
            # per-plane slices and measured 20 GB/s against this
            # path's device rate).
            recovered = self._repair_fast(
                lost_node, helper, repair_planes, plane_ind
            )
            out = recovered.reshape(lead + (self.sub_chunk_no * sc,))
            return {lost: out}

        recovered = zeros(lead + (self.sub_chunk_no, sc), np.uint8)
        U = {i: zeros(lead + (self.sub_chunk_no, sc), np.uint8)
             for i in range(n)}

        # Erasures for the uncoupled decode: the lost node's whole
        # x-row plus the aloof nodes.
        erasures = {lost_node - lost_node % q + i for i in range(q)}
        erasures |= aloof
        if len(erasures) > self.m:
            raise ValueError(
                f"repair infeasible: {len(erasures)} uncoupled erasures "
                f"> m={self.m}"
            )

        # Order repair planes by intersection score w.r.t. the lost
        # node and aloof nodes.
        ordered: dict[int, list[int]] = {}
        for z in repair_planes:
            z_vec = self._plane_vector(z)
            o = sum(
                1
                for nd in ({lost_node} | aloof)
                if nd % q == z_vec[nd // q]
            )
            if o <= 0:
                raise AssertionError("repair plane with zero order")
            ordered.setdefault(o, []).append(z)

        for o in sorted(ordered):
            planes = ordered[o]
            uitems, citems = self._plan_repair_group(
                planes, erasures, aloof, lost_node
            )
            if traced:
                self._exec_uitems_stacked(uitems, helper, U, plane_ind)
            else:
                for (node, z, c0, c1, asrc, bsrc) in uitems:
                    a = self._item_slice(asrc, helper, U, plane_ind)
                    if c1 == 0 and c0 == 1:
                        U[node] = setz(U[node], z, a)
                        continue
                    b = self._item_slice(bsrc, helper, U, plane_ind)
                    U[node] = setz(
                        U[node], z,
                        gf_mul_bytes(c0, a) ^ gf_mul_bytes(c1, b),
                    )
            # Batched uncoupled decode over this order group.
            self._repair_decode_batch(erasures, planes, U, sc, lead, traced)
            # Convert: recover coupled values of the lost chunk.
            if traced:
                recovered = self._exec_citems_stacked(
                    citems, helper, U, plane_ind, recovered
                )
            else:
                for (zdst, c0, c1, asrc, bsrc) in citems:
                    a = self._item_slice(asrc, helper, U, plane_ind)
                    if c1 == 0 and c0 == 1:
                        recovered = setz(recovered, zdst, a)
                        continue
                    b = self._item_slice(bsrc, helper, U, plane_ind)
                    recovered = setz(
                        recovered, zdst,
                        gf_mul_bytes(c0, a) ^ gf_mul_bytes(c1, b),
                    )
        out = recovered.reshape(lead + (self.sub_chunk_no * sc,))
        return {
            lost: out if traced else jax.numpy.asarray(out)
        }

    # -- fast repair (aloof-free: d = k+m-1) ---------------------------
    def _repair_fast(
        self, lost_node: int, helper: dict,
        repair_planes: list, plane_ind: dict,
    ):
        """Whole-tensor repair for the aloof-free case. With d =
        k+m-1 every helper node is present, every repair plane has
        intersection score 1, and the pair algebra reduces to
        PER-PLANE-CONSTANT GF ladders:

        a. For each row y != y_lost, the q helpers' uncoupled values
           are c0(z)*h[x][z] ^ c1(z)*h[x'][z'] where (x', z') is a
           static permutation of the same row's (helper, plane) grid
           and the coefficients depend only on the plane's digit —
           one stack + one gather + two ladders per row, instead of
           one stacked dispatch per (node, plane) work item.
        b. The lost ROW's uncoupled values come from ONE inner-MDS
           decode with the plane axis folded into the lane axis (so
           the shards-form MXU kernel serves it at full tile width).
        c. The lost chunk's q^t coupled planes are a static
           permutation of q per-row-member ladder combinations.

        Matches repair_one_lost_chunk (ErasureCodeClay.cc:454-699)
        restricted to aloof == {}; the itemized path keeps the
        general case."""
        import jax.numpy as jnp

        q, t, n = self.q, self.t, self.q * self.t
        y_l, x_l = lost_node // q, lost_node % q
        P = len(repair_planes)
        pvecs = [self._plane_vector(z) for z in repair_planes]
        sc = helper[next(iter(helper))].shape[-1]

        # -- a: uncoupled values of every non-lost row ---------------
        U: dict[int, jax.Array] = {}
        row_u: list = []  # Uy per non-lost row, ascending y
        for y in range(t):
            if y == y_l:
                continue
            Hy = jnp.stack(
                [helper[y * q + x] for x in range(q)], axis=-3
            )  # [..., q, P, sc]
            lead = Hy.shape[:-3]
            flat = Hy.reshape(lead + (q * P, sc))
            c0s = np.zeros(q * P, np.uint8)
            c1s = np.zeros(q * P, np.uint8)
            bidx = np.zeros(q * P, np.int32)
            for x in range(q):
                for p in range(P):
                    zv = pvecs[p][y]
                    i = x * P + p
                    if zv == x:  # dot: U = C
                        c0s[i], c1s[i], bidx[i] = 1, 0, i
                        continue
                    node_c, node_u = self._pair_idx(x, zv)
                    sw_c, _ = self._pair_idx(zv, x)
                    c0s[i], c1s[i] = self._pair_coeffs(
                        (node_c, sw_c), node_u
                    )
                    z_sw = repair_planes[p] + (x - zv) * _pow_int(
                        q, t - 1 - y
                    )
                    bidx[i] = zv * P + plane_ind[z_sw]
            B = jnp.take(flat, jnp.asarray(bidx), axis=-2)
            # The canonical pair transform is U = C ^ 2*(C_hi^C_lo)
            # for BOTH members ((c0,c1) = (3,2) on (self, partner)),
            # so the whole row reduces to one masked mul-by-2 — a
            # 5-op fusion instead of two 8-step ladders. The ladder
            # form stays as the fallback for any other _g4.
            if all(
                (int(c0s[i]), int(c1s[i])) in ((1, 0), (3, 2))
                for i in range(q * P)
            ):
                mask = jnp.asarray(
                    (c1s != 0).astype(np.uint8)
                ).reshape(-1, 1)
                Uy = flat ^ _gf_mul2((flat ^ B) * mask)
            else:
                Uy = _gf_mul_planes(c0s, flat) ^ _gf_mul_planes(c1s, B)
            row_u.append(Uy.reshape(Hy.shape))

        # -- b: one batched inner-MDS decode of the lost row ---------
        # The known nodes are exactly the non-lost rows, already
        # stacked per row — concat them into the [.., C, N] form and
        # hit the STACKED MXU kernel directly (the shards-form route
        # measured 102 GB/s at c=8 vs 267 stacked; the stack here is
        # one cheap concat of row tensors, not a per-shard relayout).
        from .matrix_codec import dev_bmat

        erased_row = {y_l * q + x for x in range(q)}
        present = [nd for nd in range(n) if nd not in erased_row]
        want = sorted(erased_row)
        stack = jnp.concatenate(row_u, axis=-3)  # [.., (t-1)q, P, sc]
        lead = stack.shape[:-3]
        if self.scalar_mds in ("jerasure", "isa"):
            ks = stack.reshape(lead + (len(present), P * sc))
            key = (tuple(present), tuple(want))
            bmat_np = self.mds._tables.get(
                key, lambda: self.mds._build_decode_bmat(present, want)
            )
            dec = self.mds._dispatch_bitmatrix(
                bmat_np,
                dev_bmat(self.mds._tables, key, bmat_np, True),
                ks, "decode",
            )  # [.., q, P*sc]
            for idx, node in enumerate(want):
                U[node] = dec[..., idx, :].reshape(lead + (P, sc))
        else:
            # shec inner codec: its decode runs a non-MDS subset
            # search — go through its own decode_chunks
            known = {
                node: stack[..., i, :, :].reshape(lead + (P * sc,))
                for i, node in enumerate(present)
            }
            dec = self.mds.decode_chunks(erased_row, known)
            for node in want:
                U[node] = dec[node].reshape(lead + (P, sc))

        # -- c: coupled planes of the lost chunk ---------------------
        srcs = []
        for x in range(q):
            node = y_l * q + x
            if x == x_l:
                srcs.append(U[lost_node])
                continue
            node_c, node_u = self._pair_idx(x, x_l)
            lost_c, _ = self._pair_idx(x_l, x)
            c0, c1 = self._pair_coeffs((node_c, node_u), lost_c)
            if (c0, c1) == (143, 142):
                # C_lost = C_x ^ inv2*(C_x ^ U_x): the inverse of the
                # canonical pair transform, one div-by-2 fusion
                srcs.append(
                    helper[node]
                    ^ _gf_div2(helper[node] ^ U[node])
                )
            else:
                srcs.append(
                    _gf_mul_traced(c0, helper[node])
                    ^ _gf_mul_traced(c1, U[node])
                )
        stack4 = jnp.stack(srcs, axis=-3)  # [..., q, P, sc]
        flat = stack4.reshape(stack4.shape[:-3] + (q * P, sc))
        inv = np.zeros(self.sub_chunk_no, np.int32)
        for x in range(q):
            for p in range(P):
                z_dst = repair_planes[p] + (x - x_l) * _pow_int(
                    q, t - 1 - y_l
                )
                inv[z_dst] = x * P + p
        return jnp.take(flat, jnp.asarray(inv), axis=-2)

    # -- Pallas kernel repair (general d, plane-blocked) ---------------
    def _kernel_plan(self, lost_node: int, aloof: frozenset) -> dict:
        """Static planning for the kernel repair path, cached per
        (lost node, aloof set) — digit strides, member kinds, pair
        coefficients, intersection-score groups and the B2 patch
        items.  Pure host arithmetic: one dict serves every traced
        repair of the same erasure pattern."""
        key = (lost_node, aloof)
        plan = self._kernel_plans.get(key)
        if plan is None:
            plan = self._build_kernel_plan(lost_node, aloof)
            self._kernel_plans[key] = plan
        return plan

    def _build_kernel_plan(self, lost_node: int, aloof: frozenset) -> dict:
        q, t = self.q, self.t
        y_l, x_l = lost_node // q, lost_node % q
        r = self.sub_chunk_no // q
        rows = [y for y in range(t) if y != y_l]

        def stride(y: int) -> int:
            # repair-index stride of digit y: q per free digit minor
            # to it (free = every row but y_l; y=0 most significant)
            return _pow_int(q, sum(1 for y2 in rows if y2 > y))

        def kind(node: int) -> str:
            if node in aloof:
                return "a"
            if self.k <= node < self.k + self.nu:
                return "v"
            return "r"

        strides = tuple(stride(y) for y in rows)
        kinds = tuple(
            tuple(kind(y * q + x) for x in range(q)) for y in rows
        )
        lost_kinds = tuple(kind(y_l * q + x) for x in range(q))
        # (self, partner) coefficients: forward transform U_self from
        # (C_self, C_partner), hi/lo member; inverse C_lost from
        # (C_helper, U_helper) of a lost-row member.
        pair_fwd = (
            self._pair_coeffs((0, 1), 2),
            self._pair_coeffs((1, 0), 3),
        )
        pair_inv = (
            self._pair_coeffs((0, 2), 1),
            self._pair_coeffs((1, 3), 0),
        )
        present = [
            y * q + x
            for y in rows
            for x in range(q)
            if (y * q + x) not in aloof
        ]
        want = sorted({y_l * q + x for x in range(q)} | aloof)

        def digit(p: int, y: int) -> int:
            return (p // stride(y)) % q

        score = [
            1 + sum(
                1 for nd in aloof if digit(p, nd // q) == nd % q
            )
            for p in range(r)
        ]
        groups: dict[int, np.ndarray] = {}
        for s in sorted(set(score)):
            groups[s] = np.array(
                [p for p in range(r) if score[p] == s], np.int64
            )
        # B2 patch items: helpers sharing a row with an aloof node, at
        # the planes where that aloof node is a dot.  Their uncoupled
        # value needs the aloof node's U from the companion plane (one
        # score lower) — patched between group decodes.
        patches: dict[int, list] = {}
        for nd_a in sorted(aloof):
            x_a, y_a = nd_a % q, nd_a // q
            s_a = stride(y_a)
            dots = [p for p in range(r) if digit(p, y_a) == x_a]
            for x in range(q):
                nd = y_a * q + x
                if x == x_a or nd in aloof:
                    continue
                node_c, node_u = self._pair_idx(x, x_a)
                _sw_c, sw_u = self._pair_idx(x_a, x)
                c0, c1 = self._pair_coeffs((node_c, sw_u), node_u)
                by_score: dict[int, list[int]] = {}
                for p in dots:
                    by_score.setdefault(score[p], []).append(p)
                for s, ps in by_score.items():
                    psw = [p + (x - x_a) * s_a for p in ps]
                    patches.setdefault(s, []).append((
                        nd, nd_a,
                        np.array(ps, np.int64),
                        np.array(psw, np.int64),
                        c0, c1,
                    ))
        return {
            "rows": rows,
            "strides": strides,
            "kinds": kinds,
            "lost_kinds": lost_kinds,
            "pair_fwd": pair_fwd,
            "pair_inv": pair_inv,
            "present": present,
            "want": want,
            "groups": groups,
            "patches": patches,
            "seq": _pow_int(q, sum(1 for y2 in rows if y2 > y_l)),
        }

    def _repair_kernels(self, lost_node, helper, aloof, sc):
        """All repair stages on the plane-blocked Pallas kernels
        (ops/clay_kernels.py) + per-score-group MXU decodes: HBM sees
        each helper byte once in, each recovered byte once out — the
        XLA formulation's stack/gather/permute intermediates cost
        ~10x the payload in HBM traffic.  General d: aloof nodes are
        decoded alongside the lost row and their U feeds the next
        score group's B2 patches (repair_one_lost_chunk's helper
        split, ErasureCodeClay.cc:454-699).  Returns None when the
        kernels are gated off or the geometry does not fit (the XLA
        paths take over)."""
        import numpy as _np

        from ceph_tpu.ops import clay_kernels
        from ceph_tpu.ops.pallas_encode import on_tpu as _on_tpu
        from ceph_tpu.utils import config

        q, t = self.q, self.t
        r = self.sub_chunk_no // q
        sample = helper[next(iter(helper))]
        lead = sample.shape[:-2]
        b = int(_np.prod(lead, initial=1))
        if (
            not config.get("ec_clay_kernels")
            or self.scalar_mds not in ("jerasure", "isa")
            or not clay_kernels.supported(b, sc, q, t)
        ):
            return None
        import jax.numpy as jnp

        from .matrix_codec import dev_bmat

        plan = self._kernel_plan(lost_node, frozenset(aloof))
        interp = not _on_tpu()
        flat = {
            node: helper[node].reshape((b, r * sc)) for node in helper
        }
        real_in = [
            flat[y * q + x]
            for ri, y in enumerate(plan["rows"])
            for x in range(q)
            if plan["kinds"][ri][x] == "r"
        ]
        # stage a: every B1 pair transform in one plane-blocked pass
        U = dict(zip(plan["present"], clay_kernels.uncoupled_rows(
            q, plan["strides"], plan["kinds"], plan["pair_fwd"],
            real_in, r, sc, interp,
        )))
        # stage b: inner-MDS decode of lost row + aloof, one dispatch
        # per intersection-score group (aloof-free: exactly one).
        present, want = plan["present"], plan["want"]
        key = (tuple(present), tuple(want))
        bmat_np = self.mds._tables.get(
            key, lambda: self.mds._build_decode_bmat(present, want)
        )
        bdev = dev_bmat(self.mds._tables, key, bmat_np, True)
        groups = plan["groups"]
        if len(groups) == 1:
            dec = self.mds._dispatch_bitmatrix_shards(
                bmat_np, bdev, [U[nd] for nd in present], "decode"
            )
            Uw = dict(zip(want, dec))
        else:
            Uv = {nd: U[nd].reshape(b, r, sc) for nd in present}
            Uwb = {
                nd: jnp.zeros((b, r, sc), _np.uint8) for nd in want
            }
            for s in sorted(groups):
                for (nd, nd_a, ps, psw, c0, c1) in plan[
                    "patches"
                ].get(s, ()):
                    cx = jnp.take(
                        flat[nd].reshape(b, r, sc),
                        jnp.asarray(ps), axis=1,
                    )
                    ua = jnp.take(Uwb[nd_a], jnp.asarray(psw), axis=1)
                    val = (
                        _gf_mul_traced(c0, cx)
                        ^ _gf_mul_traced(c1, ua)
                    )
                    Uv[nd] = Uv[nd].at[:, ps, :].set(val)
                zsel = jnp.asarray(groups[s])
                known = [
                    jnp.take(Uv[nd], zsel, axis=1).reshape(b, -1)
                    for nd in present
                ]
                dec = self.mds._dispatch_bitmatrix_shards(
                    bmat_np, bdev, known, "decode"
                )
                for i, nd in enumerate(want):
                    Uwb[nd] = Uwb[nd].at[:, groups[s], :].set(
                        dec[i].reshape(b, len(groups[s]), sc)
                    )
            Uw = {nd: v.reshape(b, r * sc) for nd, v in Uwb.items()}
        # stage c: couple + blocked scatter of the lost chunk
        y_l, x_l = lost_node // q, lost_node % q
        udec = [Uw[y_l * q + x] for x in range(q)]
        lost_help = [
            flat[y_l * q + x]
            for x in range(q)
            if x != x_l and plan["lost_kinds"][x] == "r"
        ]
        rec = clay_kernels.couple_scatter(
            q, x_l, plan["lost_kinds"], plan["pair_inv"],
            udec, lost_help, plan["seq"], r, sc, interp,
        )
        return rec.reshape(lead + (self.sub_chunk_no, sc))

    # -- repair work-item planning + stacked execution -----------------
    def _plan_repair_group(
        self,
        planes: list[int],
        erasures: set[int],
        aloof: set[int],
        lost_node: int,
    ):
        """Static work items for one intersection-score group — ONE
        source of truth for the pair algebra, executed either stacked
        (traced device path) or element-at-a-time (host path).

        U item:  (node, z, c0, c1, a_src, b_src): U[node][z] =
                 c0*a ^ c1*b.
        C item:  (z_dst, c0, c1, a_src, b_src): recovered[z_dst] = ...
        src: ("h", node, z) helper packet at repair-plane z, or
             ("u", node, z) U packet at absolute plane z.
        """
        q, t = self.q, self.t
        uitems, citems = [], []
        for z in planes:
            z_vec = self._plane_vector(z)
            for y in range(t):
                for x in range(q):
                    node = y * q + x
                    if node in erasures:
                        continue
                    node_sw = y * q + z_vec[y]
                    z_sw = self._z_sw(z, x, y, z_vec)
                    # Tuple indices of this node and its companion in
                    # the canonical (C_hi, C_lo, U_hi, U_lo).
                    node_c, node_u = self._pair_idx(x, z_vec[y])
                    sw_c, sw_u = self._pair_idx(z_vec[y], x)
                    if node_sw in aloof:
                        # U_xy from (C_xy, U_sw) — U_sw was decoded in
                        # an earlier (lower-order) plane group.
                        c0, c1 = self._pair_coeffs((node_c, sw_u), node_u)
                        uitems.append((
                            node, z, c0, c1,
                            ("h", node, z), ("u", node_sw, z_sw),
                        ))
                    elif z_vec[y] != x:
                        # Both coupled values are helper data.
                        c0, c1 = self._pair_coeffs((node_c, sw_c), node_u)
                        uitems.append((
                            node, z, c0, c1,
                            ("h", node, z), ("h", node_sw, z_sw),
                        ))
                    else:
                        uitems.append((
                            node, z, 1, 0,
                            ("h", node, z), ("h", node, z),
                        ))
            for node in sorted(erasures):
                if node in aloof:
                    continue
                x, y = node % q, node // q
                node_sw = y * q + z_vec[y]
                z_sw = self._z_sw(z, x, y, z_vec)
                if x == z_vec[y]:
                    if node == lost_node:
                        citems.append((
                            z, 1, 0, ("u", node, z), ("u", node, z)
                        ))
                else:
                    # Helper member of the lost row: its coupled
                    # (helper) value plus its U give the LOST node's
                    # coupled value at the companion plane.
                    if y != lost_node // q or node_sw != lost_node:
                        raise AssertionError("unexpected repair pair")
                    node_c, node_u = self._pair_idx(x, z_vec[y])
                    lost_c, _ = self._pair_idx(z_vec[y], x)
                    c0, c1 = self._pair_coeffs((node_c, node_u), lost_c)
                    citems.append((
                        z_sw, c0, c1, ("h", node, z), ("u", node, z)
                    ))
        return uitems, citems

    @staticmethod
    def _item_slice(src, helper, U, plane_ind):
        kind, node, z = src
        if kind == "h":
            return helper[node][..., plane_ind[z], :]
        return U[node][..., z, :]

    def _exec_uitems_stacked(self, uitems, helper, U, plane_ind) -> None:
        """All pair transforms of a plane group as ONE stacked
        dispatch: [P, lead, sc] operand stacks, per-row constant GF
        ladder, then grouped scatter back into U."""
        import jax.numpy as jnp

        if not uitems:
            return
        A = jnp.stack([
            self._item_slice(a, helper, U, plane_ind)
            for (_, _, _, _, a, _) in uitems
        ])
        B = jnp.stack([
            self._item_slice(b, helper, U, plane_ind)
            for (_, _, _, _, _, b) in uitems
        ])
        c0s = np.array([it[2] for it in uitems], np.uint8)
        c1s = np.array([it[3] for it in uitems], np.uint8)
        out = _gf_mul_vec_traced(c0s, A) ^ _gf_mul_vec_traced(c1s, B)
        by_node: dict[int, list[int]] = {}
        for idx, (node, *_rest) in enumerate(uitems):
            by_node.setdefault(node, []).append(idx)
        for node, idxs in by_node.items():
            zs = np.array([uitems[i][1] for i in idxs])
            sel = jnp.moveaxis(out[np.array(idxs)], 0, -2)
            U[node] = U[node].at[..., zs, :].set(sel)

    def _exec_citems_stacked(
        self, citems, helper, U, plane_ind, recovered
    ):
        import jax.numpy as jnp

        if not citems:
            return recovered
        A = jnp.stack([
            self._item_slice(a, helper, U, plane_ind)
            for (_, _, _, a, _) in citems
        ])
        B = jnp.stack([
            self._item_slice(b, helper, U, plane_ind)
            for (_, _, _, _, b) in citems
        ])
        c0s = np.array([it[1] for it in citems], np.uint8)
        c1s = np.array([it[2] for it in citems], np.uint8)
        out = _gf_mul_vec_traced(c0s, A) ^ _gf_mul_vec_traced(c1s, B)
        zs = np.array([it[0] for it in citems])
        sel = jnp.moveaxis(out, 0, -2)
        return recovered.at[..., zs, :].set(sel)

    def _repair_decode_batch(
        self,
        erasures: set[int],
        planes: list[int],
        U: dict,
        sc: int,
        lead: tuple,
        traced: bool = False,
    ) -> None:
        import jax.numpy as jnp

        n = self.q * self.t
        zsel = np.asarray(planes)
        # host path keeps numpy: the inner decode's dispatch then
        # serves small ops from host GF tables and ROUTES large ones
        # (mesh/DCN take host-staged inputs only); converting to
        # device arrays here barred both and forced einsum
        conv = jnp.asarray if traced else np.ascontiguousarray
        known = {
            node: conv(U[node][..., zsel, :])
            for node in range(n)
            if node not in erasures
        }
        out = self.mds.decode_chunks(set(erasures), known)
        for node in erasures:
            if traced:
                U[node] = U[node].at[..., zsel, :].set(out[node])
            else:
                U[node][..., zsel, :] = np.asarray(out[node])


registry.register("clay", ClayCodec, PLUGIN_ABI_VERSION)
