"""Bit-matrix (XOR-schedule) erasure codecs — the liberation family.

The reference's jerasure plugin runs liberation / blaum_roth /
liber8tion as w-bit bit-matrix codes executed as XOR schedules over
"packets" (ErasureCodeJerasure.h:188-324). Here a chunk is w packets,
the coding matrix is [m*w, k*w] over GF(2), and encode/decode is the
same mod-2 MXU matmul as the byte codes — XOR networks are *natively*
this formulation on TPU (SURVEY.md section 7 "Design stance").

Construction note: the vendored jerasure/gf-complete sources are
absent from the reference snapshot (empty submodules), so the
matrices are built from the PUBLISHED definitions rather than the C
files: ``liberation_bitmatrix`` ports Plank's FAST'08 construction
(cyclic shifts plus the one correction bit per column, w prime),
``blaum_roth_bitmatrix`` the Blaum-Roth ring form over
GF(2)[x]/(1 + x + ... + x^w), and liber8tion's envelope is served by
``gf2w_power_bitmatrix`` (generator powers, guaranteed MDS at w=8).
Every construction re-verifies MDS exhaustively at build time, and
bit-compatibility IS tested: corpus v1 freezes encoded chunks for
each technique (tests/corpus/v1, tests/test_corpus.py), so the
matrices — and the kernels applying them — can never drift across
versions. The earlier searched minimal-density RAID-6 matrices
(``raid6_bitmatrix``) remain available as ``construction=v0``, pinned
by the corpus v0 entries.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf import gf_matrix_to_bitmatrix
from ceph_tpu.gf.bitmatrix import bitmatrix_invert, bitmatrix_matmul
from ceph_tpu.ops import xor_schedule
from ceph_tpu.ops.bitplane import xor_bytes

from .base import ErasureCodeBase
from .interface import Flag
from .matrix_codec import (
    BitplaneDispatchMixin,
    DecodeTableCache,
    _dispatch_counters,
    dev_bmat,
)


def _shift(w: int, d: int) -> np.ndarray:
    """Cyclic shift matrix S^d: ones at (i, (i+d) mod w)."""
    m = np.zeros((w, w), dtype=np.uint8)
    for i in range(w):
        m[i, (i + d) % w] = 1
    return m


def _invertible(m: np.ndarray) -> bool:
    try:
        bitmatrix_invert(m)
        return True
    except ValueError:
        return False


@functools.lru_cache(maxsize=None)
def raid6_bitmatrix(k: int, w: int) -> bytes:
    """Search a minimal-density RAID-6 bit-matrix code.

    P row: identity blocks. Q row: X_j = S^j plus at most one correction
    bit, chosen (deterministic scan order) so that every X_j and every
    pairwise X_i ^ X_j is invertible — the exact MDS condition for
    two-parity bit-matrix codes. Returns [2*w, k*w] packed bytes.
    """
    if k > w:
        raise ValueError(f"k={k} must be <= w={w}")
    blocks: list[np.ndarray] = []
    cells = [(r, c) for r in range(w) for c in range(w)]
    for j in range(k):
        base = _shift(w, j)
        placed = None
        # Iterative deepening over correction-bit count: the bare
        # shift, then 1 bit, then 2 (prime w always succeeds at <= 1,
        # so those matrices — corpus-frozen since v0 — are unchanged;
        # even w, where S^d ^ S^e is never invertible, needs 2).
        def candidates():
            yield ()
            for cell in cells:
                yield (cell,)
            for a in range(len(cells)):
                for b in range(a + 1, len(cells)):
                    yield (cells[a], cells[b])

        for cand in candidates():
            x = base.copy()
            for r, c in cand:
                x[r, c] ^= 1
            if not _invertible(x):
                continue
            if all(_invertible(x ^ b) for b in blocks):
                placed = x
                break
        if placed is None:
            raise ValueError(
                f"no minimal-density RAID-6 construction found for k={k}, w={w}"
            )
        blocks.append(placed)
    coding = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        coding[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
        coding[w:, j * w : (j + 1) * w] = blocks[j]
    return coding.tobytes()


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    return all(n % i for i in range(2, int(n**0.5) + 1))


@functools.lru_cache(maxsize=None)
def liberation_bitmatrix(k: int, w: int) -> bytes:
    """The Liberation code construction (Plank, FAST'08) — the matrix
    ``liberation_coding_bitmatrix`` builds for the reference's
    liberation technique (ErasureCodeJerasure.cc:676; the vendored
    jerasure sources are absent from the snapshot, so this is ported
    from the paper's published definition, not the C file).

    w prime, k <= w. P row: identity blocks. Q block X_i: ones at
    (r, (r+i) mod w) for every r — the cyclic shift S^i — plus, for
    i > 0, one extra bit at (y, (y+i-1) mod w) with y = i(w-1)/2 mod w.
    Total Q density k*w + k - 1 ones: the minimal-density bound the
    family is named for. MDS (every X_i and X_i ^ X_j invertible) is
    re-verified exhaustively at construction time rather than trusted.
    """
    if not _is_prime(w):
        raise ValueError(f"liberation requires prime w, got {w}")
    if k > w:
        raise ValueError(f"k={k} must be <= w={w}")
    coding = np.zeros((2 * w, k * w), dtype=np.uint8)
    blocks: list[np.ndarray] = []
    for i in range(k):
        coding[:w, i * w : (i + 1) * w] = np.eye(w, dtype=np.uint8)
        x = np.zeros((w, w), dtype=np.uint8)
        for r in range(w):
            x[r, (r + i) % w] = 1
        if i > 0:
            y = (i * ((w - 1) // 2)) % w
            x[y, (y + i - 1) % w] ^= 1
        if not _invertible(x) or any(
            not _invertible(x ^ b) for b in blocks
        ):
            raise ValueError(
                f"liberation construction not MDS for k={k}, w={w}"
            )
        blocks.append(x)
        coding[w:, i * w : (i + 1) * w] = x
    return coding.tobytes()


@functools.lru_cache(maxsize=None)
def blaum_roth_bitmatrix(k: int, w: int) -> bytes:
    """Blaum-Roth RAID-6 code over the ring GF(2)[x]/(1 + x + ... + x^w).

    Requires w+1 prime. Q block for data column j is multiplication by
    x^j (C^j with C the companion matrix of M_p(x) = (x^p - 1)/(x - 1),
    p = w+1). MDS because C^i ^ C^j = C^j (C^(i-j) ^ I) and x^d + 1 is
    coprime to M_p(x) for 0 < d < p when p is prime (their only common
    candidate root, 1, is not a root of M_p since p is odd).
    """
    if not _is_prime(w + 1):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if k > w:
        raise ValueError(f"k={k} must be <= w={w}")
    # Companion matrix: column j of C holds x^(j+1) mod M_p.
    c = np.zeros((w, w), dtype=np.uint8)
    for j in range(w - 1):
        c[j + 1, j] = 1
    c[:, w - 1] = 1  # x^w = 1 + x + ... + x^(w-1)
    coding = np.zeros((2 * w, k * w), dtype=np.uint8)
    block = np.eye(w, dtype=np.uint8)
    for j in range(k):
        coding[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
        coding[w:, j * w : (j + 1) * w] = block
        block = bitmatrix_matmul(block, c)
    return coding.tobytes()


@functools.lru_cache(maxsize=None)
def sparse_power_bitmatrix(k: int, w: int = 8) -> bytes:
    """RAID-6 Q blocks = the k *sparsest* multiplication-by-g^e
    bitmatrices over GF(2^8). Any distinct powers are pairwise MDS
    (C^a ^ C^b = C^b (C^(a-b) ^ I), multiplication by g^(a-b) + 1
    != 0), so density is a free choice — picking the sparsest k of
    the 255 powers (ones counts 8, 11, 11, 14, 14, 17, 18, 18 for
    k=8 -> 111 total vs ~128 for random powers) keeps the XOR
    schedule short. Exponents are frozen by the deterministic
    (ones, exponent) sort; the layout is corpus-pinned."""
    from ceph_tpu.gf.tables import gf_pow, mul_bitmatrix

    if w != 8:
        raise ValueError("sparse_power_bitmatrix implemented for w=8")
    if k > 2**w - 1:
        raise ValueError(f"k={k} too large for w={w}")
    dens = sorted(
        (int(np.asarray(mul_bitmatrix(gf_pow(2, e))).sum()), e)
        for e in range(2**w - 1)
    )
    chosen = sorted(e for _, e in dens[:k])
    coding = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j, e in enumerate(chosen):
        coding[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
        coding[w:, j * w : (j + 1) * w] = mul_bitmatrix(gf_pow(2, e))
    return coding.tobytes()


@functools.lru_cache(maxsize=None)
def gf2w_power_bitmatrix(k: int, w: int = 8) -> bytes:
    """RAID-6 bit-matrix with Q blocks = powers of the GF(2^w) generator.

    X_j = C^j with C the companion matrix of the field polynomial (0x11D
    for w=8), i.e. multiplication by g^j. MDS for k <= 2^w - 1: every C^j
    is invertible and C^i ^ C^j = C^j(C^(i-j) ^ I) is multiplication by
    g^(i-j) + 1 != 0. Used for the liber8tion envelope (w=8): the
    reference's liber8tion matrices minimize XOR-schedule density, which
    is irrelevant on the MXU — this construction keeps the same envelope
    and packet layout with guaranteed MDS.
    """
    from ceph_tpu.gf.tables import mul_bitmatrix, gf_pow

    if w != 8:
        raise ValueError("gf2w_power_bitmatrix implemented for w=8")
    if k > 2**w - 1:
        raise ValueError(f"k={k} too large for w={w}")
    coding = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        coding[:w, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
        coding[w:, j * w : (j + 1) * w] = mul_bitmatrix(gf_pow(2, j))
    return coding.tobytes()


class BitMatrixCodec(BitplaneDispatchMixin, ErasureCodeBase):
    """Erasure codec driven by a [m*w, k*w] GF(2) coding matrix.

    Chunk layout: chunk = w consecutive packets of chunk_size/w bytes
    (the jerasure packet convention, with packetsize implied by chunk
    size rather than a separate profile knob — TPU tiling makes the
    packet the natural unit).

    Engine note (round 4): a packet-selection XOR network IS a GF(2^8)
    matrix apply whose matrix entries happen to be 0/1 — GF(2) is the
    subfield {0,1} of GF(2^8), so the packet matrix routes through the
    SAME dispatch engine as the byte codes (host GF tables / mesh /
    Pallas MXU kernel / einsum, with ec_dispatch counters), the way
    the reference funnels both jerasure_matrix_encode and
    jerasure_schedule_encode into one plugin hot path.
    """

    def __init__(self) -> None:
        super().__init__()
        self.w = 0
        self.coding_bitmatrix: np.ndarray | None = None  # [m*w, k*w]
        self._tables = DecodeTableCache()       # device matrices
        self._host_tables = DecodeTableCache()  # packet 0/1 matrices

    def _set_bitmatrix(self, coding: np.ndarray) -> None:
        assert coding.shape == (self.m * self.w, self.k * self.w)
        self.coding_bitmatrix = coding.astype(np.uint8)
        # the packet matrix as a GF(2^8) 0/1 byte matrix, expanded to
        # bit-plane form for the device engine (kron with I8)
        self._encode_bmat_np = gf_matrix_to_bitmatrix(self.coding_bitmatrix)
        self._encode_bmat = jnp.asarray(self._encode_bmat_np)

    def get_flags(self) -> Flag:
        return (
            Flag.OPTIMIZED_SUPPORTED
            | Flag.ZERO_INPUT_ZERO_OUTPUT
            | Flag.ZERO_PADDING_EXPECTED
            | Flag.PARITY_DELTA_OPTIMIZATION
            | Flag.PARITY_DELTA_CHUNK_GRANULARITY
        )

    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunks must split into w lane-aligned packets."""
        from .base import CHUNK_ALIGN

        per = -(-stripe_width // self.k)
        unit = self.w * CHUNK_ALIGN
        return -(-per // unit) * unit

    # [..., S, N] chunks -> [..., S*w, N/w] packets
    def _to_packets(self, chunks: jax.Array) -> jax.Array:
        *lead, s, n = chunks.shape
        assert n % self.w == 0, (n, self.w)
        return chunks.reshape(*lead, s * self.w, n // self.w)

    def _to_chunks(self, packets: jax.Array) -> jax.Array:
        *lead, sw, p = packets.shape
        return packets.reshape(*lead, sw // self.w, p * self.w)

    def _apply_packet_matrix(
        self,
        mat01: np.ndarray,
        stacked: jax.Array,
        op: str,
        tables: "tuple[np.ndarray, jax.Array] | None" = None,
    ) -> jax.Array:
        """Apply a packet-level 0/1 matrix to [..., S, N] chunks via
        the shared engine: packetize, route (host / mesh / DCN /
        XOR-schedule / Pallas / einsum), de-packetize. ``tables``
        passes precomputed bit-expanded forms (the encode path keeps
        them resident)."""
        from ceph_tpu.utils import config

        packets = self._to_packets(stacked)
        multi = self._mesh_routable(packets) or self._dcn_routable(
            packets
        )
        if not multi and self._host_sized(packets):
            from ceph_tpu.gf import gf_apply_bytes_host

            _dispatch_counters().inc(f"host_{op}")
            out = gf_apply_bytes_host(mat01, np.asarray(packets))
            return self._to_chunks(out)
        out = None
        if config.get("ec_use_sched") and not multi:
            # schedule-native route: sparse packet matrices ARE XOR
            # networks (jerasure_schedule_encode's insight), and the
            # round-11 optimizer CSE-compresses denser shapes —
            # inverted decode tables, parity-delta columns — under
            # the op-count gate. Matrices still over the gate, or
            # shapes no schedule kernel can tile, fall through to the
            # MXU engine — counted here, the terminal schedule probe
            # (the shards-form probe upstream never counts).
            rows = self._schedule_rows(mat01)
            if rows is None:
                _dispatch_counters().inc("sched_rejected_density")
            elif not xor_schedule.supported(
                (1,) + packets.shape[-2:]
            ):
                _dispatch_counters().inc("sched_rejected_shape")
            else:
                _dispatch_counters().inc(f"sched_{op}")
                out = xor_schedule.xor_schedule_apply(rows, packets)
        if out is None:
            if tables:
                bm_np, bm_dev = tables
            else:
                bm_np, key = self._host_bits(mat01)
                bm_dev = dev_bmat(
                    self._tables, key, bm_np,
                    isinstance(packets, jax.core.Tracer),
                )
            out = self._dispatch_bitmatrix(bm_np, bm_dev, packets, op)
        return self._to_chunks(out)

    def _host_bits(self, mat01: np.ndarray):
        """(bit-expanded HOST matrix, cache key) for a packet 0/1
        matrix — the one source of truth for the ("bits", ...) cache
        (shared with the DCN worker's host-side decode)."""
        key = ("bits", mat01.tobytes())
        return self._tables.get(
            key, lambda: gf_matrix_to_bitmatrix(mat01)
        ), key

    def _try_sched_shards(
        self, mat01: np.ndarray, shards: list, op: str
    ):
        """The no-copy hot path: route a packet-matrix apply through
        the multi-operand schedule kernel, shard arrays in, shard
        arrays out — no [.., n, chunk] stack, no packetize reshape
        (both are real relayout copies on TPU; see
        ops/xor_schedule.py). Returns the list of output shards, or
        None when any precondition fails (over-gate matrix, off-TPU,
        VMEM-oversized chunks, mesh/DCN installed, host-sized numpy
        input — each of those keeps its existing route). Rejections
        are NOT counted here: the packetized probe in
        _apply_packet_matrix is the terminal one."""
        return self._sched_shards_route(
            mat01, shards, self.w, op, count_reject=False
        )

    def _schedule_rows(self, mat01: np.ndarray):
        """The route's schedule for a 0/1 packet matrix — the CSE'd
        multi-level program under ``ec_sched_opt`` (gated on post-CSE
        op count), the pinned selection form otherwise (gated on raw
        density) — or None when the matrix stays over its gate.
        Cached process-wide in ops.xor_schedule (schedules depend
        only on the matrix bytes)."""
        from ceph_tpu.utils import config

        return xor_schedule.routable_schedule(
            mat01, config.get("ec_sched_opt")
        )

    def encode_chunks(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        shards = self._shard_list(data)
        outs = self._try_sched_shards(
            self.coding_bitmatrix, shards, "encode"
        )
        if outs is not None:
            return {self.k + i: outs[i] for i in range(self.m)}
        parity = self._apply_packet_matrix(
            self.coding_bitmatrix,
            self._stack_data(data),
            "encode",
            tables=(self._encode_bmat_np, self._encode_bmat),
        )
        return {self.k + i: parity[..., i, :] for i in range(self.m)}

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        present = sorted(chunks)
        want = sorted(w for w in want_to_read if w not in chunks)
        if not want:
            return {w: chunks[w] for w in want_to_read}
        key = (tuple(present), tuple(want))
        dec01 = self._host_tables.get(
            key, lambda: self._build_decode_bitmatrix(present, want)
        )
        shard_list = [chunks[i] for i in present]
        outs = self._try_sched_shards(dec01, shard_list, "decode")
        if outs is not None:
            result = {w: chunks[w] for w in want_to_read if w in chunks}
            for idx, wshard in enumerate(want):
                result[wshard] = outs[idx]
            return result
        stacked = self._stack(shard_list)
        out = self._apply_packet_matrix(dec01, stacked, "decode")
        result = {w: chunks[w] for w in want_to_read if w in chunks}
        for idx, wshard in enumerate(want):
            result[wshard] = out[..., idx, :]
        return result

    # -- parity delta (RMW) -------------------------------------------
    def encode_delta(
        self, old_data: jax.Array, new_data: jax.Array
    ) -> jax.Array:
        return xor_bytes(old_data, new_data)

    def apply_delta(
        self,
        delta: dict[int, jax.Array],
        parity: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        """parity'_j = parity_j XOR (packet-matrix columns of the
        changed chunks applied to the delta packets) — the
        schedule_apply_delta analog (ErasureCodeJerasure.h:110-119).

        Delta buffers must be whole chunks (the codec sets
        PARITY_DELTA_CHUNK_GRANULARITY): a sub-chunk write's parity
        update scatters across the entire chunk through the packet
        structure, so the pipeline hands in chunk-aligned windows.
        """
        cols = sorted(delta)
        w = self.w
        pcols = [c * w + t for c in cols for t in range(w)]
        mat01 = np.ascontiguousarray(self.coding_bitmatrix[:, pcols])
        shard_list = [delta[c] for c in cols]
        outs = self._try_sched_shards(mat01, shard_list, "delta")
        if outs is not None:
            return {
                pid: xor_bytes(p, outs[pid - self.k])
                for pid, p in parity.items()
            }
        stacked = self._stack(shard_list)
        contrib = self._apply_packet_matrix(mat01, stacked, "delta")
        out = {}
        for pid, p in parity.items():
            c = contrib[..., pid - self.k, :]
            if isinstance(p, np.ndarray) and isinstance(c, np.ndarray):
                out[pid] = np.bitwise_xor(p, c)
            else:
                out[pid] = xor_bytes(p, c)
        return out

    def _build_decode_bitmatrix(
        self, present: list[int], want: list[int]
    ) -> jax.Array:
        """Invert the surviving (k*w)-row sub-bitmatrix, then compose
        wanted rows (jerasure_invert_bitmatrix's role)."""
        kw = self.k * self.w
        full = np.zeros(((self.k + self.m) * self.w, kw), dtype=np.uint8)
        for i in range(self.k):
            full[i * self.w : (i + 1) * self.w, i * self.w : (i + 1) * self.w] = (
                np.eye(self.w, dtype=np.uint8)
            )
        full[kw:, :] = self.coding_bitmatrix
        # Greedy rank extension over survivor row-blocks.
        rows = []
        for s in present:
            rows.extend(range(s * self.w, (s + 1) * self.w))
        # Select kw independent rows (first k blocks usually suffice).
        sel = full[rows[:kw], :]
        try:
            inv = bitmatrix_invert(sel)
            chosen = rows[:kw]
        except ValueError:
            # Rank-extend row by row over GF(2).
            chosen = []
            basis: list[np.ndarray] = []
            for ridx, r in enumerate(rows):
                if len(chosen) == kw:
                    break
                v = full[r].copy()
                for e in basis:
                    lead = int(np.argmax(e != 0))
                    if v[lead]:
                        v ^= e
                if v.any():
                    chosen.append(r)
                    basis.append(v)
            if len(chosen) < kw:
                raise ValueError("erasure pattern not decodable")
            inv = bitmatrix_invert(full[chosen, :])
        # data packet rows in terms of chosen survivor rows:
        # data = inv @ chosen_rows; wanted shard rows = full_rows @ data.
        dec = np.zeros((len(want) * self.w, len(present) * self.w), dtype=np.uint8)
        # Map chosen row -> column position among present packet rows.
        col_of = {r: i for i, r in enumerate(rows)}
        for wi, wshard in enumerate(want):
            wrows = full[wshard * self.w : (wshard + 1) * self.w, :]
            # [w, kw] coefficients over the chosen survivor rows.
            comp = bitmatrix_matmul(wrows, inv)
            for a in range(self.w):
                for b, r in enumerate(chosen):
                    dec[wi * self.w + a, col_of[r]] = comp[a, b]
        # host 0/1 matrix — cached in _host_tables and consumed by
        # both routes (the device route bit-expands via _host_bits +
        # dev_bmat)
        return dec
