"""Single-parity XOR codec — the ``xor`` plugin.

RAID-4/5-class protection: one parity chunk equal to the XOR of the k
data chunks (generator parity row all ones over GF(2^8); trivially
MDS for m=1 since every column is nonzero). The reference carries no
standalone xor plugin — its XOR codes live inside jerasure's
bit-matrix techniques — but Azure-LRC-style locally repairable codes
pair GF global parities with *XOR local parities*, and that is this
plugin's job here: ``codecs/lrc.py`` uses it for generated local
layers under ``local_parity=xor``, so local-group repair rows are
0/1-valued and ride the schedule-native XOR engine (the round-11
``_try_sched_bytes`` w=1 route: encode, decode, AND parity-delta all
dispatch as pure XOR programs with ``sched_*`` counter visibility)
instead of streaming a bit-plane matrix through the MXU.

Usable standalone too (``plugin=xor``, profile ``k=<n>``): the
cheapest single-fault pool config there is.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import PLUGIN_ABI_VERSION

from .base import to_int
from .interface import ErasureCodeProfile
from .matrix_codec import MatrixErasureCodec
from .registry import registry


class XorCodec(MatrixErasureCodec):
    """k data chunks + 1 XOR parity, on the shared byte-matrix
    dispatch engine (host GF tables for small ops; the schedule
    engine's w=1 route on TPU — the all-ones row IS a one-line XOR
    schedule; MXU/einsum otherwise)."""

    DEFAULT_K = 2

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, 1)
        if self.k < 1:
            raise ValueError(f"k={self.k} must be >= 1")
        if self.m != 1:
            raise ValueError("xor plugin supports m=1 only")
        g = np.vstack(
            [np.eye(self.k, dtype=np.uint8),
             np.ones((1, self.k), dtype=np.uint8)]
        )
        self._set_generator(g)


registry.register("xor", XorCodec, PLUGIN_ABI_VERSION)
