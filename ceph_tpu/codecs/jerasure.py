"""The jerasure plugin's seven techniques.

Mirrors ErasureCodeJerasure.{h,cc} (reference
src/erasure-code/jerasure/ErasureCodeJerasure.h:124-324): one codec
class per technique, selected by the ``technique`` profile key. The
matrix techniques run on the GF(2^8) bit-plane MXU engine; the
bit-matrix techniques (cauchy schedules in the reference; liberation
family here) run on the packet mod-2 engine.

Technique parity with the reference:

- reed_sol_van      — Vandermonde RS; the only technique flagged
                      OPTIMIZED_SUPPORTED upstream (ErasureCodeJerasure.h:55-57)
- reed_sol_r6_op    — RAID-6 optimized (P = XOR, Q = powers of 2)
- cauchy_orig       — original Cauchy matrix
- cauchy_good       — Cauchy with XOR-count-minimizing row scaling
- liberation        — minimal-density RAID-6 bit-matrix, w prime, k <= w
- blaum_roth        — RAID-6 bit-matrix, w+1 prime, k <= w
- liber8tion        — RAID-6 bit-matrix, w = 8, k <= 8

Profile keys: k, m, technique, w, packetsize, construction.
``packetsize`` is accepted for interop — the reference plugin writes
its default (2048, ErasureCodeJerasure.h DEFAULT_PACKETSIZE) into
every profile it normalizes, so reference-originated profiles carry
the key — but the value is advisory here: packet geometry on TPU is
derived from chunk size (chunk = w packets), which the class docstring
documents. Negative values are still rejected.

``construction`` selects the bit-matrix build for the packet
techniques: omitted/default uses the reference-derived constructions
(liberation = Plank FAST'08, blaum_roth = Blaum-Roth 1993, liber8tion
= deterministic minimal-density search — the published liber8tion
tables are in the absent vendored sources); ``v0`` pins the round-1
constructions so corpus-v0 archives stay bit-reproducible forever.
An UNVERSIONED profile means the reference construction: profiles
that predate the key come from the reference ecosystem (which never
writes one), so interop with those wins; framework archives from
before the switch are exactly the corpus-v0 entries, which carry the
explicit pin.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu import PLUGIN_ABI_VERSION
from ceph_tpu.gf import (
    cauchy_good_matrix,
    cauchy_original_matrix,
    raid6_matrix,
    vandermonde_rs_matrix,
)

from .base import to_int
from .bitmatrix_codec import (
    BitMatrixCodec,
    _is_prime,
    blaum_roth_bitmatrix,
    gf2w_power_bitmatrix,
    liberation_bitmatrix,
    raid6_bitmatrix,
    sparse_power_bitmatrix,
)
from .interface import ErasureCodeProfile, Flag
from .matrix_codec import MatrixErasureCodec
from .registry import registry


def _accept_packetsize(profile: ErasureCodeProfile) -> int:
    """packetsize: accepted, validated, advisory. The reference plugin
    defaults it to 2048 and writes it into every normalized profile
    (ErasureCodeJerasure.h DEFAULT_PACKETSIZE; .cc:649), so rejecting
    a nonzero value broke reference-originated profiles (round-4
    advisor finding). Geometry here is still chunk-derived — chunk =
    w lane-aligned packets — so the value only survives as profile
    metadata; 0/omitted means the same thing."""
    ps = to_int("packetsize", profile, 0)
    if ps < 0:
        raise ValueError(f"packetsize={ps} must be >= 0")
    return ps


class JerasureMatrixCodec(MatrixErasureCodec):
    technique = "reed_sol_van"
    DEFAULT_K = 2   # ErasureCodeJerasure defaults (k=2, m=1 upstream)
    DEFAULT_M = 1

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        self.packetsize = _accept_packetsize(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        self.w = to_int("w", profile, 8)
        if self.k < 1 or self.m < 1:
            raise ValueError(f"k={self.k}, m={self.m} must be >= 1")
        if self.w != 8:
            # TPU engine is GF(2^8); w=8 is also the reference default.
            raise ValueError(f"technique {self.technique} supports w=8 only")
        self._set_generator(self._make_matrix())

    def _make_matrix(self) -> np.ndarray:
        return vandermonde_rs_matrix(self.k, self.m)


class ReedSolVan(JerasureMatrixCodec):
    technique = "reed_sol_van"


class ReedSolR6(JerasureMatrixCodec):
    technique = "reed_sol_r6_op"
    DEFAULT_M = 2

    def init(self, profile: ErasureCodeProfile) -> None:
        if to_int("m", profile, 2) != 2:
            raise ValueError("reed_sol_r6_op requires m=2")
        super().init(profile)

    def _make_matrix(self) -> np.ndarray:
        return raid6_matrix(self.k)


class CauchyOrig(JerasureMatrixCodec):
    technique = "cauchy_orig"

    def _make_matrix(self) -> np.ndarray:
        return cauchy_original_matrix(self.k, self.m)


class CauchyGood(JerasureMatrixCodec):
    technique = "cauchy_good"

    def _make_matrix(self) -> np.ndarray:
        return cauchy_good_matrix(self.k, self.m)


class LiberationBase(BitMatrixCodec):
    """Shared init for the RAID-6 bit-matrix techniques; subclasses
    override the two varying hooks (_check_w, _build_matrix).

    ``construction`` in the profile picks the matrix build: the
    default is the reference-derived construction for each technique;
    ``v0`` pins this framework's round-1 matrices (deterministic
    minimal-density search for liberation, GF(2^8)-generator powers
    for liber8tion) so the frozen corpus-v0 archives stay reproducible
    — the cross-version guarantee corpus checking exists for."""

    technique = "liberation"
    DEFAULT_W = 7
    CONSTRUCTIONS = ("default", "v0")

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        self.k = to_int("k", profile, 2)
        self.m = to_int("m", profile, 2)
        self.w = to_int("w", profile, self.DEFAULT_W)
        self.construction = str(
            profile.get("construction", "default")
        )
        self.packetsize = _accept_packetsize(profile)
        if self.construction not in self.CONSTRUCTIONS:
            raise ValueError(
                f"unknown construction {self.construction!r}; choose "
                f"from {self.CONSTRUCTIONS}"
            )
        if self.k < 1:
            raise ValueError(f"k={self.k} must be >= 1")
        if self.m != 2:
            raise ValueError(f"technique {self.technique} requires m=2")
        self._check_w()
        if self.k > self.w:
            raise ValueError(f"k={self.k} must be <= w={self.w}")
        coding = np.frombuffer(
            self._build_matrix(), dtype=np.uint8
        ).reshape(2 * self.w, self.k * self.w)
        self._set_bitmatrix(coding)

    def _check_w(self) -> None:
        if not _is_prime(self.w):
            raise ValueError(f"liberation requires prime w, got {self.w}")

    def _build_matrix(self) -> bytes:
        if self.construction == "v0":
            return raid6_bitmatrix(self.k, self.w)
        return liberation_bitmatrix(self.k, self.w)


class Liberation(LiberationBase):
    technique = "liberation"


class BlaumRoth(LiberationBase):
    technique = "blaum_roth"
    DEFAULT_W = 6

    def _check_w(self) -> None:
        if not _is_prime(self.w + 1):
            raise ValueError(
                f"blaum_roth requires w+1 prime, got w={self.w}"
            )

    def _build_matrix(self) -> bytes:
        # one construction only: the ring-multiplication form IS the
        # Blaum-Roth 1993 definition, and it has been stable since v0
        return blaum_roth_bitmatrix(self.k, self.w)


class Liber8tion(LiberationBase):
    technique = "liber8tion"
    DEFAULT_W = 8

    def _check_w(self) -> None:
        if self.w != 8:
            raise ValueError("liber8tion requires w=8")
        if to_int("k", self.profile, 2) > 8:
            raise ValueError("liber8tion requires k <= 8")

    def _build_matrix(self) -> bytes:
        if self.construction == "v0":
            return gf2w_power_bitmatrix(self.k, 8)
        # The published liber8tion tables live in the vendored
        # liber8tion.c the snapshot lacks; these deterministic sparse
        # constructions keep the same envelope (w=8, m=2, k<=8) and
        # density class, frozen and corpus-pinned. k <= 4: minimal-
        # density search (2 correction bits suffice); k >= 5 (where
        # the search space runs dry): the k sparsest GF(2^8)
        # generator-power blocks.
        if self.k <= 4:
            return raid6_bitmatrix(self.k, 8)
        return sparse_power_bitmatrix(self.k, 8)


TECHNIQUES = {
    c.technique: c
    for c in (
        ReedSolVan,
        ReedSolR6,
        CauchyOrig,
        CauchyGood,
        Liberation,
        BlaumRoth,
        Liber8tion,
    )
}


class JerasureDispatch:
    """Factory facade: reads ``technique`` and becomes the right class
    (the ErasureCodePluginJerasure::factory switch)."""

    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique", "reed_sol_van")
        if technique not in TECHNIQUES:
            raise ValueError(
                f"unknown jerasure technique {technique!r}; "
                f"choose from {sorted(TECHNIQUES)}"
            )
        impl = TECHNIQUES[technique]()
        impl.init(profile)
        # Adopt the concrete technique's class and state wholesale; all
        # techniques are plain ErasureCodeBase subclasses so the swap is
        # safe and keeps isinstance() truthful.
        self.__class__ = impl.__class__
        self.__dict__ = impl.__dict__


registry.register("jerasure", JerasureDispatch, PLUGIN_ABI_VERSION)
