"""Locally Repairable Codes via layered nested codes — the lrc plugin.

Behavioral mirror of src/erasure-code/lrc/ErasureCodeLrc.{h,cc}: a
profile either gives ``k``/``m``/``l`` (the "kml" form, expanded to a
generated mapping + layer list, ErasureCodeLrc.cc:291-360) or an
explicit ``mapping`` string plus a ``layers`` JSON array
``[["<chunks_map>", {<profile>}], ...]`` (ErasureCodeLrc.cc:139-248).

Each layer is itself an inner MDS codec (default jerasure
reed_sol_van here; the reference defaults to isa) applied to the subset
of global chunk *positions* its map selects: ``D`` = layer data, ``c``
= layer coding, ``_`` = not in this layer. Local layers let a single
lost chunk rebuild from its small group instead of k survivors —
the locality property ``minimum_to_decode`` exposes (3-case search,
ErasureCodeLrc.cc _minimum_to_decode).

TPU note: a full-stripe encode composes the whole layer cascade into
ONE [m, k] generator (see init) — a single shards-form kernel dispatch
regardless of layer count. Decode keeps the layered walk (locality is
its whole point), and each inner layer decode rides the zero-waste
shards-form MXU kernel: local repair of one lost chunk is one small
[1*8, l*8] matmul over the local group's survivors, with no
block-diagonal padding tax and no [.., C, N] stack relayout
(ops/pallas_encode.py round-6 packing).

Round 11 — the schedule route for local repair: the kml form accepts
``local_parity=xor`` (default ``rs`` keeps the corpus-pinned
reed_sol_van layout), which generates the local layers on the ``xor``
plugin — Azure-LRC-style XOR local parities. Their encode, repair,
and parity-delta rows are then 0/1-valued, so the inner dispatch
rides the schedule-native XOR engine (matrix_codec._try_sched_bytes,
w=1: one multi-operand VPU kernel over the local group, ``sched_*``
counters) instead of streaming a bit-plane matrix through the MXU —
the fixed-engine rate the ``lrc_local_repair_gbps`` bench row
measures. GF-coefficient local parities (the ``rs`` default)
mathematically cannot ride a byte-XOR engine — their repair rows mix
bits within bytes — which is why this is a layout option, not a
dispatch flag.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp

from ceph_tpu import PLUGIN_ABI_VERSION

from .base import ErasureCodeBase, to_int
from .interface import ErasureCodeProfile, Flag, SubChunkPlan
from .matrix_codec import BitplaneDispatchMixin, _dispatch_counters
from .registry import registry


class Layer:
    """One nested code layer over a subset of global positions."""

    def __init__(self, chunks_map: str, profile: ErasureCodeProfile) -> None:
        self.chunks_map = chunks_map
        self.profile = dict(profile)
        # Global positions, in inner-codec order: data first, coding after
        # (layers_init, ErasureCodeLrc.cc:209-248).
        self.data = [i for i, c in enumerate(chunks_map) if c == "D"]
        self.coding = [i for i, c in enumerate(chunks_map) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunk_set = set(self.chunks)
        self.codec = None  # set by layers_init

    def init_codec(self) -> None:
        prof = dict(self.profile)
        prof.setdefault("k", str(len(self.data)))
        prof.setdefault("m", str(len(self.coding)))
        prof.setdefault("plugin", "jerasure")
        if prof["plugin"] == "jerasure":
            prof.setdefault("technique", "reed_sol_van")
        plugin = prof.pop("plugin")
        self.codec = registry.factory(plugin, prof)


class LrcCodec(BitplaneDispatchMixin, ErasureCodeBase):
    """The lrc plugin. Shard ids at the API are logical (0..k-1 data,
    k.. parity); the mapping string defines stored positions, exposed
    via get_chunk_mapping."""

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        prof = dict(profile)
        self._parse_kml(prof)
        if "mapping" not in prof:
            raise ValueError(f"the 'mapping' profile is missing from {prof}")
        mapping = prof["mapping"]
        if "layers" not in prof:
            raise ValueError(f"the 'layers' profile is missing from {prof}")
        self.layers = self._layers_parse(prof["layers"])
        for layer in self.layers:
            layer.init_codec()
        self.mapping = mapping
        self.k = mapping.count("D")
        self.m = len(mapping) - self.k
        self._sanity_checks(prof["layers"])
        # Logical -> position: data ids take the 'D' positions in order,
        # parity ids the rest.
        d_pos = [i for i, c in enumerate(mapping) if c == "D"]
        p_pos = [i for i, c in enumerate(mapping) if c != "D"]
        self.chunk_mapping = d_pos + p_pos
        self._pos_to_logical = {p: i for i, p in enumerate(self.chunk_mapping)}
        # TPU delta: encode is GF-linear through every layer, so the
        # whole layer cascade composes into ONE [m, k] generator —
        # a full-stripe encode is then a single shards-form kernel
        # dispatch instead of len(layers) serialized launches (which
        # measured 77 GB/s vs ~190 for the equivalent single matrix
        # on the bench geometry). Byte-identical to the layered walk:
        # local parities over globally-generated chunks substitute
        # the global rows (L @ [D; G@D] = (L1 ^ L2*G) @ D). Decode
        # keeps the layered walk — locality is its whole point.
        self._composite = self._compose_generator()
        if self._composite is not None:
            from ceph_tpu.gf import gf_matrix_to_bitmatrix

            self._comp_bmat_np = gf_matrix_to_bitmatrix(self._composite)
            self._comp_bmat = jnp.asarray(self._comp_bmat_np)

    def _compose_generator(self):
        """[m, k] composite parity generator over the data chunks, or
        None when a layer's inner codec exposes no byte generator."""
        import numpy as np

        from ceph_tpu.gf.matrices import gf_matmul_np

        rows: dict[int, np.ndarray] = {}
        for i in range(self.k):
            r = np.zeros(self.k, np.uint8)
            r[i] = 1
            rows[self.chunk_mapping[i]] = r
        for layer in self.layers:
            gen = getattr(layer.codec, "generator", None)
            if gen is None:
                return None
            kl = len(layer.data)
            inmat = np.stack([
                rows.get(p, np.zeros(self.k, np.uint8))
                for p in layer.data
            ])
            coding = gf_matmul_np(np.asarray(gen)[kl:, :], inmat)
            for j, p in enumerate(layer.coding):
                rows[p] = coding[j]
        parity_pos = self.chunk_mapping[self.k :]
        if any(p not in rows for p in parity_pos):
            return None
        return np.stack([rows[p] for p in parity_pos])

    # -- profile parsing ----------------------------------------------
    def _parse_kml(self, prof: ErasureCodeProfile) -> None:
        """Expand k/m/l into mapping + layers (parse_kml,
        ErasureCodeLrc.cc:291-360). ``local_parity`` picks the
        generated local layers' code: ``rs`` (default; reed_sol_van,
        the corpus-pinned layout) or ``xor`` (the xor plugin —
        Azure-LRC-style XOR local parities whose repair rides the
        schedule engine). Global layers are always RS."""
        local_parity = prof.pop("local_parity", "rs")
        if local_parity not in ("rs", "xor"):
            raise ValueError(
                f"local_parity={local_parity!r} must be 'rs' or 'xor'"
            )
        k = to_int("k", prof, -1)
        m = to_int("m", prof, -1)
        l = to_int("l", prof, -1)
        if k == -1 and m == -1 and l == -1:
            if local_parity != "rs":
                raise ValueError(
                    "local_parity applies to the k/m/l form only "
                    "(explicit layers name their own plugin)"
                )
            return
        if -1 in (k, m, l):
            raise ValueError("All of k, m, l must be set or none of them")
        for key in ("mapping", "layers"):
            if key in prof:
                raise ValueError(
                    f"The {key} parameter cannot be set when k, m, l are set"
                )
        if l == 0 or (k + m) % l:
            raise ValueError(f"k + m must be a multiple of l (k={k} m={m} l={l})")
        groups = (k + m) // l
        if k % groups:
            raise ValueError("k must be a multiple of (k + m) / l")
        if m % groups:
            raise ValueError("m must be a multiple of (k + m) / l")
        kg, mg = k // groups, m // groups
        prof["mapping"] = ("D" * kg + "_" * mg + "_") * groups
        layer_list = []
        # Global layer covers every group's data+coding positions.
        layer_list.append(
            [("D" * kg + "c" * mg + "_") * groups, ""]
        )
        # One local layer per group: group data + group coding as local
        # data, the trailing slot as the local parity.
        local_prof = "plugin=xor" if local_parity == "xor" else ""
        for g in range(groups):
            row = (
                "_" * (g * (kg + mg + 1))
                + "D" * (kg + mg)
                + "c"
                + "_" * ((groups - g - 1) * (kg + mg + 1))
            )
            layer_list.append([row, local_prof])
        prof["layers"] = json.dumps(layer_list)

    def _layers_parse(self, description: str) -> list[Layer]:
        try:
            arr = json.loads(description)
        except json.JSONDecodeError as e:
            raise ValueError(f"layers is not valid JSON: {e}") from e
        if not isinstance(arr, list):
            raise ValueError(f"layers must be a JSON array, got {arr!r}")
        layers = []
        for pos, entry in enumerate(arr):
            if not isinstance(entry, list):
                raise ValueError(
                    f"each element of layers must be a JSON array but "
                    f"position {pos} is {entry!r}"
                )
            if not entry or not isinstance(entry[0], str):
                raise ValueError(
                    f"the first element of entry {pos} must be a string"
                )
            chunks_map = entry[0]
            layer_prof: ErasureCodeProfile = {}
            if len(entry) > 1:
                second = entry[1]
                if isinstance(second, dict):
                    layer_prof = {k: str(v) for k, v in second.items()}
                elif isinstance(second, str):
                    for kv in second.split():
                        if "=" not in kv:
                            raise ValueError(
                                f"expected key=value in layer profile, got {kv!r}"
                            )
                        key, val = kv.split("=", 1)
                        layer_prof[key] = val
                else:
                    raise ValueError(
                        f"the second element of entry {pos} must be a "
                        f"string or object, got {second!r}"
                    )
            layers.append(Layer(chunks_map, layer_prof))
        return layers

    def _sanity_checks(self, description: str) -> None:
        if len(self.layers) < 1:
            raise ValueError(
                f"layers parameter has {len(self.layers)} which is less "
                f"than the minimum of one: {description}"
            )
        n = len(self.mapping)
        for i, layer in enumerate(self.layers):
            if len(layer.chunks_map) != n:
                raise ValueError(
                    f"the mapping of layer {i} ({layer.chunks_map!r}) is "
                    f"expected to be {n} characters long but is "
                    f"{len(layer.chunks_map)} characters long instead"
                )
        # Producibility: walking layers in encode order, every layer
        # data position must already be known (global 'D' or an earlier
        # layer's 'c'), and every non-'D' mapping position must be some
        # layer's coding output — otherwise encode would emit garbage
        # or crash where the reference rejects the profile.
        known = {i for i, ch in enumerate(self.mapping) if ch == "D"}
        for i, layer in enumerate(self.layers):
            missing = [p for p in layer.data if p not in known]
            if missing:
                raise ValueError(
                    f"layer {i} ({layer.chunks_map!r}) reads positions "
                    f"{missing} that no earlier layer produces"
                )
            known |= set(layer.coding)
        unproduced = [
            p for p, ch in enumerate(self.mapping)
            if ch != "D" and p not in known
        ]
        if unproduced:
            raise ValueError(
                f"mapping positions {unproduced} are coding chunks but "
                f"no layer produces them"
            )

    # -- geometry ------------------------------------------------------
    def get_flags(self) -> Flag:
        return (
            Flag.PARTIAL_READ_OPTIMIZATION
            | Flag.PARTIAL_WRITE_OPTIMIZATION
            | Flag.ZERO_INPUT_ZERO_OUTPUT
        )

    # -- position/logical translation ---------------------------------
    def _to_positions(self, logical: set[int]) -> set[int]:
        return {self.chunk_mapping[s] for s in logical}

    def _to_logical(self, positions: set[int]) -> set[int]:
        return {self._pos_to_logical[p] for p in positions}

    # -- encode --------------------------------------------------------
    def encode_chunks(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        if self._composite is not None:
            return self._encode_composite(data)
        return self._encode_layered(data)

    def _encode_composite(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        """All layers as one matrix apply (see init)."""
        import numpy as np

        shards, xp = self._shard_list_xp(data)
        if self._shards_host_route(shards, xp is np):
            from ceph_tpu.gf import gf_apply_bytes_host

            _dispatch_counters().inc("host_encode")
            out = gf_apply_bytes_host(
                self._composite, np.stack(shards, axis=-2)
            )
            return {
                self.k + j: out[..., j, :] for j in range(self.m)
            }
        outs = self._dispatch_bitmatrix_shards(
            self._comp_bmat_np, self._comp_bmat, shards, "encode"
        )
        return {self.k + j: outs[j] for j in range(self.m)}

    def _encode_layered(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        sample = next(iter(data.values()))
        pool: dict[int, jax.Array] = {}
        for i in range(self.k):
            pool[self.chunk_mapping[i]] = data.get(
                i, jnp.zeros_like(sample)
            )
        # Apply layers in order: the global layer first, then locals
        # (which may consume globally-generated coding chunks as their
        # data — the generated kml layout does exactly this).
        for layer in self.layers:
            kl = len(layer.data)
            layer_in = {j: pool[p] for j, p in enumerate(layer.data) if p in pool}
            parity = layer.codec.encode_chunks(layer_in)
            for j, p in enumerate(layer.coding):
                pool[p] = parity[kl + j]
        return {
            self.k + j: pool[p]
            for j, p in enumerate(self.chunk_mapping[self.k :])
        }

    # -- decode --------------------------------------------------------
    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        pool: dict[int, jax.Array] = {
            self.chunk_mapping[s]: arr for s, arr in chunks.items()
        }
        want_pos = self._to_positions(set(want_to_read))
        n = len(self.mapping)
        # Reverse passes until converged (decode_chunks reverse-layer
        # walk, ErasureCodeLrc.cc): local layers rebuild their group
        # cheaply; the global layer mops up.
        progress = True
        while progress and not want_pos <= set(pool):
            progress = False
            for layer in reversed(self.layers):
                erased = [p for p in layer.chunks if p not in pool]
                if not erased:
                    continue
                inner_m = layer.codec.get_coding_chunk_count()
                if len(erased) > inner_m:
                    continue
                avail = {p for p in layer.chunk_set if p in pool}
                # Inner decode over layer-local ids.
                inner_id = {p: j for j, p in enumerate(layer.chunks)}
                inner_chunks = {inner_id[p]: pool[p] for p in avail}
                inner_want = {inner_id[p] for p in erased}
                try:
                    out = layer.codec.decode_chunks(inner_want, inner_chunks)
                except ValueError:
                    continue
                for p in erased:
                    pool[p] = out[inner_id[p]]
                progress = True
        missing = want_pos - set(pool)
        if missing:
            raise ValueError(
                f"unable to read positions {sorted(missing)} from "
                f"{sorted(self._to_logical(set(pool) & set(range(n))))}"
            )
        return {
            s: pool[self.chunk_mapping[s]] for s in want_to_read
        }

    # -- planning ------------------------------------------------------
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> SubChunkPlan:
        """The 3-case locality-aware minimum (ErasureCodeLrc.cc
        _minimum_to_decode): no-erasure fast path; cheapest recovering
        layers bottom-up; then all-available if a multi-layer cascade
        can still recover everything."""
        want_pos = self._to_positions(set(want_to_read))
        avail_pos = self._to_positions(set(available))
        n = len(self.mapping)
        erasures_total = {p for p in range(n) if p not in avail_pos}
        erasures_want = want_pos & erasures_total

        if not erasures_want:
            return {s: [(0, 1)] for s in want_to_read}

        minimum: set[int] = set()
        erasures_not_recovered = set(erasures_total)
        for layer in reversed(self.layers):
            layer_want = want_pos & layer.chunk_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                minimum |= layer_want
                continue
            erased = layer.chunk_set & erasures_not_recovered
            if len(erased) > layer.codec.get_coding_chunk_count():
                continue
            minimum |= layer.chunk_set - erasures_not_recovered
            erasures_not_recovered -= erased
            erasures_want -= erased
        if not erasures_want:
            minimum |= want_pos
            minimum -= erasures_total
            return {s: [(0, 1)] for s in self._to_logical(minimum)}

        # Case 3: cascade over all layers, greedily marking recoverable.
        remaining = set(erasures_total)
        for layer in reversed(self.layers):
            layer_erasures = layer.chunk_set & remaining
            if not layer_erasures:
                continue
            if len(layer_erasures) <= layer.codec.get_coding_chunk_count():
                remaining -= layer_erasures
        if not remaining:
            return {s: [(0, 1)] for s in self._to_logical(avail_pos)}
        raise ValueError(
            f"not enough chunks in {sorted(available)} to read "
            f"{sorted(want_to_read)}"
        )


registry.register("lrc", LrcCodec, PLUGIN_ABI_VERSION)
