"""Codec plugin registry.

The dlopen-free analog of ``ErasureCodePluginRegistry``
(src/erasure-code/ErasureCodePlugin.{h,cc}): a process-wide singleton
mapping plugin name -> factory, with the same tested contract —
version handshake before registration (ErasureCodePlugin.cc:120-178),
factory() caching, ``preload()`` at startup, and typed failures for the
load-path behaviors the reference exercises with fake plugins
(FailToInitialize / FailToRegister / MissingVersion —
src/test/erasure-code/ErasureCodePlugin*.cc).
"""

from __future__ import annotations

import threading
from typing import Callable

from ceph_tpu import PLUGIN_ABI_VERSION

from .interface import ErasureCodec, ErasureCodeProfile


class PluginLoadError(RuntimeError):
    """Load/handshake failures (bad version, missing entry point)."""


class ErasureCodePluginRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._factories: dict[str, Callable[[], ErasureCodec]] = {}
        self._versions: dict[str, str] = {}

    def register(
        self,
        name: str,
        factory: Callable[[], ErasureCodec],
        version: str = PLUGIN_ABI_VERSION,
    ) -> None:
        """The __erasure_code_init entry-point analog. Refuses mismatched
        ABI versions (the __erasure_code_version handshake)."""
        if version != PLUGIN_ABI_VERSION:
            raise PluginLoadError(
                f"plugin {name!r} ABI {version!r} != {PLUGIN_ABI_VERSION!r}"
            )
        with self._lock:
            if name in self._factories:
                raise PluginLoadError(f"plugin {name!r} already registered")
            self._factories[name] = factory
            self._versions[name] = version

    def load(self, name: str) -> None:
        """Import ceph_tpu.codecs.<name> so it can self-register — the
        dlopen("libec_<name>.so") analog."""
        import importlib

        with self._lock:
            if name in self._factories:
                return
        try:
            importlib.import_module(f"ceph_tpu.codecs.{name}")
        except ImportError as e:
            raise PluginLoadError(f"cannot load plugin {name!r}: {e}") from e
        with self._lock:
            if name not in self._factories:
                raise PluginLoadError(
                    f"plugin module {name!r} loaded but did not register"
                )

    def preload(self, names: list[str]) -> None:
        """Daemon-start preload (verified by the reference's standalone
        tests, qa/standalone/erasure-code/test-erasure-code.sh:35)."""
        for n in names:
            self.load(n)

    def factory(
        self, name: str, profile: ErasureCodeProfile
    ) -> ErasureCodec:
        """Instantiate + init a codec; ValueError propagates for invalid
        profiles (the mon-side validation path, OSDMonitor.cc:7714)."""
        self.load(name)
        with self._lock:
            fac = self._factories[name]
        codec = fac()
        codec.init(dict(profile))
        return codec

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._factories)


registry = ErasureCodePluginRegistry()


def create_codec(name: str, **profile: str) -> ErasureCodec:
    """Convenience: ``create_codec("isa", k="8", m="4")``."""
    return registry.factory(name, {k: str(v) for k, v in profile.items()})
