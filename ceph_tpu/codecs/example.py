"""Toy XOR codec — the ``ErasureCodeExample`` analog.

The reference exercises its base-class logic against a trivial XOR
code (src/test/erasure-code/ErasureCodeExample.h: k data chunks, one
parity = XOR of all, any single erasure recoverable). Same role here:
a minimal, obviously-correct codec for registry and base-class tests,
and the smallest possible example of implementing the codec contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import ErasureCodeBase, to_int
from .interface import ErasureCodeProfile, Flag
from .registry import registry


class ErasureCodeExample(ErasureCodeBase):
    """k data + 1 XOR parity; decodes any single missing chunk."""

    def init(self, profile: ErasureCodeProfile) -> None:
        self.profile = dict(profile)
        self.k = to_int("k", profile, 2)
        self.m = 1
        if self.k < 2:
            raise ValueError(f"k={self.k} must be >= 2")

    def get_flags(self) -> Flag:
        return Flag.ZERO_PADDING_EXPECTED | Flag.PARITY_DELTA_OPTIMIZATION

    def encode_chunks(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        shards = self._stack_data(data)
        parity = shards[..., 0, :]
        for i in range(1, self.k):
            parity = jnp.bitwise_xor(parity, shards[..., i, :])
        return {self.k: parity}

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        missing = [s for s in want_to_read if s not in chunks]
        if not missing:
            return {s: chunks[s] for s in want_to_read}
        if len(missing) > 1:
            raise ValueError(
                f"XOR code cannot decode {len(missing)} erasures"
            )
        acc = None
        for s, c in chunks.items():
            if s <= self.k:  # data or the single parity
                acc = c if acc is None else jnp.bitwise_xor(acc, c)
        out = {s: chunks[s] for s in want_to_read if s in chunks}
        out[missing[0]] = acc
        return out

    def encode_delta(
        self, old_data: jax.Array, new_data: jax.Array
    ) -> jax.Array:
        return jnp.bitwise_xor(old_data, new_data)

    def apply_delta(
        self,
        delta: dict[int, jax.Array],
        parity: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        out = dict(parity)
        for _shard, d in delta.items():
            out[self.k] = jnp.bitwise_xor(out[self.k], d)
        return out


registry.register("example", ErasureCodeExample)
