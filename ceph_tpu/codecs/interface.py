"""The erasure-codec contract.

Behavioral mirror of ``ErasureCodeInterface``
(reference src/erasure-code/ErasureCodeInterface.h:182-725), new
("optimized EC") generation: chunk maps are ``dict[int, Array]`` keyed by
shard id (the ``shard_id_map`` analog), encode/decode operate on
batched device arrays, parity-delta read-modify-write is first-class,
and sub-chunk granularity (CLAY) is expressed as per-shard
``(offset, count)`` ranges exactly as the reference's
``minimum_to_decode`` returns them (ErasureCodeInterface.h:309-344).

Design deltas from the reference, on purpose (TPU-first):

- Chunks carry an arbitrary leading batch shape ``[..., chunk_bytes]``;
  a "stripe batch" is one device array, so a million stripes encode in
  one MXU dispatch instead of a per-stripe virtual call.
- No dlopen: codecs are Python classes in a registry with an explicit
  ABI-version handshake (``ceph_tpu.PLUGIN_ABI_VERSION``), preserving
  the load-path contract that the reference tests aggressively
  (src/test/erasure-code/ErasureCodePlugin*.cc).
"""

from __future__ import annotations

import enum
from typing import Protocol, runtime_checkable

import jax

ErasureCodeProfile = dict[str, str]

# Per-shard sub-chunk read plan: list of (offset, count) in sub-chunk
# units — ErasureCodeInterface.h:309 ("vector<pair<int,int>>").
SubChunkPlan = dict[int, list[tuple[int, int]]]


class Flag(enum.Flag):
    """Plugin optimization capability flags.

    Mirrors the enum at ErasureCodeInterface.h:646-684. The pipeline
    consults these to choose partial-write strategies (WritePlan) and
    zero-elision, exactly like ECTransaction does in the reference.
    """

    NONE = 0
    PARTIAL_READ_OPTIMIZATION = enum.auto()
    PARTIAL_WRITE_OPTIMIZATION = enum.auto()
    ZERO_INPUT_ZERO_OUTPUT = enum.auto()
    ZERO_PADDING_EXPECTED = enum.auto()
    PARITY_DELTA_OPTIMIZATION = enum.auto()
    REQUIRE_SUB_CHUNKS = enum.auto()
    OPTIMIZED_SUPPORTED = enum.auto()
    #: Parity-delta windows must be whole chunks: packet-layout codes
    #: (liberation family) scatter a sub-chunk write's parity update
    #: across the entire chunk through the packet structure — the
    #: packetsize-granularity constraint of jerasure's
    #: schedule_apply_delta (ErasureCodeJerasure.h:110-119). The
    #: write planner chunk-aligns parity extents and the delta driver
    #: hands the codec chunk-shaped buffers when this is set.
    PARITY_DELTA_CHUNK_GRANULARITY = enum.auto()


@runtime_checkable
class ErasureCodec(Protocol):
    """The codec contract. All array maps are ``{shard_id: [..., bytes]}``.

    Shard ids 0..k-1 are data, k..k+m-1 are parity *logical* positions;
    ``get_chunk_mapping`` permutes logical -> stored positions
    (ErasureCodeInterface.h:613).
    """

    def init(self, profile: ErasureCodeProfile) -> None:
        """Validate + adopt a profile; raise ValueError on bad/missing keys
        (the init/parse contract of ErasureCodeInterface.h:223-240)."""
        ...

    def get_chunk_count(self) -> int: ...          # k + m
    def get_data_chunk_count(self) -> int: ...     # k
    def get_coding_chunk_count(self) -> int: ...   # m
    def get_sub_chunk_count(self) -> int: ...      # 1 except CLAY (q^t)

    def get_chunk_size(self, stripe_width: int) -> int:
        """Bytes per chunk for an object of ``stripe_width`` bytes,
        including padding/alignment (ErasureCodeInterface.h:269)."""
        ...

    def get_flags(self) -> Flag: ...

    def get_chunk_mapping(self) -> list[int]: ...

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> SubChunkPlan:
        """Minimum shards (with sub-chunk ranges) needed to produce
        ``want_to_read``; raise IOError-alike ValueError if impossible
        (ErasureCodeInterface.h:309)."""
        ...

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int]
    ) -> set[int]:
        """Cost-aware variant (ErasureCodeInterface.h:346)."""
        ...

    def encode_chunks(
        self, data: dict[int, jax.Array]
    ) -> dict[int, jax.Array]:
        """All-data-shards in, parity map out (ErasureCodeInterface.h:449).
        Missing data shards are treated as zero (the shared zero-buffer
        convention of the reference's encode_chunks)."""
        ...

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        """Reconstruct ``want_to_read`` shards from surviving ``chunks``
        (ErasureCodeInterface.h:571)."""
        ...

    def encode_delta(
        self, old_data: jax.Array, new_data: jax.Array
    ) -> jax.Array:
        """Delta for parity-delta RMW (ErasureCodeInterface.h:471)."""
        ...

    def apply_delta(
        self,
        delta: dict[int, jax.Array],
        parity: dict[int, jax.Array],
    ) -> dict[int, jax.Array]:
        """parity' = parity + G_col * delta per changed data shard
        (ErasureCodeInterface.h:499)."""
        ...
