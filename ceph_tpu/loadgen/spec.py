"""Declarative workload specs — the radosbench/ceph_test_rados
workload surface (qa/suites/rados/thrash-erasure-code/workloads/
ec-radosbench.yaml collapsed to a dataclass).

A spec names an op mix (seq/rand full-object writes, reads,
reconstruct-reads, sub-stripe RMW overwrites), sizing (object size,
object count, queue depth = closed-loop worker count), an object
popularity law (uniform or zipfian), and the run length in ops.
Everything is deterministic from ``seed``: object contents, patch
bytes, popularity draws, and the op sequence are all derived from it,
so a failed run replays bit-identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

#: op classes a mix may weight (driver.py implements each)
OP_CLASSES = (
    "seq_write", "rand_write", "read", "reconstruct_read",
    "rmw_overwrite",
)


def parse_mix(text: str) -> dict[str, float]:
    """``"seq_write=2,read=5,rmw_overwrite=1"`` -> weight dict.
    Unknown classes are an error (a typo'd class silently dropping a
    workload leg would fake coverage)."""
    mix: dict[str, float] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition("=")
        name = name.strip()
        if name not in OP_CLASSES:
            raise ValueError(
                f"unknown op class {name!r} (know {OP_CLASSES})"
            )
        mix[name] = float(w) if w else 1.0
    if not mix or sum(mix.values()) <= 0:
        raise ValueError(f"empty op mix {text!r}")
    return mix


@dataclass
class WorkloadSpec:
    """One load-generation run, fully determined by its fields."""

    #: op class -> weight (normalized at run time)
    mix: dict[str, float] = field(
        default_factory=lambda: {"seq_write": 1.0, "read": 1.0}
    )
    object_size: int = 64 * 1024
    #: working-set cap: seq_write beyond this wraps onto rand_write
    #: targets so the set stays bounded (radosbench --no-cleanup cap)
    max_objects: int = 256
    #: closed-loop workers == queue depth (each worker has exactly
    #: one op in flight, the radosbench -t contract)
    queue_depth: int = 8
    total_ops: int = 200
    #: ops excluded from histograms/throughput at the front (JIT
    #: compile + connection warmup; still accounted for exactly-once)
    warmup_ops: int = 0
    #: "uniform" | "zipfian" object pick for read/overwrite classes
    popularity: str = "uniform"
    zipf_theta: float = 0.9
    #: sub-stripe RMW patch length cap (bytes)
    rmw_max_len: int = 2048
    seed: int = 0xEC
    #: measure small-op latency on the device clock (tunnel-RTT
    #: independent percentiles — see recorder.DeviceClock)
    device_clock: bool = False
    #: pipelined submission (round-10): a few issuer threads keep up
    #: to ``queue_depth`` ASYNC ops on the wire through the objecter's
    #: completion engine, instead of one blocking thread per depth
    #: slot — queue depth actually reaches the wire at qd ≫ 12.
    #: False restores the classic one-thread-per-slot closed loop.
    async_submit: bool = True
    #: capture the N slowest assembled traces at end of run into the
    #: report (``report["traces"]``: span trees + critical paths +
    #: Chrome trace JSON — utils/trace_assembly.py); 0 = off
    trace_capture: int = 0
    #: multi-tenant mode: tenant name -> override dict. Each tenant
    #: runs its OWN closed loop (own IoCtx tagged with the tenant, own
    #: recorder/histograms, own oid namespace via a derived seed) with
    #: any of this spec's fields overridden per tenant — ``mix`` (dict
    #: or parse_mix string), ``object_size``, ``queue_depth``,
    #: ``total_ops``, ... — plus an optional ``qos`` key: a QoSSpec
    #: field dict installed on the pool for that tenant before the run
    #: (reservation/weight/limit in ops/s and bytes/s). Empty dict =
    #: classic single-tenant run.
    tenants: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        for name in self.mix:
            if name not in OP_CLASSES:
                raise ValueError(f"unknown op class {name!r}")
        if sum(self.mix.values()) <= 0:
            raise ValueError("op mix weights must sum > 0")
        if self.queue_depth < 1 or self.total_ops < 1:
            raise ValueError("queue_depth and total_ops must be >= 1")
        if self.object_size < 1 or self.max_objects < 1:
            raise ValueError(
                "object_size and max_objects must be >= 1"
            )
        if self.warmup_ops >= self.total_ops:
            raise ValueError("warmup_ops must be < total_ops")
        if self.popularity not in ("uniform", "zipfian"):
            raise ValueError(
                f"popularity must be uniform|zipfian, "
                f"got {self.popularity!r}"
            )


def tenant_specs(
    spec: WorkloadSpec,
) -> "dict[str, tuple[WorkloadSpec, dict | None]]":
    """Explode a multi-tenant spec into per-tenant sub-specs:
    ``tenant -> (spec, qos)`` where ``qos`` is the tenant's QoSSpec
    field dict (or None). Each sub-spec inherits every base field,
    applies the tenant's overrides, and derives a per-tenant seed so
    oid namespaces (``lg-<seed>-<idx>``), contents and op sequences
    never collide across tenants."""
    import zlib
    from dataclasses import fields as _fields

    base = {
        f.name: getattr(spec, f.name)
        for f in _fields(spec) if f.name != "tenants"
    }
    out: dict[str, tuple[WorkloadSpec, dict | None]] = {}
    for tenant in sorted(spec.tenants):
        ov = dict(spec.tenants[tenant] or {})
        qos = ov.pop("qos", None)
        if isinstance(ov.get("mix"), str):
            ov["mix"] = parse_mix(ov["mix"])
        kw = dict(base)
        kw["seed"] = (
            spec.seed ^ (zlib.crc32(tenant.encode()) & 0x7FFFFF)
        )
        kw.update(ov)
        out[tenant] = (WorkloadSpec(**kw), qos)
    return out


def default_tenants(n: int) -> dict:
    """``--tenants N``: N identically-shaped tenants t0..t{N-1}
    (per-tenant knobs come from explicit ``tenants=`` specs)."""
    if n < 1:
        raise ValueError("tenants must be >= 1")
    return {f"t{i}": {} for i in range(n)}


class Popularity:
    """Object-index sampler: uniform, or zipfian by popularity rank
    (rank r drawn with mass 1/r^theta — the YCSB hot-set law; object
    identity is a stable shuffle of ranks so heat is spread across
    the namespace, not clustered at low indices)."""

    def __init__(self, spec: WorkloadSpec) -> None:
        self._spec = spec
        self._cdf: np.ndarray | None = None
        self._perm: np.ndarray | None = None
        self._cdf_n = 0

    def pick(self, rng: np.random.Generator, n: int) -> int:
        """An index in [0, n) under the spec's law."""
        if n <= 1:
            return 0
        if self._spec.popularity == "uniform":
            return int(rng.integers(0, n))
        if self._cdf is None or self._cdf_n != n:
            w = 1.0 / np.power(
                np.arange(1, n + 1), self._spec.zipf_theta
            )
            self._cdf = np.cumsum(w) / w.sum()
            self._perm = np.random.default_rng(
                self._spec.seed ^ 0x21F
            ).permutation(n)
            self._cdf_n = n
        rank = int(np.searchsorted(self._cdf, rng.random()))
        return int(self._perm[min(rank, n - 1)])


def object_bytes(seed: int, obj_idx: int, version: int,
                 size: int) -> bytes:
    """Deterministic full-object content for (spec seed, object,
    version) — verification regenerates instead of remembering."""
    return np.random.default_rng(
        [seed & 0x7FFFFFFF, obj_idx, version]
    ).integers(0, 256, size, dtype=np.uint8).tobytes()


def patch_bytes(
    seed: int, obj_idx: int, version: int, patch_no: int,
    size: int, max_len: int,
) -> tuple[int, bytes]:
    """Deterministic RMW patch #patch_no on top of (version): returns
    (offset, payload). Readers replay base + patches 1..n to rebuild
    the expected image with zero per-object memory."""
    rng = np.random.default_rng(
        [seed & 0x7FFFFFFF, obj_idx, version, patch_no]
    )
    ln = int(rng.integers(1, min(max_len, size) + 1))
    off = int(rng.integers(0, max(size - ln, 0) + 1))
    return off, rng.integers(0, 256, ln, dtype=np.uint8).tobytes()


def expected_image(
    seed: int, obj_idx: int, version: int, n_patches: int,
    size: int, max_len: int,
) -> bytes:
    """The object's exact expected bytes after ``n_patches`` RMW
    overwrites on ``version`` — pure function of the spec."""
    img = bytearray(object_bytes(seed, obj_idx, version, size))
    for p in range(1, n_patches + 1):
        off, payload = patch_bytes(
            seed, obj_idx, version, p, size, max_len
        )
        img[off:off + len(payload)] = payload
    return bytes(img)


#: canned specs (bench/CLI `--preset`); smoke is the CI surface
PRESETS: dict[str, dict] = {
    "smoke": dict(
        mix={"seq_write": 3, "rand_write": 1, "read": 3,
             "reconstruct_read": 1, "rmw_overwrite": 1},
        object_size=8192, max_objects=16, queue_depth=4,
        total_ops=80, warmup_ops=8, popularity="zipfian",
    ),
    "mixed": dict(
        mix={"seq_write": 2, "rand_write": 1, "read": 4,
             "reconstruct_read": 1, "rmw_overwrite": 1},
        object_size=256 * 1024, max_objects=128, queue_depth=16,
        total_ops=600, warmup_ops=32, popularity="zipfian",
    ),
    "write-heavy": dict(
        mix={"seq_write": 4, "rand_write": 2, "rmw_overwrite": 1},
        object_size=1 << 20, max_objects=64, queue_depth=16,
        total_ops=400, warmup_ops=16,
    ),
    "read-heavy": dict(
        mix={"seq_write": 1, "read": 8},
        object_size=1 << 20, max_objects=64, queue_depth=16,
        total_ops=400, warmup_ops=16, popularity="zipfian",
    ),
}


def preset(name: str, **overrides) -> WorkloadSpec:
    if name not in PRESETS:
        raise ValueError(
            f"unknown preset {name!r} (know {sorted(PRESETS)})"
        )
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return WorkloadSpec(**kw)
