"""Log2-bucketed latency histogram — the HDR-histogram role.

The reference records op latencies into ``PerfCounters`` power-of-2
histograms (``l_osd_op_lat`` and friends) and teuthology's radosbench
wrapper reports percentile latencies per op class. Here one compact
structure serves both: log2 major buckets with linear sub-buckets
(HDR-style — constant relative error everywhere on the range), exact
min/max tracking, merge for per-worker aggregation, and interpolated
percentiles.

Values are SECONDS. The default range spans 1 us .. 128 s; anything
below clamps into the first bucket, anything above into the last
(and ``max`` still reports the true extreme).
"""

from __future__ import annotations

import math

#: linear sub-buckets per power of two: 16 gives <= 6.25% relative
#: quantile error, plenty under scheduler jitter
SUBS = 16
_LO = 1e-6        # 1 us: below any real op
_DECADES = 27     # 2**27 us ~= 134 s: above any sane op timeout


class Log2Histogram:
    """Fixed-size log2/linear histogram of seconds."""

    def __init__(self) -> None:
        self.counts = [0] * (_DECADES * SUBS)
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def _index(self, v: float) -> int:
        if v <= _LO:
            return 0
        major = int(math.log2(v / _LO))
        if major >= _DECADES:
            return len(self.counts) - 1
        lo = _LO * (1 << major)
        sub = int((v - lo) / lo * SUBS)
        return min(major * SUBS + min(sub, SUBS - 1),
                   len(self.counts) - 1)

    def _bounds(self, idx: int) -> tuple[float, float]:
        major, sub = divmod(idx, SUBS)
        lo = _LO * (1 << major)
        return lo + sub * lo / SUBS, lo + (sub + 1) * lo / SUBS

    def record(self, seconds: float) -> None:
        self.counts[self._index(seconds)] += 1
        self.n += 1
        self.sum += seconds
        if seconds < self.min:
            self.min = seconds
        if seconds > self.max:
            self.max = seconds

    def merge(self, other: "Log2Histogram") -> None:
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def percentile(self, p: float) -> float:
        """Interpolated p-th percentile (0 < p <= 100) in seconds.
        The true min/max pin the extremes so a single-sample histogram
        answers exactly."""
        if self.n == 0:
            return 0.0
        rank = p / 100.0 * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo, hi = self._bounds(i)
                frac = (rank - seen) / c
                v = lo + (hi - lo) * frac
                return min(max(v, self.min), self.max)
            seen += c
        return self.max

    @property
    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    def snapshot(self) -> dict:
        """JSON-able summary (ms, the human unit for op latency)."""
        if self.n == 0:
            return {"n": 0}
        return {
            "n": self.n,
            "mean_ms": round(self.mean * 1e3, 3),
            "p50_ms": round(self.percentile(50) * 1e3, 3),
            "p95_ms": round(self.percentile(95) * 1e3, 3),
            "p99_ms": round(self.percentile(99) * 1e3, 3),
            "max_ms": round(self.max * 1e3, 3),
        }

    def perf_buckets(self) -> tuple[list[float], list[int]]:
        """(bounds_seconds, counts) collapsed to whole powers of two —
        the shape ``PerfCountersBuilder.add_histogram`` wants (the
        full sub-bucket grid would bloat every perf dump)."""
        bounds = [_LO * (1 << d) for d in range(1, _DECADES)]
        coarse = [0] * _DECADES
        for i, c in enumerate(self.counts):
            coarse[i // SUBS] += c
        # counts layout for PerfCounters: one slot per bound + overflow
        return bounds, coarse
