"""Live-cluster load generation & benchmarking — the radosbench /
thrash-erasure-code-workload analog (qa/suites/rados/
thrash-erasure-code/workloads/ec-radosbench.yaml).

Everything the kernel benchmarks cannot see lives here: the client ->
socket OSDs -> device codec -> store money path under a declarative
op mix, with per-op verification, exactly-once accounting, HDR-style
latency recording, and a fault schedule that kills/revives OSDs
mid-run to measure degraded-window throughput and time-to-recovered.

    from ceph_tpu.loadgen import (
        FaultEvent, FaultSchedule, LoadCluster, WorkloadSpec, run_spec,
    )

    cluster = LoadCluster(n_osds=6, k=3, m=2)
    try:
        report = run_spec(
            cluster,
            WorkloadSpec(mix={"seq_write": 1, "read": 2},
                         total_ops=200),
            FaultSchedule([FaultEvent(60, "kill"),
                           FaultEvent(120, "revive")]),
        )
    finally:
        cluster.shutdown()
"""

from .cluster import LoadCluster
from .driver import LoadGenerator, run_multi_tenant, run_spec
from .faults import FaultEvent, FaultSchedule
from .forensics import run_is_green, write_bundle
from .histogram import Log2Histogram
from .recorder import DeviceClock, RunRecorder
from .spec import (
    OP_CLASSES,
    PRESETS,
    Popularity,
    WorkloadSpec,
    default_tenants,
    expected_image,
    object_bytes,
    parse_mix,
    patch_bytes,
    preset,
    tenant_specs,
)

__all__ = [
    "DeviceClock",
    "FaultEvent",
    "FaultSchedule",
    "LoadCluster",
    "LoadGenerator",
    "Log2Histogram",
    "OP_CLASSES",
    "PRESETS",
    "Popularity",
    "RunRecorder",
    "WorkloadSpec",
    "default_tenants",
    "expected_image",
    "object_bytes",
    "parse_mix",
    "patch_bytes",
    "preset",
    "run_is_green",
    "run_multi_tenant",
    "run_spec",
    "tenant_specs",
    "write_bundle",
]
