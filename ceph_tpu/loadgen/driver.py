"""Closed-loop multi-threaded load driver — the radosbench analog.

N workers (= queue depth) each keep exactly one op in flight through
the librados-style client against a live cluster: real sockets, the
map-aware objecter retry loop, device codecs on the primaries, real
stores. Every op is verified (content byte-equality AND a crc32c
check of got-vs-expected) and lands in exactly one ledger slot
(``ops_accounted == ops issued`` at exit — the exactly-once check).

Op classes (spec.mix):

- ``seq_write``        full-object write of the next sequential oid
                       (wraps to a version bump once max_objects live)
- ``rand_write``       full-object rewrite of a popular existing oid
- ``read``             full read + verify of a popular existing oid
- ``reconstruct_read`` read targeted at an object whose acting set
                       currently has a dead member — a true degraded/
                       reconstruct read while the fault schedule has
                       an OSD down, accounted as plain ``read`` when
                       the cluster is whole (``reclassified`` counts
                       them; a mix can't fake degraded coverage)
- ``rmw_overwrite``    sub-stripe patch at a derived offset (the
                       parity-delta RMW path), expected image replayed
                       from the deterministic patch chain

Object contents are pure functions of (spec.seed, object, version,
patch chain) — verification regenerates, nothing is remembered, so
the working set can exceed client memory.

Client-side observability: the objecter's ``loadgen_client`` perf
counters (inflight/completed/retried) are live during the run and the
driver adds verify-failure and per-class counters to the same set —
``admin_socket execute("perf dump")`` or the Prometheus exporter can
watch a run from outside, like daemon-side ops."""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.checksum import crc32c_scalar
from ceph_tpu.cluster.osdmap import SHARD_NONE

from .faults import FaultSchedule
from .recorder import DeviceClock, RunRecorder
from ceph_tpu.utils.lockdep import DebugLock

from .spec import (
    Popularity,
    WorkloadSpec,
    expected_image,
    object_bytes,
    patch_bytes,
)


@dataclass
class _ObjState:
    version: int = 1
    n_patches: int = 0
    #: first write landed — readers/overwriters only pick published
    #: objects (state is allocated BEFORE the create write completes,
    #: and a concurrent reader could win the object lock first)
    exists: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock)


class LoadGenerator:
    """Run a WorkloadSpec against a LoadCluster."""

    def __init__(
        self,
        cluster,
        spec: WorkloadSpec,
        fault_schedule: FaultSchedule | None = None,
        io=None,
        perf_name: str = "loadgen",
    ) -> None:
        self.cluster = cluster
        self.spec = spec
        self.faults = fault_schedule
        #: the IoCtx ops go through — a tenant run passes its own
        #: tenant-tagged ioctx so every op carries the tenant id
        self.io = io if io is not None else cluster.io
        self._perf_name = perf_name
        self.recorder = RunRecorder(warmup_ops=spec.warmup_ops)
        self._op_seq = 0
        self._ops_done = 0
        self._seq_next = 0
        self._objects: dict[int, _ObjState] = {}
        self._obj_lock = DebugLock("loadgen.objects")
        self._pick = Popularity(spec)
        self._stop = threading.Event()
        self._errors: list[str] = []
        #: (oid, version, n_patches, got_len, first_diff) per verify
        #: failure — the forensic trail a red run is debugged from
        self.verify_detail: list[tuple] = []
        self.reclassified = 0  # reconstruct_read served while whole
        self._class_names = sorted(spec.mix)
        self._weights = np.array(
            [spec.mix[c] for c in self._class_names], float
        )
        self._weights /= self._weights.sum()
        #: the objecter's client counter set (inflight/completed/
        #: resend/verify_failed) — None for perf-less clients
        self._pc = getattr(
            self.cluster.client.objecter, "perf", None
        )
        self._class_pc = self._build_class_perf()

    def _build_class_perf(self):
        """Per-class completion counters + one latency histogram in
        the process perf collection (`perf dump` / exporter surface,
        updated live per op)."""
        from ceph_tpu.utils import PerfCountersBuilder, perf_collection

        from .histogram import Log2Histogram
        from .spec import OP_CLASSES

        b = PerfCountersBuilder(perf_collection, self._perf_name)
        for cls in OP_CLASSES:
            b.add_u64_counter(f"ops_{cls}", f"completed {cls} ops")
        bounds, _ = Log2Histogram().perf_buckets()
        b.add_histogram(
            "op_latency", bounds, "op latency (seconds, log2)"
        )
        return b.create_perf_counters()

    # -- op bookkeeping -------------------------------------------------
    def _next_op(self) -> int | None:
        """Claim the next global op number, or None when done."""
        with self._obj_lock:
            if self._op_seq >= self.spec.total_ops:
                return None
            self._op_seq += 1
            return self._op_seq

    def _obj(self, idx: int) -> _ObjState:
        with self._obj_lock:
            st = self._objects.get(idx)
            if st is None:
                st = self._objects[idx] = _ObjState()
            return st

    def _live_indices(self) -> list[int]:
        with self._obj_lock:
            return sorted(
                i for i, st in self._objects.items() if st.exists
            )

    def _oid(self, idx: int) -> str:
        return f"lg-{self.spec.seed:x}-{idx}"

    # -- verification ---------------------------------------------------
    def _verify(self, idx: int, got: bytes, version: int,
                n_patches: int) -> bool:
        want = expected_image(
            self.spec.seed, idx, version, n_patches,
            self.spec.object_size, self.spec.rmw_max_len,
        )
        # checksum first (the cheap deep-scrub-style check), then the
        # definitive byte comparison — both must agree
        if crc32c_scalar(0xFFFFFFFF, got) == crc32c_scalar(
            0xFFFFFFFF, want
        ) and got == want:
            return True
        diff = next(
            (i for i, (a, b) in enumerate(zip(got, want)) if a != b),
            min(len(got), len(want)),
        )
        try:  # placement snapshot: which members served this read
            acting = self.cluster.mon.osdmap.object_to_acting(
                self.cluster.pool, self._oid(idx)
            )
        except Exception:
            acting = []
        self.verify_detail.append(
            (self._oid(idx), version, n_patches, len(got), diff,
             list(acting), list(self.cluster.dead),
             got[:24].hex())
        )
        return False

    def _degraded_target(self, rng: np.random.Generator) -> int | None:
        """An existing object whose acting set has a dead member —
        reading it forces shard reconstruction."""
        live = self._live_indices()
        if not live:
            return None
        osdmap = self.cluster.mon.osdmap
        start = int(rng.integers(0, len(live)))
        for off in range(len(live)):
            idx = live[(start + off) % len(live)]
            acting = osdmap.object_to_acting(
                self.cluster.pool, self._oid(idx)
            )
            if any(o == SHARD_NONE for o in acting):
                return idx
        return None

    # -- op implementations ---------------------------------------------
    def _op_seq_write(self, rng) -> tuple[str, int]:
        with self._obj_lock:
            if self._seq_next < self.spec.max_objects:
                idx = self._seq_next
                self._seq_next += 1
            else:
                idx = None
        if idx is None:  # working set full: wrap onto a rewrite
            return self._op_rand_write(rng)
        st = self._obj(idx)
        with st.lock:
            data = object_bytes(
                self.spec.seed, idx, st.version, self.spec.object_size
            )
            try:
                size = self.io.write_full(
                    self._oid(idx), data
                )
            except Exception:
                # outcome unknown (op may or may not have applied):
                # quarantine — the model can no longer predict this
                # object's bytes, so no later op may verify against it
                st.exists = False
                raise
            ok = size == len(data)
            st.exists = st.exists or ok
        return ("seq_write" if ok else "error"), len(data)

    def _op_rand_write(self, rng) -> tuple[str, int]:
        live = self._live_indices()
        if not live:
            return self._op_seq_write(rng)
        idx = live[self._pick.pick(rng, len(live)) % len(live)]
        st = self._obj(idx)
        with st.lock:
            st.version += 1
            st.n_patches = 0
            data = object_bytes(
                self.spec.seed, idx, st.version, self.spec.object_size
            )
            try:
                size = self.io.write_full(
                    self._oid(idx), data
                )
            except Exception:
                st.exists = False  # unknown outcome: quarantine
                raise
            ok = size == len(data)
        return ("rand_write" if ok else "error"), len(data)

    def _op_read(self, rng, want_degraded: bool = False
                 ) -> tuple[str, int]:
        idx = None
        cls = "read"
        if want_degraded:
            idx = self._degraded_target(rng)
            if idx is not None:
                cls = "reconstruct_read"
            else:
                self.reclassified += 1
        if idx is None:
            live = self._live_indices()
            if not live:
                return self._op_seq_write(rng)
            idx = live[self._pick.pick(rng, len(live)) % len(live)]
        st = self._obj(idx)
        with st.lock:
            got = self.io.read(self._oid(idx))
            good = self._verify(idx, got, st.version, st.n_patches)
        if not good:
            self._pc_inc("verify_failed")
            return "verify_failed:" + cls, len(got)
        return cls, len(got)

    def _op_rmw_overwrite(self, rng) -> tuple[str, int]:
        live = self._live_indices()
        if not live:
            return self._op_seq_write(rng)
        idx = live[self._pick.pick(rng, len(live)) % len(live)]
        st = self._obj(idx)
        with st.lock:
            patch_no = st.n_patches + 1
            off, payload = patch_bytes(
                self.spec.seed, idx, st.version, patch_no,
                self.spec.object_size, self.spec.rmw_max_len,
            )
            try:
                self.io.write(
                    self._oid(idx), payload, offset=off
                )
            except Exception:
                st.exists = False  # unknown outcome: quarantine
                raise
            st.n_patches = patch_no
        return "rmw_overwrite", len(payload)

    def _pc_inc(self, key: str) -> None:
        if self._pc is not None:
            self._pc.inc(key)

    # -- async pipelined submission (round-10) --------------------------
    # The classic loop below burns one OS thread per queue-depth slot,
    # each lock-stepping request/reply — at qd ≫ 12 the thread tier,
    # not the wire, is what the depth measures. The pipelined mode
    # keeps up to ``queue_depth`` ops IN FLIGHT through the objecter's
    # async engine with a handful of issuer threads (window semaphore
    # = depth), and a small reaper pool runs the completion half
    # (verify/record/fault-schedule) off the messenger pump threads.
    # Per-object exclusion is unchanged: the object lock is held from
    # submit to reap, exactly the span the sync path holds it.

    #: issuer threads for async mode (the window semaphore, not the
    #: thread count, is the queue depth)
    _N_ISSUERS = 4
    _N_REAPERS = 2

    _WRITE_CLASSES = frozenset(
        {"seq_write", "rand_write", "rmw_overwrite"}
    )

    def _resolve_target(self, req: str, rng) -> tuple[str, int]:
        """The sync impls' delegation rules (seq wraps onto rand once
        the set is full; read/overwrite bootstrap a create while
        nothing exists) flattened to one (class, object index)
        decision, bounded against the all-quarantined corner."""
        cls = req
        for _ in range(6):
            if cls == "seq_write":
                with self._obj_lock:
                    if self._seq_next < self.spec.max_objects:
                        idx = self._seq_next
                        self._seq_next += 1
                        return "seq_write", idx
                cls = "rand_write"
                continue
            if cls == "reconstruct_read":
                idx = self._degraded_target(rng)
                if idx is not None:
                    return "reconstruct_read", idx
                self.reclassified += 1
                cls = "read"
                continue
            live = self._live_indices()
            if not live:
                cls = "seq_write"
                continue
            return cls, live[self._pick.pick(rng, len(live)) % len(live)]
        # every object quarantined AND the namespace full: re-create
        # object 0 (a version-bumped rewrite) so the run can make
        # progress instead of spinning in the delegation loop
        return "rand_write_force", 0

    def _issue(self, req: str, rng) -> None:
        """Submit-half of one op: target resolution, object-lock
        acquire, payload derivation, async submission. The reap-half
        (``_reap_one``) releases the lock and the window slot."""
        cls, idx = self._resolve_target(req, rng)
        force = cls == "rand_write_force"
        if force:
            cls = "rand_write"
        st = self._obj(idx)
        st.lock.acquire()
        ctx: dict = {
            "req": req, "cls": cls, "idx": idx, "st": st,
            "t0": time.monotonic(),
        }

        def done(comp, _ctx=ctx) -> None:
            _ctx["comp"] = comp
            self._done_q.put(_ctx)

        try:
            oid = self._oid(idx)
            if cls in ("seq_write", "rand_write"):
                if cls == "rand_write" and (st.exists or force):
                    st.version += 1
                    st.n_patches = 0
                data = object_bytes(
                    self.spec.seed, idx, st.version,
                    self.spec.object_size,
                )
                ctx["nbytes"] = len(data)
                self.io.aio_write_full(
                    oid, data, on_complete=done
                )
            elif cls == "rmw_overwrite":
                patch_no = st.n_patches + 1
                off, payload = patch_bytes(
                    self.spec.seed, idx, st.version, patch_no,
                    self.spec.object_size, self.spec.rmw_max_len,
                )
                ctx["patch_no"] = patch_no
                ctx["nbytes"] = len(payload)
                self.io.aio_write(
                    oid, payload, offset=off, on_complete=done
                )
            else:  # read / reconstruct_read
                ctx["version"] = st.version
                ctx["n_patches"] = st.n_patches
                self.io.aio_read(oid, on_complete=done)
        except Exception as e:
            # submission itself failed: finish the op inline (exactly
            # one ledger slot either way)
            st.lock.release()
            self.recorder.record(
                req, time.monotonic() - ctx["t0"], 0, ok=False
            )
            self._errors.append(f"{req}: {type(e).__name__}: {e}")
            self._after_op()
            self._window.release()

    def _reap_one(self, ctx: dict) -> None:
        st, comp = ctx["st"], ctx["comp"]
        req, cls, idx = ctx["req"], ctx["cls"], ctx["idx"]
        lat = time.monotonic() - ctx["t0"]
        try:
            if comp.error is not None:
                if cls in self._WRITE_CLASSES:
                    # outcome unknown (the op may or may not have
                    # applied): quarantine — no later op may verify
                    # against this object's bytes
                    st.exists = False
                self.recorder.record(req, lat, 0, ok=False)
                self._errors.append(
                    f"{req}: {type(comp.error).__name__}: {comp.error}"
                )
                return
            if cls in ("seq_write", "rand_write"):
                ok = comp.reply.size == ctx["nbytes"]
                st.exists = st.exists or ok
                if ok:
                    self._record_ok(cls, lat, ctx["nbytes"])
                else:
                    self.recorder.record(
                        req, lat, ctx["nbytes"], ok=False
                    )
            elif cls == "rmw_overwrite":
                st.n_patches = ctx["patch_no"]
                self._record_ok(cls, lat, ctx["nbytes"])
            else:  # read / reconstruct_read
                got = comp.reply.data
                good = self._verify(
                    idx, got, ctx["version"], ctx["n_patches"]
                )
                if good:
                    self._record_ok(cls, lat, len(got))
                else:
                    self._pc_inc("verify_failed")
                    self.recorder.record(
                        cls, lat, len(got), ok=False,
                        verify_failed=True,
                    )
        finally:
            st.lock.release()
            self._after_op()
            self._window.release()

    def _record_ok(self, cls: str, lat: float, nbytes: int) -> None:
        self.recorder.record(cls, lat, nbytes)
        self._class_pc.inc(f"ops_{cls}")
        self._class_pc.hinc("op_latency", lat)

    def _reaper(self) -> None:
        while True:
            ctx = self._done_q.get()
            if ctx is None:
                return
            try:
                self._reap_one(ctx)
            except Exception as e:  # a reaper death would wedge run()
                self._errors.append(
                    f"reap: {type(e).__name__}: {e}"
                )

    def _issuer(self, wid: int) -> None:
        rng = np.random.default_rng(
            [self.spec.seed & 0x7FFFFFFF, 0x40B, wid]
        )
        while not self._stop.is_set():
            self._window.acquire()
            opno = self._next_op()
            if opno is None:
                self._window.release()
                return
            req = self._class_names[
                int(rng.choice(len(self._class_names), p=self._weights))
            ]
            self._issue(req, rng)
        # stopped early: the claimed window slot was never used
        # (issue path releases its own slot on every outcome)

    def _run_async(self) -> None:
        depth = self.spec.queue_depth
        self._window = threading.BoundedSemaphore(depth)
        self._done_q: queue.Queue = queue.Queue()
        reapers = [
            threading.Thread(
                target=self._reaper, daemon=True,
                name=f"loadgen-reap{r}",
            )
            for r in range(self._N_REAPERS)
        ]
        issuers = [
            threading.Thread(
                target=self._issuer, args=(w,), daemon=True,
                name=f"loadgen-issue{w}",
            )
            for w in range(min(depth, self._N_ISSUERS))
        ]
        self.recorder.t_start = time.monotonic()
        for t in reapers + issuers:
            t.start()
        for t in issuers:
            t.join()
        # drain: every in-flight op resolves (the objecter bounds each
        # with its timeout ladder), releasing its window slot
        for _ in range(depth):
            self._window.acquire()
        for _ in reapers:
            self._done_q.put(None)
        for t in reapers:
            t.join()

    # -- the worker loop ------------------------------------------------
    def _worker(self, wid: int) -> None:
        rng = np.random.default_rng(
            [self.spec.seed & 0x7FFFFFFF, 0x40B, wid]
        )
        impls = {
            "seq_write": self._op_seq_write,
            "rand_write": self._op_rand_write,
            "read": lambda r: self._op_read(r, want_degraded=False),
            "reconstruct_read": lambda r: self._op_read(
                r, want_degraded=True
            ),
            "rmw_overwrite": self._op_rmw_overwrite,
        }
        while not self._stop.is_set():
            opno = self._next_op()
            if opno is None:
                return
            req = self._class_names[
                int(rng.choice(len(self._class_names), p=self._weights))
            ]
            t0 = time.monotonic()
            try:
                cls, nbytes = impls[req](rng)
            except Exception as e:
                lat = time.monotonic() - t0
                self.recorder.record(req, lat, 0, ok=False)
                self._errors.append(f"{req}: {type(e).__name__}: {e}")
                self._after_op()
                continue
            lat = time.monotonic() - t0
            if cls.startswith("verify_failed:"):
                self.recorder.record(
                    cls.split(":", 1)[1], lat, nbytes,
                    ok=False, verify_failed=True,
                )
            elif cls == "error":
                self.recorder.record(req, lat, nbytes, ok=False)
            else:
                self.recorder.record(cls, lat, nbytes)
                self._class_pc.inc(f"ops_{cls}")
                self._class_pc.hinc("op_latency", lat)
            self._after_op()

    def _after_op(self) -> None:
        with self._obj_lock:
            self._ops_done += 1
            done = self._ops_done
        if self.faults is not None:
            try:
                self.faults.maybe_fire(done, self.cluster)
            except Exception as e:  # a broken thrash must surface
                self._errors.append(
                    f"fault: {type(e).__name__}: {e}"
                )
                self._stop.set()

    # -- entry point ----------------------------------------------------
    def run(self) -> dict:
        """Execute the spec; returns the full run report."""
        if self.spec.device_clock:
            codec = self.cluster.codec()
            self.recorder.device_floor_s = DeviceClock.measure(
                codec, codec.get_chunk_size(self.spec.object_size)
            )
        if self.spec.async_submit:
            self._run_async()
        else:
            threads = [
                threading.Thread(
                    target=self._worker, args=(w,), daemon=True,
                    name=f"loadgen-w{w}",
                )
                for w in range(self.spec.queue_depth)
            ]
            self.recorder.t_start = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        self.recorder.finish()
        if self.faults is not None:
            self.faults.settle(self.cluster)
        report = self.recorder.report()
        report["ops_in"] = self._op_seq
        report["reclassified_reads"] = self.reclassified
        with self._obj_lock:
            # objects whose write outcome is unknown (quarantined:
            # excluded from verification-bearing ops)
            report["quarantined_objects"] = sum(
                1 for st in self._objects.values() if not st.exists
            )
        report["exactly_once"] = (
            report["ops_in"] == report["ops_accounted"]
        )
        if self._errors:
            report["error_samples"] = self._errors[:10]
        if self.verify_detail:
            report["verify_detail"] = [
                list(t) for t in self.verify_detail[:10]
            ]
        if self.faults is not None:
            report["fault"] = self.faults.metrics(self.recorder)
            report["recovered"] = self.cluster.is_recovered()
        # stats-plane snapshot: the final PG state histogram + the
        # one-line `cli status` digest (soak laps log it; bench_cli
        # prints it on non-green runs)
        mon = getattr(self.cluster, "mon", None)
        if mon is not None and getattr(mon, "pgmap", None) is not None:
            try:
                for d in self.cluster.daemons.values():
                    if d.osd_id not in self.cluster.dead:
                        d.report_pg_stats(force=True)
                from ceph_tpu.cluster.pgmap import (
                    status_dict,
                    status_digest,
                )

                st = status_dict(mon)
                report["pg_states"] = st["pgs"]["histogram"]
                report["degraded_objects"] = st["degraded_objects"]
                report["status_digest"] = status_digest(st)
            except Exception:
                pass  # observability must not redden a green run
        if self.spec.trace_capture:
            # the N slowest assembled traces of the run (span trees +
            # critical paths + Chrome trace JSON): the in-process
            # cluster shares one tracer/tracker, so the process
            # snapshot IS the all-daemons merge
            from ceph_tpu.utils.trace_assembly import capture_traces

            report["traces"] = capture_traces(
                limit=self.spec.trace_capture
            )
        return report


def run_spec(
    cluster, spec: WorkloadSpec,
    fault_schedule: FaultSchedule | None = None,
) -> dict:
    """Convenience: drive ``spec`` on ``cluster`` and report. A spec
    with ``tenants`` fans out to one closed loop per tenant."""
    if spec.tenants:
        return run_multi_tenant(cluster, spec, fault_schedule)
    return LoadGenerator(cluster, spec, fault_schedule).run()


def run_multi_tenant(
    cluster, spec: WorkloadSpec,
    fault_schedule: FaultSchedule | None = None,
) -> dict:
    """Multi-tenant run: one LoadGenerator per tenant, concurrently,
    each through its OWN tenant-tagged IoCtx (the ops carry the tenant
    onto the OSDs' per-tenant mClock classes), its own recorder and a
    ``loadgen.pool.<tenant>`` perf set (the exporter's tenant label).
    A tenant's ``qos`` override installs its QoSSpec on the pool via
    the monitor BEFORE load starts, so the run exercises the pushed
    spec. The fault schedule is driven by the first tenant's op stream
    (exactly one thrash driver — double-firing kills would double the
    chaos). Report: per-tenant sections under ``tenants`` plus
    cluster-wide aggregates."""
    from .spec import tenant_specs

    per_tenant = tenant_specs(spec)
    mon = getattr(cluster, "mon", None)
    for tenant, (_tspec, qos) in per_tenant.items():
        if qos and mon is not None:
            mon.osd_pool_qos_set(cluster.pool, tenant=tenant, **qos)
    first = min(per_tenant) if per_tenant else None
    gens: dict[str, LoadGenerator] = {}
    for tenant, (tspec, _qos) in per_tenant.items():
        gens[tenant] = LoadGenerator(
            cluster, tspec,
            fault_schedule if tenant == first else None,
            io=cluster.client.open_ioctx(cluster.pool, tenant=tenant),
            perf_name=f"loadgen.pool.{tenant}",
        )
    reports: dict[str, dict] = {}
    errs: list = []

    def _one(tenant: str) -> None:
        try:
            reports[tenant] = gens[tenant].run()
        except Exception as e:  # surfaced in the aggregate, not lost
            errs.append(f"{tenant}: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(
            target=_one, args=(t,), daemon=True,
            name=f"loadgen-tenant-{t}",
        )
        for t in sorted(gens)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out: dict = {
        "tenants": {t: reports[t] for t in sorted(reports)},
        "duration_s": max(
            (r["duration_s"] for r in reports.values()), default=0.0
        ),
        "iops": round(
            sum(r["iops"] for r in reports.values()), 1
        ),
        "ops": sum(r["ops"] for r in reports.values()),
        "ops_in": sum(r["ops_in"] for r in reports.values()),
        "ops_accounted": sum(
            r["ops_accounted"] for r in reports.values()
        ),
        "bytes": sum(r["bytes"] for r in reports.values()),
        "gbps": round(
            sum(r["gbps"] for r in reports.values()), 6
        ),
        "verify_failures": sum(
            r["verify_failures"] for r in reports.values()
        ),
        "errors": sum(r["errors"] for r in reports.values()),
        "exactly_once": bool(reports) and all(
            r["exactly_once"] for r in reports.values()
        ),
    }
    if errs:
        out["error_samples"] = errs[:10]
        out["exactly_once"] = False
    for r in reports.values():
        for key in ("fault", "recovered", "pg_states",
                    "status_digest", "degraded_objects"):
            if key in r and key not in out:
                out[key] = r[key]
    return out
