"""The BENCH json ``cluster`` phase: what the LIVE TIER gives back.

Every other bench phase clocks a kernel or a codec dispatch; this one
boots the real mini-cluster (mon + socket OSDs + device codecs +
stores), drives a mixed workload with a mid-run OSD kill/revive, and
reports the end-to-end service numbers next to the kernel ones:

- ``cluster_gbps`` / ``cluster_iops``   measured-window aggregate
- ``cluster_p99_ms``                    small-op p99 from the DEVICE
  clock (host floor replaced by the trip-count-differenced device op
  time — tunnel-RTT independent, no ``latency_degraded`` flag needed;
  ``cluster_p99_host_ms`` keeps the raw host row for comparison)
- ``cluster_degraded_gbps`` / ``cluster_degraded_window_s`` /
  ``cluster_time_to_recovered_s``       the fault-schedule cut
- ``cluster_vs_kernel_frac``            cluster_gbps over the flagship
  kernel encode rate — the tax the whole service stack levies on the
  raw codec (client, sockets, daemon locks, store writes, checksums)

Sized by ``CEPH_TPU_BENCH_CLUSTER_OPS`` (default 240 ops over 48
256-KiB objects at queue depth 12 — a few-minute phase through a
degraded tunnel, seconds locally)."""

from __future__ import annotations

import os

from .cluster import LoadCluster
from .driver import run_spec
from .faults import FaultEvent, FaultSchedule
from .spec import WorkloadSpec


def measure_cluster(result: dict, enc_gbps: float) -> None:
    total_ops = int(
        os.environ.get("CEPH_TPU_BENCH_CLUSTER_OPS", "240")
    )
    cluster = LoadCluster(
        n_osds=6, k=4, m=2, pg_num=8, chunk_size=16384,
    )
    try:
        spec = WorkloadSpec(
            mix={
                "seq_write": 2, "rand_write": 1, "read": 3,
                "reconstruct_read": 1, "rmw_overwrite": 1,
            },
            object_size=256 * 1024,
            max_objects=48,
            queue_depth=12,
            total_ops=total_ops,
            warmup_ops=max(total_ops // 10, 8),
            popularity="zipfian",
            device_clock=True,
        )
        faults = FaultSchedule(
            [
                FaultEvent(at_op=total_ops // 3, action="kill"),
                FaultEvent(at_op=(2 * total_ops) // 3,
                           action="revive"),
            ]
        )
        report = run_spec(cluster, spec, faults)
    finally:
        cluster.shutdown()

    result["cluster_gbps"] = report["gbps"]
    result["cluster_iops"] = report["iops"]
    if "lat_p99_ms" in report:
        result["cluster_p99_host_ms"] = report["lat_p99_ms"]
        # device-clock p99 when the probe succeeded (VERDICT weak #6:
        # the host row measures the tunnel when RTT is degraded)
        result["cluster_p99_ms"] = report.get(
            "lat_p99_ms_device", report["lat_p99_ms"]
        )
    fault = report.get("fault", {})
    for key in (
        "degraded_gbps", "degraded_window_s", "time_to_recovered_s"
    ):
        if key in fault:
            result[f"cluster_{key}"] = fault[key]
    result["cluster_verify_failures"] = report["verify_failures"]
    result["cluster_errors"] = report["errors"]
    result["cluster_recovered"] = bool(report.get("recovered"))
    if enc_gbps:
        # the kernel-vs-cluster efficiency ratio: how much of the raw
        # codec rate survives the full service path (tiny by design
        # today — this row exists to be watched, 8 decimals so a
        # Python-socket-tier number doesn't round to zero)
        result["cluster_vs_kernel_frac"] = round(
            report["gbps"] / enc_gbps, 8
        )
