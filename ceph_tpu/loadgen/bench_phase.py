"""The BENCH json ``cluster`` phase: what the LIVE TIER gives back.

Every other bench phase clocks a kernel or a codec dispatch; this one
boots the real mini-cluster (mon + socket OSDs + device codecs +
stores), drives a mixed workload with a mid-run OSD kill/revive, and
reports the end-to-end service numbers next to the kernel ones:

- ``cluster_gbps`` / ``cluster_iops``   measured-window aggregate
- ``cluster_p99_ms``                    small-op p99 from the DEVICE
  clock (host floor replaced by the trip-count-differenced device op
  time — tunnel-RTT independent, no ``latency_degraded`` flag needed;
  ``cluster_p99_host_ms`` keeps the raw host row for comparison)
- ``cluster_degraded_gbps`` / ``cluster_degraded_window_s`` /
  ``cluster_time_to_recovered_s``       the fault-schedule cut
- ``cluster_vs_kernel_frac``            cluster_gbps over the flagship
  kernel encode rate — the tax the whole service stack levies on the
  raw codec (client, sockets, daemon locks, store writes, checksums)

Round 10 adds the serving-tier observables:

- the main leg runs at qd ≫ 12 with zipfian popularity through the
  ASYNC objecter + per-tick op coalescing, and a second leg in the
  SAME run with ``osd_op_coalescing=false`` pins the A/B:
  ``cluster_gbps_nocoal`` / ``cluster_vs_kernel_frac_nocoal`` /
  ``cluster_coalesce_speedup``;
- a scaling row: ``cluster_scale_osd<N>_gbps`` / ``_iops`` legs over
  OSD counts, and ``cluster_scale_chips<C>_gbps`` / ``_iops`` legs
  with the dispatch mesh installed over C devices (the chip axis) —
  GB/s and IOPS vs OSD count / chip count in one run.

Round 14 adds the observability-plane A/B: the same workload with
the live-op tracker + tracer OFF (``cluster_gbps_tracked`` /
``cluster_gbps_untracked`` / ``trace_overhead_frac`` = 1 −
tracked/untracked, acceptance < 0.02) — proving the always-on
plane (TrackedOp registration + event marks across objecter, RMW
and sub-op layers) is cheap enough to leave on.

Round 15 adds the stats-plane A/B the same way: reports on vs
``osd_stats_report_interval=0`` (``cluster_gbps_stats_on`` /
``cluster_gbps_stats_off`` / ``stats_report_overhead_frac`` = 1 −
on/off, acceptance < 0.01) — the PG-stats pipeline's cost on the
smallop-heavy serving path.

Sized by ``CEPH_TPU_BENCH_CLUSTER_OPS`` (default 240 ops at queue
depth ``CEPH_TPU_BENCH_CLUSTER_QD`` = 32 over
``CEPH_TPU_BENCH_CLUSTER_OBJECTS`` = 256 objects of 256 KiB; tunnel
sessions raise the env vars — thousands of objects — without code
edits). Scaling legs run at half the main leg's ops each."""

from __future__ import annotations

import os

from .cluster import LoadCluster
from .driver import run_spec
from .faults import FaultEvent, FaultSchedule
from .spec import WorkloadSpec

_MIX = {
    "seq_write": 2, "rand_write": 1, "read": 3,
    "reconstruct_read": 1, "rmw_overwrite": 1,
}


def _leg(
    total_ops: int,
    qd: int,
    max_objects: int,
    *,
    n_osds: int = 6,
    k: int = 4,
    m: int = 2,
    faults: bool = False,
    net_flaky: bool = False,
    device_clock: bool = False,
    use_mesh: bool = False,
    mesh_devices: int | None = None,
    seed: int = 0xEC,
) -> dict:
    from ceph_tpu.utils import config as _cfg

    overrides = {}
    if net_flaky:
        # lossy-link leg: lost frames must resolve via the sub-op
        # retransmit ladder + a short RPC deadline, not 10 s parks
        overrides = dict(
            osd_peer_rpc_timeout=1.0, osd_subop_resend_interval=0.2,
        )
    with _cfg.override(**overrides):
        cluster = LoadCluster(
            n_osds=n_osds, k=k, m=m, pg_num=8, chunk_size=16384,
            use_mesh=use_mesh, mesh_devices=mesh_devices,
        )
        try:
            spec = WorkloadSpec(
                mix=dict(_MIX),
                object_size=256 * 1024,
                max_objects=max_objects,
                queue_depth=qd,
                total_ops=total_ops,
                warmup_ops=max(total_ops // 10, 8),
                popularity="zipfian",
                device_clock=device_clock,
                seed=seed,
            )
            schedule = None
            if faults:
                schedule = FaultSchedule(
                    [
                        FaultEvent(at_op=total_ops // 3, action="kill"),
                        FaultEvent(at_op=(2 * total_ops) // 3,
                                   action="revive"),
                    ]
                )
            elif net_flaky:
                # degraded-link leg: the acceptance profile held for
                # the MIDDLE half of the run (fire/settle offsets)
                schedule = FaultSchedule.net_flaky(
                    total_ops, seed=seed,
                )
            return run_spec(cluster, spec, schedule)
        finally:
            cluster.shutdown()


def measure_cluster(result: dict, enc_gbps: float) -> None:
    from ceph_tpu.utils import config

    total_ops = int(
        os.environ.get("CEPH_TPU_BENCH_CLUSTER_OPS", "240")
    )
    qd = int(os.environ.get("CEPH_TPU_BENCH_CLUSTER_QD", "32"))
    max_objects = int(
        os.environ.get("CEPH_TPU_BENCH_CLUSTER_OBJECTS", "256")
    )
    report = _leg(
        total_ops, qd, max_objects, faults=True, device_clock=True
    )

    result["cluster_gbps"] = report["gbps"]
    result["cluster_iops"] = report["iops"]
    result["cluster_qd"] = qd
    result["cluster_objects"] = max_objects
    if "lat_p99_ms" in report:
        result["cluster_p99_host_ms"] = report["lat_p99_ms"]
        # device-clock p99 when the probe succeeded (VERDICT weak #6:
        # the host row measures the tunnel when RTT is degraded)
        result["cluster_p99_ms"] = report.get(
            "lat_p99_ms_device", report["lat_p99_ms"]
        )
    fault = report.get("fault", {})
    for key in (
        "degraded_gbps", "degraded_window_s", "time_to_recovered_s"
    ):
        if key in fault:
            result[f"cluster_{key}"] = fault[key]
    result["cluster_verify_failures"] = report["verify_failures"]
    result["cluster_errors"] = report["errors"]
    result["cluster_recovered"] = bool(report.get("recovered"))
    if enc_gbps:
        # the kernel-vs-cluster efficiency ratio: how much of the raw
        # codec rate survives the full service path (8 decimals so a
        # Python-socket-tier number doesn't round to zero)
        result["cluster_vs_kernel_frac"] = round(
            report["gbps"] / enc_gbps, 8
        )

    # -- degraded-link row: the same workload under the seeded
    # net_flaky acceptance profile (>=2% drop + dup + ~50 ms p95
    # delay on every inter-OSD link for the middle half of the run)
    # — what the serving tier returns when the FABRIC, not a member,
    # is the fault (arxiv 1906.08602's degraded-mode thesis)
    flaky = _leg(total_ops, qd, max_objects, net_flaky=True)
    result["cluster_degraded_link_gbps"] = flaky["gbps"]
    result["cluster_degraded_link_iops"] = flaky["iops"]
    result["cluster_degraded_link_verify_failures"] = (
        flaky["verify_failures"]
    )
    if report["gbps"]:
        result["cluster_degraded_link_frac"] = round(
            flaky["gbps"] / report["gbps"], 6
        )

    # -- A/B: the same workload with coalescing OFF, in the same run
    # (the acceptance comparison is within-run, not across BENCH
    # files — tunnel RTT drifts between sessions)
    with config.override(osd_op_coalescing=False):
        off = _leg(total_ops, qd, max_objects, seed=0xEC0FF)
    result["cluster_gbps_nocoal"] = off["gbps"]
    result["cluster_iops_nocoal"] = off["iops"]
    if enc_gbps:
        result["cluster_vs_kernel_frac_nocoal"] = round(
            off["gbps"] / enc_gbps, 8
        )
    if off["gbps"]:
        result["cluster_coalesce_speedup"] = round(
            report["gbps"] / off["gbps"], 4
        )

    # -- A/B: tracked vs untracked (round-14 observability plane) —
    # the SAME seed and sizing with the live-op tracker + tracer off,
    # pinning what the always-on plane costs the smallop-heavy path.
    # trace_overhead_frac = 1 - tracked/untracked; acceptance < 0.02
    # (cheap enough to leave on), within-run like the coalesce A/B.
    scale_ops = max(total_ops // 2, 40)
    tracked = _leg(scale_ops, qd, max_objects, seed=0x7ACE)
    from ceph_tpu.utils import tracer as _tracer

    with config.override(osd_enable_op_tracker=False):
        _was = _tracer.enabled
        _tracer.enabled = False
        try:
            untracked = _leg(
                scale_ops, qd, max_objects, seed=0x7ACE
            )
        finally:
            _tracer.enabled = _was
    result["cluster_gbps_tracked"] = tracked["gbps"]
    result["cluster_gbps_untracked"] = untracked["gbps"]
    if untracked["gbps"]:
        result["trace_overhead_frac"] = round(
            max(1.0 - tracked["gbps"] / untracked["gbps"], 0.0), 6
        )

    # -- A/B: stats reporting on vs off (round-15 stats plane) — the
    # SAME seed and sizing with `osd_stats_report_interval=0` as the
    # off arm, pinning what the tick-driven PG-stats pipeline (store
    # census + report fold + rate rings) costs the serving path.
    # stats_report_overhead_frac = 1 - on/off; acceptance < 0.01.
    stats_on = _leg(scale_ops, qd, max_objects, seed=0x57A75)
    with config.override(osd_stats_report_interval=0.0):
        stats_off = _leg(scale_ops, qd, max_objects, seed=0x57A75)
    result["cluster_gbps_stats_on"] = stats_on["gbps"]
    result["cluster_gbps_stats_off"] = stats_off["gbps"]
    if stats_off["gbps"]:
        result["stats_report_overhead_frac"] = round(
            max(1.0 - stats_on["gbps"] / stats_off["gbps"], 0.0), 6
        )

    # -- scaling rows: GB/s and IOPS vs OSD count, then vs chip count
    # (dispatch mesh over C devices). Half-length legs, no faults.
    for n_osds in (6, 9, 12):
        rep = _leg(
            scale_ops, qd, max_objects, n_osds=n_osds,
            seed=0x5CA1E + n_osds,
        )
        result[f"cluster_scale_osd{n_osds}_gbps"] = rep["gbps"]
        result[f"cluster_scale_osd{n_osds}_iops"] = rep["iops"]
    import jax

    n_dev = len(jax.devices())
    chip_legs = sorted(
        {c for c in (1, 2, 4, n_dev) if 1 <= c <= n_dev}
    )
    for chips in chip_legs:
        rep = _leg(
            scale_ops, qd, max_objects,
            use_mesh=chips > 1, mesh_devices=chips if chips > 1 else None,
            seed=0xC41B + chips,
        )
        result[f"cluster_scale_chips{chips}_gbps"] = rep["gbps"]
        result[f"cluster_scale_chips{chips}_iops"] = rep["iops"]


# -- the round-19 QoS phase: noisy neighbor + recovery slosh ------------
#: tenant A: a modest latency-sensitive mix with a reservation-bearing
#: QoS spec — the tenant whose p99 the plane must defend
_TENANT_A = {
    "mix": {"seq_write": 1, "read": 3, "rmw_overwrite": 1},
    "object_size": 64 * 1024,
    "qos": {"res_ops": 64.0, "res_bytes": 8 << 20, "weight": 4.0},
}
#: tenant B: the write-heavy flood (big objects, deep queue) whose
#: cost-tagged ops must throttle against B's OWN clocks
_TENANT_B = {
    "mix": {"seq_write": 3, "rand_write": 2},
    "object_size": 512 * 1024,
    "qos": {"weight": 1.0},
}


def qos_leg(
    total_ops: int,
    qd: int,
    max_objects: int,
    *,
    flood: bool = False,
    faults: bool = False,
    qos_on: bool = True,
    profile: str = "balanced",
    device_clock: bool = False,
    seed: int = 0x905,
) -> dict:
    """One multi-tenant leg: tenant A's modest mix, optionally tenant
    B's flood on top, optionally a mid-run most-primary kill/revive
    (recovery competing with clients), under one slosh-knob profile.
    ``qos_on=False`` is the escape hatch — every op back on the flat
    shared class."""
    from ceph_tpu.utils import config as _cfg

    tenants: dict = {"tenantA": dict(_TENANT_A)}
    tenants["tenantA"]["queue_depth"] = max(qd // 4, 2)
    tenants["tenantA"]["total_ops"] = total_ops
    if flood:
        tenants["tenantB"] = dict(_TENANT_B)
        tenants["tenantB"]["queue_depth"] = qd
        tenants["tenantB"]["total_ops"] = total_ops * 2
    with _cfg.override(osd_op_qos=qos_on, osd_mclock_profile=profile):
        cluster = LoadCluster(
            n_osds=6, k=4, m=2, pg_num=8, chunk_size=16384,
        )
        try:
            spec = WorkloadSpec(
                mix=dict(_MIX),
                object_size=64 * 1024,
                max_objects=max_objects,
                queue_depth=qd,
                total_ops=total_ops,
                warmup_ops=max(total_ops // 10, 8),
                popularity="zipfian",
                device_clock=device_clock,
                seed=seed,
                tenants=tenants,
            )
            schedule = None
            if faults:
                # kill the most-primary OSD a third in, revive at two
                # thirds: recovery work overlaps the measured window
                schedule = FaultSchedule(
                    [
                        FaultEvent(at_op=total_ops // 3, action="kill"),
                        FaultEvent(at_op=(2 * total_ops) // 3,
                                   action="revive"),
                    ]
                )
            return run_spec(cluster, spec, schedule)
        finally:
            cluster.shutdown()


def measure_qos(result: dict) -> None:
    """The noisy-neighbor A/B row and the recovery-slosh curve.

    - ``qos_tenantA_p99_{solo,noisy,noqos}_ms``: tenant A's p99 alone,
      under a tenant-B flood + concurrent recovery with QoS armed, and
      the same storm with ``osd_op_qos=false`` (the escape hatch must
      demonstrably blow past the bound or the A/B proves nothing);
      ``qos_noisy_neighbor_frac`` / ``qos_escape_hatch_frac`` are the
      degradations vs solo.
    - ``qos_slosh_<profile>_{recovery_s,p99_ms}``: time-to-recovered
      vs tenant-A p99 across the three slosh-knob settings — the knob
      must trade them monotonically.

    Sized by CEPH_TPU_BENCH_QOS_OPS / _QD (defaults 160 / 16)."""
    total_ops = int(os.environ.get("CEPH_TPU_BENCH_QOS_OPS", "160"))
    qd = int(os.environ.get("CEPH_TPU_BENCH_QOS_QD", "16"))
    max_objects = 64

    solo = qos_leg(total_ops, qd, max_objects, seed=0x905)
    noisy = qos_leg(
        total_ops, qd, max_objects, flood=True, faults=True,
        seed=0x905,
    )
    noqos = qos_leg(
        total_ops, qd, max_objects, flood=True, faults=True,
        qos_on=False, seed=0x905,
    )
    rows = {"solo": solo, "noisy": noisy, "noqos": noqos}
    a_p99: dict[str, float] = {}
    for name, rep in rows.items():
        a = rep.get("tenants", {}).get("tenantA", {})
        p99 = a.get("lat_p99_ms")
        if p99 is not None:
            a_p99[name] = p99
            result[f"qos_tenantA_p99_{name}_ms"] = p99
        result[f"qos_{name}_verify_failures"] = rep.get(
            "verify_failures", -1
        )
    if a_p99.get("solo"):
        if "noisy" in a_p99:
            result["qos_noisy_neighbor_frac"] = round(
                a_p99["noisy"] / a_p99["solo"], 4
            )
        if "noqos" in a_p99:
            result["qos_escape_hatch_frac"] = round(
                a_p99["noqos"] / a_p99["solo"], 4
            )

    # the slosh curve: one recovery-under-load leg per knob setting
    for prof in ("high_client", "balanced", "high_recovery"):
        rep = qos_leg(
            total_ops, qd, max_objects, faults=True, profile=prof,
            seed=0x5105,
        )
        ttr = rep.get("fault", {}).get("time_to_recovered_s")
        if ttr is not None:
            result[f"qos_slosh_{prof}_recovery_s"] = ttr
        a = rep.get("tenants", {}).get("tenantA", {})
        if a.get("lat_p99_ms") is not None:
            result[f"qos_slosh_{prof}_p99_ms"] = a["lat_p99_ms"]


# -- the round-20 transport phase: shm-ring lane + native codec ---------
def transport_leg(
    total_ops: int,
    qd: int,
    max_objects: int,
    *,
    transport: str = "tcp",
    native_codec: bool = True,
    op_shards: int = 1,
    faults: bool = False,
    seed: int = 0xEC20,
) -> dict:
    """One transport A/B leg: the standard mixed workload with the
    messenger lane (tcp | shm_ring), the clear-frame codec
    (native C | pure Python) and the op-shard count pinned by
    config for the whole cluster lifetime. The shm stats registry
    is reset per leg so chunks/bytes are leg-scoped."""
    from ceph_tpu.msg import shm_ring
    from ceph_tpu.utils import config as _cfg

    shm_ring.reset_stats()
    with _cfg.override(
        msgr_transport=transport,
        msgr_native_codec=native_codec,
        osd_op_num_shards=op_shards,
    ):
        cluster = LoadCluster(
            n_osds=6, k=4, m=2, pg_num=8, chunk_size=16384,
        )
        try:
            spec = WorkloadSpec(
                mix=dict(_MIX),
                object_size=256 * 1024,
                max_objects=max_objects,
                queue_depth=qd,
                total_ops=total_ops,
                warmup_ops=max(total_ops // 10, 8),
                popularity="zipfian",
                seed=seed,
            )
            schedule = None
            if faults:
                schedule = FaultSchedule(
                    [
                        FaultEvent(at_op=total_ops // 3, action="kill"),
                        FaultEvent(at_op=(2 * total_ops) // 3,
                                   action="revive"),
                    ]
                )
            report = run_spec(cluster, spec, schedule)
            report["shm"] = shm_ring.snapshot()
            return report
        finally:
            cluster.shutdown()


def hol_probe_ms(nshards: int, park_s: float = 0.75) -> float:
    """Deterministic head-of-line probe: park one op shard's lock on
    a primary for ``park_s`` (the stand-in for the EC write wedged in
    its sub-write ``drain_until`` ladder) and time a write to a
    DIFFERENT PG on the SAME primary. At one shard the sibling rides
    the park (~park_s); with a shard pool it lands in milliseconds.
    Unlike the flood x kill legs this exercises the wedge on every
    run — the ``on_shard_down`` race the real cliff needs is
    nondeterministic."""
    import time as _time

    from ceph_tpu.utils import config as _cfg

    with _cfg.override(osd_op_num_shards=nshards):
        cluster = LoadCluster(
            n_osds=5, k=2, m=1, pg_num=8, chunk_size=4096,
        )
        try:
            mon, pool = cluster.mon, cluster.pool
            pick = None
            by_primary: dict = {}
            for i in range(200):
                oid = f"holp-{i}"
                pgid = mon.osdmap.object_to_pg(pool, oid)
                primary = mon.osdmap.pg_primary(pool, pgid)
                d = cluster.daemons[primary]
                shard = d._op_shard_index(pool, pgid)
                slots = by_primary.setdefault(primary, {})
                # one shard: any two distinct PGs share slot key 0,
                # so key by pgid instead to get two distinct queues
                key = shard if nshards > 1 else pgid
                slots.setdefault(key, (oid, shard))
                if len(slots) >= 2:
                    (oid_a, shard_a), (oid_b, _sb) = list(
                        slots.values()
                    )[:2]
                    pick = (d, oid_a, shard_a, oid_b)
                    break
            if pick is None:
                return -1.0
            d, oid_a, shard_a, oid_b = pick
            payload = b"\x5a" * 8192
            cluster.io.write_full(oid_a, payload)  # peer + seed windows
            cluster.io.write_full(oid_b, payload)
            lock_a = d._op_shards[shard_a]
            with lock_a:
                t0 = _time.monotonic()
                comp = cluster.io.aio_write_full(oid_b, payload)
                try:
                    comp.wait_for_complete(park_s)
                except TimeoutError:
                    pass  # the 1-shard arm rides the park by design
            try:
                comp.wait_for_complete(10.0)
            except TimeoutError:
                return -1.0
            elapsed = _time.monotonic() - t0
            return round(elapsed * 1e3, 3) if comp.is_complete() else -1.0
        finally:
            cluster.shutdown()


def measure_transport(result: dict, enc_gbps: float) -> None:
    """The ISSUE-20 within-run A/B grid (transport x codec), the
    shm-lane headline, and the flood-kill shard ladder:

    - ``transport_{tcp,shm}_{py,native}_gbps`` four-leg grid plus a
      per-leg ``cluster_vs_kernel_frac`` row
      (``transport_<leg>_vs_kernel_frac``) — same workload, same
      seed, one process, so the ratios are tunnel-drift-free;
    - ``frame_codec_speedup``  tcp+native over tcp+python — what
      moving frame assembly/verify into C buys the wire path;
    - ``shm_ring_gbps`` / ``shm_ring_speedup``  the co-located lane
      over loopback TCP (both on the native codec);
    - ``shm_ring_chunks`` / ``shm_ring_bytes``  lane traffic proof
      (zero chunks means the negotiation never upgraded — a red
      flag, not a fast run);
    - ``transport_shards{1,4}_p{50,95,99}_ms`` /
      ``shard_hol_p95_frac``  flood x kill tenant-A latency spread
      at 1 vs 4 op shards — the head-of-line regression row. The
      parked EC write itself still drains its ~15 s ``drain_until``
      ladder at ANY shard count (that is the sub-write retransmit
      path, not the worker), so the max/p99 can cliff either way;
      what the shard pool removes is the COLLATERAL wedge — every
      other PG's queue head stuck behind the parked op — which is
      exactly the p50/p95 spread (BASELINE row 64's caveat).

    Sized by CEPH_TPU_BENCH_TRANSPORT_OPS / _QD (defaults 160/24)."""
    total_ops = int(
        os.environ.get("CEPH_TPU_BENCH_TRANSPORT_OPS", "160")
    )
    qd = int(os.environ.get("CEPH_TPU_BENCH_TRANSPORT_QD", "24"))
    max_objects = 128

    legs = {}
    for tag, transport, native in (
        ("tcp_py", "tcp", False),
        ("tcp_native", "tcp", True),
        ("shm_py", "shm_ring", False),
        ("shm_native", "shm_ring", True),
    ):
        rep = transport_leg(
            total_ops, qd, max_objects,
            transport=transport, native_codec=native,
        )
        legs[tag] = rep
        result[f"transport_{tag}_gbps"] = rep["gbps"]
        result[f"transport_{tag}_iops"] = rep["iops"]
        if enc_gbps:
            result[f"transport_{tag}_vs_kernel_frac"] = round(
                rep["gbps"] / enc_gbps, 8
            )
    if legs["tcp_py"]["gbps"]:
        result["frame_codec_speedup"] = round(
            legs["tcp_native"]["gbps"] / legs["tcp_py"]["gbps"], 4
        )
    result["shm_ring_gbps"] = legs["shm_native"]["gbps"]
    if legs["tcp_native"]["gbps"]:
        result["shm_ring_speedup"] = round(
            legs["shm_native"]["gbps"] / legs["tcp_native"]["gbps"], 4
        )
    result["shm_ring_chunks"] = legs["shm_native"]["shm"]["chunks"]
    result["shm_ring_bytes"] = legs["shm_native"]["shm"]["bytes"]

    # -- flood x kill shard ladder: the head-of-line row. Same storm
    # (tenant flood + mid-run kill/revive, qos_leg's schedule shape)
    # at 1 shard vs 4; the collateral wedge shows in the tenant-A
    # latency SPREAD (p50/p95), not the single parked op's own p99.
    from ceph_tpu.utils import config as _cfg

    for n in (1, 4):
        with _cfg.override(osd_op_num_shards=n):
            rep = qos_leg(
                total_ops, qd, max_objects=64, flood=True,
                faults=True, seed=0xEC20,
            )
        a = rep.get("tenants", {}).get("tenantA", {})
        for pct in ("p50", "p95", "p99"):
            v = a.get(f"lat_{pct}_ms")
            if v is not None:
                result[f"transport_shards{n}_{pct}_ms"] = v
    p1 = result.get("transport_shards1_p95_ms")
    pn = result.get("transport_shards4_p95_ms")
    if p1 and pn:
        # < 1.0 means the shard pool cut the storm's latency spread
        result["shard_hol_p95_frac"] = round(pn / p1, 4)

    # -- the deterministic wedge probe (parked shard, timed sibling)
    h1 = hol_probe_ms(1)
    h4 = hol_probe_ms(4)
    if h1 > 0:
        result["shard_hol_probe_shards1_ms"] = h1
    if h4 > 0:
        result["shard_hol_probe_shards4_ms"] = h4
    if h1 > 0 and h4 > 0:
        result["shard_hol_probe_frac"] = round(h4 / h1, 4)
