"""Soak/loadgen forensics bundle — the artifact a non-green run leaves
behind instead of a shrug.

When a loadgen run goes non-green (verify failures, accounting
mismatch, op errors, failed recovery) — or converges SLOWLY after a
kill (``time_to_recovered_s`` past a threshold: the ~1/7 minute-scale
outlier the chaos tier keeps brushing against) — the driver dumps one
directory of correlated state, captured BEFORE cluster teardown so
wedged ops are still live:

- ``ops_in_flight.json``   every live tracked op with its event
                           timeline (the wedged ones are the story)
- ``traces.txt``           top-N slowest assembled traces with
                           critical-path attribution
- ``traces_chrome.json``   the same traces as Chrome trace-event JSON
                           (open in Perfetto)
- ``cluster_log.jsonl``    the cluster-log tail (down-marks, slow-op
                           complaints, peering stalls, net-fault
                           arms, crash-point fires)
- ``perf_dump.json``       the full perf-counter collection
- ``lockdep.json``         the lock-dependency graph + findings
                           (cycles / rank violations / blocking-
                           under-lock, with backtraces) when the
                           run armed the lockdep detector
- ``report.json``          the run report that triggered the dump
- ``status.json``          the `ceph -s` snapshot from the stats
                           plane (when a cluster is passed in)
- ``pg_dump.json``         every PG's stats row (`ceph pg dump`)
- ``MANIFEST.json``        reason + file list

``tools/soak.sh`` arms this via ``bench_cli loadgen --forensics-dir``
on its background load loop; any harness can call
:func:`write_bundle` directly.
"""

from __future__ import annotations

import json
import os
import time


def run_is_green(
    report: dict, slow_convergence_s: float = 0.0
) -> tuple[bool, str]:
    """(green, reason): the non-green predicate the forensics trigger
    shares with the soak gate.  ``slow_convergence_s`` > 0 also trips
    on post-kill convergence slower than the threshold."""
    if report.get("verify_failures"):
        return False, (
            f"{report['verify_failures']} content-verify failures"
        )
    if not report.get("exactly_once", True):
        return False, (
            f"accounting mismatch: issued {report.get('ops_in')} != "
            f"accounted {report.get('ops_accounted')}"
        )
    if report.get("errors"):
        return False, f"{report['errors']} op errors"
    if "recovered" in report and not report["recovered"]:
        return False, "cluster not recovered at exit"
    ld = report.get("lockdep")
    if ld and any(ld.values()):
        # lockdep-armed run (soak.sh --lockdep): a cycle / rank
        # violation / unwaived blocking-under-lock finding is as red
        # as a verify failure — it is tomorrow's deadlock
        return False, (
            "lockdep findings: "
            + ", ".join(f"{k}={v}" for k, v in sorted(ld.items()) if v)
        )
    ttr = (report.get("fault") or {}).get("time_to_recovered_s")
    if (
        slow_convergence_s > 0
        and ttr is not None
        and ttr > slow_convergence_s
    ):
        return False, (
            f"slow convergence: time_to_recovered_s={ttr} > "
            f"{slow_convergence_s}"
        )
    return True, "green"


def write_bundle(
    out_dir: str,
    report: "dict | None" = None,
    reason: str = "",
    trace_capture: int = 8,
    cluster=None,
) -> dict:
    """Write the forensics bundle under ``out_dir/<stamp>/``; returns
    the manifest (with ``dir`` pointing at the bundle).  Never raises
    past best effort — forensics must not turn a red run redder.
    With ``cluster`` (a LoadCluster), the bundle also captures the
    stats plane: ``status.json`` (the `ceph -s` shape) and
    ``pg_dump.json`` (every PG's stats row) — the aggregate view a
    wedged run is triaged from."""
    from ceph_tpu.utils.cluster_log import cluster_log
    from ceph_tpu.utils.optracker import op_tracker
    from ceph_tpu.utils.perf_counters import perf_collection
    from ceph_tpu.utils.trace_assembly import capture_traces

    stamp = time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
    bundle_dir = os.path.join(out_dir, stamp)
    os.makedirs(bundle_dir, exist_ok=True)
    files: list[str] = []

    def dump(name: str, payload, jsonl: bool = False) -> None:
        path = os.path.join(bundle_dir, name)
        try:
            with open(path, "w", encoding="utf-8") as f:
                if jsonl:
                    for item in payload:
                        f.write(json.dumps(item, default=str) + "\n")
                elif isinstance(payload, str):
                    f.write(payload)
                else:
                    json.dump(payload, f, default=str, indent=1)
            files.append(name)
        except Exception:
            pass

    dump("ops_in_flight.json", op_tracker.dump_ops_in_flight())
    traces = capture_traces(limit=trace_capture)
    dump("traces.txt", traces["text"])
    dump("traces_chrome.json", traces["chrome_json"])
    dump("cluster_log.jsonl", cluster_log.last(2000), jsonl=True)
    dump("perf_dump.json", perf_collection.dump())
    from ceph_tpu.utils import lockdep

    # the lockdep graph + findings (cycles/rank/blocking carry full
    # backtraces) — trivially small when the detector is disarmed
    dump("lockdep.json", lockdep.dump())
    if report is not None:
        dump("report.json", report)
    mon = getattr(cluster, "mon", None)
    if mon is not None and getattr(mon, "pgmap", None) is not None:
        try:
            from ceph_tpu.cluster.pgmap import status_dict

            for d in cluster.daemons.values():
                if d.osd_id not in cluster.dead:
                    d.report_pg_stats(force=True)
            dump("status.json", status_dict(mon))
            dump("pg_dump.json", mon.pgmap.pg_dump())
        except Exception:
            pass
    manifest = {
        "reason": reason,
        "stamp": stamp,
        "dir": bundle_dir,
        "files": files,
        "live_ops": op_tracker.live_count(),
        "traces_captured": traces["captured"],
    }
    dump("MANIFEST.json", manifest)
    return manifest
