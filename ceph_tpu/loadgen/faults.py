"""Fault schedule — the thrasher hook (qa/tasks/ceph_manager.py
kill/revive collapsed to deterministic op-offset triggers).

A schedule is an ordered list of events pinned to completed-op
offsets. The driver fires due events inline from whichever worker
crosses the offset (single-fire under a lock), so a run with the same
spec + schedule replays the same interleaving class-for-class. The
schedule also keeps the timestamps the degraded-window metrics are
cut from: kill time, revive time, and time-to-recovered (revive ->
cluster reports every PG peered, no member missing, no catch-up or
backfill in flight)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class FaultEvent:
    #: fire once the run's completed-op counter reaches this
    at_op: int
    #: "kill" | "revive" | "dcn_kill" (hard-kill a DCN host process
    #: mid-run — the multi-chip msgr fault; ``osd`` carries the host
    #: rank, default 1)
    action: str
    #: target osd id; None = pick (kill: first live non-mon victim
    #: in id order for determinism; revive: oldest corpse)
    osd: int | None = None

    def __post_init__(self) -> None:
        if self.action not in ("kill", "revive", "dcn_kill"):
            raise ValueError(f"unknown fault action {self.action!r}")


@dataclass
class FaultSchedule:
    events: list[FaultEvent] = field(default_factory=list)
    #: bound on the post-revive recovery wait (seconds)
    recovery_timeout: float = 60.0

    def __post_init__(self) -> None:
        self.events = sorted(self.events, key=lambda e: e.at_op)
        self._lock = threading.Lock()
        self._next = 0
        self.kill_at: float | None = None      # monotonic stamps
        self.revive_at: float | None = None
        self.recovered_at: float | None = None
        self.dcn_killed_at: float | None = None
        self.killed: list[int] = []

    def maybe_fire(self, ops_done: int, cluster) -> None:
        """Fire every event whose offset has been reached. Called on
        the op path — must be cheap when nothing is due."""
        if self._next >= len(self.events):
            return
        with self._lock:
            while (
                self._next < len(self.events)
                and self.events[self._next].at_op <= ops_done
            ):
                ev = self.events[self._next]
                self._next += 1
                self._apply(ev, cluster)

    def _apply(self, ev: FaultEvent, cluster) -> None:
        if ev.action == "dcn_kill":
            cluster.kill_dcn_host(1 if ev.osd is None else ev.osd)
            self.dcn_killed_at = time.monotonic()
            return
        if ev.action == "kill":
            osd = ev.osd
            if osd is None:
                live = sorted(cluster.live_osds())
                if not live:
                    return
                osd = live[0]
            cluster.kill(osd)
            self.killed.append(osd)
            if self.kill_at is None:
                self.kill_at = time.monotonic()
        else:
            osd = ev.osd
            if osd is None:
                if not self.killed:
                    return
                osd = self.killed[0]
            cluster.revive(osd)
            if osd in self.killed:
                self.killed.remove(osd)
            self.revive_at = time.monotonic()

    def settle(self, cluster) -> None:
        """Post-run: revive anything still dead, then wait for the
        cluster to report recovered, stamping ``recovered_at``."""
        for osd in list(self.killed):
            cluster.revive(osd)
            self.killed.remove(osd)
            self.revive_at = time.monotonic()
        if cluster.wait_recovered(self.recovery_timeout):
            self.recovered_at = time.monotonic()

    def metrics(self, recorder) -> dict:
        """Degraded-window throughput + time-to-recovered rows."""
        out: dict = {}
        if self.kill_at is None:
            return out
        t_end = self.revive_at or time.monotonic()
        out["degraded_gbps"] = round(
            recorder.window_gbps(self.kill_at, t_end), 6
        )
        out["degraded_window_s"] = round(t_end - self.kill_at, 3)
        if self.revive_at is not None and self.recovered_at is not None:
            out["time_to_recovered_s"] = round(
                self.recovered_at - self.revive_at, 3
            )
        return out
