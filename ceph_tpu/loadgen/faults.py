"""Fault schedule — the thrasher hook (qa/tasks/ceph_manager.py
kill/revive collapsed to deterministic op-offset triggers).

A schedule is an ordered list of events pinned to completed-op
offsets. The driver fires due events inline from whichever worker
crosses the offset (single-fire under a lock), so a run with the same
spec + schedule replays the same interleaving class-for-class. The
schedule also keeps the timestamps the degraded-window metrics are
cut from: kill time, revive time, and time-to-recovered (revive ->
cluster reports every PG peered, no member missing, no catch-up or
backfill in flight)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from ceph_tpu.utils.lockdep import DebugLock


#: named victim pickers a kill event may carry instead of an osd id;
#: resolved against the live cluster AT FIRE TIME (a pre-run pick
#: would miss primaries reshuffled by earlier events)
VICTIM_PICKERS = ("least_primary", "most_primary")


#: fault actions that drive the network-fault plane rather than
#: process lifecycle; ``profile`` carries their parameters
NET_ACTIONS = ("net_flaky", "net_partition", "net_clear")


@dataclass
class FaultEvent:
    #: fire once the run's completed-op counter reaches this
    at_op: int
    #: "kill" | "revive" | "dcn_kill" (hard-kill a DCN host process
    #: mid-run — the multi-chip msgr fault; ``osd`` carries the host
    #: rank, default 1) | "net_flaky" (arm the seeded link-fault
    #: profile in ``profile``) | "net_partition" (partition the
    #: victim's links; ``osd``/picker chooses the victim) |
    #: "net_clear" (clear the plane and heal partitions)
    action: str
    #: target: an osd id, a named victim picker ("least_primary" |
    #: "most_primary"; kill and net_partition, resolved at fire
    #: time), or None = pick (kill: first live victim in id order for
    #: determinism; revive: oldest corpse)
    osd: int | str | None = None
    #: net_flaky: {seed, drop, dup, delay_ms, delay_jitter_ms,
    #: reorder, scope}; net_partition: {asymmetric}
    profile: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.action not in (
            "kill", "revive", "dcn_kill", *NET_ACTIONS
        ):
            raise ValueError(f"unknown fault action {self.action!r}")
        if isinstance(self.osd, str):
            if self.action not in ("kill", "net_partition"):
                raise ValueError(
                    f"named victim {self.osd!r} only targets kills "
                    "and partitions"
                )
            if self.osd not in VICTIM_PICKERS:
                raise ValueError(
                    f"unknown victim picker {self.osd!r} "
                    f"(know {VICTIM_PICKERS})"
                )


@dataclass
class FaultSchedule:
    events: list[FaultEvent] = field(default_factory=list)
    #: bound on the post-revive recovery wait (seconds)
    recovery_timeout: float = 60.0

    def __post_init__(self) -> None:
        self.events = sorted(self.events, key=lambda e: e.at_op)
        self._lock = DebugLock("loadgen.faults")
        self._next = 0
        self.kill_at: float | None = None      # monotonic stamps
        self.revive_at: float | None = None
        #: stats-plane convergence stamp (degraded-object count back
        #: to zero in the PGMap fold) — the PRIMARY time_to_recovered
        #: derivation since round 15
        self.recovered_at: float | None = None
        #: the bespoke direct-state poll's stamp, kept beside the
        #: stats one so the two derivations stay cross-checkable
        self.recovered_legacy_at: float | None = None
        self.dcn_killed_at: float | None = None
        self.killed: list[int] = []
        self._net_armed = False

    def maybe_fire(self, ops_done: int, cluster) -> None:
        """Fire every event whose offset has been reached. Called on
        the op path — must be cheap when nothing is due."""
        if self._next >= len(self.events):
            return
        with self._lock:
            while (
                self._next < len(self.events)
                and self.events[self._next].at_op <= ops_done
            ):
                ev = self.events[self._next]
                self._next += 1
                self._apply(ev, cluster)

    def _apply(self, ev: FaultEvent, cluster) -> None:
        if ev.action == "dcn_kill":
            cluster.kill_dcn_host(1 if ev.osd is None else ev.osd)
            self.dcn_killed_at = time.monotonic()
            return
        if ev.action == "net_flaky":
            cluster.net_flaky(**ev.profile)
            self._net_armed = True
            if self.kill_at is None:
                # the degraded window opens at the first link fault
                # (the degraded-link row is cut from it, like a kill's)
                self.kill_at = time.monotonic()
            return
        if ev.action == "net_partition":
            osd = ev.osd
            if isinstance(osd, str):
                osd = getattr(cluster, osd + "_osd")()
            if osd is None:
                live = sorted(cluster.live_osds())
                if not live:
                    return
                osd = live[0]
            cluster.net_partition(osd, **ev.profile)
            self._net_armed = True
            if self.kill_at is None:
                self.kill_at = time.monotonic()
            return
        if ev.action == "net_clear":
            cluster.net_heal()
            self._net_armed = False
            if self.revive_at is None:
                self.revive_at = time.monotonic()
            return
        if ev.action == "kill":
            osd = ev.osd
            if isinstance(osd, str):  # named picker, fire-time state
                osd = getattr(cluster, osd + "_osd")()
            if osd is None:
                live = sorted(cluster.live_osds())
                if not live:
                    return
                osd = live[0]
            cluster.kill(osd)
            self.killed.append(osd)
            if self.kill_at is None:
                self.kill_at = time.monotonic()
        else:
            osd = ev.osd
            if osd is None:
                if not self.killed:
                    return
                osd = self.killed[0]
            cluster.revive(osd)
            if osd in self.killed:
                self.killed.remove(osd)
            self.revive_at = time.monotonic()

    def settle(self, cluster) -> None:
        """Post-run: heal any armed link faults/partitions, revive
        anything still dead, then wait for convergence TWICE — the
        legacy direct-state poll (``recovered_legacy_at``), then the
        stats plane (``recovered_at``: every PG's report clean with
        zero degraded object copies at a post-revive epoch). The
        stats stamp is the one ``time_to_recovered_s`` is cut from;
        the two must agree within about one report interval (pinned
        by the tier-1 stats-plane smoke)."""
        if self._net_armed:
            cluster.net_heal()
            self._net_armed = False
            if self.revive_at is None:
                self.revive_at = time.monotonic()
        for osd in list(self.killed):
            cluster.revive(osd)
            self.killed.remove(osd)
            self.revive_at = time.monotonic()
        # post-revive epoch floor: stale clean reports from before the
        # fault carry older epochs and cannot fake convergence
        min_epoch = cluster.mon.osdmap.epoch
        deadline = time.monotonic() + self.recovery_timeout
        if cluster.wait_recovered(self.recovery_timeout):
            self.recovered_legacy_at = time.monotonic()
        wait_stats = getattr(cluster, "wait_recovered_stats", None)
        if wait_stats is not None:
            if wait_stats(
                max(deadline - time.monotonic(), 1.0),
                min_epoch=min_epoch,
            ):
                self.recovered_at = time.monotonic()
        else:  # stats-blind harness: the legacy stamp stands alone
            self.recovered_at = self.recovered_legacy_at

    @classmethod
    def primary_kill(
        cls, total_ops: int, recovery_timeout: float = 60.0
    ) -> "FaultSchedule":
        """The default soak schedule: kill the MOST-primary OSD a
        third of the way in (maximum simultaneous takeovers — the
        racy path the peering FSM exists for), revive it at two
        thirds, and demand full recovery at settle. Soaks target the
        takeover composition by default instead of dodging it."""
        return cls(
            [
                FaultEvent(
                    max(total_ops // 3, 1), "kill",
                    osd="most_primary",
                ),
                FaultEvent(max((2 * total_ops) // 3, 2), "revive"),
            ],
            recovery_timeout=recovery_timeout,
        )

    @classmethod
    def net_flaky(
        cls,
        total_ops: int,
        seed: int = 0xEC,
        drop: float = 0.02,
        dup: float = 0.02,
        delay_ms: float = 5.0,
        delay_jitter_ms: float = 47.0,
        reorder: float = 0.01,
        scope: str = "osd",
        fire_frac: float = 0.25,
        settle_frac: float = 0.75,
        recovery_timeout: float = 60.0,
    ) -> "FaultSchedule":
        """The lossy-link soak schedule: arm a seeded flaky profile on
        every link in ``scope`` ("osd" = inter-OSD only, "all" = the
        client legs too) a quarter of the way in, clear it at three
        quarters (the fire/settle offsets), and demand recovery at
        settle. Defaults are the acceptance profile: >= 2% drop +
        duplication + ~50 ms p95 delay, deterministic from ``seed``."""
        return cls(
            [
                FaultEvent(
                    max(int(total_ops * fire_frac), 1), "net_flaky",
                    profile=dict(
                        seed=seed, drop=drop, dup=dup,
                        delay_ms=delay_ms,
                        delay_jitter_ms=delay_jitter_ms,
                        reorder=reorder, scope=scope,
                    ),
                ),
                FaultEvent(
                    max(int(total_ops * settle_frac), 2), "net_clear"
                ),
            ],
            recovery_timeout=recovery_timeout,
        )

    @classmethod
    def net_partition(
        cls,
        total_ops: int,
        victim: "int | str" = "most_primary",
        asymmetric: bool = True,
        seed: int = 0xEC,
        fire_frac: float = 0.33,
        settle_frac: float = 0.66,
        recovery_timeout: float = 60.0,
    ) -> "FaultSchedule":
        """Partition the (default most-primary) victim's links a third
        of the way in — asymmetric by default, the half-dead case that
        forces re-election while the victim keeps talking into the
        void — and merge at two thirds; settle demands the healed
        cluster reports recovered (scrub-clean is the caller's gate)."""
        return cls(
            [
                FaultEvent(
                    max(int(total_ops * fire_frac), 1),
                    "net_partition", osd=victim,
                    profile=dict(asymmetric=asymmetric, seed=seed),
                ),
                FaultEvent(
                    max(int(total_ops * settle_frac), 2), "net_clear"
                ),
            ],
            recovery_timeout=recovery_timeout,
        )

    def metrics(self, recorder) -> dict:
        """Degraded-window throughput + time-to-recovered rows.
        ``time_to_recovered_s`` derives from the STATS PLANE
        (degraded-object count back to zero in the PGMap);
        ``time_to_recovered_legacy_s`` keeps the direct-state poll
        beside it for cross-checking."""
        out: dict = {}
        if self.kill_at is None:
            return out
        t_end = self.revive_at or time.monotonic()
        out["degraded_gbps"] = round(
            recorder.window_gbps(self.kill_at, t_end), 6
        )
        out["degraded_window_s"] = round(t_end - self.kill_at, 3)
        if self.revive_at is not None:
            if self.recovered_at is not None:
                out["time_to_recovered_s"] = round(
                    self.recovered_at - self.revive_at, 3
                )
            if self.recovered_legacy_at is not None:
                out["time_to_recovered_legacy_s"] = round(
                    self.recovered_legacy_at - self.revive_at, 3
                )
        return out
