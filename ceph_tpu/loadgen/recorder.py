"""Latency/throughput recorder for live-cluster runs.

Per op class: a log2 latency histogram (p50/p95/p99/max), bytes
moved, op/error/verify-failure counts — with warmup exclusion
(excluded ops still count toward the exactly-once ledger) and a
completion timeline so a fault window's throughput can be cut out
after the fact.

Device-clock mode (VERDICT weak #6): through a remote device tunnel
every op's host-measured latency carries the tunnel RTT, so p99 of
the host clock measures the tunnel, not the path. ``DeviceClock``
measures the op's device program once with trip-count differencing
(iterated on-device loop, min-of-reps — the bench.py methodology,
which cancels per-dispatch RTT by construction) and the recorder then
reports device-clock percentiles as

    p_dev(x) = host_p(x) - host_min + dev_per_op

i.e. the host distribution with its constant floor (tunnel RTT +
dispatch overhead, captured by the fastest op) replaced by the
measured on-device op time. Queueing spread is preserved; the tunnel
constant is gone; the rows need no ``latency_degraded`` flag.
"""

from __future__ import annotations

import threading
import time

from .histogram import Log2Histogram
from ceph_tpu.utils.lockdep import DebugLock


class ClassStats:
    """One op class's ledger."""

    def __init__(self) -> None:
        self.hist = Log2Histogram()
        self.ops = 0            # measured (post-warmup) completions
        self.warmup_ops = 0     # excluded from hist/throughput
        self.bytes = 0          # measured bytes moved
        self.errors = 0
        self.verify_failures = 0

    @property
    def accounted(self) -> int:
        return self.ops + self.warmup_ops + self.errors


class RunRecorder:
    """Thread-safe run ledger; every issued op lands in EXACTLY one
    of {measured, warmup, error} per class — ``ops_accounted`` must
    equal ops issued at the end (the exactly-once check)."""

    def __init__(self, warmup_ops: int = 0) -> None:
        self._lock = DebugLock("loadgen.recorder")
        self._classes: dict[str, ClassStats] = {}
        self._warmup_ops = warmup_ops
        self._done = 0
        #: (t_complete_monotonic, nbytes) for measured ops — the
        #: timeline the fault window is cut from
        self._timeline: list[tuple[float, int]] = []
        self.t_start = time.monotonic()
        self.t_measure_start: float | None = None
        self.t_end: float | None = None
        self.device_floor_s: float | None = None

    def _cls(self, name: str) -> ClassStats:
        st = self._classes.get(name)
        if st is None:
            st = self._classes[name] = ClassStats()
        return st

    def record(
        self, op_class: str, latency_s: float, nbytes: int,
        ok: bool = True, verify_failed: bool = False,
    ) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._cls(op_class)
            self._done += 1
            if verify_failed:
                st.verify_failures += 1
            if not ok:
                st.errors += 1
                return
            if self._done <= self._warmup_ops:
                st.warmup_ops += 1
                return
            if self.t_measure_start is None:
                self.t_measure_start = now - latency_s
            st.ops += 1
            st.bytes += nbytes
            st.hist.record(latency_s)
            self._timeline.append((now, nbytes))

    def finish(self) -> None:
        self.t_end = time.monotonic()

    # -- report ---------------------------------------------------------
    @property
    def ops_accounted(self) -> int:
        with self._lock:
            return sum(
                st.accounted for st in self._classes.values()
            )

    def window_gbps(self, t0: float, t1: float) -> float:
        """Measured-op throughput over a monotonic-clock window (the
        degraded-window cut)."""
        if t1 <= t0:
            return 0.0
        with self._lock:
            nbytes = sum(
                b for t, b in self._timeline if t0 <= t <= t1
            )
        return nbytes / (t1 - t0) / 1e9

    def _device_adjusted_ms(self, hist: Log2Histogram,
                            p: float) -> float:
        """Host percentile with the constant host floor replaced by
        the device-clock per-op time (see module docstring)."""
        host_p = hist.percentile(p)
        return max(
            host_p - hist.min + (self.device_floor_s or 0.0), 0.0
        ) * 1e3

    def report(self) -> dict:
        """Full JSON-able run report."""
        end = self.t_end if self.t_end is not None else time.monotonic()
        start = (
            self.t_measure_start
            if self.t_measure_start is not None else self.t_start
        )
        dur = max(end - start, 1e-9)
        classes: dict[str, dict] = {}
        total_bytes = 0
        total_ops = 0
        agg = Log2Histogram()
        with self._lock:
            items = list(self._classes.items())
        for name, st in items:
            total_bytes += st.bytes
            total_ops += st.ops
            agg.merge(st.hist)
            entry = {
                "ops": st.ops,
                "warmup_ops": st.warmup_ops,
                "errors": st.errors,
                "verify_failures": st.verify_failures,
                "bytes": st.bytes,
                # 6 decimals: a CI-box socket tier can legitimately
                # run sub-MB/s and must not round to a zero row
                "gbps": round(st.bytes / dur / 1e9, 6),
                "iops": round(st.ops / dur, 1),
                **st.hist.snapshot(),
            }
            if self.device_floor_s is not None and st.hist.n:
                entry["p99_ms_device"] = round(
                    self._device_adjusted_ms(st.hist, 99), 3
                )
            classes[name] = entry
        out = {
            "duration_s": round(dur, 3),
            "ops": total_ops,
            "ops_accounted": self.ops_accounted,
            "bytes": total_bytes,
            "gbps": round(total_bytes / dur / 1e9, 6),
            "iops": round(total_ops / dur, 1),
            "verify_failures": sum(
                st.verify_failures for _n, st in items
            ),
            "errors": sum(st.errors for _n, st in items),
            "classes": classes,
        }
        if agg.n:
            out.update(
                {f"lat_{k}": v for k, v in agg.snapshot().items()
                 if k != "n"}
            )
            if self.device_floor_s is not None:
                out["lat_p99_ms_device"] = round(
                    self._device_adjusted_ms(agg, 99), 3
                )
                out["device_floor_ms"] = round(
                    self.device_floor_s * 1e3, 4
                )
        return out


class DeviceClock:
    """Trip-count-differenced per-op device time for the pool codec's
    encode program — the tunnel-independent latency floor.

    The measured quantity is the ONE thing the host clock cannot see
    through a degraded tunnel: how long the op's device program
    actually runs. An iterated on-device loop (feedback-patched so
    iterations are serially dependent — bench.py methodology note 1)
    is timed at two trip counts; the differenced per-iteration time
    carries no RTT term.
    """

    @staticmethod
    def measure(codec, chunk: int, n1: int = 4, n2: int = 24,
                reps: int = 3) -> float | None:
        """Seconds per single-stripe encode of ``chunk``-byte shards,
        or None when the device path is unavailable."""
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np

            k = codec.get_data_chunk_count()
            rng = np.random.default_rng(0xDC)
            shards = tuple(
                jnp.asarray(rng.integers(0, 256, chunk, np.uint8))
                for _ in range(k)
            )

            @jax.jit
            def loop(arrs, iters):
                def body(i, carry):
                    arrs, acc = carry
                    parity = codec.encode_chunks(
                        {j: arrs[j] for j in range(k)}
                    )
                    out = parity[sorted(parity)[0]]
                    fold = jax.lax.dynamic_slice(
                        out, (0,), (min(32, chunk),)
                    )
                    first = jax.lax.dynamic_update_slice(
                        arrs[0], fold ^ jnp.uint8(i + 1), (0,)
                    )
                    return (first,) + arrs[1:], acc ^ fold[0]

                _, acc = jax.lax.fori_loop(
                    0, iters, body, (arrs, jnp.uint8(0))
                )
                return acc

            def timed(iters: int) -> float:
                t0 = time.perf_counter()
                np.asarray(loop(shards, iters))
                return time.perf_counter() - t0

            timed(n1), timed(n2)  # compile + warm
            t1 = min(timed(n1) for _ in range(reps))
            t2 = min(timed(n2) for _ in range(reps))
            per = (t2 - t1) / (n2 - n1)
            return per if per > 0 else None
        except Exception:
            return None
