"""vstart-analog cluster harness for load generation.

Boots the REAL tier: monitor + N OSD daemons over sockets (``msg/``
framed messenger), an EC pool through the profile/pool machinery,
and a ``RadosClient`` — the same stack the e2e/chaos tests drive,
packaged with the kill/revive/wait-recovered controls the fault
schedule needs (qa/tasks/ceph_manager.py kill_osd/revive_osd role).
MemStore by default: loadgen measures the service path, not the
backing-store medium, unless a store factory says otherwise."""

from __future__ import annotations

import time

from ceph_tpu.cluster import Monitor, OSDDaemon, RadosClient
from ceph_tpu.cluster.osdmap import SHARD_NONE


class LoadCluster:
    """mon + OSDs + EC pool + client, with thrasher controls."""

    def __init__(
        self,
        n_osds: int = 6,
        k: int = 3,
        m: int = 2,
        pg_num: int = 8,
        chunk_size: int = 1024,
        pool: str = "loadpool",
        plugin: str = "jerasure",
        technique: str = "reed_sol_van",
        d: int | None = None,
        store_factory=None,
        tick_period: float = 0.2,
        client_backoff: float = 0.02,
        client_op_timeout: float = 3.0,
        client_max_attempts: int = 10,
        use_mesh: bool = False,
        mesh_devices: int | None = None,
        dcn_hosts: int = 0,
        dcn_devices_per_host: int = 1,
        dcn_data_timeout: float = 60.0,
    ) -> None:
        if n_osds < k + m:
            raise ValueError(f"need >= k+m={k + m} OSDs, got {n_osds}")
        clay_d = d  # the daemon boot loop below reuses the name ``d``
        self.pool = pool
        self.k, self.m = k, m
        self.chunk_size = chunk_size
        self._tick_period = tick_period
        # -- multi-chip tier wired into the LIVE path (round-10): the
        # daemons run in-process, so the process-wide dispatch mesh /
        # DCN cluster (parallel/dispatch.py) IS the live data path —
        # every RMW encode, degraded decode and recovery rebuild the
        # daemons run from here on rides the collective fan-out, the
        # way the reference's sub-op fan-out is its distributed
        # backend. Installed BEFORE the daemons boot so even the
        # first op routes over it; shutdown() restores what was there.
        self.mesh = None
        self.dcn = None
        self._prev_mesh = self._prev_dcn = None
        if use_mesh or dcn_hosts:
            from ceph_tpu.parallel import dispatch as mesh_dispatch

            self._prev_mesh = mesh_dispatch.get_mesh()
            self._prev_dcn = mesh_dispatch.get_dcn()
            if dcn_hosts:
                from ceph_tpu.parallel.dcn import DcnCluster

                self.dcn = DcnCluster(
                    n_hosts=dcn_hosts,
                    devices_per_host=dcn_devices_per_host,
                ).start()
                self._dcn_data_timeout = dcn_data_timeout
                # the data path must fail FAST into the single-host
                # fallback when a host dies mid-op (the client's
                # retry ladder is seconds, not the raw op timeout)
                self.dcn.apply_bitmatrix = (
                    lambda bm, data, timeout=dcn_data_timeout,
                    _orig=self.dcn.apply_bitmatrix:
                    _orig(bm, data, timeout=timeout)
                )
                mesh_dispatch.set_dcn(self.dcn)
            if use_mesh:
                from ceph_tpu.parallel import make_ec_mesh

                self.mesh = make_ec_mesh(mesh_devices, k=k)
                mesh_dispatch.set_mesh(self.mesh)
        self.mon = Monitor()
        self.daemons: dict[int, OSDDaemon] = {}
        self.stores: dict[int, object] = {}
        for i in range(n_osds):
            self.mon.osd_crush_add(i, zone=f"z{i % max(m + 1, 3)}")
        for i in range(n_osds):
            store = store_factory(i) if store_factory else None
            d = OSDDaemon(
                i, self.mon, store=store, chunk_size=chunk_size,
                tick_period=tick_period,
            )
            d.start()
            self.daemons[i] = d
            self.stores[i] = d.store
        profile = {
            "plugin": plugin, "k": str(k), "m": str(m),
        }
        if plugin == "jerasure":
            profile["technique"] = technique
        if plugin == "clay":
            # CLAY pools at the cluster tier: d steers the MSR repair
            # bandwidth (default k+m-1); chunks must split into q^t
            # lane-aligned sub-chunks for the fractional sub-reads
            if clay_d is not None:
                profile["d"] = str(clay_d)
            from ceph_tpu.codecs import registry as _reg

            sub = _reg.factory("clay", dict(profile)).get_sub_chunk_count()
            if chunk_size % sub:
                raise ValueError(
                    f"chunk_size {chunk_size} must divide into the "
                    f"pool's {sub} CLAY sub-chunks"
                )
        self.mon.osd_erasure_code_profile_set("loadprof", profile)
        self.mon.osd_pool_create(pool, pg_num, "loadprof")
        # short op timeout: a kill can eat an in-flight op's reply
        # mid-run, and the default 30 s wait would freeze the whole
        # closed loop for the duration (the reqid dedup makes the
        # fast resend safe)
        # generous retry budget: a kill + peering + durability-poll
        # cooldowns can stack several seconds of eagain before an op
        # lands; the default 8-attempt ladder at this backoff gives
        # up mid-recovery and turns a healable wait into an op error
        self.client = RadosClient(
            self.mon, backoff=client_backoff,
            op_timeout=client_op_timeout,
            max_attempts=client_max_attempts,
            perf_name="loadgen_client",
        )
        self.io = self.client.open_ioctx(pool)
        self.dead: list[int] = []
        #: OSDs currently cut off by a net partition (alive but
        #: unreachable on the data plane; map-down once evidence lands)
        self.partitioned: list[int] = []

    # -- thrasher controls ---------------------------------------------
    def live_osds(self) -> list[int]:
        return [i for i in self.daemons if i not in self.dead]

    def _primary_counts(self) -> dict[int, int]:
        spec = self.mon.osdmap.pools[self.pool]
        counts = {o: 0 for o in self.live_osds()}
        for pgid in range(spec.pg_num):
            p = self.mon.osdmap.pg_primary(self.pool, pgid)
            if p in counts:
                counts[p] += 1
        return counts

    def least_primary_osd(self) -> int:
        """The live OSD leading the FEWEST PGs of the pool (ties ->
        lowest id). Killing this one exercises degraded/reconstruct
        reads, revive catch-up and the recovery clock while forcing
        the fewest primary failovers — the gentlest victim."""
        counts = self._primary_counts()
        return min(counts, key=lambda o: (counts[o], o))

    def most_primary_osd(self) -> int:
        """The live OSD leading the MOST PGs of the pool (ties ->
        lowest id). Killing this one forces the maximum number of
        primary takeovers at once — the peering-FSM torture victim,
        and the default soak target now that the takeover race
        (ROADMAP #1) is closed by construction."""
        counts = self._primary_counts()
        return min(counts, key=lambda o: (-counts[o], o))

    def kill(self, osd: int) -> None:
        """Hard-stop the daemon and mark it down (failure detection
        collapsed to a command, as the e2e tier does)."""
        if osd in self.dead:
            return
        self.daemons[osd].stop()
        self.mon.osd_down(osd)
        self.dead.append(osd)

    def revive(self, osd: int) -> None:
        """Fresh daemon over the corpse's store: boot + log catch-up
        brings the shard back (the revive_osd path)."""
        if osd not in self.dead:
            return
        d = OSDDaemon(
            osd, self.mon, store=self.stores[osd],
            chunk_size=self.chunk_size, tick_period=self._tick_period,
        )
        d.start()
        self.daemons[osd] = d
        self.dead.remove(osd)

    # -- network-fault controls (the tc/netem analog) ------------------
    def net_flaky(
        self,
        seed: int = 0xEC,
        drop: float = 0.02,
        dup: float = 0.02,
        delay_ms: float = 5.0,
        delay_jitter_ms: float = 47.0,
        reorder: float = 0.01,
        scope: str = "osd",
    ) -> None:
        """Arm a seeded flaky profile on every link: inter-OSD only
        (``scope="osd"``, the acceptance profile) or the client legs
        too (``scope="all"``). Deterministic per link from ``seed``."""
        from ceph_tpu.msg.messenger import LinkRule, net_faults

        rule = LinkRule(
            drop=drop, dup=dup, delay_ms=delay_ms,
            delay_jitter_ms=delay_jitter_ms, reorder=reorder,
        )
        net_faults.configure(seed)
        if scope == "all":
            net_faults.add_rule("*", "*", rule)
        else:
            net_faults.add_rule("osd.*", "osd.*", rule)

    def net_partition(
        self, osd: int, asymmetric: bool = False, seed: int = 0xEC,
    ) -> None:
        """Cut osd.<id> off the data plane (frames dropped; TCP stays
        up, exactly a switch eating packets). ``asymmetric`` cuts only
        the inbound half — the victim keeps sending into the void, the
        re-election torture case. Failure detection is collapsed to a
        command like ``kill()``'s: the mon marks the victim down (its
        peers' evidence), so peering re-elects deterministically."""
        from ceph_tpu.msg.messenger import net_faults

        if not net_faults.active:
            net_faults.configure(seed)
        net_faults.partition(f"osd.{osd}", asymmetric=asymmetric)
        if osd not in self.partitioned:
            self.partitioned.append(osd)
        self.mon.osd_down(osd)

    def net_heal(self) -> None:
        """Merge: clear every armed link rule (held/delayed frames
        flush) and re-announce surviving partitioned daemons to the
        mon (the MOSDBoot a real OSD sends when its links return).
        Peering then re-admits them; scrub_clean is the caller's
        convergence gate."""
        from ceph_tpu.msg.messenger import net_faults

        net_faults.clear()
        for osd in list(self.partitioned):
            self.partitioned.remove(osd)
            if osd in self.dead:
                continue  # killed while partitioned: revive's problem
            d = self.daemons[osd]
            if d.addr is not None:
                self.mon.osd_boot(osd, d.addr)

    # -- recovery observation ------------------------------------------
    def is_recovered(self) -> bool:
        """Every member up, and for every PG: a full up_acting set in
        the map, the PRIMARY's instance peered with no hole in acting
        and no shard catch-up in flight, and no backfill running
        anywhere. Non-primary instances may cache a stale acting view
        from an old interval — only the primary's view (which serves
        ops) counts."""
        if self.dead:
            return False
        osdmap = self.mon.osdmap
        spec = osdmap.pools[self.pool]
        for pgid in range(spec.pg_num):
            acting = osdmap.pg_to_up_acting(self.pool, pgid)
            if any(o == SHARD_NONE for o in acting):
                return False
            primary = next(o for o in acting if o != SHARD_NONE)
            pg = self.daemons[primary]._pgs.get((self.pool, pgid))
            if pg is None:
                continue  # never instantiated: no state to heal
            if not pg.peered.is_set():
                return False
            if any(o == SHARD_NONE for o in pg.acting):
                return False
            if pg.backend.recovering:
                return False
        for d in self.daemons.values():
            if any(t.is_alive() for t in d._backfills.values()):
                return False
        return True

    def wait_recovered(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.is_recovered():
                return True
            time.sleep(0.05)
        return self.is_recovered()

    # -- stats-plane recovery observation (round 15) --------------------
    @property
    def pgmap(self):
        """The monitor-side PGMap aggregate the stats plane folds
        primaries' reports into (cluster/pgmap.py)."""
        return self.mon.pgmap

    def is_recovered_stats(self, min_epoch: int = 0) -> bool:
        """Recovery as the STATS PLANE sees it: every reported PG of
        the pool is clean with zero degraded object copies, reported
        at/after ``min_epoch`` (pass the post-revive map epoch so a
        dead primary's stale clean report cannot fake convergence).
        PGs with no report yet (never instantiated — no data) don't
        block; any degraded data forces a report via peering."""
        if self.dead:
            return False
        spec = self.mon.osdmap.pools[self.pool]
        pgmap = self.pgmap
        seen = 0
        for pgid in range(spec.pg_num):
            s = pgmap.get(spec.pool_id, pgid)
            if s is None:
                continue
            if s.reported_epoch < min_epoch:
                return False
            if s.degraded or "clean" not in s.state:
                return False
            seen += 1
        return seen > 0

    def wait_recovered_stats(
        self, timeout: float = 60.0, min_epoch: int = 0
    ) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.is_recovered_stats(min_epoch):
                return True
            time.sleep(0.05)
        return self.is_recovered_stats(min_epoch)

    def scrub_clean(self, repair: bool = True) -> bool:
        """Primary-driven scrub sweep; True iff no object reported
        errors (after optional repair — the post-thrash convergence
        check of the chaos tier)."""
        if repair:
            for d in self.daemons.values():
                if d.osd_id not in self.dead:
                    d.scrub_all(repair=True)
        ok = True
        for d in self.daemons.values():
            if d.osd_id in self.dead:
                continue
            for _pg, results in d.scrub_all().items():
                for r in results:
                    ok = ok and r.ok
        return ok

    def codec(self):
        """The pool's codec instance (device-clock probe input)."""
        from ceph_tpu.codecs import registry

        spec = self.mon.osdmap.pools[self.pool]
        profile = dict(self.mon.osdmap.profiles[spec.profile_name])
        return registry.factory(spec.plugin, profile)

    # -- multi-chip controls -------------------------------------------
    def kill_dcn_host(self, rank: int = 1) -> None:
        """Hard-kill one DCN host process mid-run (the VERDICT r5 #8
        scenario): the next op's collective faults, the codec
        dispatcher uninstalls the cluster and serves the op on a
        single-host route, and the client's retry ladder carries any
        op parked behind the fault to completion."""
        if self.dcn is None:
            raise RuntimeError("no DCN cluster installed")
        self.dcn.procs[rank].kill()

    def dcn_live(self) -> bool:
        """True while the DCN cluster is still the installed dispatch
        route (a mid-run host fault uninstalls it)."""
        from ceph_tpu.parallel import dispatch as mesh_dispatch

        return mesh_dispatch.get_dcn() is self.dcn and self.dcn is not None

    def shutdown(self) -> None:
        from ceph_tpu.msg.messenger import net_faults

        if self.partitioned or net_faults.active:
            net_faults.clear()
            self.partitioned.clear()
        self.client.shutdown()
        for d in self.daemons.values():
            d.stop()
        if self.mesh is not None or self.dcn is not None:
            from ceph_tpu.parallel import dispatch as mesh_dispatch

            mesh_dispatch.set_mesh(self._prev_mesh)
            mesh_dispatch.set_dcn(self._prev_dcn)
            if self.dcn is not None:
                self.dcn.stop()
