"""General key/value store — the RocksDBStore / KeyValueDB analog.

The reference funnels ALL metadata through one embedded KV database
(src/kv/KeyValueDB.h, RocksDBStore.{h,cc}): BlueStore keeps onodes,
omap, and its freelist in RocksDB column families; the monitor store
is a RocksDB too. The load-bearing API surface is small and mirrored
here:

- **prefixes** (the column-family role): every key lives under a short
  string prefix; iteration and bulk deletion are prefix-scoped.
- **batched transactions**: ``transaction()`` collects set/rmkey/
  rmkeys_by_prefix ops; ``submit_transaction`` applies them atomically
  and durably (one WAL record per batch).
- **sorted iterators**: ``iterate(prefix, start)`` yields (key, value)
  in key order — the lower_bound/next contract omap listing needs.

The storage engine is an LSM collapsed to its essentials: an in-memory
sorted table + a crc-framed WAL (store/framed_log — the same framing
the FileStore journal uses), compacted into a snapshot file when the
WAL grows past ``compact_every`` batches. Crash recovery = snapshot +
WAL replay with torn-tail truncation. Records are binary (length-
prefixed op tuples), not JSON: values are arbitrary bytes.

Wire format of one batch payload:
    <u32 nops> then per op:
    <u8 kind><u16 plen><u32 klen><u32 vlen><prefix><key><value>
    kind: 0=set, 1=rmkey, 2=rmkeys_by_prefix (key/value empty)
"""

from __future__ import annotations

import os
import struct
import threading

from . import framed_log
from ceph_tpu.utils.lockdep import DebugLock

_BATCH_HDR = struct.Struct("<I")
_OP_HDR = struct.Struct("<BHII")

_SET, _RMKEY, _RMPREFIX = 0, 1, 2


class KVTransaction:
    """One atomic batch (KeyValueDB::Transaction)."""

    def __init__(self) -> None:
        self.ops: list[tuple[int, str, str, bytes]] = []

    def set(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append((_SET, prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append((_RMKEY, prefix, key, b""))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append((_RMPREFIX, prefix, "", b""))
        return self

    def encode(self) -> bytes:
        out = bytearray(_BATCH_HDR.pack(len(self.ops)))
        for kind, prefix, key, value in self.ops:
            p, k = prefix.encode(), key.encode()
            out += _OP_HDR.pack(kind, len(p), len(k), len(value))
            out += p
            out += k
            out += value
        return bytes(out)

    @classmethod
    def decode(cls, payload: bytes) -> "KVTransaction":
        txn = cls()
        (nops,) = _BATCH_HDR.unpack_from(payload, 0)
        pos = _BATCH_HDR.size
        for _ in range(nops):
            kind, plen, klen, vlen = _OP_HDR.unpack_from(payload, pos)
            pos += _OP_HDR.size
            prefix = payload[pos : pos + plen].decode()
            pos += plen
            key = payload[pos : pos + klen].decode()
            pos += klen
            value = payload[pos : pos + vlen]
            pos += vlen
            txn.ops.append((kind, prefix, key, bytes(value)))
        if pos != len(payload):
            raise ValueError("trailing bytes in KV batch")
        return txn


class FileKVBackend:
    """Host-file durability tier: crc-framed WAL + snapshot file —
    the standalone KeyValueDB's storage (a monitor store, say). The
    BlockStore passes a DeviceFS-hosted backend instead, so ITS
    metadata lives on the raw device (the BlueFS arrangement)."""

    def __init__(self, root: str, name: str, sync: bool) -> None:
        os.makedirs(root, exist_ok=True)
        self.wal_path = os.path.join(root, f"{name}.wal")
        self.snap_path = os.path.join(root, f"{name}.snap")
        self.sync = sync

    def snap_read(self) -> "bytes | None":
        if not os.path.exists(self.snap_path):
            return None
        with open(self.snap_path, "rb") as f:
            return f.read()

    def wal_replay(self) -> list[bytes]:
        return framed_log.replay(self.wal_path)

    def wal_append(self, payload: bytes) -> None:
        framed_log.append(self.wal_path, payload, sync=self.sync)

    def snap_commit(self, snapshot: bytes) -> None:
        """Snapshot durable, THEN truncate the WAL (rename-before-
        truncate fsync ordering, as BlockStore._checkpoint)."""
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(snapshot)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        dirfd = os.open(
            os.path.dirname(self.snap_path) or ".", os.O_RDONLY
        )
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        with open(self.wal_path, "wb") as wal:
            wal.flush()
            os.fsync(wal.fileno())


class DeviceKVBackend:
    """DeviceFS-hosted durability tier: WAL frames and snapshots live
    in reserved extents of the owning BlockStore's device (the BlueFS
    role, os/bluestore/BlueFS.h:253)."""

    def __init__(self, fs) -> None:
        self.fs = fs

    def snap_read(self) -> "bytes | None":
        return self.fs.snap_read()

    def wal_replay(self) -> list[bytes]:
        return self.fs.wal_replay()

    def wal_append(self, payload: bytes) -> None:
        self.fs.wal_append(payload)

    def snap_commit(self, snapshot: bytes) -> None:
        self.fs.snap_commit(snapshot)


class KeyValueDB:
    """Durable prefix-scoped KV store (RocksDBStore role)."""

    def __init__(
        self,
        root: str,
        name: str = "kv",
        compact_every: int = 512,
        sync: bool = True,
        backend=None,
    ) -> None:
        self.backend = backend or FileKVBackend(root, name, sync)
        self.compact_every = compact_every
        self.sync = sync
        self._lock = DebugLock("store.kv", rank=62)
        self._table: dict[tuple[str, str], bytes] = {}
        self._wal_batches = 0
        self._load()

    # -- recovery / compaction -----------------------------------------
    def _load(self) -> None:
        snap = self.backend.snap_read()
        if snap is not None:
            self._apply(KVTransaction.decode(snap))
        for payload in self.backend.wal_replay():
            self._apply(KVTransaction.decode(payload))
            self._wal_batches += 1
        # NO compaction here: the device backend's compaction
        # allocates extents through the owning store's allocator,
        # which is rebuilt only after this load returns (freelist
        # needs the onodes). An over-threshold WAL compacts on the
        # next submit instead.

    def _apply(self, txn: KVTransaction) -> None:
        for kind, prefix, key, value in txn.ops:
            if kind == _SET:
                self._table[(prefix, key)] = value
            elif kind == _RMKEY:
                self._table.pop((prefix, key), None)
            else:
                for pk in [
                    pk for pk in self._table if pk[0] == prefix
                ]:
                    del self._table[pk]

    def _compact(self) -> None:
        """Snapshot the table, then (logically) truncate the WAL —
        the backend makes the pair atomic its own way."""
        snap = KVTransaction()
        for (prefix, key), value in sorted(self._table.items()):
            snap.set(prefix, key, value)
        self.backend.snap_commit(snap.encode())
        self._wal_batches = 0

    # -- write side -----------------------------------------------------
    def transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit_transaction(self, txn: KVTransaction) -> None:
        """Apply one batch atomically + durably (the WAL record hits
        disk before the in-memory table changes are visible)."""
        if not txn.ops:
            return
        with self._lock:
            self.backend.wal_append(txn.encode())
            self._apply(txn)
            self._wal_batches += 1
            if self._wal_batches >= self.compact_every:
                self._compact()

    # -- read side ------------------------------------------------------
    def get(self, prefix: str, key: str) -> bytes | None:
        with self._lock:
            return self._table.get((prefix, key))

    def get_multi(
        self, prefix: str, keys: list[str]
    ) -> dict[str, bytes]:
        with self._lock:
            out = {}
            for k in keys:
                v = self._table.get((prefix, k))
                if v is not None:
                    out[k] = v
            return out

    def iterate(
        self,
        prefix: str,
        start: str | None = None,
        end: str | None = None,
    ):
        """Sorted (key, value) pairs under ``prefix``; ``start`` is a
        lower bound (inclusive), ``end`` an upper bound (exclusive) —
        the iterator surface omap paging needs."""
        with self._lock:
            items = sorted(
                (k, v) for (p, k), v in self._table.items() if p == prefix
            )
        for k, v in items:
            if start is not None and k < start:
                continue
            if end is not None and k >= end:
                break
            yield k, v

    def compact(self) -> None:
        with self._lock:
            self._compact()

    def close(self) -> None:
        pass  # all state is durable at every return from submit
