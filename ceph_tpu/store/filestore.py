"""File-backed object store with a write-ahead journal — the
persistent ObjectStore tier (the BlueStore role, simplified).

Mirrors the contract the pipelines consume (the ObjectStore subset of
os/ObjectStore.h: ``queue_transactions`` applying an atomic op list;
POSIX-short reads; attr maps) with BlueStore's durability shape
(SURVEY.md §5.4b): every transaction is serialized into an on-disk
journal (length + crc32c framed), fsync'd, THEN applied to the object
files, then retired. A crash between journal and apply replays the
journal on open — transactions are idempotent (write/zero/truncate/
setattr/rmattr/remove/touch), so at-least-once replay converges.

Layout under the root directory:

    journal.wal                  pending transactions (usually empty)
    objects/<hex(oid)>.bin       object data
    objects/<hex(oid)>.attrs     attr map (json, atomic tmp+rename)

The same test suite runs over MemStore and FileStore, the
store_test.cc pattern of the reference (one suite, every backend).
"""

from __future__ import annotations

import json
import os
import threading

from . import framed_log
from .transaction import Op, OpKind, Transaction
from ceph_tpu.utils.lockdep import DebugLock


def _enc_name(oid: str) -> str:
    return oid.encode().hex()


class FileStore:
    def __init__(self, root: str, name: str = "filestore") -> None:
        self.name = name
        self.root = root
        self.objdir = os.path.join(root, "objects")
        os.makedirs(self.objdir, exist_ok=True)
        self.journal_path = os.path.join(root, "journal.wal")
        self._lock = DebugLock("store.file", rank=60)
        self.committed_seq = 0
        self._replay()

    # -- journal -------------------------------------------------------
    def _replay(self) -> None:
        """Apply any transactions that were journaled but not retired
        (crash recovery — the BlueStore WAL replay role). Replay is
        at-least-once: ops tolerate already-applied state (a REMOVE of
        a gone object is a no-op here, unlike the strict live path)."""
        if not os.path.exists(self.journal_path):
            return
        touched: set[str] = set()
        for payload in framed_log.replay(self.journal_path):
            txn = Transaction.from_bytes(payload)
            self._apply(txn, strict=False)
            touched.update(op.oid for op in txn.ops)
        # replayed state must be durable before the journal goes away
        self._fsync_objects(touched)
        os.unlink(self.journal_path)

    def queue_transactions(
        self, txns: "list[Transaction] | Transaction"
    ) -> int:
        if isinstance(txns, Transaction):
            txns = [txns]
        with self._lock:
            if not txns:  # MemStore parity: an empty batch commits
                self.committed_seq += 1
                return self.committed_seq
            # A journal left over from a FAILED apply (exception midway
            # through step 2) holds committed intent: converge it first
            # exactly like crash recovery would — otherwise this call's
            # retire step would unlink it unreplayed.
            if os.path.exists(self.journal_path):
                self._replay()
            # 0. validate — same atomicity contract as MemStore: a
            #    failing op leaves no partial state, so check every op
            #    against simulated existence/attr state up front.
            self._validate(txns)
            # 1. journal (durable intent) — the journal FILE and its
            #    directory entry must both be durable, or a crash
            #    mid-apply could lose the journal itself and leave a
            #    half-applied transaction with nothing to replay
            for txn in txns:
                framed_log.append(self.journal_path, txn.to_bytes(),
                                  sync=False)
            jf = os.open(self.journal_path, os.O_RDONLY)
            try:
                os.fsync(jf)
            finally:
                os.close(jf)
            rd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(rd)
            finally:
                os.close(rd)
            # 2. apply — on failure the journal is LEFT IN PLACE: the
            #    next commit (or the next open) replays it to converge
            for txn in txns:
                self._apply(txn)
            # 3. make the applied state durable BEFORE retiring the
            #    journal — otherwise a power cut after the unlink but
            #    before the page cache drains loses an acked commit.
            self._fsync_objects({op.oid for txn in txns for op in txn.ops})
            # 4. retire
            os.unlink(self.journal_path)
            self.committed_seq += 1
            return self.committed_seq

    def _fsync_objects(self, oids: "set[str]") -> None:
        for oid in oids:
            for p in self._paths(oid):
                if os.path.exists(p):
                    fd = os.open(p, os.O_RDONLY)
                    try:
                        os.fsync(fd)
                    finally:
                        os.close(fd)
        dfd = os.open(self.objdir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _validate(self, txns: "list[Transaction]") -> None:
        """Dry-run the op list against simulated state so the journal
        only ever records transactions that fully apply."""
        exists: dict[str, bool] = {}
        attrs: dict[str, set] = {}

        def obj_exists(oid: str) -> bool:
            if oid not in exists:
                exists[oid] = os.path.exists(self._paths(oid)[0])
            return exists[oid]

        def attr_names(oid: str) -> set:
            if oid not in attrs:
                attrs[oid] = (
                    set(self._load_attrs(oid)) if obj_exists(oid) else set()
                )
            return attrs[oid]

        for txn in txns:
            for op in txn.ops:
                if op.kind is OpKind.REMOVE:
                    if not obj_exists(op.oid):
                        raise FileNotFoundError(op.oid)
                    exists[op.oid] = False
                    attrs[op.oid] = set()
                elif op.kind in (OpKind.RMATTR, OpKind.RMATTR_TOLERANT):
                    if op.name not in attr_names(op.oid):
                        if op.kind is OpKind.RMATTR_TOLERANT:
                            exists[op.oid] = True
                            continue
                        raise KeyError(f"{op.oid}:{op.name}")
                    attrs[op.oid].discard(op.name)
                elif op.kind is OpKind.SETATTR:
                    attr_names(op.oid).add(op.name)
                    exists[op.oid] = True
                else:  # TOUCH / WRITE / ZERO / TRUNCATE create
                    attr_names(op.oid)
                    exists[op.oid] = True

    # -- apply ---------------------------------------------------------
    def _paths(self, oid: str) -> tuple[str, str]:
        base = os.path.join(self.objdir, _enc_name(oid))
        return base + ".bin", base + ".attrs"

    def _apply(self, txn: Transaction, strict: bool = True) -> None:
        for op in txn.ops:
            self._apply_op(op, strict)

    def _apply_op(self, op: Op, strict: bool = True) -> None:
        data_path, attr_path = self._paths(op.oid)
        if op.kind is OpKind.TOUCH:
            if not os.path.exists(data_path):
                open(data_path, "wb").close()
        elif op.kind is OpKind.WRITE:
            self._ensure(data_path)
            with open(data_path, "r+b") as f:
                # seek past EOF + write zero-fills the gap (POSIX)
                f.seek(op.offset)
                f.write(op.data)
        elif op.kind is OpKind.ZERO:
            self._ensure(data_path)
            with open(data_path, "r+b") as f:
                end = op.offset + op.length
                if os.fstat(f.fileno()).st_size < end:
                    f.truncate(end)  # extends, as MemStore's zero does
                f.seek(op.offset)
                f.write(b"\0" * op.length)
        elif op.kind is OpKind.TRUNCATE:
            self._ensure(data_path)
            with open(data_path, "r+b") as f:
                # truncate both shrinks and zero-extends (POSIX)
                f.truncate(op.offset)
        elif op.kind is OpKind.REMOVE:
            if strict and not os.path.exists(data_path):
                raise FileNotFoundError(op.oid)
            for p in (data_path, attr_path):
                if os.path.exists(p):
                    os.unlink(p)
        elif op.kind is OpKind.SETATTR:
            self._ensure(data_path)
            attrs = self._load_attrs(op.oid)
            attrs[op.name] = op.data
            self._store_attrs(op.oid, attrs)
        elif op.kind in (OpKind.RMATTR, OpKind.RMATTR_TOLERANT):
            attrs = self._load_attrs(op.oid)
            if op.name not in attrs:
                if not strict or op.kind is OpKind.RMATTR_TOLERANT:
                    self._ensure(data_path)
                    return
                raise KeyError(f"{op.oid}:{op.name}")
            del attrs[op.name]
            self._store_attrs(op.oid, attrs)

    @staticmethod
    def _ensure(path: str) -> None:
        if not os.path.exists(path):
            open(path, "wb").close()

    def _load_attrs(self, oid: str) -> dict[str, bytes]:
        _, attr_path = self._paths(oid)
        if not os.path.exists(attr_path):
            return {}
        with open(attr_path) as f:
            return {k: bytes.fromhex(v) for k, v in json.load(f).items()}

    def _store_attrs(self, oid: str, attrs: dict[str, bytes]) -> None:
        _, attr_path = self._paths(oid)
        tmp = attr_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({k: v.hex() for k, v in attrs.items()}, f)
        os.replace(tmp, attr_path)  # atomic on POSIX

    # -- read path (MemStore-identical contract; same lock discipline,
    #    so readers never see a partially-applied transaction) ---------
    def exists(self, oid: str) -> bool:
        with self._lock:
            return os.path.exists(self._paths(oid)[0])

    def stat(self, oid: str) -> int:
        data_path, _ = self._paths(oid)
        with self._lock:
            try:
                return os.path.getsize(data_path)
            except OSError:
                raise FileNotFoundError(oid) from None

    def read(
        self, oid: str, offset: int = 0, length: int | None = None
    ) -> bytes:
        data_path, _ = self._paths(oid)
        with self._lock:
            try:
                with open(data_path, "rb") as f:
                    f.seek(offset)
                    return f.read() if length is None else f.read(length)
            except OSError:
                raise FileNotFoundError(oid) from None

    def getattr(self, oid: str, name: str) -> bytes:
        with self._lock:
            if not os.path.exists(self._paths(oid)[0]):
                raise FileNotFoundError(oid)
            attrs = self._load_attrs(oid)
        if name not in attrs:
            raise KeyError(f"{oid}:{name}")
        return attrs[name]

    def getattrs(self, oid: str) -> dict[str, bytes]:
        with self._lock:
            if not os.path.exists(self._paths(oid)[0]):
                raise FileNotFoundError(oid)
            return self._load_attrs(oid)

    def list_objects(self) -> list[str]:
        with self._lock:
            out = []
            for fn in os.listdir(self.objdir):
                if fn.endswith(".bin"):
                    out.append(bytes.fromhex(fn[:-4]).decode())
            return sorted(out)

    def __repr__(self) -> str:
        return f"FileStore({self.root!r})"
