"""DeviceFS — the BlueFS analog: the KV store's WAL and snapshot
hosted INSIDE the BlockStore raw device.

The reference's BlueStore is single-device self-contained because
BlueFS (os/bluestore/BlueFS.h:253) carves RocksDB's WAL and SSTs out
of the same block device the data lives on, sharing space with the
data allocator. Round 4 shipped a BlockStore whose KV metadata WAL
and snapshot were separate host files — this module closes that gap
(VERDICT r4 item 6).

Layout:

- **Superblock pair** at device blocks 0 and 1 (A/B): a crc-framed
  JSON table {seq, wal_epoch, wal extents, snap extents, snap_len}.
  Updates write the OLDER copy then fsync — the valid superblock is
  the highest-seq copy whose crc checks (atomic by alternation, the
  classic double-superblock commit).
- **WAL**: frames (framed_log format, so torn tails self-detect)
  written sequentially into extents allocated from the SAME allocator
  as object data. Each frame's payload is prefixed with the current
  ``wal_epoch``; logical truncation is just ``wal_epoch += 1`` in the
  superblock — stale frames are filtered at replay, so compaction
  never rewrites the log region.
- **Snapshot**: written to freshly allocated extents, then the
  superblock swaps to them (and bumps wal_epoch) in one update; the
  old snapshot extents are freed after the swap. Crash before the
  swap: old snapshot + old epoch -> old WAL replays. Crash after:
  new snapshot + new epoch -> old frames filtered. No torn state.

Allocation goes through the owning BlockStore's allocator with a
large minimum grant (256 KiB) so the extent tables stay tiny and the
superblock fits one block forever.
"""

from __future__ import annotations

import json
import struct
import zlib

SUPER_MAGIC = b"CTFS"
SUPER_VERSION = 1
_SUPER_HDR2 = struct.Struct("<4sIQII")  # magic, version, seq, len, crc
_FRAME_HDR = struct.Struct("<II")     # payload len, crc32 of payload
_EPOCH = struct.Struct("<Q")

#: allocation granule for WAL/snapshot extents: big grants keep the
#: extent tables O(1) and the superblock single-block
GRANT = 256 * 1024


class DeviceFSError(IOError):
    pass


class DeviceFS:
    """WAL + snapshot files hosted in reserved extents of one device.

    The owner provides raw read/write callables and an allocate/free
    pair (the shared data allocator). Two fixed blocks at the device
    head hold the superblock pair; everything else is extents."""

    def __init__(
        self,
        dev_read,
        dev_write,
        dev_sync,
        block_size: int,
        allocate,
        free,
    ) -> None:
        self._read = dev_read
        self._write = dev_write
        self._sync = dev_sync
        self.block_size = block_size
        self._allocate = allocate   # (length) -> list[(off, len)]
        self._free = free           # (off, len) -> None
        self.seq = 0
        self.wal_epoch = 0
        self.wal_extents: list[tuple[int, int]] = []
        self.snap_extents: list[tuple[int, int]] = []
        self.snap_len = 0
        self._wal_pos = 0  # logical append offset within wal extents
        self._active_slot = 0  # which superblock copy holds `seq`

    # -- superblock -----------------------------------------------------
    def _sb_offset(self, slot: int) -> int:
        return slot * self.block_size

    def reserved_extents(self) -> list[tuple[int, int]]:
        """Every device range this filesystem owns (for freelist
        rebuilds): the superblock pair + all file extents."""
        out = [(0, 2 * self.block_size)]
        out.extend(self.wal_extents)
        out.extend(self.snap_extents)
        return out

    def _encode_super(self, seq: int, staged: dict) -> bytes:
        payload = json.dumps({
            "wal_epoch": staged["wal_epoch"],
            "wal": [list(e) for e in staged["wal_extents"]],
            "snap": [list(e) for e in staged["snap_extents"]],
            "snap_len": staged["snap_len"],
        }).encode()
        hdr = _SUPER_HDR2.pack(
            SUPER_MAGIC, SUPER_VERSION, seq, len(payload),
            zlib.crc32(payload),
        )
        blob = hdr + payload
        if len(blob) > self.block_size:
            raise DeviceFSError(
                f"superblock {len(blob)}B exceeds one block — extent "
                "tables should never fragment this far (GRANT sizing)"
            )
        return blob.ljust(self.block_size, b"\x00")

    @staticmethod
    def _decode_super(raw: bytes):
        if len(raw) < _SUPER_HDR2.size:
            return None
        magic, ver, seq, plen, crc = _SUPER_HDR2.unpack_from(raw, 0)
        if magic != SUPER_MAGIC or ver != SUPER_VERSION:
            return None
        payload = raw[_SUPER_HDR2.size : _SUPER_HDR2.size + plen]
        if len(payload) != plen or zlib.crc32(payload) != crc:
            return None
        try:
            obj = json.loads(payload.decode())
        except ValueError:
            return None
        return seq, obj

    def _write_super(self, **changes) -> None:
        """Commit the table with ``changes`` applied: encode FIRST
        (any overflow raises with nothing mutated), write the
        INACTIVE copy, sync, and only then adopt the staged state
        in memory. The higher-seq valid copy wins at load, so a torn
        write of this copy leaves the other one authoritative — and
        a raised write leaves the in-memory view matching the durable
        one (a memory-ahead-of-disk epoch once silently discarded
        acked post-failure WAL frames on replay)."""
        staged = {
            f: getattr(self, f)
            for f in ("wal_epoch", "wal_extents", "snap_extents",
                      "snap_len")
        }
        staged.update(changes)
        seq = self.seq + 1
        blob = self._encode_super(seq, staged)
        slot = 1 - self._active_slot
        self._write(self._sb_offset(slot), blob)
        self._sync()
        self.seq = seq
        self._active_slot = slot
        for f, v in staged.items():
            setattr(self, f, v)

    def format(self) -> None:
        """Fresh filesystem: both superblock copies zeroed, then copy
        0 written with the empty table."""
        self._write(0, b"\x00" * (2 * self.block_size))
        self.seq = 0
        self.wal_epoch = 0
        self.wal_extents = []
        self.snap_extents = []
        self.snap_len = 0
        self._wal_pos = 0
        self._active_slot = 1  # so _write_super lands in slot 0
        self._write_super()

    @classmethod
    def probe(cls, dev_read, block_size: int) -> bool:
        """Does the device carry a DeviceFS superblock?"""
        for slot in (0, 1):
            raw = dev_read(slot * block_size, block_size)
            if cls._decode_super(raw) is not None:
                return True
        return False

    def load(self) -> None:
        best = None
        for slot in (0, 1):
            raw = self._read(self._sb_offset(slot), self.block_size)
            dec = self._decode_super(raw)
            if dec is not None and (best is None or dec[0] > best[0][0]):
                best = (dec, slot)
        if best is None:
            raise DeviceFSError("no valid DeviceFS superblock")
        (seq, obj), slot = best
        self.seq = seq
        self._active_slot = slot
        self.wal_epoch = obj["wal_epoch"]
        self.wal_extents = [tuple(e) for e in obj["wal"]]
        self.snap_extents = [tuple(e) for e in obj["snap"]]
        self.snap_len = obj["snap_len"]
        self._wal_pos = 0  # recomputed by replay()

    # -- extent-mapped IO ----------------------------------------------
    @staticmethod
    def _map(extents, pos: int, length: int):
        """(device offset, run length) pieces for a logical range."""
        out = []
        logical = 0
        for off, ln in extents:
            if length <= 0:
                break
            if pos < logical + ln:
                inner = max(0, pos - logical)
                take = min(ln - inner, length)
                out.append((off + inner, take))
                pos += take
                length -= take
            logical += ln
        if length > 0:
            raise DeviceFSError("range beyond file extents")
        return out

    def _file_write(self, extents, pos: int, data: bytes) -> None:
        for off, ln in self._map(extents, pos, len(data)):
            self._write(off, data[:ln])
            data = data[ln:]

    def _file_read(self, extents, pos: int, length: int) -> bytes:
        return b"".join(
            self._read(off, ln)
            for off, ln in self._map(extents, pos, length)
        )

    @staticmethod
    def _cap(extents) -> int:
        return sum(ln for _, ln in extents)

    # -- WAL ------------------------------------------------------------
    def wal_append(self, payload: bytes) -> None:
        """One framed record, epoch-prefixed, extents grown on demand
        (superblock updates ONLY when extents are added — the steady-
        state append path writes just the frame)."""
        body = _EPOCH.pack(self.wal_epoch) + payload
        frame = _FRAME_HDR.pack(len(body), zlib.crc32(body)) + body
        need = self._wal_pos + len(frame) - self._cap(self.wal_extents)
        if need > 0:
            grants = [tuple(g) for g in self._allocate(max(need, GRANT))]
            try:
                self._write_super(
                    wal_extents=self.wal_extents + grants
                )
            except Exception:
                for off, ln in grants:
                    self._free(off, ln)
                raise
        self._file_write(self.wal_extents, self._wal_pos, frame)
        self._sync()
        self._wal_pos += len(frame)

    def wal_replay(self) -> list[bytes]:
        """Valid current-epoch frames, in order; stops at the first
        torn/stale frame (the framed_log torn-tail rule). Also leaves
        ``_wal_pos`` at the append position."""
        out = []
        cap = self._cap(self.wal_extents)
        pos = 0
        while pos + _FRAME_HDR.size <= cap:
            hdr = self._file_read(self.wal_extents, pos, _FRAME_HDR.size)
            ln, crc = _FRAME_HDR.unpack(hdr)
            if ln == 0 or pos + _FRAME_HDR.size + ln > cap:
                break
            body = self._file_read(
                self.wal_extents, pos + _FRAME_HDR.size, ln
            )
            if zlib.crc32(body) != crc or len(body) < _EPOCH.size:
                break
            (epoch,) = _EPOCH.unpack_from(body, 0)
            if epoch != self.wal_epoch:
                break  # pre-compaction leftovers
            out.append(body[_EPOCH.size :])
            pos += _FRAME_HDR.size + ln
        self._wal_pos = pos
        return out

    # -- snapshot -------------------------------------------------------
    def snap_read(self) -> bytes | None:
        if not self.snap_extents or self.snap_len == 0:
            return None
        return self._file_read(self.snap_extents, 0, self.snap_len)

    def snap_commit(self, snapshot: bytes) -> None:
        """Durable snapshot + logical WAL truncation in ONE superblock
        swap: write the new snapshot into fresh extents, sync, then
        flip the table (new snap extents, wal_epoch+1). Old snapshot
        extents are freed after the flip; a crash OR a raised write at
        any point leaves either the complete old state or the
        complete new one (in memory too — _write_super adopts its
        staged fields only after the sync returns).

        The GRANT floor on the allocation keeps the extent table
        short even on a fragmented freelist — the superblock must fit
        one block forever, and _encode_super refuses (harmlessly,
        pre-mutation: the WAL just keeps growing until the next
        attempt) rather than overflow."""
        new_extents = [
            tuple(g)
            for g in self._allocate(max(len(snapshot), GRANT))
        ]
        try:
            self._file_write(new_extents, 0, snapshot)
            self._sync()
            old = self.snap_extents
            self._write_super(
                snap_extents=new_extents,
                snap_len=len(snapshot),
                wal_epoch=self.wal_epoch + 1,
            )
        except Exception:
            for off, ln in new_extents:
                self._free(off, ln)
            raise
        for off, ln in old:
            self._free(off, ln)
        self._wal_pos = 0
