"""In-memory object store — the ``MemStore`` analog (src/os/memstore/).

One ``MemStore`` instance plays the role of one OSD shard's local
store in pipeline tests (the reference boots MemStore-backed OSDs for
exactly this, src/test/objectstore/store_test.cc). Objects are dense
byte buffers plus an attr map; transactions apply atomically —
validated first, then applied, so a failing op leaves no partial
state (stricter than the reference's assert-on-error, deliberately:
a functional-style store suits a replayable TPU pipeline).
"""

from __future__ import annotations

import threading

from .transaction import Op, OpKind, Transaction
from ceph_tpu.utils.lockdep import DebugLock


class _Object:
    __slots__ = ("data", "attrs")

    def __init__(self) -> None:
        self.data = bytearray()
        self.attrs: dict[str, bytes] = {}

    def clone(self) -> "_Object":
        o = _Object()
        o.data = bytearray(self.data)
        o.attrs = dict(self.attrs)
        return o


class MemStore:
    """oid -> object map with atomic transaction application."""

    def __init__(self, name: str = "memstore") -> None:
        self.name = name
        self._objects: dict[str, _Object] = {}
        self._lock = DebugLock("store.mem", rank=60)
        self.committed_seq = 0  # count of applied transactions

    # -- write path ----------------------------------------------------
    def queue_transactions(self, txns: list[Transaction] | Transaction) -> int:
        """Apply transactions atomically, in order; returns the commit
        sequence (the on_commit callback's context in the reference)."""
        if isinstance(txns, Transaction):
            txns = [txns]
        with self._lock:
            staged: dict[str, _Object | None] = {}

            def get(oid: str, create: bool) -> _Object | None:
                if oid not in staged:
                    cur = self._objects.get(oid)
                    staged[oid] = cur.clone() if cur is not None else None
                if staged[oid] is None and create:
                    staged[oid] = _Object()
                return staged[oid]

            for t in txns:
                for op in t.ops:
                    self._apply(op, get, staged)
            for oid, obj in staged.items():
                if obj is None:
                    self._objects.pop(oid, None)
                else:
                    self._objects[oid] = obj
            self.committed_seq += 1
            return self.committed_seq

    @staticmethod
    def _apply(op: Op, get, staged: dict) -> None:
        if op.kind is OpKind.TOUCH:
            get(op.oid, create=True)
            return
        if op.kind is OpKind.REMOVE:
            obj = get(op.oid, create=False)
            if obj is None:
                raise FileNotFoundError(op.oid)
            staged[op.oid] = None
            return
        if op.kind is OpKind.WRITE:
            obj = get(op.oid, create=True)
            end = op.offset + len(op.data)
            if len(obj.data) < end:
                obj.data.extend(b"\0" * (end - len(obj.data)))
            obj.data[op.offset:end] = op.data
            return
        if op.kind is OpKind.ZERO:
            obj = get(op.oid, create=True)
            end = op.offset + op.length
            if len(obj.data) < end:
                obj.data.extend(b"\0" * (end - len(obj.data)))
            obj.data[op.offset:end] = b"\0" * op.length
            return
        if op.kind is OpKind.TRUNCATE:
            obj = get(op.oid, create=True)
            size = op.offset
            if len(obj.data) > size:
                del obj.data[size:]
            else:
                obj.data.extend(b"\0" * (size - len(obj.data)))
            return
        if op.kind is OpKind.SETATTR:
            obj = get(op.oid, create=True)
            obj.attrs[op.name] = op.data
            return
        if op.kind in (OpKind.RMATTR, OpKind.RMATTR_TOLERANT):
            obj = get(op.oid, create=False)
            if obj is None or op.name not in obj.attrs:
                if op.kind is OpKind.RMATTR_TOLERANT:
                    get(op.oid, create=True)
                    return
                raise KeyError(f"{op.oid}:{op.name}")
            del obj.attrs[op.name]
            return

    # -- read path -----------------------------------------------------
    def exists(self, oid: str) -> bool:
        with self._lock:
            return oid in self._objects

    def stat(self, oid: str) -> int:
        """Object size in bytes; FileNotFoundError if absent."""
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            return len(obj.data)

    def read(self, oid: str, offset: int = 0, length: int | None = None) -> bytes:
        """Read a range; short if it extends past EOF (POSIX-style, as
        MemStore::read). FileNotFoundError if the object is absent."""
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            if length is None:
                length = len(obj.data) - offset
            return bytes(obj.data[offset:offset + length])

    def getattr(self, oid: str, name: str) -> bytes:
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            if name not in obj.attrs:
                raise KeyError(f"{oid}:{name}")
            return obj.attrs[name]

    def getattrs(self, oid: str) -> dict[str, bytes]:
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            return dict(obj.attrs)

    def list_objects(self) -> list[str]:
        with self._lock:
            return sorted(self._objects)

    def __repr__(self) -> str:
        return f"MemStore({self.name!r}, objects={len(self._objects)})"
