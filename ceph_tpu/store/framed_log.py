"""Crc-framed append-only log — the shared WAL framing used by both
the FileStore journal and the monitor store (one implementation of
the length+crc32c record format, one torn-tail policy).

Records are ``<u32 len><u32 crc32c(payload)><payload>``. ``scan``
returns every intact record plus the byte offset where validity ends;
``replay`` additionally TRUNCATES the file at that offset — a torn
tail must not survive, or appends after a crash would land behind it
and every later record would be unreachable to the next scan.
"""

from __future__ import annotations

import os
import struct

from ceph_tpu.checksum import crc32c_scalar as _crc

HDR = struct.Struct("<II")


def append(path: str, payload: bytes, sync: bool = True) -> None:
    with open(path, "ab") as f:
        f.write(HDR.pack(len(payload), _crc(0xFFFFFFFF, payload)))
        f.write(payload)
        if sync:
            f.flush()
            os.fsync(f.fileno())


def scan(raw: bytes) -> tuple[list[bytes], int]:
    """Intact payloads + the offset where the valid prefix ends."""
    out: list[bytes] = []
    pos = 0
    while pos + HDR.size <= len(raw):
        length, crc = HDR.unpack_from(raw, pos)
        payload = raw[pos + HDR.size : pos + HDR.size + length]
        if len(payload) < length or _crc(0xFFFFFFFF, payload) != crc:
            break  # torn tail
        out.append(payload)
        pos += HDR.size + length
    return out, pos


def replay(path: str) -> list[bytes]:
    """Read intact records; truncate any torn tail away."""
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        raw = f.read()
    payloads, valid = scan(raw)
    if valid < len(raw):
        with open(path, "r+b") as f:
            f.truncate(valid)
            f.flush()
            os.fsync(f.fileno())
    return payloads
