"""BlockStore — the BlueStore analog: objects on a raw block device.

Mirrors BlueStore's structural shape (src/os/bluestore/BlueStore.cc):

- **one flat device** (a preallocated file standing in for the raw
  block device) holds all object data as allocator-granted extents;
- **metadata lives in the embedded KV store, not in a filesystem**:
  onodes (oid → blob list + attrs) are rows in ``store.kvstore``
  under the "O" prefix — the BlueStore-onodes-in-RocksDB architecture
  (BlueStore.cc keeps onodes/omap in RocksDB column families). Each
  transaction batch commits ONE KV batch containing only the onodes
  it touched (delta commits, not a full-table dump); the KV store's
  own WAL + snapshot compaction provide recovery;
- **allocator-managed free space** (Btree/Bitmap/Hybrid — the
  reference's allocator family) rebuilt on open from the object table
  (the FreelistManager inversion: used = union of live blobs);
- **every blob carries a checksum**: crc32c per csum-block stored in
  the blob metadata and verified on every read (BlueStore::_verify_csum,
  BlueStore.cc:12878) — a flipped bit on the device surfaces as EIO,
  never as silently corrupt data. Blob csums come from TWO sources:
  a WRITE op carrying fused encode+csum kernel output (Op.csums —
  per-block crc32c computed on device while the bytes were resident
  for the EC encode matmul) is adopted directly after a seed-shift
  XOR, so the hot write path hashes nothing on the host; every other
  write (unaligned ranges, partial tail blocks, non-EC callers)
  falls back to the host scalar path behind the Checksummer facade
  (checksum.crc32c_scalar). Read-side verification always recomputes
  on the host facade — the store never trusts bytes it returns;
- transactions follow the same validated-atomic contract as
  MemStore/FileStore: the SAME store test suite runs over all three
  backends (the store_test.cc pattern).

Write path (BlueStore::queue_transactions shape, simplified to the
COW case): allocate fresh extents for the written range's blocks, write
+ fsync data, then commit the metadata record to the WAL — data blocks
are never overwritten in place, so a torn data write cannot damage
committed state (the deferred-write/COW discipline collapsed to
always-COW).
"""

from __future__ import annotations

import json
import os
import threading

from ceph_tpu.checksum import crc32c_scalar as _crc
from ceph_tpu.checksum import crc32c_seed_shift

from . import framed_log
from .allocator import ALLOCATORS, AllocError
from .devicefs import DeviceFS
from .kvstore import DeviceKVBackend, KeyValueDB
from .transaction import Op, OpKind, Transaction
from ceph_tpu.utils.lockdep import DebugLock

#: KV prefixes (the column-family layout, BlueStore PREFIX_* style):
#: O = onodes, S = store-wide state (committed seq)
PREFIX_ONODE = "O"
PREFIX_STATE = "S"

CSUM_SEED = 0xFFFFFFFF


class _Blob:
    """One contiguous stored run: device extent + per-block csums."""

    __slots__ = ("offset", "length", "csums")

    def __init__(self, offset: int, length: int, csums: list[int]) -> None:
        self.offset = offset  # device offset
        self.length = length
        self.csums = csums    # crc32c per csum block

    def to_obj(self):
        return [self.offset, self.length, self.csums]

    @classmethod
    def from_obj(cls, o):
        return cls(o[0], o[1], list(o[2]))


class _Onode:
    """Object metadata (the BlueStore Onode): logical block map."""

    __slots__ = ("size", "blobs", "attrs")

    def __init__(self) -> None:
        self.size = 0
        self.blobs: dict[int, _Blob] = {}  # logical block off -> blob
        self.attrs: dict[str, bytes] = {}

    def to_obj(self):
        return {
            "size": self.size,
            "blobs": {str(k): b.to_obj() for k, b in self.blobs.items()},
            "attrs": {k: v.hex() for k, v in self.attrs.items()},
        }

    @classmethod
    def from_obj(cls, o):
        n = cls()
        n.size = o["size"]
        n.blobs = {int(k): _Blob.from_obj(b) for k, b in o["blobs"].items()}
        n.attrs = {k: bytes.fromhex(v) for k, v in o["attrs"].items()}
        return n


class CsumError(IOError):
    """Stored data failed checksum verification (the EIO surface of
    BlueStore::_verify_csum)."""


class BlockStore:
    """ObjectStore over one raw device file."""

    def __init__(
        self,
        root: str,
        size: int = 1 << 28,
        block_size: int = 4096,
        csum_block: int = 4096,
        allocator: str = "hybrid",
        name: str = "blockstore",
        checkpoint_every: int = 256,
    ) -> None:
        self.name = name
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.block_size = block_size
        self.csum_block = csum_block
        self.checkpoint_every = checkpoint_every
        self.device_path = os.path.join(root, "block")
        self.wal_path = os.path.join(root, "meta.wal")      # legacy
        self.ckpt_path = os.path.join(root, "meta.ckpt")    # legacy
        self._lock = DebugLock("store.block", rank=60)
        self.committed_seq = 0
        if not os.path.exists(self.device_path):
            with open(self.device_path, "wb") as f:
                f.truncate(size)
        # r+b, NOT a+b: append mode would ignore seeks on write
        self._dev = open(self.device_path, "r+b")
        self.device_size = os.path.getsize(self.device_path)
        self._objects: dict[str, _Onode] = {}
        # -- metadata home: DeviceFS (the BlueFS analog) hosts the KV
        # WAL/snapshot in reserved extents of THIS device, so the
        # store is single-device self-contained (BlueFS.h:253). A
        # store that already has host-file KV data keeps that legacy
        # layout (its device blocks 0-1 may hold object data).
        self._fs = None
        legacy_kv = any(
            os.path.exists(p)
            for p in (
                os.path.join(root, "kv.wal"),
                os.path.join(root, "kv.snap"),
                self.wal_path,
                self.ckpt_path,
            )
        )
        fs = DeviceFS(
            self._dev_read, self._dev_write, self._dev_sync,
            block_size,
            lambda n: self.allocator.allocate(n),
            lambda off, ln: self.allocator.release([(off, ln)]),
        )
        if DeviceFS.probe(self._dev_read, block_size):
            fs.load()
            self._fs = fs
        elif not legacy_kv:
            fs.format()
            self._fs = fs
        backend = DeviceKVBackend(self._fs) if self._fs else None
        # distinct "kv" namespace: the legacy format owned meta.wal
        self._kvdb = KeyValueDB(
            root, name="kv", compact_every=checkpoint_every,
            backend=backend,
        )
        self._load_metadata()
        self.allocator = ALLOCATORS[allocator](block_size)
        self._rebuild_freelist()

    # -- metadata persistence (onodes as KV rows) ----------------------
    def _load_metadata(self) -> None:
        self._import_legacy_metadata()
        raw_seq = self._kvdb.get(PREFIX_STATE, "seq")
        self.committed_seq = int(raw_seq) if raw_seq else 0
        self._objects = {
            oid: _Onode.from_obj(json.loads(raw))
            for oid, raw in self._kvdb.iterate(PREFIX_ONODE)
        }

    def _import_legacy_metadata(self) -> None:
        """One-shot upgrade from the pre-KV format (full-table JSON
        checkpoint + WAL records) into KV rows — the format-migration
        discipline BlueStore applies between its own metadata
        revisions. Legacy files are removed once their content is
        durable in the KV store."""
        if not (
            os.path.exists(self.ckpt_path) or os.path.exists(self.wal_path)
        ):
            return
        raw_kv_seq = self._kvdb.get(PREFIX_STATE, "seq")
        kv_seq = int(raw_kv_seq) if raw_kv_seq else -1
        seq, objects = 0, {}
        if os.path.exists(self.ckpt_path):
            with open(self.ckpt_path) as f:
                snap = json.load(f)
            seq, objects = snap["seq"], dict(snap["objects"])
        for payload in framed_log.replay(self.wal_path):
            rec = json.loads(payload.decode())
            if rec["seq"] > seq:
                seq, objects = rec["seq"], dict(rec["objects"])
        if kv_seq >= seq:
            # An earlier migration already absorbed this content (we
            # crashed between the two removes below): importing again
            # from a STALE checkpoint would rewind the KV rows past
            # acked transactions. Just finish the cleanup.
            for path in (self.wal_path, self.ckpt_path):
                if os.path.exists(path):
                    os.remove(path)
            return
        txn = self._kvdb.transaction()
        txn.rmkeys_by_prefix(PREFIX_ONODE)
        for oid, obj in objects.items():
            txn.set(PREFIX_ONODE, oid, json.dumps(obj).encode())
        txn.set(PREFIX_STATE, "seq", str(seq).encode())
        self._kvdb.submit_transaction(txn)
        self._kvdb.compact()  # durable snapshot before dropping legacy
        # WAL first: if we crash between the removes, a surviving ckpt
        # re-imports the same content (idempotent); a surviving EMPTY
        # wal alone would re-import nothing and wipe the rows.
        for path in (self.wal_path, self.ckpt_path):
            if os.path.exists(path):
                os.remove(path)

    def _commit_metadata(self, staged: "dict[str, _Onode | None]") -> None:
        """One KV batch per transaction batch, containing ONLY the
        onodes this batch touched (delta commits — the reason the
        metadata tier is a KV store and not a journaled table dump)."""
        self.committed_seq += 1
        txn = self._kvdb.transaction()
        for oid, onode in staged.items():
            if onode is None:
                txn.rmkey(PREFIX_ONODE, oid)
            else:
                txn.set(
                    PREFIX_ONODE, oid, json.dumps(onode.to_obj()).encode()
                )
        txn.set(PREFIX_STATE, "seq", str(self.committed_seq).encode())
        self._kvdb.submit_transaction(txn)

    def _rebuild_freelist(self) -> None:
        """FreelistManager inversion: free = device minus live blobs
        minus the DeviceFS's own extents (superblocks + KV WAL/snap —
        the BlueFS space-sharing arrangement)."""
        used: list[tuple[int, int]] = []
        for onode in self._objects.values():
            for blob in onode.blobs.values():
                n_blocks = -(-blob.length // self.block_size)
                used.append((blob.offset, n_blocks * self.block_size))
        if self._fs is not None:
            for off, ln in self._fs.reserved_extents():
                n_blocks = -(-ln // self.block_size)
                used.append((off, n_blocks * self.block_size))
        used.sort()
        pos = 0
        for off, ln in used:
            if off > pos:
                self.allocator.init_add_free(pos, off - pos)
            pos = max(pos, off + ln)
        if pos < self.device_size:
            self.allocator.init_add_free(pos, self.device_size - pos)

    # -- device IO ------------------------------------------------------
    def _dev_write(self, offset: int, data: bytes) -> None:
        self._dev.seek(offset)
        self._dev.write(data)

    def _dev_read(self, offset: int, length: int) -> bytes:
        self._dev.seek(offset)
        return self._dev.read(length)

    def _dev_sync(self) -> None:
        self._dev.flush()
        os.fsync(self._dev.fileno())

    def _csum(self, data: bytes) -> list[int]:
        out = []
        for i in range(0, len(data), self.csum_block):
            out.append(_crc(CSUM_SEED, data[i : i + self.csum_block]))
        return out

    # -- transaction application ---------------------------------------
    def queue_transactions(
        self, txns: "list[Transaction] | Transaction"
    ) -> int:
        if isinstance(txns, Transaction):
            txns = [txns]
        with self._lock:
            staged = {
                oid: self._clone_onode(oid)
                for txn in txns
                for oid in {op.oid for op in txn.ops}
            }
            freed: list[tuple[int, int]] = []
            allocated: list[tuple[int, int]] = []
            try:
                for txn in txns:
                    for op in txn.ops:
                        self._apply_op(op, staged, freed, allocated)
            except Exception:
                self.allocator.release(allocated)
                raise
            self._dev.flush()
            os.fsync(self._dev.fileno())
            for oid, onode in staged.items():
                if onode is None:
                    self._objects.pop(oid, None)
                else:
                    self._objects[oid] = onode
            self._commit_metadata(staged)
            # old blocks join the freelist only AFTER the metadata that
            # stops referencing them is durable (COW discipline)
            self.allocator.release(freed)
            return self.committed_seq

    def _clone_onode(self, oid: str) -> "_Onode | None":
        cur = self._objects.get(oid)
        if cur is None:
            return None
        n = _Onode()
        n.size = cur.size
        n.blobs = dict(cur.blobs)  # blobs are immutable (COW)
        n.attrs = dict(cur.attrs)
        return n

    def _get(self, staged, oid: str, create: bool) -> "_Onode | None":
        onode = staged.get(oid)
        if onode is None and create:
            onode = _Onode()
            staged[oid] = onode
        return onode

    def _apply_op(self, op: Op, staged, freed, allocated) -> None:
        bs = self.block_size
        if op.kind is OpKind.TOUCH:
            self._get(staged, op.oid, create=True)
        elif op.kind is OpKind.WRITE:
            onode = self._get(staged, op.oid, create=True)
            self._write_range(
                onode, op.offset, op.data, freed, allocated,
                csums=op.csums, csum_block=op.csum_block,
            )
            onode.size = max(onode.size, op.offset + len(op.data))
        elif op.kind is OpKind.ZERO:
            onode = self._get(staged, op.oid, create=True)
            self._write_range(
                onode, op.offset, b"\0" * op.length, freed, allocated
            )
            onode.size = max(onode.size, op.offset + op.length)
        elif op.kind is OpKind.TRUNCATE:
            onode = self._get(staged, op.oid, create=True)
            if op.offset < onode.size:
                for boff in sorted(onode.blobs):
                    blob = onode.blobs.get(boff)
                    if blob is None:
                        continue
                    if boff >= op.offset:
                        onode.blobs.pop(boff)
                        n = -(-blob.length // bs)
                        freed.append((blob.offset, n * bs))
                    elif boff + blob.length > op.offset:
                        # straddling blob: trim it, or its stale tail
                        # bytes would resurface when the object is
                        # later zero-extended past the cut
                        head = self._blob_bytes(blob)[: op.offset - boff]
                        onode.blobs.pop(boff)
                        n = -(-blob.length // bs)
                        freed.append((blob.offset, n * bs))
                        self._store_run(onode, boff, head, allocated)
            onode.size = op.offset
        elif op.kind is OpKind.REMOVE:
            onode = staged.get(op.oid)
            if onode is None:
                raise FileNotFoundError(op.oid)
            for blob in onode.blobs.values():
                n = -(-blob.length // bs)
                freed.append((blob.offset, n * bs))
            staged[op.oid] = None
        elif op.kind is OpKind.SETATTR:
            onode = self._get(staged, op.oid, create=True)
            onode.attrs[op.name] = op.data
        elif op.kind in (OpKind.RMATTR, OpKind.RMATTR_TOLERANT):
            onode = staged.get(op.oid)
            if onode is None or op.name not in onode.attrs:
                if op.kind is OpKind.RMATTR_TOLERANT:
                    self._get(staged, op.oid, create=True)
                    return
                raise KeyError(f"{op.oid}:{op.name}")
            del onode.attrs[op.name]

    def _write_range(
        self, onode: _Onode, offset: int, data: bytes, freed, allocated,
        csums=None, csum_block: int = 0,
    ) -> None:
        """COW block write: the touched blocks are rewritten to fresh
        extents; partial head/tail blocks merge old content first.

        ``csums``: optional kernel-produced ZERO-INIT per-block crc32c
        of ``data`` (fused encode+csum). Adopted only when they
        describe the stored blocks exactly — block-aligned offset and
        length at this store's csum granularity, no boundary merge —
        else the host facade re-hashes (partial tail blocks always
        fall back: crc(partial) != crc(zero-padded block))."""
        if not data:
            return
        bs = self.block_size
        lo = (offset // bs) * bs
        hi = -(-(offset + len(data)) // bs) * bs
        provided = None
        if (
            csums is not None
            and csum_block == self.csum_block
            and bs % self.csum_block == 0
            and offset == lo
            and offset + len(data) == hi
            and len(csums) * self.csum_block == len(data)
        ):
            shift = self._csum_seed_shift()
            provided = [int(v) ^ shift for v in csums]
        buf = bytearray(hi - lo)
        # Preserve surrounding bytes of PARTIALLY covered boundary
        # blocks only. A fully covered block is never read — so a
        # full-block overwrite can REPLACE a corrupt blob (scrub
        # repair) instead of tripping on its checksum.
        if offset > lo:
            buf[:bs] = self._read_onode(onode, lo, bs).ljust(bs, b"\0")
        if offset + len(data) < hi:
            buf[-bs:] = self._read_onode(onode, hi - bs, bs).ljust(bs, b"\0")
        buf[offset - lo : offset - lo + len(data)] = data
        extents = self.allocator.allocate(hi - lo)
        allocated.extend(extents)
        # drop the old blobs covering [lo, hi)
        for boff in sorted(onode.blobs):
            blob = onode.blobs[boff]
            bend = boff + blob.length
            if bend <= lo or boff >= hi:
                continue
            del onode.blobs[boff]
            n = -(-blob.length // bs)
            freed.append((blob.offset, n * bs))
            # resurrect the parts outside [lo, hi) by re-writing them
            # into the new buffer's window... they are already there
            # via _read_onode for boundary blocks; interior fully
            # overwritten. Blobs never span the window boundary beyond
            # one block because writes are block-granular COW.
            if boff < lo:
                head = self._blob_bytes(blob)[: lo - boff]
                self._store_run(onode, boff, head, allocated)
            if bend > hi:
                tail = self._blob_bytes(blob)[hi - boff :]
                self._store_run(onode, hi, tail, allocated)
        pos = 0
        cb = self.csum_block
        for dev_off, ln in extents:
            chunk = bytes(buf[pos : pos + ln])
            self._dev_write(dev_off, chunk)
            self._store_blob(
                onode, lo + pos, dev_off, chunk,
                provided[pos // cb : (pos + ln) // cb]
                if provided is not None else None,
            )
            pos += ln

    def _store_run(self, onode, logical_off, data, allocated) -> None:
        if not data:
            return
        extents = self.allocator.allocate(len(data))
        allocated.extend(extents)
        pos = 0
        for dev_off, ln in extents:
            chunk = bytes(data[pos : pos + ln])
            self._dev_write(dev_off, chunk)
            self._store_blob(onode, logical_off + pos, dev_off, chunk)
            pos += ln

    def _store_blob(
        self, onode, logical_off, dev_off, data, csums=None
    ) -> None:
        onode.blobs[logical_off] = _Blob(
            dev_off, len(data),
            list(csums) if csums is not None else self._csum(data),
        )

    def _csum_seed_shift(self) -> int:
        """crc(CSUM_SEED, B) = crc(0, B) ^ this, for any csum block —
        converts the fused kernel's zero-init csums to this store's
        seed with one XOR per block (no bytes re-hashed)."""
        if not hasattr(self, "_seed_shift"):
            self._seed_shift = crc32c_seed_shift(
                self.csum_block, CSUM_SEED
            )
        return self._seed_shift

    def _blob_read_verified(
        self, blob: _Blob, rel_off: int, rel_len: int
    ) -> bytes:
        """Read a range WITHIN a blob, verifying only the touched csum
        blocks (BlueStore::_verify_csum checks the read's blocks, not
        the whole blob). EVERY path that consumes stored bytes goes
        through here — including internal ones like truncate's trim —
        so corruption can never be re-checksummed into a fresh blob."""
        cb = self.csum_block
        blk_lo = rel_off // cb
        blk_hi = -(-(rel_off + rel_len) // cb)
        win_lo = blk_lo * cb
        win_len = min(blk_hi * cb, blob.length) - win_lo
        raw = self._dev_read(blob.offset + win_lo, win_len)
        for i in range(blk_lo, blk_hi):
            got = _crc(
                CSUM_SEED,
                raw[(i - blk_lo) * cb : (i - blk_lo + 1) * cb],
            )
            if got != blob.csums[i]:
                raise CsumError(
                    f"csum mismatch at blob +{i * cb} (dev "
                    f"{blob.offset:#x}): got {got:#x} want "
                    f"{blob.csums[i]:#x}"
                )
        return raw[rel_off - win_lo : rel_off - win_lo + rel_len]

    def _blob_bytes(self, blob: _Blob) -> bytes:
        return self._blob_read_verified(blob, 0, blob.length)

    def _read_onode(self, onode: _Onode, offset: int, length: int) -> bytes:
        """Assemble + VERIFY a logical range from the blob map; holes
        read as zeros; only the touched csum blocks are checked."""
        out = bytearray(length)
        for boff in sorted(onode.blobs):
            blob = onode.blobs[boff]
            bend = boff + blob.length
            s = max(boff, offset)
            e = min(bend, offset + length)
            if s >= e:
                continue
            out[s - offset : e - offset] = self._blob_read_verified(
                blob, s - boff, e - s
            )
        return bytes(out)

    # -- read path (MemStore-identical contract) ------------------------
    def exists(self, oid: str) -> bool:
        with self._lock:
            return oid in self._objects

    def stat(self, oid: str) -> int:
        with self._lock:
            onode = self._objects.get(oid)
            if onode is None:
                raise FileNotFoundError(oid)
            return onode.size

    def read(self, oid: str, offset: int = 0, length: int | None = None) -> bytes:
        with self._lock:
            onode = self._objects.get(oid)
            if onode is None:
                raise FileNotFoundError(oid)
            if length is None:
                length = max(onode.size - offset, 0)
            length = max(min(length, onode.size - offset), 0)
            return self._read_onode(onode, offset, length)

    def getattr(self, oid: str, name: str) -> bytes:
        with self._lock:
            onode = self._objects.get(oid)
            if onode is None:
                raise FileNotFoundError(oid)
            if name not in onode.attrs:
                raise KeyError(f"{oid}:{name}")
            return onode.attrs[name]

    def getattrs(self, oid: str) -> dict[str, bytes]:
        with self._lock:
            onode = self._objects.get(oid)
            if onode is None:
                raise FileNotFoundError(oid)
            return dict(onode.attrs)

    def list_objects(self) -> list[str]:
        with self._lock:
            return sorted(self._objects)

    def close(self) -> None:
        with self._lock:
            self._kvdb.compact()
            self._dev.close()

    def __repr__(self) -> str:
        return (
            f"BlockStore({self.root!r}, objects={len(self._objects)}, "
            f"free={self.allocator.get_free()})"
        )
